"""Finite-field MPC primitives for secure aggregation (TurboAggregate).

Parity: fedml_api/distributed/turboaggregate/mpc_function.py — BGW secret
sharing (:62-108), Lagrange Coded Computing encode/decode (:111-260),
additive shares (:214-224), and DH-style key agreement (:263-275).

These are *control-plane* host ops on small integers; they stay numpy
(int64 + Python-int modular inverses), not XLA — the data-plane model math
stays on TPU and enters/leaves this layer through fixed-point quantization
(`quantize`/`dequantize`).
"""
from __future__ import annotations

import numpy as np

# A 31-bit prime (reference uses p = 2^31 - 1 style fields); int64 products
# of two <p residues overflow, so reduce via Python ints / object math where
# needed. 2147483647 = 2^31 - 1 (Mersenne).
DEFAULT_PRIME = 2_147_483_647


def _mod(a: np.ndarray, p: int) -> np.ndarray:
    return np.mod(a, p)


def modinv(a: int, p: int) -> int:
    return pow(int(a), p - 2, p)


def modmat(A: np.ndarray, B: np.ndarray, p: int) -> np.ndarray:
    """Modular matrix product with object-int accumulation (no overflow)."""
    A = A.astype(object)
    B = B.astype(object)
    return np.mod(A @ B, p).astype(np.int64)


# -- fixed-point bridge ------------------------------------------------------

def quantize(x: np.ndarray, scale: int = 2 ** 16,
             p: int = DEFAULT_PRIME,
             max_abs: int | None = None) -> np.ndarray:
    """float → field: round(x·scale) mod p, negatives wrap to [p/2, p).

    Non-finite inputs are rejected FIRST: inf/NaN cast to INT64_MIN
    under .astype(np.int64) (and np.abs(INT64_MIN) stays negative), so
    they would slide past the magnitude check below and encode as
    garbage — the named refusal here is the enforcement a byzantine or
    diverged client cannot blind through masking.

    Field-overflow bound: the signed fixed-point magnitude |round(x·scale)|
    must stay ≤ (p−1)//2 — the field's signed half-range — or the value
    would alias across the negative/positive boundary (a large positive
    reading back as negative and vice versa) and every downstream sum
    would be silently garbage.  Out-of-range values raise a named
    ValueError instead of wrapping; both signs are pinned at the boundary
    in tests/test_mpc.py.  With the default scale 2^16 and p = 2^31−1 the
    usable float range is ±16383.999; aggregate sums share the same bound,
    so K summands must jointly satisfy K·max|x|·scale ≤ (p−1)//2 —
    callers that fold K rows pass ``max_abs=(p−1)//(2K)`` to enforce
    their per-summand slice of that budget (secagg client_row does),
    because a sum that wraps is undetectable after the fact."""
    x = np.asarray(x, np.float64)
    if x.size and not np.all(np.isfinite(x)):
        raise ValueError(
            "fixed-point quantize: non-finite input (inf/NaN) cannot be "
            "encoded in the field — clip or drop the row upstream")
    q = np.round(x * scale).astype(np.int64)
    bound = (p - 1) // 2
    if max_abs is not None:
        bound = min(int(max_abs), bound)
    if q.size and int(np.max(np.abs(q))) > bound:
        bad = float(np.max(np.abs(x)))
        why = ("the value would alias across the sign boundary after "
               "mod p" if bound == (p - 1) // 2 else
               "past the caller's per-summand share of the field range, "
               "the aggregate sum could cross the signed half-range and "
               "alias at dequantize")
        raise ValueError(
            f"fixed-point field overflow: |x|·scale reaches "
            f"{int(np.max(np.abs(q)))} > bound {bound} "
            f"(max |x| = {bad:g}, scale = {scale}) — {why}; reduce the "
            f"scale or clip the input")
    return _mod(q, p)


def dequantize(q: np.ndarray, scale: int = 2 ** 16,
               p: int = DEFAULT_PRIME) -> np.ndarray:
    """field → float, mapping the upper half back to negatives."""
    q = np.asarray(q, np.int64)
    signed = np.where(q > p // 2, q - p, q)
    return signed.astype(np.float64) / scale


# -- polynomial secret sharing (BGW) ----------------------------------------

def BGW_encoding(X: np.ndarray, N: int, T: int, p: int = DEFAULT_PRIME,
                 seed: int | None = None) -> np.ndarray:
    """Shamir/BGW: share secret array X (field elements) into N shares with
    threshold T (any T+1 reconstruct). Returns [N, *X.shape]
    (mpc_function.py:62-83)."""
    rs = np.random.RandomState(seed)
    X = np.mod(np.asarray(X, np.int64), p)
    coeffs = [X] + [rs.randint(0, p, X.shape).astype(np.int64)
                    for _ in range(T)]
    alphas = np.arange(1, N + 1, dtype=np.int64)
    shares = np.empty((N,) + X.shape, np.int64)
    for i, a in enumerate(alphas):
        acc = np.zeros(X.shape, dtype=object)
        apow = 1
        for c in coeffs:
            acc = acc + c.astype(object) * apow
            apow = (apow * int(a)) % p
        shares[i] = np.mod(acc, p).astype(np.int64)
    return shares


def _lagrange_coeffs_at(targets: np.ndarray, evals: np.ndarray,
                        p: int) -> np.ndarray:
    """W[i][j]: weight of eval point j when interpolating at target i."""
    W = np.empty((len(targets), len(evals)), np.int64)
    for ti, t in enumerate(targets):
        for j, aj in enumerate(evals):
            num, den = 1, 1
            for m, am in enumerate(evals):
                if m == j:
                    continue
                num = (num * ((int(t) - int(am)) % p)) % p
                den = (den * ((int(aj) - int(am)) % p)) % p
            W[ti, j] = (num * modinv(den, p)) % p
    return W


def BGW_decoding(shares: np.ndarray, worker_idx: np.ndarray,
                 p: int = DEFAULT_PRIME) -> np.ndarray:
    """Reconstruct the secret from ≥T+1 shares (rows of `shares` correspond
    to worker indices `worker_idx`, 0-based) — mpc_function.py:86-108."""
    alphas = np.asarray(worker_idx, np.int64) + 1
    W = _lagrange_coeffs_at(np.zeros(1, np.int64), alphas, p)[0]
    flat = shares.reshape(shares.shape[0], -1)
    out = modmat(W[None, :], flat, p)[0]
    return out.reshape(shares.shape[1:])


# -- Lagrange Coded Computing ------------------------------------------------

def LCC_encoding(X: np.ndarray, N: int, K: int, T: int = 0,
                 p: int = DEFAULT_PRIME, seed: int | None = None) -> np.ndarray:
    """Encode K data blocks (leading axis of X, shape [K, ...]) into N coded
    blocks via Lagrange interpolation through betas 1..K(+T random pads),
    evaluated at alphas K+T+1..K+T+N (mpc_function.py:111-170).  With T>0,
    T uniformly-random pad blocks give T-privacy."""
    rs = np.random.RandomState(seed)
    X = np.mod(np.asarray(X, np.int64), p)
    K_, rest = X.shape[0], X.shape[1:]
    assert K_ == K
    if T > 0:
        pads = rs.randint(0, p, (T,) + rest).astype(np.int64)
        X = np.concatenate([X, pads], axis=0)
    betas = np.arange(1, K + T + 1, dtype=np.int64)
    alphas = np.arange(K + T + 1, K + T + N + 1, dtype=np.int64)
    W = _lagrange_coeffs_at(alphas, betas, p)         # [N, K+T]
    flat = X.reshape(K + T, -1)
    out = modmat(W, flat, p)
    return out.reshape((N,) + rest)


def LCC_decoding(coded: np.ndarray, worker_idx: np.ndarray, N: int, K: int,
                 T: int = 0, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Recover the K data blocks from any K+T coded blocks
    (mpc_function.py:173-213)."""
    alphas_all = np.arange(K + T + 1, K + T + N + 1, dtype=np.int64)
    evals = alphas_all[np.asarray(worker_idx)]
    betas = np.arange(1, K + T + 1, dtype=np.int64)
    W = _lagrange_coeffs_at(betas, evals, p)          # [K+T, len(idx)]
    flat = coded.reshape(coded.shape[0], -1)
    out = modmat(W, flat, p)
    return out.reshape((K + T,) + coded.shape[1:])[:K]


# -- additive sharing + key agreement ----------------------------------------

def additive_shares(X: np.ndarray, N: int, p: int = DEFAULT_PRIME,
                    seed: int | None = None) -> np.ndarray:
    """Split X into N uniformly-random shares summing to X mod p
    (mpc_function.py:214-224)."""
    rs = np.random.RandomState(seed)
    X = np.mod(np.asarray(X, np.int64), p)
    shares = rs.randint(0, p, (N - 1,) + X.shape).astype(np.int64)
    last = np.mod(X.astype(object) - shares.astype(object).sum(axis=0),
                  p).astype(np.int64)
    return np.concatenate([shares, last[None]], axis=0)


def pk_gen(sk: int, g: int = 5, p: int = DEFAULT_PRIME) -> int:
    """Diffie-Hellman-style public key g^sk mod p (mpc_function.py:263-269)."""
    return pow(g, int(sk), p)


def shared_key(pk_other: int, sk_self: int, p: int = DEFAULT_PRIME) -> int:
    """pairwise shared secret pk_other^sk_self mod p (:271-275)."""
    return pow(int(pk_other), int(sk_self), p)
