"""Pytree arithmetic for federated aggregation.

These are the TPU-native replacement for the reference's server-side
dict-of-tensors loops (FedAVGAggregator.aggregate,
reference fedml_api/distributed/fedavg/FedAVGAggregator.py:59-88): instead of
a Python loop over state_dict keys on CPU, aggregation is a jit-able
tree-map over stacked leaves that XLA fuses into a handful of HBM-bandwidth
bound kernels (and into a single `psum` when the client axis is sharded over
a mesh).
"""
from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

Pytree = Any


def tree_weighted_mean(trees_stacked: Pytree, weights: jax.Array) -> Pytree:
    """Sample-weighted mean over leading (client) axis of stacked pytrees.

    ``sum_i (n_i / N) * w_i`` — exactly the FedAvg aggregation rule of the
    reference (FedAVGAggregator.py:73-81), including averaging *all* leaves
    (BN/GN statistics included, matching the reference's iteration over every
    state_dict key).

    Args:
      trees_stacked: pytree whose leaves have a leading axis of size C
        (number of clients).
      weights: [C] float array of per-client sample counts (need not be
        normalized).
    """
    w = weights / jnp.sum(weights)

    def _avg(leaf):
        wb = w.reshape((-1,) + (1,) * (leaf.ndim - 1)).astype(leaf.dtype)
        return jnp.sum(leaf * wb, axis=0)

    return jax.tree.map(_avg, trees_stacked)


def tree_stack(trees: Sequence[Pytree]) -> Pytree:
    """Stack a list of identically-structured pytrees along a new axis 0."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(tree: Pytree) -> list[Pytree]:
    """Inverse of tree_stack: split leading axis into a list of pytrees."""
    leaves, treedef = jax.tree.flatten(tree)
    n = leaves[0].shape[0]
    return [jax.tree.unflatten(treedef, [leaf[i] for leaf in leaves]) for i in range(n)]


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * jnp.asarray(s, dtype=x.dtype), tree)


def tree_dot(a: Pytree, b: Pytree) -> jax.Array:
    parts = jax.tree.leaves(jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b))
    return jnp.sum(jnp.stack([p.astype(jnp.float32) for p in parts]))


def tree_l2_norm(tree: Pytree) -> jax.Array:
    """Global L2 norm over all leaves (the reference's vectorize_weight +
    torch.norm, robust_aggregation.py:4-9)."""
    sq = jax.tree.leaves(jax.tree.map(lambda x: jnp.sum(jnp.square(x.astype(jnp.float32))), tree))
    return jnp.sqrt(jnp.sum(jnp.stack(sq)))


def clip_scale(sq_norm, max_norm):
    """THE norm-clip factor:  min(1, τ/‖·‖)  from a SQUARED norm, with
    the 1e-24 floor inside the sqrt guarding the zero-update case.

    One definition, three call sites (the ISSUE-9 dedupe): the pytree
    clip below (→ core/robust.norm_diff_clip), the pallas clip-agg's
    host-side factor (ops/aggregate.robust_weighted_mean_pallas), and
    the flat-row admission/DP clip (core/robust.clip_row →
    async_/defense.py).  They reduce their squared norms differently
    (tree-sum vs tile-accumulated vs flat dot), so the cross-pin in
    tests/test_robustness.py holds on the FACTOR given equal sq_norm —
    routing all three through here is what keeps the DP-FedAvg clip
    and the admission clip from drifting."""
    norm = jnp.sqrt(jnp.maximum(jnp.asarray(sq_norm, jnp.float32), 1e-24))
    return jnp.minimum(1.0, max_norm / norm)


def tree_sq_norm(tree: Pytree) -> jax.Array:
    """Global squared L2 norm over all leaves (f32 accumulate)."""
    sq = jax.tree.leaves(jax.tree.map(
        lambda x: jnp.sum(jnp.square(x.astype(jnp.float32))), tree))
    return jnp.sum(jnp.stack(sq))


def tree_clip_by_norm(tree: Pytree, max_norm) -> Pytree:
    return tree_scale(tree, clip_scale(tree_sq_norm(tree), max_norm))


def tree_cast(tree: Pytree, dtype) -> Pytree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def vectorize_weights(tree: Pytree) -> jax.Array:
    """Flatten a parameter pytree into one 1-D vector (reference
    robust_aggregation.py:4-9). Useful for MPC encoding and norm math."""
    return jnp.concatenate([jnp.ravel(x) for x in jax.tree.leaves(tree)])


def unvectorize_weights(vec: jax.Array, like: Pytree) -> Pytree:
    """Inverse of vectorize_weights given a template pytree."""
    leaves, treedef = jax.tree.flatten(like)
    out, off = [], 0
    for leaf in leaves:
        n = leaf.size
        out.append(vec[off:off + n].reshape(leaf.shape).astype(leaf.dtype))
        off += n
    return jax.tree.unflatten(treedef, out)


def tree_select(pred, new: Pytree, old: Pytree) -> Pytree:
    """Elementwise `jnp.where(pred, new, old)` over two matching pytrees.

    The standard empty-batch guard: an all-padding batch must be a no-op,
    but momentum / weight-decay / prox updates are nonzero even at zero
    data gradient — so freeze params and optimizer state when the batch
    holds no real samples (the reference iterates only real batches)."""
    return jax.tree.map(lambda n, o: jnp.where(pred, n, o), new, old)


def tree_merge_counts(kept: Pytree, advanced: Pytree) -> Pytree:
    """Return `kept` with every SCHEDULE step count (the ``count`` field
    of optax ``ScaleByScheduleState`` NamedTuples) taken from `advanced`.

    The empty-batch guard freezes optimizer state via tree_select, which
    also freezes the schedule step count — so padded-lane clients would
    stall on the LR schedule while real steps elapse.  The SCHEDULE
    count measures elapsed local steps, not applied updates: merging the
    advanced count back makes every client in a ragged cohort walk the
    same LR trajectory over the padded E x B loop (the CLI sizes
    total_steps to the padded batch count).  Other counts — notably
    ScaleByAdamState.count, whose bias correction must agree with the
    frozen mu/nu moments — and momentum / moment buffers stay frozen."""
    if hasattr(kept, "_fields"):          # optax states are NamedTuples
        schedule = type(kept).__name__ == "ScaleByScheduleState"
        return type(kept)(**{
            f: (getattr(advanced, f) if f == "count" and schedule
                else tree_merge_counts(getattr(kept, f),
                                       getattr(advanced, f)))
            for f in kept._fields})
    if isinstance(kept, (list, tuple)):
        return type(kept)(tree_merge_counts(k, a)
                          for k, a in zip(kept, advanced))
    if isinstance(kept, dict):
        return {k: tree_merge_counts(v, advanced[k])
                for k, v in kept.items()}
    return kept


def tree_vary_noop(tree: Pytree, shard) -> Pytree:
    """Value-preserving select that makes `tree` carry the shard data's
    shard_map variance type.

    Why: under shard_map, the empty-batch guard's tree_select varies any
    STATEFUL optimizer state after the first step (has_data depends on
    the shard), while a freshly tx.init'd state is replicated-typed — a
    lax.scan carry-type mismatch.  select(pred, x, x) with a pred that is
    data-dependent but always true fixes the type without changing a bit.
    The invariant lives here so every local-training loop uses the same
    trick."""
    pred = jnp.sum(shard["mask"]) >= 0        # always true, shard-typed
    return tree_select(pred, tree, tree)
