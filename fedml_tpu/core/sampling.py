"""Deterministic per-round client sampling.

Reproduces the reference's sampling semantics exactly
(FedAVGAggregator.client_sampling, reference
fedml_api/distributed/fedavg/FedAVGAggregator.py:90-98):
``np.random.seed(round_idx); np.random.choice(range(N), k, replace=False)``
— so runs are comparable round-for-round with the reference, and the
equivalence oracle (BASELINE.md) stays valid.  A JAX-native sampler is also
provided for fully-jitted round loops.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class ClientSampler:
    """Seeded-by-round sampler with the reference's numpy semantics."""

    def __init__(self, client_num_in_total: int, client_num_per_round: int):
        self.client_num_in_total = client_num_in_total
        self.client_num_per_round = client_num_per_round

    @classmethod
    def for_data(cls, data, cfg) -> "ClientSampler":
        """Sampler over the clients the DATA actually has: real-file
        loaders honor the file's natural client count, which can differ
        from cfg.client_num_in_total — sampling cfg's range would gather
        out-of-range ids (silently clamped by jnp.take) and train wrong
        shards under wrong weights.  Every engine must construct its
        sampler through this."""
        n_total = data.client_num
        if n_total != cfg.client_num_in_total:
            import logging
            logging.getLogger(__name__).warning(
                "dataset has %d clients but client_num_in_total=%d; "
                "sampling over the dataset's %d",
                n_total, cfg.client_num_in_total, n_total)
        return cls(n_total, cfg.client_num_per_round)

    def sample(self, round_idx: int) -> np.ndarray:
        # >= (not ==): per_round beyond the population is full
        # participation too, and must agree with sample_jax's branch so
        # cohort ordering (and thus rng-lane pairing) matches
        if self.client_num_per_round >= self.client_num_in_total:
            return np.arange(self.client_num_in_total, dtype=np.int64)
        num = min(self.client_num_per_round, self.client_num_in_total)
        np.random.seed(round_idx)  # deterministic, matches reference
        return np.asarray(
            np.random.choice(range(self.client_num_in_total), num, replace=False),
            dtype=np.int64,
        )

    def sample_fast(self, round_idx: int,
                    k: Optional[int] = None) -> np.ndarray:
        """BITWISE-equal twin of `sample` that neither reseeds the
        GLOBAL numpy RNG nor builds a Python `range(N)` list — the
        cross-device fast path (ISSUE 10): `np.random.seed(r)` +
        `np.random.choice(range(N), ...)` delegates to a global legacy
        RandomState, so a PRIVATE `RandomState(r)` walks the identical
        Mersenne-Twister stream (and `choice(N, ...)` indexes the same
        permutation the range-array path takes) — cross-pinned against
        the oracle in tests/test_scale.py.  Per draw this is still an
        O(N) numpy permutation internally, but transient ndarray scratch
        instead of an O(N) boxed-int list, and concurrency-safe: nothing
        else sharing the process loses its RNG state.  `k` overrides the
        cohort size (the streaming sampler's variable-width draws)."""
        k = self.client_num_per_round if k is None else int(k)
        if k >= self.client_num_in_total:
            return np.arange(self.client_num_in_total, dtype=np.int64)
        rs = np.random.RandomState(round_idx)
        return np.asarray(
            rs.choice(self.client_num_in_total, k, replace=False),
            dtype=np.int64,
        )

    def sample_jax(self, round_idx: jax.Array) -> jax.Array:
        """Traceable variant for fully-jitted round loops: derives a fold-in
        key from the round index and takes the first k of a permutation.
        (Not bit-identical to numpy — use `sample` when oracle comparability
        with the reference matters.)  Full participation returns arange,
        mirroring `sample` — so client→rng-lane pairing matches the Python
        loop exactly in that regime."""
        if self.client_num_per_round >= self.client_num_in_total:
            return jnp.arange(self.client_num_in_total, dtype=jnp.int32)
        key = jax.random.fold_in(jax.random.PRNGKey(0), round_idx)
        perm = jax.random.permutation(key, self.client_num_in_total)
        return perm[: self.client_num_per_round]
