"""Deterministic per-round client sampling.

Reproduces the reference's sampling semantics exactly
(FedAVGAggregator.client_sampling, reference
fedml_api/distributed/fedavg/FedAVGAggregator.py:90-98):
``np.random.seed(round_idx); np.random.choice(range(N), k, replace=False)``
— so runs are comparable round-for-round with the reference, and the
equivalence oracle (BASELINE.md) stays valid.  A JAX-native sampler is also
provided for fully-jitted round loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class ClientSampler:
    """Seeded-by-round sampler with the reference's numpy semantics."""

    def __init__(self, client_num_in_total: int, client_num_per_round: int):
        self.client_num_in_total = client_num_in_total
        self.client_num_per_round = client_num_per_round

    def sample(self, round_idx: int) -> np.ndarray:
        if self.client_num_in_total == self.client_num_per_round:
            return np.arange(self.client_num_in_total, dtype=np.int64)
        num = min(self.client_num_per_round, self.client_num_in_total)
        np.random.seed(round_idx)  # deterministic, matches reference
        return np.asarray(
            np.random.choice(range(self.client_num_in_total), num, replace=False),
            dtype=np.int64,
        )

    def sample_jax(self, round_idx: jax.Array) -> jax.Array:
        """Traceable variant for fully-jitted round loops: derives a fold-in
        key from the round index and takes the first k of a permutation.
        (Not bit-identical to numpy — use `sample` when oracle comparability
        with the reference matters.)"""
        key = jax.random.fold_in(jax.random.PRNGKey(0), round_idx)
        perm = jax.random.permutation(key, self.client_num_in_total)
        return perm[: self.client_num_per_round]
