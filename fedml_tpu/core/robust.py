"""Byzantine-robust aggregation primitives.

Parity with reference fedml_core/robustness/robust_aggregation.py: norm
-difference clipping ``w_t + clip(w_local - w_t)`` (:38-49) and weak-DP
Gaussian noise (:51-55).  The reference excludes BatchNorm running stats from
the norm via `is_weight_param` (:28-29); here the caller passes the params
subtree (stats live in a separate collection in flax, so the split is
structural, not name-matching).

All ops are pure pytree functions — they run inside the jitted aggregation
step, not in a host loop.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from fedml_tpu.core.pytree import (clip_scale, tree_add, tree_clip_by_norm,
                                   tree_sub)

Pytree = Any

__all__ = ["norm_diff_clip", "clip_scale", "clip_row", "add_weak_dp_noise",
           "krum_select_flat", "krum_scores_flat", "multi_krum_select_flat",
           "default_multi_krum_m", "krum_select", "multi_krum_select",
           "coordinate_median", "trimmed_mean"]


def norm_diff_clip(local_params: Pytree, global_params: Pytree,
                   norm_bound: float) -> Pytree:
    """Clip the update (w_local - w_global) to `norm_bound` and re-apply:
    returns w_global + clip(w_local - w_global).  The clip factor is the
    ONE shared definition (core/pytree.clip_scale) — the pallas fused
    clip-agg and the flat-row admission/DP clip use the same one."""
    diff = tree_sub(local_params, global_params)
    return tree_add(global_params, tree_clip_by_norm(diff, norm_bound))


def clip_row(row: jax.Array, norm_bound: float) -> jax.Array:
    """Flat-row norm clip: `row * clip_scale(‖row‖², bound)` — the
    RowLayout-row form of norm_diff_clip's clip (callers pass the DELTA
    row, i.e. uplink − global, and re-add the global themselves).  The
    async admission pipeline and the DP-FedAvg per-client clip
    (async_/defense.py) both resolve here, so the two cannot drift."""
    row = jnp.asarray(row, jnp.float32)
    return row * clip_scale(jnp.sum(row * row), norm_bound)


def add_weak_dp_noise(params: Pytree, rng: jax.Array, stddev: float) -> Pytree:
    """Per-leaf Gaussian noise with std `stddev` (weak differential privacy)."""
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(rng, len(leaves))
    noised = [leaf + stddev * jax.random.normal(k, leaf.shape, leaf.dtype)
              for leaf, k in zip(leaves, keys)]
    return jax.tree.unflatten(treedef, noised)


def krum_select_flat(flat: jax.Array, n_byzantine: int) -> jax.Array:
    """Krum on a [K, P] client-update matrix: index of the client whose
    update has the smallest sum of squared distances to its n-f-2 nearest
    neighbors.  Gram-matrix form (krum_scores_flat): O(K·P + K²) memory,
    and the K×P matmul runs on the MXU — never materialize the [K,K,P]
    broadcast."""
    return jnp.argmin(krum_scores_flat(flat, n_byzantine))


def krum_scores_flat(flat: jax.Array, n_byzantine: int) -> jax.Array:
    """Per-client krum scores on a [K, P] matrix: Σ of squared distances
    to the n-f-2 nearest neighbors (the quantity krum argmins and
    multi-krum top-m's — one definition for both)."""
    sq = jnp.sum(flat * flat, axis=1)
    d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (flat @ flat.T), 0.0)
    n = flat.shape[0]
    k = max(n - n_byzantine - 2, 1)
    d2 = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, d2)
    # NaN/Inf guard: a non-finite row would otherwise poison EVERY
    # pairwise distance it touches (NaN sorts unpredictably and argmin
    # propagates it), letting one garbage uplink break the selection for
    # honest clients too.  Non-finite distances become +inf: the bad row
    # scores inf (never selected) and drops out of everyone else's
    # k-nearest sums — for finite inputs this where() is the identity.
    d2 = jnp.where(jnp.isfinite(d2) | jnp.eye(n, dtype=bool), d2, jnp.inf)
    return jnp.sum(jnp.sort(d2, axis=1)[:, :k], axis=1)


def default_multi_krum_m(K: int, n_byzantine: int,
                         m: "int | None" = None) -> int:
    """Multi-krum selection size: the Blanchard et al. 2017 default
    m = K - f - 2 when unset, clamped to [1, K] either way — THE one
    definition both the single-device and mesh engines share."""
    if m is None:
        m = K - n_byzantine - 2
    return max(1, min(m, K))


def multi_krum_select_flat(flat: jax.Array, n_byzantine: int,
                           m: int) -> jax.Array:
    """Multi-krum on a [K, P] matrix: indices of the m clients with the
    LOWEST krum scores (Blanchard et al. 2017 §4 — m=1 degenerates to
    krum; the aggregate is the plain mean of the selected updates)."""
    scores = krum_scores_flat(flat, n_byzantine)
    m = max(1, min(m, flat.shape[0]))
    return jnp.argsort(scores)[:m]


def _flatten_clients(stacked_params: Pytree) -> jax.Array:
    """[K, ...] stacked pytree -> the [K, P] matrix the krum family
    scores (ONE definition of the flattening convention)."""
    return jnp.concatenate(
        [x.reshape(x.shape[0], -1)
         for x in jax.tree.leaves(stacked_params)], axis=1)


def krum_select(stacked_params: Pytree, n_byzantine: int) -> jax.Array:
    """Krum over a stacked pytree.  (An addition beyond the reference's
    clip+noise, standard in the robust-FL literature.)"""
    return krum_select_flat(_flatten_clients(stacked_params), n_byzantine)


def multi_krum_select(stacked_params: Pytree, n_byzantine: int,
                      m: int) -> jax.Array:
    """Multi-krum over a stacked pytree: indices of the m best-scored
    clients (their plain mean is the aggregate)."""
    return multi_krum_select_flat(_flatten_clients(stacked_params),
                                  n_byzantine, m)


def coordinate_median(stacked_params: Pytree) -> Pytree:
    """Coordinate-wise median over the client axis."""
    return jax.tree.map(lambda x: jnp.median(x, axis=0), stacked_params)


def trimmed_mean(stacked_params: Pytree, trim_k: int) -> Pytree:
    """Coordinate-wise trimmed mean: drop the k largest and smallest
    (k is capped so at least one value survives)."""
    def _tm(x):
        n = x.shape[0]
        k = min(trim_k, (n - 1) // 2)
        s = jnp.sort(x, axis=0)
        return jnp.mean(s[k:n - k], axis=0)
    return jax.tree.map(_tm, stacked_params)
