from fedml_tpu.core.pytree import (
    tree_weighted_mean,
    tree_select,
    tree_stack,
    tree_unstack,
    tree_zeros_like,
    tree_add,
    tree_sub,
    tree_scale,
    tree_dot,
    tree_l2_norm,
    tree_clip_by_norm,
    tree_cast,
    vectorize_weights,
)
from fedml_tpu.core.partition import (
    partition_homo,
    partition_dirichlet,
    partition_power_law,
    record_data_stats,
)
from fedml_tpu.core.sampling import ClientSampler
from fedml_tpu.core.trainer import ClientTrainer, TrainState
from fedml_tpu.core.topology import (
    SymmetricTopologyManager,
    AsymmetricTopologyManager,
)
from fedml_tpu.core.robust import (norm_diff_clip, add_weak_dp_noise,
                                   clip_scale, clip_row)

__all__ = [
    "tree_weighted_mean", "tree_select", "tree_stack", "tree_unstack",
    "tree_zeros_like",
    "tree_add", "tree_sub", "tree_scale", "tree_dot", "tree_l2_norm",
    "tree_clip_by_norm", "tree_cast", "vectorize_weights",
    "partition_homo", "partition_dirichlet", "partition_power_law",
    "record_data_stats", "ClientSampler", "ClientTrainer", "TrainState",
    "SymmetricTopologyManager", "AsymmetricTopologyManager",
    "norm_diff_clip", "add_weak_dp_noise", "clip_scale", "clip_row",
]
