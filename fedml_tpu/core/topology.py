"""Topology managers for decentralized algorithms.

Parity with reference fedml_core/distributed/topology/: symmetric
(ring + Watts-Strogatz rewiring, row-normalized weights,
symmetric_topology_manager.py:21-52) and asymmetric (directed, random link
deletion, asymmetric_topology_manager.py:23-100).  The adjacency matrix
doubles as the gossip mixing matrix consumed by the decentralized engine
(neighbor exchange = `lax.ppermute` / matmul over the client axis).
"""
from __future__ import annotations

import numpy as np


class BaseTopologyManager:
    topology: np.ndarray  # [n, n] row-normalized mixing weights

    def get_in_neighbor_idx_list(self, node_index: int) -> list[int]:
        col = self.topology[:, node_index]
        return [i for i in range(len(col)) if col[i] != 0 and i != node_index]

    def get_out_neighbor_idx_list(self, node_index: int) -> list[int]:
        row = self.topology[node_index]
        return [i for i in range(len(row)) if row[i] != 0 and i != node_index]

    def get_in_neighbor_weights(self, node_index: int) -> np.ndarray:
        return self.topology[:, node_index]

    def get_out_neighbor_weights(self, node_index: int) -> np.ndarray:
        return self.topology[node_index]

    def mixing_matrix(self) -> np.ndarray:
        return self.topology


class SymmetricTopologyManager(BaseTopologyManager):
    """Undirected ring with `neighbor_num` extra Watts-Strogatz style links,
    symmetrized, rows normalized to sum to 1."""

    def __init__(self, n: int, neighbor_num: int = 2, seed: int = 0):
        self.n = n
        self.neighbor_num = min(neighbor_num, n - 1)
        self.seed = seed
        self.topology = np.zeros((n, n))
        self.generate_topology()

    def generate_topology(self):
        n, k = self.n, self.neighbor_num
        rng = np.random.RandomState(self.seed)
        adj = np.eye(n)
        # ring base
        for i in range(n):
            adj[i, (i + 1) % n] = 1
            adj[i, (i - 1) % n] = 1
        # extra random links per node (Watts-Strogatz flavored rewiring)
        extra = max(0, k - 2)
        for i in range(n):
            choices = [j for j in range(n) if j != i and adj[i, j] == 0]
            rng.shuffle(choices)
            for j in choices[:extra]:
                adj[i, j] = 1
        adj = np.maximum(adj, adj.T)  # symmetrize
        self.topology = adj / adj.sum(axis=1, keepdims=True)


class AsymmetricTopologyManager(BaseTopologyManager):
    """Directed variant: start from the symmetric graph, randomly delete
    out-links (keeping the ring connected), renormalize rows."""

    def __init__(self, n: int, neighbor_num: int = 3, deleted_ratio: float = 0.3,
                 seed: int = 0):
        self.n = n
        self.neighbor_num = neighbor_num
        self.deleted_ratio = deleted_ratio
        self.seed = seed
        self.topology = np.zeros((n, n))
        self.generate_topology()

    def generate_topology(self):
        base = SymmetricTopologyManager(self.n, self.neighbor_num, self.seed)
        adj = (base.topology > 0).astype(float)
        rng = np.random.RandomState(self.seed + 1)
        for i in range(self.n):
            for j in range(self.n):
                ring = j in ((i + 1) % self.n, (i - 1) % self.n, i)
                if adj[i, j] and not ring and rng.rand() < self.deleted_ratio:
                    adj[i, j] = 0
        self.topology = adj / adj.sum(axis=1, keepdims=True)
