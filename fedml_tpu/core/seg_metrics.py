"""Segmentation evaluation metrics — confusion-matrix based.

Parity: fedml_api/distributed/fedseg/utils.py (Evaluator with
pixel-accuracy / class-accuracy / mIoU / FWIoU) and the per-class metric
keeper in FedSegAggregator.py:105-186 (`EvaluationMetricsKeeper`).

TPU-native: the confusion matrix is one `jnp.bincount`-style scatter-add
under jit; metrics derive from it on host.
"""
from __future__ import annotations


import jax.numpy as jnp
import numpy as np


def confusion_matrix(pred: jnp.ndarray, label: jnp.ndarray,
                     mask: jnp.ndarray, num_classes: int) -> jnp.ndarray:
    """[C, C] counts; rows = true class, cols = predicted. Mask-aware.
    Out-of-range labels (VOC void 255) are excluded, exactly the reference
    Evaluator's ``(gt >= 0) & (gt < num_class)`` mask (fedseg utils.py
    Evaluator._generate_matrix)."""
    lab = label.reshape(-1)
    valid = (mask.reshape(-1) > 0) & (lab >= 0) & (lab < num_classes)
    idx = lab * num_classes + pred.reshape(-1)
    idx = jnp.where(valid, idx, num_classes * num_classes)   # spill bucket
    counts = jnp.zeros(num_classes * num_classes + 1, jnp.float32)
    counts = counts.at[idx].add(1.0)
    return counts[:-1].reshape(num_classes, num_classes)


def pixel_accuracy(cm: np.ndarray) -> float:
    return float(np.diag(cm).sum() / np.maximum(cm.sum(), 1.0))


def pixel_accuracy_class(cm: np.ndarray) -> float:
    per = np.diag(cm) / np.maximum(cm.sum(axis=1), 1.0)
    return float(np.nanmean(per))


def mean_iou(cm: np.ndarray) -> float:
    inter = np.diag(cm)
    union = cm.sum(axis=1) + cm.sum(axis=0) - inter
    iou = inter / np.maximum(union, 1.0)
    present = cm.sum(axis=1) > 0
    return float(iou[present].mean()) if present.any() else 0.0


def frequency_weighted_iou(cm: np.ndarray) -> float:
    freq = cm.sum(axis=1) / np.maximum(cm.sum(), 1.0)
    inter = np.diag(cm)
    union = cm.sum(axis=1) + cm.sum(axis=0) - inter
    iou = inter / np.maximum(union, 1.0)
    return float((freq[freq > 0] * iou[freq > 0]).sum())


class EvaluationMetricsKeeper:
    """Round-indexed best-metric tracker (FedSegAggregator.py:105-186)."""

    def __init__(self):
        self.history: list[dict] = []
        self.best: dict[str, float] = {}

    def update(self, round_idx: int, metrics: dict) -> None:
        entry = dict(metrics, round=round_idx)
        self.history.append(entry)
        for k, v in metrics.items():
            if isinstance(v, (int, float)) and v > self.best.get(k, -np.inf):
                self.best[k] = float(v)

    def summary(self) -> dict:
        return {"best": dict(self.best), "rounds": len(self.history)}
