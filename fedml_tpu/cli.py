"""Unified experiment launcher — the fedml_experiments parity surface.

One CLI replaces the reference's per-(algorithm × paradigm) main_*.py files
and the fed_launch unified launcher (fedml_experiments/distributed/
fed_launch/main.py): the canonical flag set of main_fedavg.py:46-135 plus
`--algorithm` dispatch.  `mpirun -np N` + hostfiles + gpu_mapping.yaml are
replaced by the device mesh: `--mesh` runs the cohort mesh-sharded over all
visible TPU chips (pjit/shard_map); without it the vmap simulation engine
runs on one chip (the reference's "standalone" paradigm).

Usage:
  python -m fedml_tpu.cli --algorithm fedavg --dataset mnist --model lr \
      --client_num_in_total 1000 --client_num_per_round 10 --comm_round 100
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np
from typing import Optional

from fedml_tpu.utils.config import FedConfig

ALGORITHMS = ("fedavg", "fedopt", "fedprox", "fednova", "fedavg_robust",
              "hierarchical", "decentralized", "fednas", "fedgan",
              "fedgkt", "splitnn", "fedseg", "vfl", "turboaggregate",
              "centralized")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("fedml_tpu",
                                description="TPU-native federated learning")
    # canonical reference flags (main_fedavg.py:46-135)
    p.add_argument("--algorithm", choices=ALGORITHMS, default="fedavg")
    p.add_argument("--model", type=str, default="lr")
    p.add_argument("--dataset", type=str, default="mnist")
    p.add_argument("--data_dir", type=str, default=None)
    p.add_argument("--partition_method", type=str, default="hetero")
    p.add_argument("--partition_alpha", type=float, default=0.5)
    p.add_argument("--client_num_in_total", type=int, default=10)
    p.add_argument("--client_num_per_round", type=int, default=10)
    p.add_argument("--comm_round", type=int, default=10)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch_size", type=int, default=10)
    p.add_argument("--client_optimizer", type=str, default="sgd")
    p.add_argument("--lr", type=float, default=0.03)
    p.add_argument("--momentum", type=float, default=0.0)
    p.add_argument("--wd", type=float, default=0.0)
    # None = "not set on the command line": FedConfig supplies the FedOpt
    # defaults (sgd @ 1.0 / 0.0) while fedgkt can tell an explicit
    # --server_momentum 0.0 apart from the flag being absent
    p.add_argument("--server_optimizer", type=str, default=None)
    p.add_argument("--server_lr", type=float, default=None)
    p.add_argument("--server_momentum", type=float, default=None)
    p.add_argument("--prox_mu", type=float, default=0.0)
    p.add_argument("--norm_bound", type=float, default=5.0)
    p.add_argument("--stddev", type=float, default=0.0)
    p.add_argument("--frequency_of_the_test", type=int, default=5)
    p.add_argument("--no_local_test_eval", dest="local_test_eval",
                   action="store_false",
                   help="skip the per-client test eval inside evaluate() "
                        "(reference _local_test_on_all_clients parity is "
                        "ON by default)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ci", type=int, default=0)
    p.add_argument("--synthetic_scale", type=float, default=1.0)
    p.add_argument("--train_dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"])
    # fedseg utils parity: LR_Scheduler (poly/cos/step + warmup) and
    # SegmentationLosses (focal, ignore_index) — fedseg/utils.py:71-157
    p.add_argument("--lr_scheduler", type=str, default=None,
                   choices=("poly", "cos", "step"),
                   help="per-local-round LR schedule over E*B steps")
    p.add_argument("--lr_step", type=int, default=0,
                   help="step schedule: epochs per 0.1x decay")
    p.add_argument("--warmup_epochs", type=int, default=0)
    p.add_argument("--loss_type", type=str, default=None,
                   choices=("ce", "focal"),
                   help="override the dataset-derived loss")
    p.add_argument("--train_ignore_id", type=int, default=None,
                   help="label id excluded from train loss + metrics "
                        "(segmentation void label, reference 255)")
    p.add_argument("--max_batches_per_client", type=int, default=None)
    p.add_argument("--augment", action="store_true",
                   help="crop+flip(+cutout) augmentation in the train step")
    # real multi-process deployment (the reference's run_fedavg_grpc.sh /
    # run_fedavg_trpc.sh launch pattern): one process per rank
    p.add_argument("--deploy", choices=("server", "client"), default=None,
                   help="run ONE deployment rank over sockets instead of "
                        "the in-process simulation")
    p.add_argument("--rank", type=int, default=0)
    p.add_argument("--world_size", type=int, default=3,
                   help="server + clients (deployment mode)")
    p.add_argument("--comm_backend", type=str, default="TCP",
                   choices=("GRPC", "TCP", "NATIVE_TCP"))
    p.add_argument("--base_port", type=int, default=52000)
    p.add_argument("--wire_transport", type=str, default="none",
                   choices=("none", "bf16", "int8"),
                   help="deployment mode: lossy wire dtype for the "
                        "server->client model sync (wire codec v2, "
                        "comm/message.py) — bf16 halves / int8 quarters "
                        "the downlink model bytes; client uploads feed "
                        "the aggregation and ALWAYS ride exact.  "
                        "'none' (default) keeps every payload exact; "
                        "FEDML_WIRE_V1=1 force-disables v2 framing "
                        "process-wide (the escape hatch)")
    p.add_argument("--wire_compress", action="store_true",
                   help="deployment mode: zlib-compress the wire "
                        "frame's header+small-array section (lossless; "
                        "wire codec v2)")
    # chaos + reliability (ISSUE 8, comm/chaos.py + comm/reliability.py)
    p.add_argument("--reliable", action="store_true",
                   help="deployment mode: envelope frames with the "
                        "reliability layer (per-peer seq + CRC32, "
                        "ack/nack, backoff resend, duplicate "
                        "suppression) — exactly-once ingestion over "
                        "lossy links; FEDML_RELIABLE=0 force-disables "
                        "it process-wide (the escape hatch)")
    p.add_argument("--chaos_drop", type=float, default=0.0,
                   help="deployment mode: P(inbound frame dropped) — "
                        "seeded wire-level fault injection "
                        "(comm/chaos.py); pair with --reliable to "
                        "exercise the resend path")
    p.add_argument("--chaos_dup", type=float, default=0.0,
                   help="deployment mode: P(inbound frame duplicated)")
    p.add_argument("--chaos_corrupt", type=float, default=0.0,
                   help="deployment mode: P(inbound frame byte-flipped "
                        "— quarantined + nacked under --reliable)")
    p.add_argument("--chaos_delay", type=float, default=0.0,
                   help="deployment mode: P(inbound frame delayed "
                        "~exp(10ms))")
    p.add_argument("--chaos_seed", type=int, default=0,
                   help="fault-injection seed: same seed = same "
                        "per-stream injected-event trace")
    # overload-safe reactor transport (ISSUE 11, comm/reactor.py)
    p.add_argument("--tcp_transport", choices=("reactor", "threads"),
                   default="reactor",
                   help="deployment mode, TCP/NATIVE_TCP: 'reactor' "
                        "(default) = the selector event-loop transport "
                        "— bounded per-connection buffers, slow-peer "
                        "stall eviction, per-connection rate ceilings, "
                        "load shedding and graceful drain (holds 10k "
                        "live connections); 'threads' = the legacy "
                        "one-recv-thread-per-connection path "
                        "(FEDML_TCP_REACTOR=0 forces it process-wide)")
    p.add_argument("--conn_reactors", type=int, default=1,
                   help="reactor transport: event loops (≈ one per "
                        "core on a busy server)")
    p.add_argument("--conn_max", type=int, default=16384,
                   help="reactor transport: inbound-connection "
                        "admission ceiling — accepts past it are shed "
                        "(counted in comm_uplinks_shed_total)")
    p.add_argument("--conn_stall_timeout_s", type=float, default=30.0,
                   help="reactor transport: slowloris eviction — a "
                        "connection mid-frame with no progress for "
                        "this long is closed (comm_connections_"
                        "evicted_total{reason=stall})")
    p.add_argument("--conn_max_frames_per_sec", type=float, default=None,
                   help="reactor transport: per-connection frame-rate "
                        "ceiling (violating windows throttle, repeat "
                        "offenders evict with reason=rate); unset = "
                        "no ceiling")
    p.add_argument("--conn_max_bytes_per_sec", type=float, default=None,
                   help="reactor transport: per-connection byte-rate "
                        "ceiling (same throttle-then-evict ladder)")
    # async federation (fedml_tpu/async_): buffered staleness-aware
    # commits over a seeded client-lifecycle simulator — FedBuff-style
    # semi-async (commit on K buffered results or a deadline), FedAsync
    # as the K=1 degenerate config.  PERF.md "Async federation".
    p.add_argument("--async", dest="async_mode", action="store_true",
                   help="run the buffered asynchronous scheduler "
                        "(fedml_tpu/async_) instead of synchronous "
                        "rounds: commits fire on --async_buffer_k "
                        "buffered results or --async_round_deadline_s, "
                        "client results are staleness-discounted "
                        "(--async_staleness), and client churn comes "
                        "from the seeded lifecycle simulator "
                        "(--async_latency/--async_dropout_prob).  "
                        "comm_round counts COMMITS.  FedAvg/FedProx "
                        "only; incompatible with --mesh")
    p.add_argument("--async_buffer_k", type=int, default=None,
                   help="aggregation-buffer capacity K (default "
                        "client_num_per_round; 1 = pure FedAsync)")
    p.add_argument("--async_concurrency", type=int, default=None,
                   help="clients in flight at once (default "
                        "max(buffer_k, client_num_per_round))")
    p.add_argument("--async_round_deadline_s", type=float, default=None,
                   help="commit a part-full buffer after this many "
                        "(simulated) seconds since the last commit — "
                        "the crash/straggler escape hatch")
    p.add_argument("--async_staleness", type=str, default="constant",
                   choices=("constant", "polynomial", "hinge"),
                   help="staleness-discount family (FedAsync §5)")
    p.add_argument("--async_staleness_a", type=float, default=0.5,
                   help="polynomial exponent / hinge slope")
    p.add_argument("--async_staleness_b", type=float, default=4.0,
                   help="hinge knee (staleness where discounting starts)")
    p.add_argument("--async_mix", type=float, default=1.0,
                   help="server mixing rate alpha: v <- (1-a)v + "
                        "a*discounted_buffer_mean (1.0 installs the "
                        "mean — the FedAvg-degenerate setting)")
    p.add_argument("--async_seed", type=int, default=None,
                   help="lifecycle-simulator seed (default --seed); two "
                        "runs with equal seeds produce identical event "
                        "traces")
    p.add_argument("--async_latency", type=str, default="none",
                   choices=("none", "lognormal", "pareto"),
                   help="per-dispatch client latency family")
    p.add_argument("--async_latency_scale", type=float, default=1.0,
                   help="latency scale in simulated seconds")
    p.add_argument("--async_latency_sigma", type=float, default=0.5,
                   help="lognormal sigma / per-client heterogeneity "
                        "uses --async_heterogeneity")
    p.add_argument("--async_pareto_alpha", type=float, default=2.0,
                   help="pareto tail index for --async_latency pareto "
                        "(>1 for a finite mean; lower = heavier tail)")
    p.add_argument("--async_heterogeneity", type=float, default=0.0,
                   help="per-client persistent speed-factor spread "
                        "(lognormal sigma; 0 = homogeneous fleet)")
    p.add_argument("--async_dropout_prob", type=float, default=0.0,
                   help="P(crash mid-round) per dispatch")
    p.add_argument("--async_rejoin_prob", type=float, default=1.0,
                   help="P(a crashed client ever rejoins)")
    p.add_argument("--async_rejoin_delay_s", type=float, default=5.0,
                   help="mean rejoin delay (exponential, simulated s)")
    # million-client serving spine (ISSUE 10, fedml_tpu/scale/):
    # trace-driven arrival processes shape the async lifecycle's
    # turnaround with a load curve — at the trough of the diurnal cycle
    # (or outside a flash crowd) the fleet answers slower, so staleness
    # and deadline behavior see production load shapes.  The standalone
    # heavy-traffic bench is `python bench.py --mode serve`.
    p.add_argument("--arrival_process", type=str, default="none",
                   choices=("none", "constant", "diurnal", "flash",
                            "trace"),
                   help="with --async: load-curve family modulating "
                        "dispatch turnaround (fedml_tpu/scale/"
                        "arrivals.py) — diurnal sinusoid, flash-crowd "
                        "burst, or a replayed timestamp trace")
    p.add_argument("--arrival_rate", type=float, default=100.0,
                   help="base arrivals/sec of the load curve "
                        "(virtual seconds)")
    p.add_argument("--arrival_period_s", type=float, default=86400.0,
                   help="diurnal period (simulated seconds)")
    p.add_argument("--arrival_amplitude", type=float, default=0.8,
                   help="diurnal swing in [0, 1)")
    p.add_argument("--arrival_flash_at", type=float, default=300.0,
                   help="flash-crowd onset (simulated seconds)")
    p.add_argument("--arrival_flash_duration", type=float, default=60.0,
                   help="flash-crowd duration (simulated seconds)")
    p.add_argument("--arrival_flash_boost", type=float, default=10.0,
                   help="flash-crowd rate multiplier")
    p.add_argument("--arrival_trace", type=str, default=None,
                   help="replayed-trace file: one arrival timestamp "
                        "per line (--arrival_process trace)")
    # adversarial robustness (ISSUE 9, fedml_tpu/async_/adversary.py +
    # defense.py): a seeded byzantine cohort rides the lifecycle, and
    # the server's admission pipeline + bucketed robust streaming
    # commit defend the async aggregation.  PERF.md "Adversarial
    # robustness".
    p.add_argument("--attack_mode", type=str, default="none",
                   choices=("none", "signflip", "boost", "gaussian",
                            "labelflip", "backdoor", "mixed"),
                   help="with --async: seeded byzantine-client attack — "
                        "signflip reverses update directions, boost is "
                        "scaled model replacement, gaussian adds noise, "
                        "labelflip/backdoor poison the attackers' "
                        "shards (data/poison.py), mixed = boost + "
                        "labelflip (the acceptance arm)")
    p.add_argument("--attack_frac", type=float, default=0.2,
                   help="byzantine fraction of the fleet")
    p.add_argument("--attack_boost", type=float, default=10.0,
                   help="model-replacement scale (boost/mixed)")
    p.add_argument("--attack_noise_std", type=float, default=1.0,
                   help="gaussian-attack noise std")
    p.add_argument("--attack_target_label", type=int, default=0,
                   help="label-flip/backdoor target class")
    p.add_argument("--attack_collude", action="store_true",
                   help="colluding cohort: every byzantine client at a "
                        "version sends the identical crafted row")
    p.add_argument("--attack_stale", action="store_true",
                   help="stale-attack: byzantine uplinks are timed to "
                        "land at high staleness (--attack_stale_lag)")
    p.add_argument("--attack_stale_lag", type=float, default=3.0,
                   help="extra byzantine dispatch latency (sim seconds)")
    p.add_argument("--attack_seed", type=int, default=0,
                   help="adversary seed: same seed = same byzantine set "
                        "and corruption streams")
    p.add_argument("--defense_norm_bound", type=float, default=None,
                   help="admission clip: client update deltas are "
                        "norm-clipped to this bound at the insert path "
                        "(the ONE clip definition norm_diff_clip/the "
                        "pallas clip-agg share)")
    p.add_argument("--defense_screen", action="store_true",
                   help="arm the z-score + cosine anomaly screen "
                        "against a running reference of accepted "
                        "updates (quarantines instead of folding)")
    p.add_argument("--defense_z_max", type=float, default=4.0,
                   help="robust z threshold on the update-delta norm")
    p.add_argument("--defense_cos_min", type=float, default=-1.0,
                   help="cosine floor vs the accepted-direction "
                        "reference (-1 disables; catches sign-flip)")
    p.add_argument("--defense_warmup", type=int, default=8,
                   help="accepted updates before the screen arms")
    p.add_argument("--defense_buckets", type=int, default=1,
                   help="bucketed robust streaming aggregation: B "
                        "seeded bucket accumulators, committed via a "
                        "robust combine ACROSS bucket means (O(B*P) "
                        "memory; 1 + trim 0 = the exact PR-6 streaming "
                        "commit)")
    p.add_argument("--defense_combine", type=str, default="trimmed_mean",
                   choices=("mean", "trimmed_mean", "median"),
                   help="combine across bucket means")
    p.add_argument("--defense_trim_k", type=int, default=0,
                   help="buckets trimmed per side (trimmed_mean)")
    p.add_argument("--defense_dp_clip", type=float, default=None,
                   help="DP-FedAvg per-client clip S (uses the shared "
                        "clip definition; required by --defense_dp_noise)")
    p.add_argument("--defense_dp_noise", type=float, default=0.0,
                   help="DP-FedAvg noise multiplier z: Gaussian noise "
                        "sigma z*S/m added inside the jitted commit")
    p.add_argument("--defense_seed", type=int, default=0,
                   help="bucket-assignment seed")
    # secure aggregation (ISSUE 20, fedml_tpu/secure/): pairwise-mask
    # uplinks over the live messaging FSMs — the server only ever sees
    # masked field words; masks cancel exactly in the cohort sum and
    # dropout recovery reconstructs a dead client's masks from
    # escrowed key shares.  PERF.md "Secure aggregation".
    p.add_argument("--secure_agg", action="store_true",
                   help="pairwise-mask secure aggregation on the "
                        "messaging paths (sync FSM, or the live async "
                        "server with --async); combine with "
                        "--defense_dp_clip/--defense_dp_noise for the "
                        "end-to-end private mode (client-side clip+"
                        "noise BEFORE masking)")
    p.add_argument("--secure_threshold", type=int, default=0,
                   help="minimum surviving clients to unmask a round "
                        "(also the key-share reconstruction threshold); "
                        "0 = cohort majority")
    p.add_argument("--secure_scale", type=int, default=2 ** 16,
                   help="fixed-point quantization scale (field words = "
                        "round(x*scale) mod p); the usable range is "
                        "±(p-1)/(2*scale)")
    p.add_argument("--secure_seed", type=int, default=0,
                   help="keyring seed: every rank derives the same DH "
                        "key material + escrowed shares from it "
                        "(simulation-grade trust model — see "
                        "fedml_tpu/secure/secagg.py)")
    # TPU-native replacements for mpirun/hostfile/gpu_mapping
    p.add_argument("--streaming", action="store_true",
                   help="host-resident client stack; upload only each "
                        "round's sampled cohort (cross-device scale)")
    p.add_argument("--cohort_chunk", type=int, default=None,
                   help="max client model replicas live per shard "
                        "(default 8; tools/profile_bench.py)")
    p.add_argument("--batch_unroll", type=int, default=None,
                   help="unroll factor of the local batch scan (perf "
                        "knob; 8 measured -2.5%% on the v5e bench round "
                        "at chunk 2, PERF.md)")
    p.add_argument("--local_dtype", type=str, default=None,
                   choices=("float32", "bfloat16"),
                   help="dtype of the LOCAL training masters (mesh "
                        "engines): bfloat16 runs the per-client step "
                        "chain bf16 end-to-end, aggregation/globals stay "
                        "f32 (the measured v5e bench recipe, PERF.md)")
    p.add_argument("--stack_dtype", type=str, default=None,
                   choices=("float32", "bfloat16", "uint8"),
                   help="device storage dtype of the client stack's "
                        "INPUTS (mesh engines): bfloat16 halves the "
                        "cohort's HBM footprint and upload bytes — the "
                        "lever for >512 bench-shaped clients per chip "
                        "(measured knee 1.32x -> 1.06x at 1024; "
                        "PERF.md); uint8 stores image cohorts in their "
                        "native 8-bit form (4x fewer bytes than f32, 2x "
                        "fewer than bf16) with the per-dataset dequant "
                        "fused into the jitted round program (PERF.md "
                        "'Transfer compression').  Both are accuracy "
                        "tradeoffs the user opts into; omit the flag "
                        "for the exact f32 path")
    p.add_argument("--stream_block", type=int, default=None,
                   help="block-streamed rounds (FedAvg-family mesh "
                        "engines): upload the cohort in blocks of this "
                        "many clients WITHIN each round (double-"
                        "buffered), accumulating the linear sums on "
                        "device — device data memory becomes O(block), "
                        "so the cohort axis is bounded by host RAM, not "
                        "HBM; the cohort's bytes cross host->device "
                        "every round (SCALING.md).  Implies --streaming")
    p.add_argument("--no_prefetch", action="store_true",
                   help="disable the background host->device prefetch "
                        "pipeline on the streaming/block-stream mesh "
                        "paths (strictly synchronous gather->upload->"
                        "compute — the escape hatch for bitwise "
                        "comparison against the pipelined rounds; "
                        "PERF.md 'Prefetch pipeline')")
    p.add_argument("--no_flat_stack", action="store_true",
                   help="disable flat image-cohort storage (mesh "
                        "engines store image inputs [C,B,bs,h*w*c] and "
                        "restore per chunk in-scan; avoids XLA's padded "
                        "tiled relayout of small minor dims — measured "
                        "on v5e: removes the 1024-cohort knee outright "
                        "and unblocks 2048-client bf16 cohorts that "
                        "otherwise OOM in compile, SCALING.md)")
    p.add_argument("--mesh", action="store_true",
                   help="shard the cohort over all visible devices")
    p.add_argument("--mesh_batch", type=int, default=None,
                   help="with --mesh: fold this many devices into a "
                        "'batch' axis (clients x batch mesh) — each "
                        "client's per-step batch splits over it with a "
                        "per-step grad psum (per-client sample "
                        "parallelism for chips > cohort; must divide "
                        "both the device count and the batch size)")
    p.add_argument("--multihost", action="store_true",
                   help="join the multi-host runtime first "
                        "(jax.distributed.initialize; replaces mpirun)")
    p.add_argument("--multihost_procs", type=int, default=None,
                   help="self-spawn this many processes as a multihost "
                        "cluster on this box (the dev harness; equals "
                        "`tools/launch_multihost.py --procs N -- <this "
                        "command>`): each process trains its client-id "
                        "range's blocks on a LOCAL mesh and the P-sized "
                        "carry allreduces across processes "
                        "(two-level aggregation, ISSUE 13)")
    p.add_argument("--agg_blocks", type=int, default=None,
                   help="multihost: block count of the two-level "
                        "reduction tree (default: the process count). "
                        "The tree is a function of the BLOCK partition, "
                        "not the topology — pin it across runs to keep "
                        "commits bitwise comparable at different "
                        "process counts")
    p.add_argument("--elastic", action="store_true",
                   help="multihost: elastic membership (ISSUE 14) — a "
                        "dead or hung rank triggers an epoch-numbered "
                        "view change and the survivors re-adopt its "
                        "blocks mid-round (bitwise-identical commits by "
                        "the block-partition contract); a restarted "
                        "rank (FEDML_MH_REJOIN=1, set by the launcher's "
                        "--respawn) rejoins via config-digest handshake "
                        "+ a rank-0 model snapshot.  Default is "
                        "FAIL-FAST: one dead rank kills the cluster, "
                        "named")
    p.add_argument("--hb_timeout_s", type=float, default=2.0,
                   help="with --elastic: heartbeat silence after which "
                        "a rank is suspected hung (the SIGSTOP "
                        "detector; detection runs between allgathers, "
                        "not only inside one)")
    p.add_argument("--cluster_serve", action="store_true",
                   help="run the fused serving cluster (ISSUE 18) "
                        "instead of training: this process binds a "
                        "reactor endpoint on --cluster_port (+rank) "
                        "and serves live-socket uplinks into its "
                        "registry-shard lanes, folding lane partials "
                        "cross-host at each commit barrier.  Composes "
                        "with --multihost_procs N --elastic (one host "
                        "per process); drive load with `python -m "
                        "fedml_tpu.comm.connswarm CFG.json` pointed at "
                        "the endpoints")
    p.add_argument("--cluster_port", type=int, default=54300,
                   help="cluster serving: this host's uplink endpoint "
                        "port is cluster_port + rank")
    p.add_argument("--cluster_population", type=int, default=4096,
                   help="cluster serving: total client-id space, "
                        "range-partitioned across hosts")
    p.add_argument("--cluster_commits", type=int, default=8,
                   help="cluster serving: commit windows to serve")
    p.add_argument("--cluster_buffer_k", type=int, default=16,
                   help="cluster serving: uplinks per lane per commit "
                        "window")
    p.add_argument("--cluster_row_dim", type=int, default=256,
                   help="cluster serving: flat model row dimension")
    p.add_argument("--cluster_connections", type=int, default=64,
                   help="cluster serving: reactor connection budget "
                        "per host")
    p.add_argument("--cluster_ingest_pool", type=int, default=2,
                   help="cluster serving: decode-pool workers per host")
    p.add_argument("--cluster_window_s", type=float, default=10.0,
                   help="cluster serving: commit-window deadline — a "
                        "lane with no socket traffic contributes what "
                        "it has when this passes instead of wedging "
                        "the cluster barrier")
    p.add_argument("--carry_codec", type=str, default="f32",
                   choices=("f32", "int8", "int8_ef", "topk", "topk_ef"),
                   help="multihost: wire codec for the inter-host carry "
                        "(ISSUE 16/19). f32 (default) is the bitwise "
                        "escape hatch — bytes identical to the PR-13/14 "
                        "tier; int8 is per-chunk affine fixed-point "
                        "(~4x fewer bytes); int8_ef adds per-block "
                        "error-feedback residuals so the SUM over "
                        "rounds converges to the true sum; topk ships "
                        "only the k=dim/16 largest-|v| entries (~7.5x "
                        "fewer bytes, LOSSY); topk_ef adds the int8_ef "
                        "residual discipline to top-k so the summed "
                        "carry drift stays a single round's truncation")
    p.add_argument("--overlap_exchange", action="store_true",
                   help="multihost: ship each block's encoded carry as "
                        "soon as it is computed so the DCN exchange "
                        "overlaps the remaining blocks' compute "
                        "(AsyncValue send chain). Commits are "
                        "bitwise-identical to the serial exchange at "
                        "the same codec — frames concatenate in the "
                        "same global block order")
    p.add_argument("--group_num", type=int, default=2,
                   help="hierarchical: silo count")
    p.add_argument("--group_comm_round", type=int, default=2)
    p.add_argument("--defense", type=str, default="norm_clip",
                   choices=("norm_clip", "krum", "multi_krum", "median",
                            "trimmed_mean"))
    p.add_argument("--n_byzantine", type=int, default=0,
                   help="assumed Byzantine count (krum neighbor count, "
                        "trimmed-mean trim width)")
    p.add_argument("--multi_krum_m", type=int, default=None,
                   help="multi-krum selection size (default K - f - 2)")
    p.add_argument("--topology", type=str, default="ring",
                   choices=("ring", "ws", "asymmetric"),
                   help="decentralized graph: ring = symmetric ring "
                        "(add Watts-Strogatz extra links by raising "
                        "--neighbor_num above 2); ws = deprecated alias "
                        "for ring; asymmetric = directed with randomly "
                        "deleted links (reference "
                        "asymmetric_topology_manager.py)")
    p.add_argument("--neighbor_num", type=int, default=2,
                   help="ring topology: neighbors per worker; >2 adds "
                        "Watts-Strogatz style extra links "
                        "(symmetric_topology_manager.py:21-52)")
    p.add_argument("--unrolled", action="store_true",
                   help="fednas: 2nd-order architect")
    p.add_argument("--gdas", action="store_true",
                   help="fednas: GDAS single-path gumbel sampling")
    p.add_argument("--nas_channels", type=int, default=16)
    p.add_argument("--nas_layers", type=int, default=8)
    p.add_argument("--nas_steps", type=int, default=4)
    p.add_argument("--nas_multiplier", type=int, default=4)
    # observability / checkpointing (SURVEY.md §5 gaps the build fills)
    p.add_argument("--obs_dir", type=str, default=None,
                   help="enable the unified observability layer "
                        "(fedml_tpu/obs): span tracer (Chrome-trace + "
                        "JSONL exports), metrics registry (Prometheus "
                        "text + JSON snapshots — comm bytes per "
                        "backend, retries, round/upload walls, jit "
                        "compiles, HBM gauges), and a flight recorder "
                        "that dumps recent events + thread stacks on "
                        "SIGUSR1, engine errors, or round-deadline "
                        "overruns.  Artifacts land in this directory; "
                        "defaults off (zero overhead).  PERF.md "
                        "'Observability' has the triage recipes")
    p.add_argument("--round_deadline_s", type=float, default=None,
                   help="with --obs_dir: flight-recorder dump when one "
                        "round exceeds this wall-clock (the hang/"
                        "straggler tripwire; the run is NOT killed)")
    p.add_argument("--obs_http_port", type=int, default=None,
                   help="serve the loopback introspection endpoint on "
                        "this port (0 = ephemeral): /metrics Prometheus "
                        "text, /rollup JSON, /flight dump trigger — "
                        "long async/torture runs become inspectable "
                        "without SIGUSR1 shell access.  Works without "
                        "--obs_dir (metrics are always on); "
                        "FEDML_OBS_HTTP_PORT is the env twin")
    p.add_argument("--slo", action="store_true",
                   help="run the default serving-spine SLO pack "
                        "(fedml_tpu/obs/slo.py) as a periodic "
                        "background evaluator: committed-updates/sec "
                        "floor, admission/loop-lag p95 ceilings, zero "
                        "quarantines/evictions/sheds/recv-deaths.  A "
                        "breach increments slo_breaches_total{slo}, "
                        "fires a throttled flight dump (with "
                        "--obs_dir), and surfaces on the httpd /slo "
                        "endpoint and obs.rollup().  Works without "
                        "--obs_dir (metrics are always on)")
    p.add_argument("--slo_period_s", type=float, default=5.0,
                   help="with --slo: seconds between SLO evaluation "
                        "windows (each window judges the metric DELTAS "
                        "since the previous one)")
    p.add_argument("--run_dir", type=str, default="./runs")
    p.add_argument("--run_name", type=str, default=None)
    p.add_argument("--ckpt_dir", type=str, default=None)
    p.add_argument("--ckpt_every", type=int, default=0)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--profile_dir", type=str, default=None)
    return p


def _load(cfg: FedConfig, store_uint8: bool = False):
    from fedml_tpu.data import load_data
    return load_data(cfg.dataset, data_dir=cfg.data_dir,
                     client_num_in_total=cfg.client_num_in_total,
                     batch_size=cfg.batch_size,
                     partition_method=cfg.partition_method,
                     partition_alpha=cfg.partition_alpha,
                     max_batches_per_client=cfg.max_batches_per_client,
                     seed=cfg.seed, synthetic_scale=cfg.synthetic_scale,
                     store_uint8=store_uint8)


# engines that consume the mesh cohort path's knobs (--stack_dtype,
# --stream_block, ...) — the uint8 loader storage is gated on these so a
# non-mesh engine can never receive a quantized stack it cannot dequant
_STACK_DTYPE_ALGOS = ("fedavg", "fedopt", "fedprox", "fednova",
                      "fedavg_robust")


def _trainer(cfg: FedConfig, data, model_name: Optional[str] = None,
             force_time_axis: bool = False,
             default_train_ignore: Optional[int] = None):
    """Build the ClientTrainer for a run.  `model_name` overrides
    cfg.model (fedseg forces segnet), `force_time_axis` broadcasts the
    per-sample mask over trailing label axes (sequence time OR seg H,W),
    `default_train_ignore` is the void label applied when the user gave
    no --train_ignore_id (VOC 255)."""
    import jax.numpy as jnp
    from fedml_tpu.core.trainer import ClientTrainer, make_lr_schedule
    from fedml_tpu.models import create_model
    loss = "bce" if cfg.dataset == "stackoverflow_lr" else "ce"
    if cfg.loss_type:
        loss = cfg.loss_type
    # LEAF shakespeare is a scalar next-char task (model predicts the last
    # position only, reference rnn.py:30-33); the TFF variants are per-position
    has_time = force_time_axis or cfg.dataset in ("fed_shakespeare",
                                                  "stackoverflow_nwp")
    kw = ({"last_only": True}
          if cfg.model in ("rnn", "transformer")
          and cfg.dataset == "shakespeare" else {})
    model = create_model(model_name or cfg.model, data.class_num, **kw)
    dtype = jnp.bfloat16 if cfg.train_dtype == "bfloat16" else jnp.float32
    aug = None
    if cfg.augment:
        if default_train_ignore is not None:
            # segmentation: augment transforms x only, which would
            # misalign the spatial labels
            raise SystemExit("--augment is not supported for fedseg")
        if data.client_shards["x"].ndim != 6:   # [C, B, bs, H, W, ch] images
            raise SystemExit("--augment requires an image dataset")
        from fedml_tpu.data.augment import make_augment_fn
        cut = 16 if cfg.dataset in ("cifar10", "cifar100", "cinic10",
                                    "fed_cifar100") else None
        aug = make_augment_fn(crop_padding=4, flip=True, cutout_length=cut)
    # TFF metric convention: NWP/snippet accuracy ignores <pad> (= id 0 in
    # both text.py vocab layouts)
    ignore = 0 if cfg.dataset in ("fed_shakespeare",
                                  "stackoverflow_nwp") else None
    lr = cfg.lr
    if cfg.lr_scheduler:
        # schedule spans one local round: E epochs x B padded batches
        # (the reference recreates its scheduler per train() call too).
        # Padding steps advance the schedule count (trainer.train_step /
        # tree_merge_counts) so ragged clients traverse the same full
        # decay; the reference instead decays over each client's real
        # batch count — deviation documented in PARITY.md
        B = data.client_shards["x"].shape[1]
        lr = make_lr_schedule(cfg.lr_scheduler, cfg.lr,
                              total_steps=cfg.epochs * B,
                              iters_per_epoch=B,
                              lr_step_epochs=cfg.lr_step,
                              warmup_steps=cfg.warmup_epochs * B)
    train_ignore = (default_train_ignore if cfg.train_ignore_id is None
                    else cfg.train_ignore_id)
    return ClientTrainer(model, loss=loss, optimizer=cfg.client_optimizer,
                         lr=lr, momentum=cfg.momentum,
                         weight_decay=cfg.wd, prox_mu=cfg.prox_mu,
                         has_time_axis=has_time, train_dtype=dtype,
                         augment=aug, eval_ignore_id=ignore,
                         train_ignore_id=train_ignore,
                         batch_unroll=cfg.batch_unroll)


def _local_dtype(args):
    """--local_dtype flag -> jnp dtype (None = f32 locals)."""
    if args.local_dtype == "bfloat16":
        import jax.numpy as jnp
        return jnp.bfloat16
    return None


def _stack_dtype(args):
    """--stack_dtype flag -> jnp dtype (None/float32 = store inputs as
    loaded).  Unknown values raise — argparse choices guard the CLI, but
    programmatic callers (sweep drivers building Namespace objects by
    hand) must not have a typo silently mean 'f32 stack'."""
    v = getattr(args, "stack_dtype", None)
    if v in (None, "float32"):
        return None
    import jax.numpy as jnp
    if v == "bfloat16":
        return jnp.bfloat16
    if v == "uint8":
        return jnp.uint8
    raise SystemExit(
        f"--stack_dtype {v!r} is not supported (choose float32, "
        "bfloat16, or uint8)")


def _attack_config(args):
    """--attack_* flags -> AttackConfig (None when no attack)."""
    if getattr(args, "attack_mode", "none") == "none":
        return None
    from fedml_tpu.async_ import AttackConfig
    return AttackConfig(
        mode=args.attack_mode, frac=args.attack_frac,
        boost=args.attack_boost, noise_std=args.attack_noise_std,
        target_label=args.attack_target_label,
        collude=args.attack_collude, stale=args.attack_stale,
        stale_lag=args.attack_stale_lag, seed=args.attack_seed)


def _defense_config(args):
    """--defense_* flags -> DefenseConfig (None when every stage is at
    its defaults — the undefended PR-6 fast path stays untouched)."""
    if not (args.defense_norm_bound is not None or args.defense_screen
            or args.defense_buckets > 1 or args.defense_trim_k > 0
            or args.defense_combine != "trimmed_mean"
            or args.defense_dp_noise > 0.0
            or args.defense_dp_clip is not None):
        return None
    from fedml_tpu.async_ import DefenseConfig
    return DefenseConfig(
        norm_bound=args.defense_norm_bound, screen=args.defense_screen,
        z_max=args.defense_z_max, cos_min=args.defense_cos_min,
        screen_warmup=args.defense_warmup, buckets=args.defense_buckets,
        combine=args.defense_combine, trim_k=args.defense_trim_k,
        dp_clip=args.defense_dp_clip, dp_noise=args.defense_dp_noise,
        seed=args.defense_seed)


def _secure_config(args):
    """--secure_agg flags -> SecAggConfig (None when secure mode is off).

    The private mode composes through the DEFENSE DP flags on purpose:
    --defense_dp_clip/--defense_dp_noise become CLIENT-side clip+noise
    applied before masking (the server never sees a per-client row, so
    server-side DP is impossible under masks)."""
    if not getattr(args, "secure_agg", False):
        return None
    from fedml_tpu.secure import SecAggConfig
    return SecAggConfig(
        threshold=args.secure_threshold,
        scale=args.secure_scale,
        seed=args.secure_seed,
        dp_clip=args.defense_dp_clip,
        dp_noise=args.defense_dp_noise)


def _arrival_config(args):
    """--arrival_* flags -> ArrivalConfig (None when mode is 'none')."""
    if getattr(args, "arrival_process", "none") == "none":
        return None
    from fedml_tpu.scale import ArrivalConfig
    return ArrivalConfig(
        mode=args.arrival_process, rate=args.arrival_rate,
        period_s=args.arrival_period_s, amplitude=args.arrival_amplitude,
        flash_at_s=args.arrival_flash_at,
        flash_duration_s=args.arrival_flash_duration,
        flash_boost=args.arrival_flash_boost,
        trace_path=args.arrival_trace, seed=args.seed)


def _build_async_engine(args, cfg: FedConfig, data):
    """--async: the buffered staleness-aware scheduler over the seeded
    lifecycle simulator (fedml_tpu/async_).  FedAvg/FedProx only — the
    commit program is the FedAvg mixing rule; other aggregation families
    have no async formulation here yet."""
    from fedml_tpu.async_ import AsyncFedAvgEngine, LifecycleConfig
    if args.algorithm not in ("fedavg", "fedprox"):
        raise SystemExit(f"--async supports fedavg/fedprox, not "
                         f"{args.algorithm!r}")
    if args.mesh:
        raise SystemExit("--async runs the vmap dispatch-wave engine; "
                         "--mesh is not supported (the async cohort is "
                         "bounded by --async_concurrency, not HBM)")
    lc = LifecycleConfig(
        latency=args.async_latency,
        latency_scale=args.async_latency_scale,
        latency_sigma=args.async_latency_sigma,
        pareto_alpha=args.async_pareto_alpha,
        heterogeneity=args.async_heterogeneity,
        dropout_prob=args.async_dropout_prob,
        rejoin_prob=args.async_rejoin_prob,
        rejoin_delay_s=args.async_rejoin_delay_s,
        seed=args.async_seed if args.async_seed is not None else cfg.seed)
    return AsyncFedAvgEngine(
        _trainer(cfg, data), data, cfg,
        buffer_k=args.async_buffer_k,
        concurrency=args.async_concurrency,
        staleness=args.async_staleness,
        staleness_a=args.async_staleness_a,
        staleness_b=args.async_staleness_b,
        mix=args.async_mix,
        round_deadline_s=args.async_round_deadline_s,
        lifecycle_cfg=lc,
        attack=_attack_config(args),
        defense=_defense_config(args),
        arrivals=_arrival_config(args))


def build_engine(args, cfg: FedConfig, data):
    """Algorithm dispatch (the reference's fed_launch algorithm select)."""
    algo = args.algorithm
    if getattr(args, "async_mode", False):
        return _build_async_engine(args, cfg, data)
    if (getattr(args, "attack_mode", "none") != "none"
            or _defense_config(args) is not None):
        logging.getLogger(__name__).warning(
            "--attack_*/--defense_* reach only the --async engine "
            "(the sync robust path is --algorithm fedavg_robust "
            "--defense ...); ignored by %s", algo)
    if getattr(args, "arrival_process", "none") != "none":
        logging.getLogger(__name__).warning(
            "--arrival_* reaches only the --async engine (sync rounds "
            "have no virtual clock to shape); ignored by %s", algo)
    mesh = None
    if args.mesh_batch is not None and args.mesh_batch < 1:
        raise SystemExit(f"--mesh_batch must be >= 1, got {args.mesh_batch}")
    if (args.streaming or args.cohort_chunk or args.local_dtype
            or args.stack_dtype or args.mesh_batch) and not args.mesh:
        raise SystemExit("--streaming/--cohort_chunk/--local_dtype/"
                         "--stack_dtype/"
                         "--mesh_batch require --mesh (they configure the "
                         "mesh engine's cohort path)")
    if args.mesh:
        from fedml_tpu.parallel.mesh import make_mesh, make_mesh_batch
        if args.mesh_batch:
            if algo not in ("fedavg", "fedopt", "fedprox", "fednova",
                            "fedavg_robust", "fedseg"):
                raise SystemExit(f"--mesh_batch supports the FedAvg-family "
                                 f"mesh engines, not {algo!r}")
            import jax as _jax
            n_dev = len(_jax.devices())
            if n_dev % args.mesh_batch:
                raise SystemExit(f"--mesh_batch {args.mesh_batch} must "
                                 f"divide the device count ({n_dev})")
            if cfg.batch_size % args.mesh_batch:
                raise SystemExit(f"--mesh_batch {args.mesh_batch} must "
                                 f"divide the batch size "
                                 f"({cfg.batch_size})")
            mesh = make_mesh_batch(n_dev // args.mesh_batch,
                                   args.mesh_batch)
        else:
            from fedml_tpu.parallel.multihost import (MultihostContext,
                                                      make_local_mesh)
            # under a launched multihost cluster the engine's mesh is
            # the LOCAL (intra-host) tier — cross-host traffic is the
            # runner's carry allreduce, never an in-program collective
            mesh = (make_local_mesh()
                    if MultihostContext.from_env() is not None
                    else make_mesh())

    if mesh is not None and algo not in ("fedavg", "fedopt", "fedprox",
                                         "fednova", "fedavg_robust",
                                         "hierarchical", "decentralized",
                                         "fedseg", "fedgan", "fedgkt",
                                         "centralized", "fednas"):
        logging.getLogger(__name__).warning(
            "--mesh has no %s engine; running the single-device path", algo)

    if args.stack_dtype and algo not in _STACK_DTYPE_ALGOS:
        logging.getLogger(__name__).warning(
            "--stack_dtype reaches only the FedAvg-family mesh engines; "
            "ignored by %s", algo)
    if args.stream_block is not None and (
            mesh is None or algo not in _STACK_DTYPE_ALGOS):
        logging.getLogger(__name__).warning(
            "--stream_block reaches only the FedAvg-family MESH engines "
            "(needs --mesh); ignored by %s%s", algo,
            "" if mesh is not None else " without --mesh")
    if args.batch_unroll is not None and algo in ("fednas", "fedgan",
                                                  "fedgkt", "splitnn",
                                                  "vfl"):
        # same courtesy the other engine knobs get (see the per-branch
        # --streaming/--cohort_chunk warnings): these engines never build
        # a ClientTrainer batch scan, so the knob cannot reach one
        logging.getLogger(__name__).warning(
            "--batch_unroll is ignored by %s (no ClientTrainer batch "
            "scan)", algo)
    if algo in ("fedavg", "fedopt", "fedprox", "fednova", "fedavg_robust",
                "turboaggregate", "centralized"):
        trainer = _trainer(cfg, data)
        if mesh is not None and algo in ("fedavg", "fedopt", "fedprox",
                                         "fednova", "fedavg_robust"):
            import jax.numpy as jnp
            from fedml_tpu.parallel import (MeshFedAvgEngine,
                                            MeshFedNovaEngine,
                                            MeshFedOptEngine,
                                            MeshFedProxEngine,
                                            MeshRobustEngine)
            cls = {"fedavg": MeshFedAvgEngine, "fedopt": MeshFedOptEngine,
                   "fedprox": MeshFedProxEngine,
                   "fednova": MeshFedNovaEngine,
                   "fedavg_robust": MeshRobustEngine}[algo]
            kw = {}
            if algo == "fedavg_robust":
                # all five defenses run on the mesh (order-statistic
                # ones via the replicated cohort matrix — or the
                # two-phase block stream with --stream_block)
                kw = dict(defense=args.defense,
                          n_byzantine=args.n_byzantine,
                          multi_krum_m=args.multi_krum_m)
            return cls(trainer, data, cfg, mesh=mesh,
                       streaming=args.streaming, chunk=args.cohort_chunk,
                       local_dtype=_local_dtype(args),
                       stack_dtype=_stack_dtype(args),
                       flat_stack=not args.no_flat_stack,
                       stream_block=args.stream_block,
                       prefetch=not args.no_prefetch, **kw)
        if algo == "centralized":
            from fedml_tpu.algorithms.centralized import CentralizedTrainer
            if mesh is not None and (args.streaming or args.cohort_chunk
                                     or args.local_dtype):
                logging.getLogger(__name__).warning(
                    "centralized mesh DP ignores --streaming/"
                    "--cohort_chunk/--local_dtype")
            # mesh = the reference's DDP: batch axis sharded over devices
            return CentralizedTrainer(trainer, data, cfg, mesh=mesh)
        from fedml_tpu import algorithms as A
        cls = {"fedavg": A.FedAvgEngine, "fedopt": A.FedOptEngine,
               "fedprox": A.FedProxEngine, "fednova": A.FedNovaEngine}.get(algo)
        if cls is not None:
            return cls(trainer, data, cfg)
        if algo == "fedavg_robust":
            return A.FedAvgRobustEngine(trainer, data, cfg,
                                        defense=args.defense,
                                        n_byzantine=args.n_byzantine,
                                        multi_krum_m=args.multi_krum_m)
        from fedml_tpu.algorithms.turboaggregate import TurboAggregateEngine
        return TurboAggregateEngine(trainer, data, cfg)

    if algo == "hierarchical":
        if args.streaming:
            logging.getLogger(__name__).warning(
                "--streaming has no hierarchical engine path; the client "
                "stack stays device-resident")
        if mesh is not None:
            from fedml_tpu.parallel import MeshHierarchicalEngine
            from fedml_tpu.parallel.mesh import make_mesh_2d
            mesh2 = make_mesh_2d(args.group_num)
            return MeshHierarchicalEngine(
                _trainer(cfg, data), data, cfg, mesh=mesh2,
                group_comm_round=args.group_comm_round,
                chunk=args.cohort_chunk, local_dtype=_local_dtype(args),
                flat_stack=not args.no_flat_stack)
        from fedml_tpu.algorithms import HierarchicalFedAvgEngine
        return HierarchicalFedAvgEngine(
            _trainer(cfg, data), data, cfg, group_num=args.group_num,
            group_comm_round=args.group_comm_round)

    if algo == "decentralized":
        if mesh is not None:
            if args.local_dtype == "bfloat16":
                logging.getLogger(__name__).warning(
                    "--local_dtype bfloat16 does not apply to gossip: "
                    "worker models PERSIST across rounds (no f32 global "
                    "to re-cast from each round), so bf16 masters would "
                    "accumulate rounding round over round; use "
                    "--train_dtype bfloat16 for bf16 compute instead")
            from fedml_tpu.parallel import MeshGossipEngine
            return MeshGossipEngine(_trainer(cfg, data), data, cfg,
                                    mesh=mesh,
                                    flat_stack=not args.no_flat_stack)
        from fedml_tpu.algorithms import DecentralizedGossipEngine
        from fedml_tpu.core.topology import (AsymmetricTopologyManager,
                                             SymmetricTopologyManager)
        C = cfg.client_num_in_total
        if args.topology == "ws":
            logging.getLogger(__name__).warning(
                "--topology ws is a deprecated alias for ring (use "
                "--neighbor_num > 2 for Watts-Strogatz extra links)")
        topo = (AsymmetricTopologyManager(C)
                if args.topology == "asymmetric"
                else SymmetricTopologyManager(
                    C, neighbor_num=args.neighbor_num))
        topo.generate_topology()
        return DecentralizedGossipEngine(_trainer(cfg, data), data, cfg,
                                         topology=topo)

    if algo == "fednas":
        nas_kw = dict(unrolled=args.unrolled, gdas=args.gdas,
                      C=args.nas_channels, layers=args.nas_layers,
                      steps=args.nas_steps,
                      multiplier=args.nas_multiplier)
        if mesh is not None:
            if args.streaming or args.local_dtype:
                logging.getLogger(__name__).warning(
                    "fednas mesh engine supports --cohort_chunk only; "
                    "--streaming/--local_dtype are ignored")
            from fedml_tpu.algorithms.fednas import make_mesh_fednas_engine
            return make_mesh_fednas_engine(data, cfg, mesh=mesh,
                                           chunk=args.cohort_chunk,
                                           **nas_kw)
        from fedml_tpu.algorithms import FedNASSearchEngine
        return FedNASSearchEngine(data, cfg, **nas_kw)

    if algo == "fedseg":
        from fedml_tpu.algorithms.fedseg import (FedSegEngine,
                                                 make_mesh_fedseg_engine)
        # segnet model, mask broadcast over label H,W, VOC void 255
        # (reference SegmentationLosses ignore_index, fedseg/utils.py:72)
        trainer = _trainer(cfg, data, model_name="segnet",
                           force_time_axis=True, default_train_ignore=255)
        if mesh is not None:
            return make_mesh_fedseg_engine(
                trainer, data, cfg, mesh=mesh, streaming=args.streaming,
                chunk=args.cohort_chunk, local_dtype=_local_dtype(args),
                prefetch=not args.no_prefetch)
        return FedSegEngine(trainer, data, cfg)

    if algo == "fedgan":
        from fedml_tpu.algorithms.fedgan import (FedGANEngine,
                                                 make_mesh_fedgan_engine)
        from fedml_tpu.models.gan import Discriminator, Generator
        out_dim = int(np.prod(data.client_shards["x"].shape[3:]))
        if mesh is not None:
            if args.streaming or args.local_dtype:
                logging.getLogger(__name__).warning(
                    "fedgan mesh engine supports --cohort_chunk only; "
                    "--streaming/--local_dtype are ignored")
            return make_mesh_fedgan_engine(
                Generator(latent_dim=64, out_dim=out_dim), Discriminator(),
                data, cfg, latent_dim=64, mesh=mesh,
                chunk=args.cohort_chunk)
        return FedGANEngine(Generator(latent_dim=64, out_dim=out_dim),
                            Discriminator(), data, cfg, latent_dim=64)

    if algo == "fedgkt":
        from fedml_tpu.algorithms.fedgkt import FedGKTEngine
        from fedml_tpu.models.resnet_gkt import (ResNetClientGKT,
                                                 ResNetServerGKT)
        # GKT's server optimizer TRAINS the big model (client-lr default,
        # GKTServerTrainer.py:39-44) — the FedOpt flag defaults
        # (sgd @ server_lr=1.0) are a different convention, so only
        # --server_* flags the user actually passed (parser default None)
        # are forwarded; an explicit 0.0/1.0/"sgd" now sticks
        kw = {}
        if args.server_optimizer is not None:
            kw["server_optimizer"] = args.server_optimizer
        if args.server_lr is not None:
            kw["server_lr"] = args.server_lr
        if args.server_momentum is not None:
            kw["server_momentum"] = args.server_momentum
        models = (ResNetClientGKT(num_classes=data.class_num),
                  ResNetServerGKT(num_classes=data.class_num))
        if mesh is not None:
            from fedml_tpu.algorithms.fedgkt import MeshFedGKTEngine
            if args.streaming or args.cohort_chunk or args.local_dtype:
                logging.getLogger(__name__).warning(
                    "fedgkt mesh engine ignores --streaming/"
                    "--cohort_chunk/--local_dtype (GKT is "
                    "full-participation resident; phases are GSPMD-"
                    "sharded, not cohort-chunked)")
            return MeshFedGKTEngine(*models, data, cfg, mesh=mesh, **kw)
        return FedGKTEngine(*models, data, cfg, **kw)

    if algo == "splitnn":
        from fedml_tpu.algorithms.split_nn import SplitNNEngine
        from fedml_tpu.models.split import split_cnn, split_mlp
        is_img = data.client_shards["x"].ndim >= 5
        cm, sm = (split_cnn(data.class_num) if is_img
                  else split_mlp(data.class_num))
        return SplitNNEngine(cm, sm, data, cfg)

    if algo == "vfl":
        from fedml_tpu.algorithms.vertical_fl import VFLEngine
        from fedml_tpu.data import load_vfl_data
        x, y, splits = load_vfl_data(
            cfg.dataset if cfg.dataset in ("nus_wide", "lending_club")
            else "lending_club", data_dir=cfg.data_dir)
        eng = VFLEngine(splits, cfg)
        eng._vfl_data = (x, y)          # consumed by main()
        return eng

    raise ValueError(f"unknown algorithm {algo!r}")


def _run_secure(args, cfg: FedConfig, logger) -> int:
    """--secure_agg: run a messaging FSM with the pairwise-mask data
    plane (fedml_tpu/secure/).  Secure mode only exists on the LIVE
    engines — the sync fedavg_messaging FSM and the async lifecycle
    server — because the vmap dispatch-wave engine has no per-client
    wire to mask.  `--async --secure_agg` keeps the lifecycle simulator
    (latency/dropout) but forces the cohort barrier: masks only cancel
    over the full round cohort, so partial buffers are unmasked at the
    commit barrier via share reconstruction, never committed early."""
    import jax
    import jax.numpy as jnp

    log = logging.getLogger(__name__)
    sec = _secure_config(args)
    if (args.defense_screen or args.defense_norm_bound is not None
            or args.defense_buckets > 1 or args.defense_trim_k > 0
            or args.defense_combine != "trimmed_mean"):
        log.warning(
            "--defense_screen/--defense_norm_bound/--defense_buckets/"
            "--defense_trim_k/--defense_combine are blinded under "
            "--secure_agg: the server only ever sees masked field words, "
            "so plaintext admission screening cannot run.  The surviving "
            "enforcement is the client-side quantizer range refusal "
            "(PERF.md 'Secure aggregation')")

    data = _load(cfg)
    trainer = _trainer(cfg, data)

    if getattr(args, "async_mode", False):
        if args.async_buffer_k is not None:
            log.warning(
                "--async_buffer_k is ignored under --secure_agg (the "
                "masked fold is a cohort barrier: buffer_k == cohort)")
        from fedml_tpu.async_ import LifecycleConfig
        from fedml_tpu.async_.lifecycle import run_async_messaging
        lc = LifecycleConfig(
            latency=args.async_latency,
            latency_scale=args.async_latency_scale,
            latency_sigma=args.async_latency_sigma,
            pareto_alpha=args.async_pareto_alpha,
            heterogeneity=args.async_heterogeneity,
            dropout_prob=args.async_dropout_prob,
            rejoin_prob=args.async_rejoin_prob,
            rejoin_delay_s=args.async_rejoin_delay_s,
            seed=(args.async_seed if args.async_seed is not None
                  else cfg.seed))
        variables, server = run_async_messaging(
            trainer, data, cfg,
            buffer_k=cfg.client_num_per_round,
            worker_num=cfg.client_num_per_round,
            total_commits=cfg.comm_round,
            deadline_s=args.async_round_deadline_s,
            mix=args.async_mix,
            lifecycle_cfg=lc,
            secure=sec)
        extra = {"rounds": server.version,
                 "secure_below_threshold": server.secure_below_threshold,
                 **{f"secagg_{k}": v
                    for k, v in server._secure.report().items()}}
    else:
        from fedml_tpu.comm.fedavg_messaging import run_messaging_fedavg
        variables = run_messaging_fedavg(
            trainer, data, cfg, worker_num=cfg.client_num_per_round,
            secure=sec)
        extra = {"rounds": cfg.comm_round}

    eval_fn = jax.jit(trainer.evaluate)
    sums = eval_fn(jax.tree.map(jnp.asarray, variables),
                   jax.tree.map(jnp.asarray, data.test_global))
    cnt = max(float(sums["count"]), 1.0)
    logger.log({"test_acc": float(sums["correct"]) / cnt,
                "test_loss": float(sums["loss_sum"]) / cnt, **extra})
    return 0


def _run_deployment(args, cfg: FedConfig, logger) -> int:
    """One deployment rank over real sockets (reference run_fedavg_grpc.sh /
    run_fedavg_trpc.sh: N OS processes, rank 0 = server).  Both roles load
    the dataset (clients need shards, the server needs the init model and
    eval split); the model exchange runs the fedavg_messaging FSM."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.comm.fedavg_messaging import (FedAvgAggregator,
                                                 FedAvgClientManager,
                                                 FedAvgServerManager)

    # rank-prefixed logs, one process per rank (reference
    # main_fedavg.py:415-420 logger format parity)
    for h in logging.getLogger().handlers:
        h.setFormatter(logging.Formatter(
            f"[rank {args.rank}] %(asctime)s %(name)s "
            "%(levelname)s %(message)s"))

    data = _load(cfg)
    trainer = _trainer(cfg, data)
    size = args.world_size
    if args.deploy == "client" and not 1 <= args.rank < size:
        raise SystemExit(
            f"--deploy client needs --rank in [1, {size - 1}] "
            f"(rank 0 is the server); got {args.rank}")
    ip_config = {r: "127.0.0.1" for r in range(size)}
    kw = dict(ip_config=ip_config, base_port=args.base_port)
    if args.comm_backend in ("TCP", "NATIVE_TCP"):
        # ISSUE 11: transport choice + the overload-safety knobs are
        # deployment flags, not code edits — a flash crowd is survived
        # by configuration
        from fedml_tpu.comm.reactor import ReactorConfig
        kw["reactor"] = args.tcp_transport == "reactor"
        kw["reactor_config"] = ReactorConfig(
            reactors=args.conn_reactors,
            max_connections=args.conn_max,
            stall_timeout_s=args.conn_stall_timeout_s,
            max_frames_per_sec=args.conn_max_frames_per_sec,
            max_bytes_per_sec=args.conn_max_bytes_per_sec)

    def _harden(manager) -> None:
        """ISSUE 8: opt this rank's transport into the reliability
        envelope and/or install the seeded fault injector — both
        CLI-driven so robustness scenarios are a flag, not a code
        edit."""
        if args.reliable:
            manager.com_manager.enable_reliability()
        rates = {k: getattr(args, f"chaos_{k}")
                 for k in ("drop", "dup", "corrupt", "delay")}
        if any(v > 0.0 for v in rates.values()):
            from fedml_tpu.comm.chaos import ChaosConfig, ChaosPolicy
            manager.com_manager.install_chaos(
                ChaosPolicy(ChaosConfig(seed=args.chaos_seed, **rates)))

    from fedml_tpu.utils.context import graceful_abort

    # deployed secure mode: every rank rebuilds the SAME SecureAggregator
    # from --secure_seed (keyring + escrow are deterministic), so the
    # masked protocol needs no extra key-exchange round trips on the wire
    secagg = None
    sec_cfg = _secure_config(args)
    if sec_cfg is not None:
        from fedml_tpu.async_.staleness import flat_dim
        from fedml_tpu.secure import SecureAggregator
        iv = trainer.init(jax.random.PRNGKey(cfg.seed),
                          jnp.asarray(data.client_shards["x"][0, 0]))
        secagg = SecureAggregator(sec_cfg, range(1, size), flat_dim(iv))

    if args.deploy == "server":
        init_vars = trainer.init(
            jax.random.PRNGKey(cfg.seed),
            jnp.asarray(data.client_shards["x"][0, 0]))
        agg = FedAvgAggregator(init_vars, size - 1,
                               cfg.client_num_in_total, size - 1,
                               secure=secagg)
        server = FedAvgServerManager(
            agg, cfg.comm_round, 0, size, args.comm_backend,
            model_transport=(None if args.wire_transport == "none"
                             else args.wire_transport),
            wire_compress=args.wire_compress, **kw)
        _harden(server)
        with graceful_abort(server):
            server.run_async()
            server.send_init_msg()
            if not server.done.wait(timeout=600):
                raise TimeoutError(
                    "deployment server: rounds did not finish")
        server.finish()
        variables = jax.tree.map(jnp.asarray, agg.variables)
        eval_fn = jax.jit(trainer.evaluate)
        sums = eval_fn(variables, jax.tree.map(jnp.asarray,
                                               data.test_global))
        cnt = max(float(sums["count"]), 1.0)
        logger.log({"test_acc": float(sums["correct"]) / cnt,
                    "test_loss": float(sums["loss_sum"]) / cnt,
                    "rounds": server.round_idx})
        return 0

    client = FedAvgClientManager(trainer, data, cfg.epochs, args.rank, size,
                                 args.comm_backend,
                                 total_rounds=cfg.comm_round,
                                 wire_compress=args.wire_compress,
                                 secure=secagg, **kw)
    _harden(client)
    with graceful_abort(client):
        client.run()        # blocks until total_rounds uploads are done
    return 0


def _notify_sweep(args) -> None:
    """wandb-sweep coordination (reference fedavg/utils.py:19-27): agents
    block on a named pipe until the run reports completion.  Called from
    EVERY run mode's exit path."""
    pipe = os.environ.get("FEDML_SWEEP_PIPE")
    if pipe:
        from fedml_tpu.utils.context import (
            post_complete_message_to_sweep_process)
        post_complete_message_to_sweep_process(vars(args), pipe_path=pipe)


def _strip_arg(argv: list[str], flag: str) -> list[str]:
    """Remove `flag` (and its value, both `--f N` and `--f=N` forms)
    from an argv copy — the multihost self-spawn must not recurse."""
    out, skip = [], False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == flag:
            skip = True
            continue
        if a.startswith(flag + "="):
            continue
        out.append(a)
    return out


def _run_cluster_serve_cli(args, mh_ctx) -> int:
    """One serving host of the fused cluster (ISSUE 18): bind the
    reactor endpoint at cluster_port + rank, serve live-socket uplinks
    into this rank's registry-shard lanes, fold partials cross-host at
    each commit barrier, and print the host report as one JSON line
    (the same contract mh_worker's serve_cluster route honors)."""
    import hashlib
    import json

    from fedml_tpu import obs
    from fedml_tpu.scale.cluster import run_cluster_serve
    if args.obs_dir:
        obs.configure(args.obs_dir)
    else:
        obs.configure_from_env()
    rank, world = (0, 1) if mh_ctx is None else (mh_ctx.rank,
                                                 mh_ctx.world)
    channel = None
    if world > 1:
        from fedml_tpu.parallel.multihost import ElasticChannel
        knobs = {k: getattr(args, k) for k in
                 ("cluster_population", "cluster_commits",
                  "cluster_buffer_k", "cluster_row_dim",
                  "cluster_connections", "cluster_window_s")}
        digest = hashlib.md5(json.dumps(
            knobs, sort_keys=True).encode()).hexdigest()
        channel = ElasticChannel(
            mh_ctx, n_items=world, config_digest=digest,
            timeout_s=120.0, hb_interval_s=0.25,
            hb_timeout_s=args.hb_timeout_s)
    try:
        report = run_cluster_serve(
            args.cluster_population,
            commits=args.cluster_commits,
            warmup_commits=min(2, args.cluster_commits - 1),
            buffer_k=args.cluster_buffer_k,
            row_dim=args.cluster_row_dim,
            port=args.cluster_port + rank,
            partition=(rank, world), channel=channel,
            elastic=world > 1,
            n_connections=args.cluster_connections,
            ingest_pool=args.cluster_ingest_pool,
            window_deadline_s=args.cluster_window_s,
            slo_window=(rank == 0))
    finally:
        if channel is not None:
            channel.close()
    print(json.dumps({"rank": rank, "world": world,
                      "serve_cluster": report}), flush=True)
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    from fedml_tpu.parallel.multihost import MultihostContext
    mh_ctx = MultihostContext.from_env()
    if args.multihost_procs is not None and mh_ctx is None:
        # self-spawn harness: re-exec this exact command N times wired
        # as one cluster (children see FEDML_MH_* and take the runner
        # path below instead of re-spawning)
        if args.multihost_procs < 1:
            raise SystemExit(f"--multihost_procs must be >= 1, got "
                             f"{args.multihost_procs}")
        from fedml_tpu.parallel.multihost import (MultihostLaunchError,
                                                  spawn_cluster)
        child = ([sys.executable, "-m", "fedml_tpu.cli"]
                 + _strip_arg(list(argv if argv is not None
                                   else sys.argv[1:]),
                              "--multihost_procs"))
        try:
            for rank, out in enumerate(spawn_cluster(
                    child, args.multihost_procs,
                    jax_distributed=args.multihost,
                    elastic=args.elastic, echo=True)):
                for line in out.splitlines():
                    print(f"[rank {rank}] {line}")
        except MultihostLaunchError as e:
            print(f"multihost launch failed: {e}", file=sys.stderr)
            return 1
        return 0
    if args.cluster_serve:
        # ISSUE 18: the fused serving cluster — no FedConfig, no
        # training engines; this process is one serving host
        return _run_cluster_serve_cli(args, mh_ctx)
    if args.batch_unroll is not None and args.batch_unroll < 1:
        # here, not in build_engine: the --deploy path builds its
        # trainer without build_engine and must get the same clean error
        raise SystemExit(
            f"--batch_unroll must be >= 1, got {args.batch_unroll}")
    cfg = FedConfig.from_args(args)
    cfg.ci = bool(args.ci)
    from fedml_tpu import obs
    if args.obs_dir:
        obs_dir = args.obs_dir
        if mh_ctx is not None and mh_ctx.world > 1:
            # one obs dir per RANK: co-launched processes handed the
            # same --obs_dir race each other's export tmp files (and
            # silently interleave traces); per-rank subdirs are also
            # what tools/trace_timeline.py wants as inputs.  A
            # REJOINING incarnation (elastic respawn) reuses its rank
            # id within the SAME run, so rank alone would clobber the
            # dead incarnation's traces — namespace the rejoin by pid
            # too (ISSUE 14)
            sub = f"rank{mh_ctx.rank}"
            if os.environ.get("FEDML_MH_REJOIN") == "1":
                sub = f"rank{mh_ctx.rank}-pid{os.getpid()}"
            obs_dir = os.path.join(obs_dir, sub)
        obs.configure(obs_dir)
    else:
        obs.configure_from_env()     # FEDML_OBS_DIR (tools/isolate_hang)
    if args.obs_http_port is not None:
        port = obs.serve_http(args.obs_http_port).port
        logging.getLogger(__name__).info(
            "obs introspection endpoint on http://127.0.0.1:%d "
            "(/metrics /rollup /healthz /slo /cluster /flight)", port)
    slo_engine = None
    if args.slo:
        if args.slo_period_s <= 0:
            raise SystemExit(
                f"--slo_period_s must be > 0, got {args.slo_period_s}")
        from fedml_tpu.obs import slo as slo_mod
        specs = slo_mod.default_slo_pack()
        if mh_ctx is not None and mh_ctx.rank == 0 and mh_ctx.world > 1:
            # the coordinator judges the CLUSTER too (ISSUE 17): its
            # folded registry carries every rank's series, so the
            # cluster pack (round floor, barrier-wait p95, view-change
            # latency, zero deaths) evaluates alongside the local one
            from fedml_tpu.obs import cluster as cluster_mod
            specs = specs + cluster_mod.cluster_slo_pack()
        slo_engine = slo_mod.SloEngine(specs).start(args.slo_period_s)
    if mh_ctx is not None and mh_ctx.jax_coordinator:
        # launcher-wired jax.distributed (chip path: makes each host's
        # local chips visible); must run before any backend init
        from fedml_tpu.parallel.multihost import init_multihost
        init_multihost(coordinator_address=mh_ctx.jax_coordinator,
                       num_processes=mh_ctx.world,
                       process_id=mh_ctx.rank, required=True)
    elif args.multihost:
        from fedml_tpu.parallel.multihost import init_multihost
        init_multihost(required=True)

    from fedml_tpu.utils.metrics import RunLogger
    logger = RunLogger(root=args.run_dir, project="fedml_tpu",
                       name=args.run_name, config=vars(args))

    def _finish_obs():
        # explicit export (atexit also fires, but in-process callers —
        # tests, sweep drivers — want artifacts before main() returns)
        if slo_engine is not None:
            # one final window so a breach in the run's tail still
            # lands in the exported counters/rollup
            slo_engine.stop(final_evaluate=True)
        if obs.enabled():
            obs.export()

    if args.deploy:
        rc = _run_deployment(args, cfg, logger)
        logger.finish()
        _finish_obs()
        _notify_sweep(args)
        return rc

    if args.secure_agg:
        rc = _run_secure(args, cfg, logger)
        logger.finish()
        _finish_obs()
        _notify_sweep(args)
        return rc
    ckpt = None
    if args.ckpt_dir:
        from fedml_tpu.utils.checkpoint import FedCheckpointManager
        ckpt = FedCheckpointManager(args.ckpt_dir)

    if args.algorithm == "vfl":
        eng = build_engine(args, cfg, None)
        x, y = eng._vfl_data
        params = eng.fit(x, y, epochs=cfg.comm_round)
        logger.log({"train_acc": eng.score(params, x, y)})
        logger.finish()
        _finish_obs()
        _notify_sweep(args)
        return 0

    # uint8 cohort storage starts at the LOADER when the engine will
    # dequant on device: the stack never takes the f32 detour through
    # host RAM (4x less resident than f32, and H2D moves the same u8
    # bytes).  The mesh gate mirrors build_engine's --stack_dtype check.
    store_u8 = (args.stack_dtype == "uint8" and args.mesh
                and args.algorithm in _STACK_DTYPE_ALGOS)
    data = _load(cfg, store_uint8=store_u8)
    eng = build_engine(args, cfg, data)

    import inspect
    mh_runner = None
    if mh_ctx is not None or args.agg_blocks is not None or args.elastic:
        from fedml_tpu.parallel.multihost import (ElasticRunner,
                                                  MultihostRunner)
        if not args.mesh:
            raise SystemExit(
                "multihost execution drives the mesh engines: add --mesh")
        if ckpt is not None:
            logging.getLogger(__name__).warning(
                "--ckpt_dir is ignored under multihost execution (the "
                "two-level runner does not checkpoint yet)")
        if args.elastic:
            # elastic membership: view changes + block re-adoption on
            # rank death, rejoin on respawn; fail-fast stays the
            # default below
            mh_runner = ElasticRunner(
                eng, mh_ctx, n_blocks=args.agg_blocks,
                hb_timeout_s=args.hb_timeout_s,
                carry_codec=args.carry_codec,
                overlap_exchange=args.overlap_exchange)
        else:
            mh_runner = MultihostRunner(
                eng, mh_ctx, n_blocks=args.agg_blocks,
                carry_codec=args.carry_codec,
                overlap_exchange=args.overlap_exchange)

    run_params = inspect.signature(eng.run).parameters
    engine_logs = "logger" in run_params

    def _run():
        if mh_runner is not None:
            try:
                mh_runner.run(logger=logger)
            finally:
                mh_runner.close()
            return
        kw = {}
        if engine_logs:
            kw = dict(logger=logger, ckpt=ckpt,
                      ckpt_every=args.ckpt_every, resume=args.resume)
        eng.run(**kw)

    if args.profile_dir:
        from fedml_tpu.utils.profiling import trace
        with trace(args.profile_dir):
            _run()
    else:
        _run()

    # engines that took the logger already logged each eval round
    if eng.metrics_history and not engine_logs:
        logger.log(eng.metrics_history[-1])
    logger.finish()
    _finish_obs()
    _notify_sweep(args)
    return 0


def entry() -> None:
    """Console-script entry (`fedml-tpu ...`, pyproject [project.scripts])."""
    sys.exit(main())


if __name__ == "__main__":
    entry()
