"""jax version compatibility shims.

The framework targets current jax, where `shard_map` is a top-level API
(`jax.shard_map`) and replicated→varying casts ride the vma type system
(`jax.lax.pcast(..., to="varying")`).  The container's baked toolchain
can lag (jax 0.4.37 ships `shard_map` under `jax.experimental.shard_map`
and predates vma types entirely), which would fail every mesh-engine
path at attribute lookup.  `install()` patches the gap once, at package
import (fedml_tpu/__init__.py); it is a strict no-op on jax versions
that already expose the real APIs.

Shim semantics on old jax:

* `jax.shard_map` → `jax.experimental.shard_map.shard_map` with
  `check_rep=False`: the old replication tracker predates pvary/pcast
  and rejects the engines' scan carries (a replicated zero carry updated
  with shard-varying values), exactly the pattern the vma type system
  was built to express.  The engines' outputs marked `P()` really are
  replicated — every reduction is a psum over the full mesh — so
  disabling the tracker changes nothing but the type check.
* `jax.lax.pcast` → identity: with no vma types there is nothing to
  cast; `pvary_tree` becomes a no-op, which is the correct degenerate.
* `jax.lax.axis_size` → `psum(1, axis)`: on a non-traced literal psum
  specializes statically, so the result is a concrete Python int usable
  in trace-time branches (the batch-axis rng fold-in guard in
  core/trainer.py and gossip's shard count) — verified under shard_map
  on this jaxlib.

Design note: this mutates the global jax namespace, which co-resident
code could observe via hasattr feature-detection.  Accepted tradeoff:
fedml_tpu owns the process at every entry point in this repo (cli,
bench, tools, tests), the patch only ADDS attributes that the target
jax version defines anyway, and the alternative — a wrapper module
imported at every one of the ~20 call sites across 8 modules — keeps
the same degraded semantics while diverging the source from the
current-jax spelling it targets.  The shims disappear (install() is a
no-op) the moment the toolchain jax catches up.
"""
from __future__ import annotations

import jax


def install() -> None:
    """Idempotently patch missing jax APIs (see module docstring)."""
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _exp_shard_map

        def shard_map(f, mesh, in_specs, out_specs, **kw):
            kw.setdefault("check_rep", False)
            return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, **kw)

        jax.shard_map = shard_map
    if not hasattr(jax.lax, "pcast"):
        jax.lax.pcast = lambda x, axis_names, to="varying": x
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)


install()
