"""FedNAS — federated neural architecture search (DARTS), TPU-native.

Reference: fedml_api/distributed/fednas/{FedNASTrainer.py:34-128,
FedNASAggregator.py:71-113}.  Each client alternates an architecture
(alpha) step on a validation split with a weight (w) step on a train
split; the server sample-weight-averages BOTH trees separately; after the
search phase the strongest genotype is discretized and retrained with
plain FedAvg.

TPU-native redesign:
  * The whole cohort's local search runs as ONE jitted program —
    vmap(local_search) over the client axis, then a weighted tree-mean of
    (w, alpha) — replacing one-process-per-client MPI message exchange.
  * The second-order architect is EXACT here: the reference approximates
    the Hessian-vector product with finite differences
    (architect.py:229-260) because torch can't differentiate through an
    optimizer step cheaply; JAX differentiates through the unrolled
    update `w' = w − η ∇w L_train` directly, so
    ∇α L_val(w'(α), α) is one `jax.grad` — fewer FLOPs, no ε tuning.
  * Each client's padded batch stream is split into DISJOINT halves —
    first half trains w, second half drives the alpha step — mirroring the
    reference's 50/50 train/valid loader split (FedNASTrainer.py:49-60).
    A client with a single batch falls back to single-level search.
"""
from __future__ import annotations

import functools
import logging
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core.pytree import (tree_select, tree_vary_noop,
                                   tree_weighted_mean)
from fedml_tpu.core.sampling import ClientSampler
from fedml_tpu.core.trainer import masked_cross_entropy
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.models.darts import (DartsNetwork, DartsSearchNetwork,
                                    derive_genotype, init_alphas,
                                    st_gumbel_softmax)
from fedml_tpu.utils.config import FedConfig

log = logging.getLogger(__name__)
Pytree = Any


class FedNASSearchEngine:
    """Search phase: federated bilevel optimization of (w, alpha)."""

    def __init__(self, data: FederatedData, cfg: FedConfig,
                 num_classes: Optional[int] = None, C: int = 16,
                 layers: int = 8, steps: int = 4, multiplier: int = 4,
                 unrolled: bool = False, gdas: bool = False,
                 gdas_tau: float = 1.0,
                 arch_lr: float = 3e-4, arch_weight_decay: float = 1e-3,
                 momentum: float = 0.9, weight_decay: float = 3e-4,
                 grad_clip: float = 5.0, donate: bool = True):
        self.data = data
        self.cfg = cfg
        self.steps = steps
        self.multiplier = multiplier
        # GDAS (model_search_gdas.py): one sampled op per edge via
        # straight-through gumbel — the supernet then receives pre-mixed
        # weights instead of raw logits
        self.gdas = gdas
        self.gdas_tau = gdas_tau
        self.model = DartsSearchNetwork(
            num_classes=num_classes or data.class_num, C=C, layers=layers,
            steps=steps, multiplier=multiplier, softmax_weights=not gdas)
        self.unrolled = unrolled
        self.eta = cfg.lr                       # inner lr for the unroll
        # w optimizer: SGD + momentum + weight decay (FedNASTrainer.py:66-71)
        self.w_tx = optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.add_decayed_weights(weight_decay),
            optax.sgd(cfg.lr, momentum=momentum))
        # alpha optimizer: Adam(3e-4, b=(0.5, 0.999)), wd 1e-3
        # (FedNASTrainer.py:73-76)
        self.a_tx = optax.chain(
            optax.add_decayed_weights(arch_weight_decay),
            optax.scale_by_adam(b1=0.5, b2=0.999),
            optax.scale(-arch_lr))
        self.sampler = ClientSampler.for_data(data, cfg)
        self.round_fn = jax.jit(
            self._round, donate_argnums=(0, 1) if donate else ())
        self.eval_fn = jax.jit(self._eval_shard_metrics)
        self._test_shard = jax.tree.map(jnp.asarray, data.test_global)
        self.metrics_history: list[dict] = []

    # -- init ----------------------------------------------------------------
    def init_state(self, rng: Optional[jax.Array] = None):
        rng = rng if rng is not None else jax.random.PRNGKey(self.cfg.seed)
        r_alpha, r_w = jax.random.split(rng)
        alphas = init_alphas(r_alpha, steps=self.steps)
        sample = jnp.asarray(self.data.client_shards["x"][0, :1, 0])
        params = self.model.init(r_w, sample, alphas)["params"]
        return params, alphas

    # -- losses --------------------------------------------------------------
    def _mix(self, alphas, rng):
        """GDAS: logits → straight-through one-hot samples per edge."""
        rn, rr = jax.random.split(rng)
        return {"normal": st_gumbel_softmax(alphas["normal"], rn,
                                            self.gdas_tau),
                "reduce": st_gumbel_softmax(alphas["reduce"], rr,
                                            self.gdas_tau)}

    def _loss(self, params, alphas, batch, gumbel_rng=None):
        if self.gdas:
            alphas = self._mix(alphas, gumbel_rng)
        logits = self.model.apply({"params": params}, batch["x"], alphas)
        return masked_cross_entropy(logits, batch["y"], batch["mask"])

    def _arch_grad(self, params, alphas, train_batch, val_batch, rng=None):
        if not self.unrolled:
            # first-order: ∇α L_val(w, α)   (architect.py step_single_level)
            return jax.grad(self._loss, argnums=1)(params, alphas,
                                                   val_batch, rng)

        # exact second-order: differentiate through w' = w − η ∇w L_train
        def unrolled_val(alphas):
            gw = jax.grad(self._loss)(params, alphas, train_batch, rng)
            w2 = jax.tree.map(lambda w, g: w - self.eta * g, params, gw)
            return self._loss(w2, alphas, val_batch, rng)
        return jax.grad(unrolled_val)(alphas)

    # -- one client's local search (epochs × batches, scanned) ---------------
    def _local_search(self, params, alphas, shard, epochs: int,
                      rng=None):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        # disjoint 50/50 split of the batch stream (ref FedNASTrainer.py:49-60).
        # Interleaved even/odd — NOT first/second half: padding batches are
        # tail-appended, so a contiguous split would hand any client with
        # <= B/2 real batches an all-padding validation half and silently
        # zero its architecture signal. Interleaving shares the padding tail
        # proportionally between the two streams.
        B = shard["mask"].shape[0]
        half = B // 2
        if half > 0:
            train_shard = jax.tree.map(lambda a: a[0::2][:half], shard)
            val_shard = jax.tree.map(lambda a: a[1::2][:half], shard)
        else:            # single-batch client: degenerate single-level mode
            train_shard = val_shard = shard
        n_samples = jnp.sum(shard["mask"])   # full-shard sample weight
        # tree_vary_noop: shard_map vma alignment for the stateful w/arch
        # optimizer states (core/pytree.py)
        w_opt = tree_vary_noop(self.w_tx.init(params), shard)
        a_opt = tree_vary_noop(self.a_tx.init(alphas), shard)
        shard = train_shard

        def batch_body(carry, batches):
            params, alphas, w_opt, a_opt, rng = carry
            rng, gr1, gr2 = jax.random.split(rng, 3)
            tb, vb = batches
            has_data = jnp.sum(tb["mask"]) > 0
            has_val = jnp.sum(vb["mask"]) > 0
            # alpha step on the val batch — gated on the VAL batch's mask:
            # an empty val batch must not turn the alpha step into pure
            # Adam-normalized weight decay
            ga = self._arch_grad(params, alphas, tb, vb, gr1)
            ua, a_opt2 = self.a_tx.update(ga, a_opt, alphas)
            alphas2 = optax.apply_updates(alphas, ua)
            keep_a = functools.partial(tree_select, has_val)
            alphas2, a_opt2 = keep_a(alphas2, alphas), keep_a(a_opt2, a_opt)
            # w step on the train batch (with the updated alphas)
            loss, gw = jax.value_and_grad(self._loss)(params, alphas2, tb,
                                                      gr2)
            uw, w_opt2 = self.w_tx.update(gw, w_opt, params)
            params2 = optax.apply_updates(params, uw)
            keep = functools.partial(tree_select, has_data)
            carry = (keep(params2, params), alphas2,
                     keep(w_opt2, w_opt), a_opt2, rng)
            return carry, (jnp.where(has_data, loss, 0.0),
                           jnp.sum(tb["mask"]))

        def epoch_body(carry, _):
            carry, (losses, counts) = jax.lax.scan(
                batch_body, carry, (shard, val_shard))
            return carry, jnp.sum(losses * counts) / jnp.maximum(
                jnp.sum(counts), 1.0)

        (params, alphas, _, _, _), epoch_losses = jax.lax.scan(
            epoch_body, (params, alphas, w_opt, a_opt, rng), None,
            length=epochs)
        return params, alphas, jnp.mean(epoch_losses), n_samples

    # -- one federated round -------------------------------------------------
    def _round(self, params, alphas, cohort, rng):
        K = cohort["mask"].shape[0]
        rngs = jax.random.split(rng, K)
        def one(shard, crng):
            return self._local_search(params, alphas, shard,
                                      self.cfg.epochs, crng)
        ps, als, losses, ns = jax.vmap(one)(cohort, rngs)
        # server averages weights AND alphas separately, sample-weighted
        # (FedNASAggregator.py:71-113)
        new_params = tree_weighted_mean(ps, ns)
        new_alphas = tree_weighted_mean(als, ns)
        train_loss = jnp.sum(losses * ns) / jnp.maximum(jnp.sum(ns), 1.0)
        return new_params, new_alphas, {"train_loss": train_loss}

    # -- eval ----------------------------------------------------------------
    def _eval_shard_metrics(self, params, alphas, shard):
        if self.gdas:
            # deterministic eval: the argmax (sampled-free) architecture
            alphas = {k: jax.nn.one_hot(jnp.argmax(v, -1), v.shape[-1])
                      for k, v in alphas.items()}
        def body(carry, batch):
            logits = self.model.apply({"params": params}, batch["x"], alphas)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"])
            m = batch["mask"]
            pred = jnp.argmax(logits, -1)
            ok = (pred == batch["y"]).astype(jnp.float32) * m
            return (carry[0] + jnp.sum(ce * m), carry[1] + jnp.sum(ok),
                    carry[2] + jnp.sum(m)), None
        (ls, ok, n), _ = jax.lax.scan(body, (0.0, 0.0, 0.0), shard)
        return {"loss": ls / jnp.maximum(n, 1.0),
                "acc": ok / jnp.maximum(n, 1.0)}

    def evaluate(self, params, alphas) -> dict:
        m = self.eval_fn(params, alphas, self._test_shard)
        return {f"test_{k}": float(v) for k, v in m.items()}

    # -- driver --------------------------------------------------------------
    def _round_args(self, round_idx: int) -> tuple:
        """Round-input hook (the FedAvgEngine pattern); the mesh variant
        overrides this with the padded-cohort policy."""
        ids = self.sampler.sample(round_idx)
        cohort, _ = self.data.cohort(ids)
        return (cohort,)

    def run(self, rounds: Optional[int] = None):
        cfg = self.cfg
        params, alphas = self.init_state()
        rounds = rounds if rounds is not None else cfg.comm_round
        rng_base = jax.random.PRNGKey(cfg.seed + 11)
        for round_idx in range(rounds):
            t0 = time.time()
            params, alphas, m = self.round_fn(
                params, alphas, *self._round_args(round_idx),
                jax.random.fold_in(rng_base, round_idx))
            if (round_idx % cfg.frequency_of_the_test == 0
                    or round_idx == rounds - 1):
                stats = self.evaluate(params, alphas)
                stats.update(round=round_idx,
                             train_loss=float(m["train_loss"]),
                             round_time=time.time() - t0)
                self.metrics_history.append(stats)
                log.info("fednas search %s", stats)
        return params, alphas

    def genotype(self, alphas) -> Any:
        return derive_genotype(alphas, steps=self.steps,
                               multiplier=self.multiplier)


def make_mesh_fednas_engine(data: FederatedData, cfg: FedConfig,
                            mesh=None, chunk: Optional[int] = None,
                            **nas_kw):
    """Mesh-sharded FedNAS search: the cohort's bilevel local searches
    shard over the client mesh, and BOTH aggregation trees (w and alpha,
    FedNASAggregator.py:71-113) ride weighted psums through the same
    chunked scan pattern as the FedAvg engines.  The heaviest algorithm
    in the zoo (second-order architect per batch) — exactly where mesh
    scaling pays."""
    from jax.sharding import PartitionSpec as P

    from fedml_tpu.parallel.engine import pad_and_chunk, pad_ids
    from fedml_tpu.parallel.mesh import make_mesh, pvary_tree

    class MeshFedNASSearchEngine(FedNASSearchEngine):
        def __init__(self, data, cfg, mesh=None, chunk=None, **kw):
            self.mesh = mesh if mesh is not None else make_mesh()
            self.n_shards = self.mesh.size
            self.chunk = chunk
            super().__init__(data, cfg, **kw)
            self.round_fn = jax.jit(
                self._mesh_round,
                donate_argnums=(0, 1) if kw.get("donate", True) else ())

        def _round_args(self, round_idx: int) -> tuple:
            ids, wmask = pad_ids(self.sampler.sample(round_idx),
                                 self.n_shards)
            cohort, _ = self.data.cohort(ids)
            return (cohort, jnp.asarray(wmask))

        def _mesh_round(self, params, alphas, cohort, wmask, rng):
            mesh, axes = self.mesh, self.mesh.axis_names
            csh = P(axes)
            K = cohort["mask"].shape[0]
            rngs = jax.random.split(rng, K)
            epochs = self.cfg.epochs

            def body(params, alphas, cohort, wmask, rngs):
                pv = pvary_tree(params, axes)
                av = pvary_tree(alphas, axes)
                ch_c, ch_w, ch_r = pad_and_chunk(cohort, wmask, rngs,
                                                 self.chunk or 4)

                def chunk_body(carry, xs):
                    pnum, anum, den, lsum = carry
                    cs, cw, cr = xs
                    ps, als, losses, ns = jax.vmap(
                        lambda s, r: self._local_search(pv, av, s, epochs,
                                                        r))(cs, cr)
                    w = ns * cw          # zero-weight pad lanes drop out
                    from fedml_tpu.parallel.engine import weighted_acc
                    acc = weighted_acc(w)
                    return (jax.tree.map(acc, pnum, ps),
                            jax.tree.map(acc, anum, als),
                            den + jnp.sum(w),
                            lsum + jnp.sum(losses * w)), None

                zp = pvary_tree(jax.tree.map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), params),
                    axes)
                za = pvary_tree(jax.tree.map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), alphas),
                    axes)
                zf = pvary_tree(jnp.float32(0), axes)
                (pnum, anum, den, lsum), _ = jax.lax.scan(
                    chunk_body, (zp, za, zf, zf), (ch_c, ch_w, ch_r))
                pnum = jax.lax.psum(pnum, axes)
                anum = jax.lax.psum(anum, axes)
                den = jnp.maximum(jax.lax.psum(den, axes), 1.0)
                new_p = jax.tree.map(
                    lambda s, ref: (s / den).astype(ref.dtype), pnum,
                    params)
                new_a = jax.tree.map(
                    lambda s, ref: (s / den).astype(ref.dtype), anum,
                    alphas)
                loss = jax.lax.psum(lsum, axes) / den
                return new_p, new_a, loss

            new_p, new_a, loss = jax.shard_map(
                body, mesh=mesh, in_specs=(P(), P(), csh, csh, csh),
                out_specs=(P(), P(), P()))(params, alphas, cohort, wmask,
                                           rngs)
            return new_p, new_a, {"train_loss": loss}

    return MeshFedNASSearchEngine(data, cfg, mesh=mesh, chunk=chunk,
                                  **nas_kw)


def make_train_engine(genotype, data: FederatedData, cfg: FedConfig,
                      C: int = 36, layers: int = 20, mesh=None, **kw):
    """Train phase: FedAvg over the discretized DartsNetwork (the
    reference's post-search stage, CI-script-fednas.sh two-phase flow)."""
    from fedml_tpu.algorithms.fedavg import FedAvgEngine
    from fedml_tpu.core.trainer import ClientTrainer
    model = DartsNetwork(num_classes=data.class_num, genotype=genotype,
                         C=C, layers=layers)
    trainer = ClientTrainer(model, lr=cfg.lr, momentum=0.9,
                            weight_decay=3e-4)
    if mesh is not None:
        from fedml_tpu.parallel import MeshFedAvgEngine
        return MeshFedAvgEngine(trainer, data, cfg, mesh=mesh, **kw)
    return FedAvgEngine(trainer, data, cfg, **kw)
