"""Hierarchical FL — two-tier FedAvg (clients -> groups -> global).

Reference (fedml_api/standalone/hierarchical_fl/trainer.py:44-69, group.py:
24-46): groups run `group_comm_round` inner FedAvg rounds starting from the
global model, then the global model is the sample-weighted average of group
models.  Oracle: with full participation/full batch/E=1 the result is
invariant to the grouping (CI-script-fedavg.sh:51-59).

TPU-native: cohort reshaped to [G, M, ...]; inner group rounds are a
`lax.scan`, clients within a group a `vmap`, groups a second `vmap` — the
whole two-tier schedule is one XLA program.  On a pod this maps to psum
within an ICI slice (group tier) and a cross-slice reduction over DCN
(global tier) — see parallel/engine.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.core.pytree import tree_weighted_mean


class HierarchicalFedAvgEngine(FedAvgEngine):
    def __init__(self, trainer, data, cfg, group_num: int = 2,
                 group_comm_round: int = 1, **kw):
        self.group_num = group_num
        self.group_comm_round = group_comm_round
        super().__init__(trainer, data, cfg, **kw)

    def _round(self, variables, server_state, cohort, rng):
        """One *global* round = `group_comm_round` inner rounds per group."""
        K = cohort["mask"].shape[0]
        G = self.group_num
        assert K % G == 0, "cohort must split evenly into groups"
        M = K // G
        grouped = jax.tree.map(
            lambda a: a.reshape((G, M) + a.shape[1:]), cohort)
        rng, _ = jax.random.split(rng)

        def group_inner(group_vars, shards, grng):
            """`group_comm_round` FedAvg rounds inside one group."""
            def inner_round(carry, r):
                gv, k = carry
                k, sub = jax.random.split(k)
                crngs = jax.random.split(sub, M)
                sv, losses, ns = jax.vmap(
                    lambda sh, cr: self.trainer.local_train(
                        gv, sh, cr, self.cfg.epochs))(shards, crngs)
                gv = tree_weighted_mean(sv, ns)
                return (gv, k), (jnp.sum(losses * ns) / jnp.sum(ns), jnp.sum(ns))

            (gv, _), (losses, ns) = jax.lax.scan(
                inner_round, (group_vars, grng), jnp.arange(self.group_comm_round))
            return gv, jnp.mean(losses), ns[-1]

        grngs = jax.random.split(rng, G)
        group_vars, group_losses, group_ns = jax.vmap(
            group_inner, in_axes=(None, 0, 0))(variables, grouped, grngs)
        new_variables = tree_weighted_mean(group_vars, group_ns)
        train_loss = jnp.sum(group_losses * group_ns) / jnp.sum(group_ns)
        return new_variables, server_state, {"train_loss": train_loss}
