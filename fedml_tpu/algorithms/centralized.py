"""Centralized (non-FL) baseline trainer.

Reference: fedml_api/centralized/centralized_trainer.py +
fedml_experiments/centralized/main.py (the only classic data-parallel path
in the reference — PyTorch DDP).  TPU-native, data parallelism is a sharded
batch axis under jit; see parallel/engine.py for the mesh version.  This is
also one side of the correctness oracle: FedAvg with full participation,
full batch, E=1 must match this trainer's accuracy to 3 decimals
(CI-script-fedavg.sh:41-47).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.utils.config import FedConfig


class CentralizedTrainer:
    """`mesh` turns on classic data parallelism — the reference's DDP
    (centralized_trainer.py:7,39, main.py:301-377) as a batch-sharded
    mesh axis: every batch's sample dim is sharded over the devices
    (padded with zero-mask samples to a device multiple), params stay
    replicated, and XLA inserts the gradient psums."""

    def __init__(self, trainer: ClientTrainer, data: FederatedData,
                 cfg: FedConfig, mesh=None):
        self.trainer = trainer
        self.data = data
        self.cfg = cfg
        self.mesh = mesh
        self._data_sharding = None
        self._padded = False
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self.n_shards = mesh.size
            # [B, bs, ...]: shard the SAMPLE axis (classic DP)
            self._data_sharding = NamedSharding(mesh, P(None,
                                                        mesh.axis_names[0]))
        self.epoch_fn = jax.jit(
            lambda v, shard, rng: trainer.local_train(v, shard, rng, 1))
        self.eval_fn = jax.jit(trainer.evaluate)
        self.metrics_history: list[dict] = []
        self._shard_cache: dict = {}

    def _upload(self, shard, is_train: bool = False):
        if self._data_sharding is None:
            return jax.tree.map(jnp.asarray, shard)
        import numpy as np
        bs = shard["mask"].shape[1]
        pad = (-bs) % self.n_shards
        if pad:
            if is_train:
                # only TRAIN padding biases BatchNorm stats; padded eval
                # shards are harmless (mask guards every eval metric)
                self._padded = True
            shard = {k: np.concatenate(
                [np.asarray(v),
                 np.zeros(v.shape[:1] + (pad,) + v.shape[2:],
                          np.asarray(v).dtype)], axis=1)
                for k, v in shard.items()}
        return jax.tree.map(
            lambda a: jax.device_put(jnp.asarray(a), self._data_sharding),
            shard)

    def run(self, epochs: Optional[int] = None, variables=None):
        cfg = self.cfg
        rng = jax.random.PRNGKey(cfg.seed)
        if "train" not in self._shard_cache:   # upload once, reuse
            self._shard_cache["train"] = self._upload(self.data.train_global,
                                                      is_train=True)
        shard = self._shard_cache["train"]
        if variables is None:
            variables = self.trainer.init(rng, shard["x"][0])
        if self._padded and any(k != "params" for k in variables):
            # BatchNorm batch statistics average over ALL samples of a
            # batch (the mask only guards the loss), so zero-mask padding
            # would bias them — refuse instead of silently diverging from
            # the unsharded oracle
            raise ValueError(
                "mesh data-parallel centralized training with a "
                "stats-carrying model (BatchNorm) needs batch_size "
                f"divisible by the {self.n_shards} devices (got padding)")
        epochs = epochs if epochs is not None else cfg.comm_round
        for ep in range(epochs):
            rng, r = jax.random.split(rng)
            variables, loss, _ = self.epoch_fn(variables, shard, r)
            if ep % cfg.frequency_of_the_test == 0 or ep == epochs - 1:
                stats = self.evaluate(variables)
                stats.update(epoch=ep, train_loss=float(loss))
                self.metrics_history.append(stats)
        return variables

    def evaluate(self, variables) -> dict:
        out = {}
        for split in ("train", "test"):
            if split not in self._shard_cache:   # upload once, reuse
                src = (self.data.train_global if split == "train"
                       else self.data.test_global)
                # is_train for the train split even on an eval-first call
                # path: run() reuses this cached shard for training, so
                # the BatchNorm zero-pad guard must see its padding
                self._shard_cache[split] = self._upload(
                    src, is_train=(split == "train"))
            sums = self.eval_fn(variables, self._shard_cache[split])
            cnt = max(float(sums["count"]), 1.0)
            out[f"{split}_acc"] = float(sums["correct"]) / cnt
            out[f"{split}_loss"] = float(sums["loss_sum"]) / cnt
        return out
