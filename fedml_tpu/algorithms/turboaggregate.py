"""TurboAggregate — secure aggregation with additive masking + coded groups.

Parity: fedml_api/distributed/turboaggregate/ (TA_Aggregator.py,
TA_decentralized_worker.py, mpc_function.py) and the standalone simulation
(fedml_api/standalone/turboaggregate/TA_trainer.py).

Mechanism kept from the reference: clients quantize their model update into
a prime field, split it into additive shares (one per peer), exchange
shares, and upload only *sums of shares* — the server reconstructs the
aggregate exactly but never sees an individual update.  The LCC layer adds
straggler-resilient coded redundancy across client groups
(mpc_function.py:111-260).

TPU division of labor: local training is the jitted ClientTrainer engine;
masking/unmasking is host-side numpy on the flattened update (the
crypto is integer control-plane work, not MXU work).
"""
from __future__ import annotations

import logging
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.core import mpc

log = logging.getLogger(__name__)
Pytree = Any


def _flatten(tree: Pytree) -> tuple[np.ndarray, Any]:
    leaves, treedef = jax.tree.flatten(tree)
    flat = np.concatenate([np.asarray(l, np.float64).ravel() for l in leaves])
    shapes = [l.shape for l in leaves]
    return flat, (treedef, shapes)


def _unflatten(flat: np.ndarray, spec) -> Pytree:
    treedef, shapes = spec
    out, off = [], 0
    for s in shapes:
        n = int(np.prod(s)) if s else 1
        out.append(flat[off:off + n].reshape(s).astype(np.float32))
        off += n
    return jax.tree.unflatten(treedef, out)


class TurboAggregateEngine(FedAvgEngine):
    """FedAvg whose aggregation runs through secure additive masking.

    The weighted mean Σ n_i·w_i / Σ n_i is computed on *masked* field
    elements: each client's contribution n_i·w_i is quantized and
    additively shared across the cohort; the server sums per-client share
    sums — identical result (to fixed-point precision), zero visibility
    into any single w_i."""

    def __init__(self, trainer, data, cfg, scale: int = 2 ** 16,
                 prime: int = mpc.DEFAULT_PRIME):
        # donation is never safe here: secure_round reuses `variables`
        # after round_fn would have consumed its buffer
        super().__init__(trainer, data, cfg, donate=False)
        self.scale = scale
        self.prime = prime
        # per-client jitted local train (clients are genuinely separate
        # parties here — no cross-client vmap, matching the trust model)
        self._local = jax.jit(
            lambda v, shard, rng: trainer.local_train(v, shard, rng,
                                                      cfg.epochs))

    def secure_round(self, variables: Pytree, round_idx: int,
                     rng: jax.Array) -> Pytree:
        ids = self.sampler.sample(round_idx)
        K = len(ids)
        rngs = jax.random.split(rng, K)
        flats, ns = [], []
        spec = None
        for k, cid in enumerate(ids):
            shard = jax.tree.map(lambda a, c=int(cid): jnp.asarray(a[c]),
                                 self.data.client_shards)
            v, _loss, n = self._local(variables, shard, rngs[k])
            flat, spec = _flatten(v)
            flats.append(flat)
            ns.append(float(n))
        ns = np.asarray(ns)
        total = ns.sum()

        # -- secure aggregation of Σ (n_i/Σn)·w_i ---------------------------
        # each party quantizes its weighted contribution, splits into K
        # additive shares; party j accumulates the j-th share of everyone;
        # the server sums the K accumulators.
        accum = np.zeros((K, flats[0].size), np.int64)
        for i in range(K):
            contrib = mpc.quantize(flats[i] * (ns[i] / total), self.scale,
                                   self.prime)
            shares = mpc.additive_shares(contrib, K, self.prime,
                                         seed=round_idx * 997 + i)
            accum = np.mod(accum + shares, self.prime)
        masked_sums = np.mod(accum.astype(object).sum(axis=0),
                             self.prime).astype(np.int64)
        agg = mpc.dequantize(masked_sums, self.scale, self.prime)
        return _unflatten(agg, spec)

    def run(self, variables: Optional[Pytree] = None,
            rounds: Optional[int] = None) -> Pytree:
        cfg = self.cfg
        variables = variables if variables is not None else self.init_variables()
        rng = jax.random.PRNGKey(cfg.seed + 1)
        rounds = rounds if rounds is not None else cfg.comm_round
        for round_idx in range(rounds):
            rng, r = jax.random.split(rng)
            agg = self.secure_round(variables, round_idx, r)
            variables = jax.tree.map(jnp.asarray, agg)
            if (round_idx % cfg.frequency_of_the_test == 0
                    or round_idx == rounds - 1):
                stats = self.evaluate(variables)
                stats["round"] = round_idx
                self.metrics_history.append(stats)
                log.info("TA round %d: %s", round_idx, stats)
        return variables


def lcc_coded_groups(group_updates: np.ndarray, N: int, K: int, T: int = 1,
                     drop: Optional[list[int]] = None,
                     p: int = mpc.DEFAULT_PRIME) -> np.ndarray:
    """Straggler-resilient group aggregation: LCC-encode K group updates into
    N coded blocks, lose `drop` workers, decode from the survivors
    (TA_decentralized_worker.py + mpc_function.py:111-213)."""
    coded = mpc.LCC_encoding(group_updates, N, K, T, p)
    alive = [i for i in range(N) if not drop or i not in drop]
    assert len(alive) >= K + T, "too many stragglers for the code rate"
    return mpc.LCC_decoding(coded[alive[:K + T]], np.asarray(alive[:K + T]),
                            N, K, T, p)
