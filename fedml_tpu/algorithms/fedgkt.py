"""FedGKT — group knowledge transfer (split training via distillation).

Parity: fedml_api/distributed/fedgkt/ — the client runs a small CNN and
uploads per-sample feature maps + logits + labels
(GKTClientTrainer.py:49-129); the server trains a large CNN on those
features with CE + KL distillation toward the client logits
(GKTServerTrainer.py:42-48, 193-291, `KL_Loss(temperature)` in utils.py),
then returns its own logits per client for the client's next local phase.

TPU-native: client-side local training is a jitted scan (CE + KL to the
server's last logits); the server-side distillation epoch is a jitted scan
over every client's uploaded feature batches.  The exchange is arrays, not
pickled tensors; when clients are remote the same arrays ride the comm
layer.
"""
from __future__ import annotations

import functools
import logging
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core.pytree import tree_select
from fedml_tpu.core.trainer import make_optimizer, masked_accuracy_sums
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.utils.config import FedConfig

log = logging.getLogger(__name__)
Pytree = Any


def kl_divergence_loss(student_logits, teacher_logits, mask,
                       temperature: float = 3.0):
    """KL(teacher ‖ student) with temperature scaling (fedgkt/utils.py
    KL_Loss): T² · KL(softmax(t/T) ‖ log_softmax(s/T))."""
    t = jax.nn.softmax(teacher_logits / temperature, axis=-1)
    s = jax.nn.log_softmax(student_logits / temperature, axis=-1)
    per = jnp.sum(t * (jnp.log(jnp.clip(t, 1e-8)) - s), axis=-1)
    m = mask.astype(per.dtype)
    return (temperature ** 2) * jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.)


class FedGKTEngine:
    """client_model: x → (features, logits); server_model: features → logits."""

    def __init__(self, client_model, server_model, data: FederatedData,
                 cfg: FedConfig, temperature: float = 3.0,
                 server_epochs: int = 1, server_optimizer: Optional[str] = None,
                 server_lr: Optional[float] = None,
                 server_momentum: float = 0.9, server_wd: float = 1e-4):
        self.client_model = client_model
        self.server_model = server_model
        self.data = data
        self.cfg = cfg
        self.temperature = temperature
        self.server_epochs = server_epochs
        self.client_tx = make_optimizer(cfg.client_optimizer, cfg.lr,
                                        cfg.momentum, cfg.wd)
        # the GKT server optimizer TRAINS the big model at the CLIENT lr
        # with momentum 0.9 + wd 1e-4 (GKTServerTrainer.py:39-44) — it is
        # NOT FedOpt's pseudo-gradient server_lr=1.0 convention, which
        # diverges the distillation instantly on real-size models
        self.server_tx = make_optimizer(
            server_optimizer or cfg.client_optimizer,
            cfg.lr if server_lr is None else server_lr,
            server_momentum, weight_decay=server_wd)
        # ALL clients' local phases as one vmapped program (the reference
        # trains clients in separate processes; a python loop over jit
        # calls would serialize C dispatches per round)
        self._client_phase_v = jax.jit(jax.vmap(self._client_phase))
        self._server_phase_j = jax.jit(self._server_phase)
        self._eval = jax.jit(self._eval_sums)
        self.metrics_history: list[dict] = []

    # -- init ----------------------------------------------------------------
    def init_params(self, rng: Optional[jax.Array] = None):
        rng = rng if rng is not None else jax.random.PRNGKey(self.cfg.seed)
        r1, r2 = jax.random.split(rng)
        x = jnp.asarray(self.data.client_shards["x"][0, 0])
        cp = self.client_model.init(r1, x)["params"]
        feats, _ = self.client_model.apply({"params": cp}, x)
        sp = self.server_model.init(r2, feats)["params"]
        return cp, sp

    # -- client phase: local CE + KL(server logits) --------------------------
    def _client_phase(self, client_params, shard, server_logits):
        """shard: {x,y,mask}[B,bs,...]; server_logits [B,bs,C] (zeros in
        round 0 ⇒ pure CE, matching the reference's whether_distill_on_the_
        client bootstrap)."""
        opt = self.client_tx.init(client_params)

        def loss_fn(p, batch, slog):
            feats, logits = self.client_model.apply({"params": p}, batch["x"])
            m = batch["mask"]
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"])
            ce = jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0)
            kl = kl_divergence_loss(logits, slog, m, self.temperature)
            use_kl = jnp.any(jnp.abs(slog) > 0)
            return ce + jnp.where(use_kl, kl, 0.0)

        def step(carry, inp):
            p, opt = carry
            batch, slog = inp
            loss, g = jax.value_and_grad(loss_fn)(p, batch, slog)
            has = jnp.sum(batch["mask"]) > 0
            u, opt2 = self.client_tx.update(g, opt, p)
            keep = functools.partial(tree_select, has)
            return (keep(optax.apply_updates(p, u), p), keep(opt2, opt)), loss

        def epoch(carry, _):
            carry, losses = jax.lax.scan(step, carry, (shard, server_logits))
            return carry, losses.mean()

        (p, _), losses = jax.lax.scan(epoch, (client_params, opt), None,
                                      length=self.cfg.epochs)
        # upload: features + logits for every sample (extracted_feature_dict /
        # logits_dict upload, GKTClientTrainer.py:49-129)
        feats, logits = jax.vmap(
            lambda b: self.client_model.apply({"params": p}, b))(shard["x"])
        return p, feats, logits, losses.mean()

    # -- server phase: distill on uploaded features --------------------------
    def _server_phase(self, server_params, opt_state, feats, logits, ys,
                      masks):
        """feats/logits/ys/masks have a leading client axis [K,B,...]; the
        server's epoch is a scan over the flattened client×batch stream
        (GKTServerTrainer.train_and_distill_on_server, :193-291)."""
        K, B = masks.shape[0], masks.shape[1]
        fl = lambda a: a.reshape((K * B,) + a.shape[2:])
        stream = (fl(feats), fl(logits), fl(ys), fl(masks))

        def loss_fn(p, f, clog, y, m):
            slog = self.server_model.apply({"params": p}, f)
            ce = optax.softmax_cross_entropy_with_integer_labels(slog, y)
            ce = jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0)
            return ce + kl_divergence_loss(slog, clog, m, self.temperature)

        def step(carry, inp):
            p, opt = carry
            f, clog, y, m = inp
            loss, g = jax.value_and_grad(loss_fn)(p, f, clog, y, m)
            has = jnp.sum(m) > 0
            u, opt2 = self.server_tx.update(g, opt, p)
            keep = functools.partial(tree_select, has)
            return (keep(optax.apply_updates(p, u), p), keep(opt2, opt)), loss

        # all-padding steps (zero-weight pad clients on the mesh) are
        # frozen no-ops; they must not dilute the epoch-loss metric either
        step_real = (stream[3].sum(axis=1) > 0).astype(jnp.float32)

        def epoch(carry, _):
            carry, losses = jax.lax.scan(step, carry, stream)
            return carry, (jnp.sum(losses * step_real)
                           / jnp.maximum(step_real.sum(), 1.0))

        (p, opt_state), losses = jax.lax.scan(
            epoch, (server_params, opt_state), None,
            length=self.server_epochs)
        # per-client server logits for the next client phase
        slog = jax.vmap(jax.vmap(
            lambda f: self.server_model.apply({"params": p}, f)))(feats)
        return p, opt_state, slog, losses.mean()

    # -- driver ---------------------------------------------------------------
    def _setup_device_data(self):
        """Device placement hook: returns (shards for the client phase,
        y and mask for the server phase).  The mesh engine overrides this
        to commit each to its phase's layout (client- vs batch-sharded)."""
        shards, _ = self.data.device_shards()
        return shards, shards["y"], shards["mask"]

    def run(self, rounds: Optional[int] = None):
        cfg = self.cfg
        cp0, sp = self.init_params()
        C = self.data.client_num
        # [C, ...] stacked per-client models: every client's local phase
        # runs in ONE vmapped program per round
        cp_stack = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (C,) + a.shape), cp0)
        server_opt = self.server_tx.init(sp)
        shards, y_srv, m_srv = self._setup_device_data()
        B, bs = shards["mask"].shape[1:3]
        sample_logits = jnp.zeros((C, B, bs, self.data.class_num))
        rounds = rounds if rounds is not None else cfg.comm_round
        for round_idx in range(rounds):
            t0 = time.time()
            cp_stack, feats, logits, losses = self._client_phase_v(
                cp_stack, shards, sample_logits)
            sp, server_opt, sample_logits, s_loss = self._server_phase_j(
                sp, server_opt, feats, logits, y_srv, m_srv)
            if (round_idx % cfg.frequency_of_the_test == 0
                    or round_idx == rounds - 1):
                stats = self.evaluate(
                    jax.tree.map(lambda a: a[0], cp_stack), sp)
                # mean over clients that HAVE data (mesh pads the stack
                # with zero-weight clients whose loss is a frozen 0)
                real = jnp.asarray(self.data.client_num_samples) > 0
                stats.update(round=round_idx,
                             client_loss=float(
                                 jnp.sum(losses * real)
                                 / jnp.maximum(real.sum(), 1)),
                             server_loss=float(s_loss),
                             round_time=time.time() - t0)
                self.metrics_history.append(stats)
                log.info("gkt round %d: %s", round_idx, stats)
        client_params = [jax.tree.map(lambda a, c=cid: a[c], cp_stack)
                         for cid in range(C)]
        return client_params, sp

    def _eval_sums(self, cp, sp, shard):
        def one(batch):
            f, _ = self.client_model.apply({"params": cp}, batch["x"])
            logits = self.server_model.apply({"params": sp}, f)
            return masked_accuracy_sums(logits, batch["y"], batch["mask"])
        c, n = jax.vmap(one)(shard)
        return c.sum(), n.sum()

    def evaluate(self, client_params, server_params) -> dict:
        shard = jax.tree.map(jnp.asarray, self.data.test_global)
        c, n = self._eval(client_params, server_params, shard)
        return {"test_acc": float(c) / max(float(n), 1.0)}


class MeshFedGKTEngine(FedGKTEngine):
    """FedGKT over a device mesh.

    Two different parallel axes, matching the phase structure:

    * client phase — the [C, ...] per-client model stack and shards are
      sharded on the CLIENT axis; each device runs the vmapped local
      phase for its slice (embarrassingly parallel, zero collectives).
    * server phase — the reference's ONE classic-DP use is the GKT
      server (`nn.DataParallel(model)`, GKTServerTrainer.py:27-29, whose
      measured win is the incidental batch-scaling row in BASELINE.md):
      here each distillation step's BATCH axis is sharded over the mesh,
      params stay replicated, and XLA inserts the gradient psums — GSPMD
      batch parallelism instead of replicated-module scatter/gather.

    Both phases keep the exact single-device program (this class only
    re-jits them with explicit shardings), so mesh == single-device up to
    float reassociation — pinned by the oracle test."""

    def __init__(self, client_model, server_model, data: FederatedData,
                 cfg: FedConfig, mesh=None, **kw):
        import dataclasses

        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from fedml_tpu.parallel.mesh import make_mesh, pad_cohort
        self.mesh = mesh if mesh is not None else make_mesh()
        self._real_clients = data.client_num
        n_dev = int(np.prod(list(self.mesh.shape.values())))
        shards = dict(data.client_shards)
        w = np.asarray(data.client_num_samples, np.float32)
        pad_c = (-data.client_num) % n_dev
        pad_bs = (-shards["mask"].shape[2]) % n_dev
        if pad_c:
            # GKT is full-participation resident: pad the stack itself
            # with zero-mask clients (their local phase is a no-op and
            # their mask-0 feature batches freeze the server scan steps)
            shards, w = pad_cohort(shards, w, n_dev)
        if pad_bs:
            # the server phase shards each step's BATCH axis; pad it to a
            # device multiple with mask-0 samples (masked losses/metrics
            # give them zero weight in both phases)
            def pad2(a):
                width = [(0, 0)] * a.ndim
                width[2] = (0, pad_bs)
                return np.pad(np.asarray(a), width)
            shards = {k: pad2(v) for k, v in shards.items()}
        if pad_c or pad_bs:
            data = dataclasses.replace(data, client_shards=shards,
                                       client_num_samples=w,
                                       _device_cache={})
        super().__init__(client_model, server_model, data, cfg, **kw)
        axes = self.mesh.axis_names
        csh = NamedSharding(self.mesh, P(axes))           # leading C axis
        rep = NamedSharding(self.mesh, P())
        bsh = NamedSharding(self.mesh, P(None, None, axes))  # [K,B,bs,...]
        self._csh, self._bsh = csh, bsh
        # the client phase EMITS feats/logits batch-sharded (XLA inserts
        # the client→server all-to-all inside the program — the "upload");
        # jit rejects committed args whose layout differs from
        # in_shardings, so the boundary layouts must agree exactly
        self._client_phase_v = jax.jit(
            jax.vmap(self._client_phase),
            in_shardings=(csh, csh, csh),
            out_shardings=(csh, bsh, bsh, csh))
        self._server_phase_j = jax.jit(
            self._server_phase,
            in_shardings=(rep, rep, bsh, bsh, bsh, bsh),
            # slog leaves client-sharded: the next client phase consumes
            # it on the client axis (the per-client logits download)
            out_shardings=(rep, rep, csh, rep))

    def _setup_device_data(self):
        # place the HOST arrays directly (not via device_shards(), whose
        # cache would pin a second, unsharded full-stack copy in HBM)
        shards = self.data.client_shards
        client_shards = {k: jax.device_put(v, self._csh)
                         for k, v in shards.items()}
        return (client_shards, jax.device_put(shards["y"], self._bsh),
                jax.device_put(shards["mask"], self._bsh))

    def run(self, rounds: Optional[int] = None):
        client_params, sp = super().run(rounds)
        return client_params[:self._real_clients], sp
