"""Classical vertical FL — multi-party logistic regression over a feature
split.

Parity: fedml_api/standalone/classical_vertical_fl/ (vfl.py:1-56,
party_models.py:1-119, vfl_fixture.py) and the distributed variant
(guest_trainer.py:113-126, host_trainer.py): each *party* owns a disjoint
feature slice of the same samples; hosts send their logit components to the
guest, the guest adds its own component + label loss, and sends back the
common gradient; every party backprops its local feature extractor.

TPU-native: the per-party feature extractors are a single vmapped dense
stack over the party axis — one jit program computes all parties' forward
components, the summed logit, and every party's gradients in one backward
pass.  The trust boundary is structural (disjoint param subtrees +
feature slices), so the same code drives the message-layer deployment.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.trainer import make_optimizer
from fedml_tpu.utils.config import FedConfig

log = logging.getLogger(__name__)
Pytree = Any


class VFLEngine:
    """n_parties-way vertical logistic regression (binary, like the
    reference's lending-club / NUS-WIDE tasks).

    Party p owns feature slice `feature_splits[p]` and a linear extractor
    x_p → R^hidden; the guest (party 0) additionally owns an interactive
    classifier over the summed party outputs (party_models.py guest/host
    split)."""

    def __init__(self, feature_splits: Sequence[int], cfg: FedConfig,
                 hidden: int = 16):
        self.splits = list(feature_splits)      # feature dims per party
        self.n_parties = len(self.splits)
        self.hidden = hidden
        self.cfg = cfg
        self.tx = make_optimizer(cfg.client_optimizer, cfg.lr, cfg.momentum,
                                 cfg.wd)
        self._step = jax.jit(self._train_step)
        self.metrics_history: list[dict] = []

    # -- params: one subtree per party ---------------------------------------
    def init_params(self, rng: Optional[jax.Array] = None) -> Pytree:
        rng = rng if rng is not None else jax.random.PRNGKey(self.cfg.seed)
        keys = jax.random.split(rng, self.n_parties + 1)
        params = {}
        for p, (d, k) in enumerate(zip(self.splits, keys[:-1])):
            params[f"party_{p}"] = {
                "kernel": jax.random.normal(k, (d, self.hidden)) *
                          (1.0 / np.sqrt(d)),
                "bias": jnp.zeros((self.hidden,)),
            }
        params["guest_head"] = {
            "kernel": jax.random.normal(keys[-1], (self.hidden, 1)) * 0.1,
            "bias": jnp.zeros((1,)),
        }
        return params

    def _party_slices(self, x):
        out, off = [], 0
        for d in self.splits:
            out.append(x[:, off:off + d])
            off += d
        return out

    def _forward(self, params, x):
        # each host computes its component locally (host_trainer.py), the
        # guest sums and applies its head (guest_trainer.py:113-126)
        comps = [xs @ params[f"party_{p}"]["kernel"]
                 + params[f"party_{p}"]["bias"]
                 for p, xs in enumerate(self._party_slices(x))]
        z = jnp.sum(jnp.stack(comps), axis=0)
        h = params["guest_head"]
        return (jax.nn.relu(z) @ h["kernel"] + h["bias"])[:, 0]

    def _loss(self, params, batch):
        logits = self._forward(params, batch["x"])
        ls = optax.sigmoid_binary_cross_entropy(logits,
                                                batch["y"].astype(jnp.float32))
        m = batch["mask"].astype(jnp.float32)
        return jnp.sum(ls * m) / jnp.maximum(jnp.sum(m), 1.0)

    def _train_step(self, params, opt_state, batch):
        loss, grads = jax.value_and_grad(self._loss)(params, batch)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # -- driver --------------------------------------------------------------
    def fit(self, x: np.ndarray, y: np.ndarray,
            x_test: Optional[np.ndarray] = None,
            y_test: Optional[np.ndarray] = None,
            epochs: Optional[int] = None) -> Pytree:
        cfg = self.cfg
        bs = cfg.batch_size
        params = self.init_params()
        opt_state = self.tx.init(params)
        n = len(y)
        epochs = epochs if epochs is not None else cfg.comm_round
        rs = np.random.RandomState(cfg.seed)
        for epoch in range(epochs):
            t0 = time.time()
            order = rs.permutation(n)
            losses = []
            for i in range(0, n, bs):
                idx = order[i:i + bs]
                # pad the tail batch to the static batch size, mask the pad
                pad = bs - len(idx)
                mask = np.concatenate([np.ones(len(idx), np.float32),
                                       np.zeros(pad, np.float32)])
                idx = np.concatenate([idx, np.zeros(pad, idx.dtype)])
                batch = {"x": jnp.asarray(x[idx]), "y": jnp.asarray(y[idx]),
                         "mask": jnp.asarray(mask)}
                params, opt_state, loss = self._step(params, opt_state, batch)
                losses.append(float(loss))
            stats = {"epoch": epoch, "train_loss": float(np.mean(losses)),
                     "epoch_time": time.time() - t0}
            if x_test is not None:
                stats["test_auc_acc"] = self.score(params, x_test, y_test)
            self.metrics_history.append(stats)
            log.info("vfl epoch %d: %s", epoch, stats)
        return params

    def score(self, params, x, y) -> float:
        logits = self._forward(params, jnp.asarray(x))
        pred = (np.asarray(logits) > 0).astype(np.int64)
        return float((pred == np.asarray(y)).mean())
