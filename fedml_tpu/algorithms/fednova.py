"""FedNova — normalized averaging.

Reference (fedml_api/standalone/fednova/fednova.py:50-200,
fednova_trainer.py:97-125): each client i runs tau_i local steps; the server
averages *normalized* update directions d_i = (w_global - w_i)/tau_i with
data weights p_i, then applies w_new = w_global - tau_eff * d where
tau_eff = sum_i p_i tau_i.  This removes the objective inconsistency of
FedAvg under heterogeneous local work.

TPU-native: tau_i is computed from the shard mask (number of non-empty
batches x epochs) inside the jitted round; no custom Optimizer subclass is
needed because the normalization happens at aggregation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgEngine


def fednova_tau(shard, epochs, batch_axes=()):
    """tau_i = local optimization steps that saw real data: non-empty
    batches x epochs (the reference's step counter,
    fednova.py local_normalizing_vec).

    Under a batch-split mesh (`batch_axes`) a step counts when the batch
    has valid samples on ANY shard — matching train_step's global
    empty-batch guard — so per-batch counts are psum'd first."""
    counts = jnp.sum(shard["mask"], axis=1)
    if batch_axes:
        counts = jax.lax.pcast(jax.lax.psum(counts, batch_axes),
                               batch_axes, to="varying")
    nonempty = jnp.sum((counts > 0).astype(jnp.float32))
    return nonempty * epochs


class FedNovaEngine(FedAvgEngine):
    def _round(self, variables, server_state, cohort, rng):
        K = cohort["mask"].shape[0]
        rng, _ = jax.random.split(rng)
        client_rngs = jax.random.split(rng, K)

        def one_client(shard, crng):
            new_vars, loss, n = self.trainer.local_train(
                variables, shard, crng, self.cfg.epochs)
            return new_vars, loss, n, fednova_tau(shard, self.cfg.epochs)

        stacked_vars, losses, ns, taus = jax.vmap(one_client)(cohort, client_rngs)
        p = ns / jnp.sum(ns)
        tau_eff = jnp.sum(p * taus)

        def nova_avg(g_leaf, stacked_leaf):
            # d = sum_i p_i (g - w_i)/tau_i ; w_new = g - tau_eff * d
            shape = (-1,) + (1,) * (stacked_leaf.ndim - 1)
            pi = p.reshape(shape).astype(stacked_leaf.dtype)
            ti = taus.reshape(shape).astype(stacked_leaf.dtype)
            d = jnp.sum(pi * (g_leaf[None] - stacked_leaf) / jnp.maximum(ti, 1.0),
                        axis=0)
            return g_leaf - tau_eff.astype(stacked_leaf.dtype) * d

        new_params = jax.tree.map(nova_avg, variables["params"],
                                  stacked_vars["params"])
        # stats collections: SAMPLE-weighted mean (zero-weight padded
        # lanes contribute nothing — a plain mean would count them)
        new_vars = {k: jax.tree.map(
            lambda s: jnp.einsum("k,k...->...", p.astype(s.dtype), s), v)
            for k, v in stacked_vars.items() if k != "params"}
        new_vars["params"] = new_params
        train_loss = jnp.sum(losses * ns) / jnp.sum(ns)
        return new_vars, server_state, {"train_loss": train_loss}
