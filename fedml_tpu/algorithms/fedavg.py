"""FedAvg — the canonical algorithm, TPU-native.

Reference call stack (SURVEY.md §3.1/§3.2): one OS process per client, MPI
message per model exchange, server aggregates state dicts in a Python loop.
Here the whole round is ONE jit-compiled XLA program:

    round_fn(variables, cohort_shards, rng)
      = vmap(local_train) over the cohort axis       (clients in parallel)
      → sample-weighted tree mean                    (aggregation)

The cohort axis can further be sharded over a `Mesh` (parallel/engine.py) so
aggregation lowers to a `psum` over ICI.  The Python layer is only: sample
client ids (reference-identical numpy semantics), gather the cohort with
`jnp.take`, log metrics.

Parity targets: fedml_api/standalone/fedavg/fedavg_api.py:40-115 (loop,
_aggregate), fedml_api/distributed/fedavg/FedAVGAggregator.py:59-98
(weighted average + sampling), FedAVGTrainer/MyModelTrainer (local SGD).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu import obs
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.sampling import ClientSampler
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.utils.config import FedConfig

log = logging.getLogger(__name__)
Pytree = Any


class FedAvgEngine:
    """Standalone-simulation FedAvg (single device or vmap cohort)."""

    def __init__(self, trainer: ClientTrainer, data: FederatedData,
                 cfg: FedConfig, donate: bool = True,
                 pallas_agg: bool = False):
        self.trainer = trainer
        self.data = data
        self.cfg = cfg
        # opt-in fused aggregation kernel (fedml_tpu/ops); the default XLA
        # tree-mean is already fused well — the kernel wins when the whole
        # stack is flattened anyway (robust pipeline) or on very many leaves
        self.pallas_agg = pallas_agg
        self.donate = donate
        self.sampler = ClientSampler.for_data(data, cfg)
        # donate BOTH the variables and the server state (FedOpt's adam
        # moments are 2x params — donating avoids an HBM copy per round)
        self.round_fn = jax.jit(
            self._round, donate_argnums=(0, 1) if donate else ())
        self.eval_fn = jax.jit(self.trainer.evaluate)
        # upload eval shards once; evaluate() then runs fully device-side
        self._eval_shards = {
            "train": jax.tree.map(jnp.asarray, data.train_global),
            "test": jax.tree.map(jnp.asarray, data.test_global),
        }
        self._local_eval_fn = None    # built lazily by evaluate_local
        self._local_eval_shards = {}
        self.metrics_history: list[dict] = []

    # ---- server state (FedOpt's persistent optimizer etc.) ----------------
    def server_init(self, variables: Pytree) -> Pytree:
        return ()

    # ---- aggregation customization point (FedOpt/robust override) --------
    def aggregate(self, stacked_variables: Pytree, weights: jax.Array,
                  global_variables: Pytree, server_state: Pytree,
                  rng: jax.Array) -> tuple[Pytree, Pytree]:
        """Sample-weighted mean over ALL variable collections (params and
        batch_stats alike), matching the reference's iteration over every
        state_dict key (FedAVGAggregator.py:74-81)."""
        if self.pallas_agg:
            from fedml_tpu.ops import weighted_mean_pallas
            return weighted_mean_pallas(stacked_variables, weights), server_state
        return tree_weighted_mean(stacked_variables, weights), server_state

    # ---- one federated round, fully jitted -------------------------------
    def _round(self, variables: Pytree, server_state: Pytree, cohort: dict,
               rng: jax.Array):
        K = cohort["mask"].shape[0]
        rng, agg_rng = jax.random.split(rng)
        client_rngs = jax.random.split(rng, K)
        global_params = variables["params"] if self.trainer.prox_mu > 0 else None

        def one_client(shard, crng):
            return self.trainer.local_train(
                variables, shard, crng, self.cfg.epochs,
                global_params=global_params)

        stacked_vars, losses, ns = jax.vmap(one_client)(cohort, client_rngs)
        new_variables, server_state = self.aggregate(
            stacked_vars, ns, variables, server_state, agg_rng)
        train_loss = jnp.sum(losses * ns) / jnp.sum(ns)
        return new_variables, server_state, {"train_loss": train_loss}

    # ---- driver loop ------------------------------------------------------
    def init_variables(self, rng: Optional[jax.Array] = None) -> Pytree:
        rng = rng if rng is not None else jax.random.PRNGKey(self.cfg.seed)
        sample = jnp.asarray(self.data.client_shards["x"][0, 0])
        return self.trainer.init(rng, sample)

    # ---- driver-loop hooks (mesh engines override) ------------------------
    def _prepare_variables(self, variables: Pytree) -> Pytree:
        """Post-init/post-restore placement hook (mesh: replicate)."""
        return variables

    def _round_args(self, round_idx: int) -> tuple:
        """Per-round positional args for round_fn between server_state and
        the rng (mesh: the resident device stack + padded cohort ids)."""
        client_ids = self.sampler.sample(round_idx)
        cohort, _ = self.data.cohort(client_ids)
        return (cohort,)

    def run(self, variables: Optional[Pytree] = None,
            rounds: Optional[int] = None, logger=None, ckpt=None,
            ckpt_every: int = 0, resume: bool = False) -> Pytree:
        """The reference's train() loop (fedavg_api.py:40-81), plus the
        round-level checkpoint/resume the reference lacks (SURVEY.md §5):
        `ckpt` is a utils.checkpoint.FedCheckpointManager; with `resume`
        the run continues bitwise-identically (per-round rngs are
        fold_in(round_idx), the sampler reseeds per round).  This one loop
        drives the vmap-simulation and all mesh engines via the
        _prepare_variables/_round_args hooks."""
        cfg = self.cfg
        variables = variables if variables is not None else self.init_variables()
        variables = self._prepare_variables(variables)
        server_state = self.server_init(variables)
        rng_base = jax.random.PRNGKey(cfg.seed + 1)
        rounds = rounds if rounds is not None else cfg.comm_round
        self._rounds_limit = rounds       # lets _round_args bound prefetch
        start = 0
        if ckpt is not None and resume and ckpt.latest_round() is not None:
            start, variables, server_state = ckpt.restore(
                variables, server_state)
            start += 1
            variables = self._prepare_variables(variables)
            # restored state arrives committed to one local device; mesh
            # engines re-replicate it (a multi-process mesh jit rejects
            # the mixed placement outright)
            server_state = self._prepare_server_state(server_state)
            log.info("resumed from round %d", start - 1)
        # observability (fedml_tpu/obs; all no-ops unless --obs_dir):
        # each round gets a span + an optional deadline watchdog (a
        # flight-recorder dump fires if the round overruns
        # cfg.round_deadline_s — the artifact tools/isolate_hang.py
        # collects); an unhandled error dumps the ring before re-raising
        deadline_s = getattr(cfg, "round_deadline_s", None)
        engine_name = type(self).__name__
        try:
            for round_idx in range(start, rounds):
                t0 = time.time()
                round_rng = jax.random.fold_in(rng_base, round_idx)
                with obs.deadline(f"round{round_idx}", deadline_s), \
                        obs.span("round", round=round_idx,
                                 engine=engine_name):
                    variables, server_state, m = self.round_fn(
                        variables, server_state,
                        *self._round_args(round_idx), round_rng)
                if (round_idx % cfg.frequency_of_the_test == 0
                        or round_idx == rounds - 1):
                    with obs.span("eval", round=round_idx):
                        stats = self.evaluate(variables)
                    stats.update(round=round_idx,
                                 train_loss=float(m["train_loss"]),
                                 round_time=time.time() - t0)
                    self.metrics_history.append(stats)
                    if logger is not None:
                        logger.log(stats, step=round_idx)
                    log.info("round %d: %s", round_idx, stats)
                    if obs.enabled():       # live/peak HBM per eval round
                        obs.sample_device_memory()
                if ckpt is not None and ckpt_every and \
                        (round_idx + 1) % ckpt_every == 0:
                    with obs.span("checkpoint", round=round_idx):
                        ckpt.save(round_idx, variables, server_state)
        except Exception as e:
            obs.dump_flight(f"engine_error:{engine_name}: {e!r}")
            raise
        return variables

    def evaluate(self, variables: Pytree) -> dict:
        """Server-side eval on global train/test shards
        (FedAVGAggregator.test_on_server_for_all_clients, :110-164)."""
        out = {}
        for split, shard in self._eval_shards.items():
            sums = self.eval_fn(variables, shard)
            cnt = float(sums["count"])
            out[f"{split}_acc"] = float(sums["correct"]) / max(cnt, 1.0)
            out[f"{split}_loss"] = float(sums["loss_sum"]) / max(cnt, 1.0)
        if (self.cfg.local_test_eval
                and self.data.test_client_shards is not None
                and not getattr(self, "streaming", False)):
            # streaming exists because the per-client stack does NOT fit
            # in HBM — never auto-materialize it for eval there.
            # --no_local_test_eval opts out of the cost entirely; mesh
            # engines shard the uploaded test stack (_upload_eval_stack)
            out.update(self.evaluate_local(variables))
        return out

    def _local_eval_transform(self, shard: dict) -> dict:
        """Per-client shard hook inside evaluate_local's vmap (mesh
        engines restore flat_stack x here; identity for this engine)."""
        return shard

    def _prepare_server_state(self, server_state):
        """Device placement for a checkpoint-restored server_state (mesh
        engines replicate over the mesh; identity here)."""
        return server_state

    def _upload_eval_stack(self, shards):
        """Device placement for the [C,...] per-client eval stack (mesh
        engines override to shard the client axis — evaluate_local must
        not concentrate a stack on one device that training had to
        shard to fit)."""
        return jax.tree.map(jnp.asarray, shards)

    def evaluate_local(self, variables: Pytree, split: str = "test") -> dict:
        """Eval on every client's OWN shard — the reference's
        _local_test_on_all_clients (fedavg_api.py:117-213): per-client
        correct/total sums aggregated into one weighted accuracy, for the
        clients' test shards (split="test", needs the dataset's natural
        per-client test split) or train shards (split="train", always
        available — the reference's local Train/Acc).  With cfg.ci the
        eval truncates to the first client (the reference's --ci 1 CPU-CI
        mode, fedavg_api.py:157-162)."""
        if split not in ("train", "test"):
            raise ValueError(f"split must be 'train' or 'test', got "
                             f"{split!r}")
        if split == "test" and self.data.test_client_shards is None:
            raise ValueError("this dataset has no per-client test shards")
        if getattr(self, "streaming", False):
            raise ValueError("streaming engines keep the client stack on "
                             "host; evaluate_local would materialize it "
                             "in HBM")
        if self._local_eval_fn is None:
            # _local_eval_transform: mesh engines restore flat_stack x
            # in-program before the per-client eval (identity here)
            self._local_eval_fn = jax.jit(jax.vmap(
                lambda v, s: self.trainer.evaluate(
                    v, self._local_eval_transform(s)),
                in_axes=(None, 0)))
        if split not in self._local_eval_shards:
            if split == "train" and not self.cfg.ci:
                # a train stack is already device-resident for cohorts —
                # reuse it rather than holding a second HBM copy: the mesh
                # engine's padded sharded stack (zero-weight pad lanes
                # have mask 0, so they add nothing to the sums), else the
                # plain engine's device_shards cache.  Only a [C, ...]
                # stack qualifies (the hierarchical engine keeps a
                # silo-major [S, C/S, ...] layout — fall through to a
                # fresh upload there).
                resident = getattr(self, "_stack", None)
                if (resident is not None
                        and resident["mask"].ndim
                        != np.asarray(self.data.client_shards["mask"]).ndim):
                    resident = None
                self._local_eval_shards[split] = (
                    resident if resident is not None
                    else self.data.device_shards()[0])
            else:
                # upload once (ci-truncated if set), like _eval_shards
                shards = (self.data.test_client_shards if split == "test"
                          else self.data.client_shards)
                if self.cfg.ci:
                    shards = jax.tree.map(lambda a: a[:1], shards)
                self._local_eval_shards[split] = \
                    self._upload_eval_stack(shards)
        sums = self._local_eval_fn(variables,
                                   self._local_eval_shards[split])
        cnt = float(jnp.sum(sums["count"]))
        return {
            f"local_{split}_acc":
                float(jnp.sum(sums["correct"])) / max(cnt, 1.0),
            f"local_{split}_loss":
                float(jnp.sum(sums["loss_sum"])) / max(cnt, 1.0),
        }
