from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.algorithms.fedopt import FedOptEngine
from fedml_tpu.algorithms.fedprox import FedProxEngine
from fedml_tpu.algorithms.fednova import FedNovaEngine
from fedml_tpu.algorithms.fedavg_robust import FedAvgRobustEngine
from fedml_tpu.algorithms.hierarchical import HierarchicalFedAvgEngine
from fedml_tpu.algorithms.decentralized import DecentralizedGossipEngine
from fedml_tpu.algorithms.fednas import FedNASSearchEngine

__all__ = [
    "FedAvgEngine", "FedOptEngine", "FedProxEngine", "FedNovaEngine",
    "FedAvgRobustEngine", "HierarchicalFedAvgEngine",
    "DecentralizedGossipEngine", "FedNASSearchEngine",
]
