"""FedProx — FedAvg + proximal term (mu/2)||w - w_global||^2 in the client
loss.

Reference: fedml_api/distributed/fedprox/ mirrors FedAvg file-for-file; the
prox term lives in the client trainer config.  Here it is literally the
FedAvg engine with the trainer's prox_mu enabled — the ClientTrainer adds the
term inside the jitted loss (core/trainer.py), so the whole-round program is
unchanged in structure.
"""
from __future__ import annotations

import copy

from fedml_tpu.algorithms.fedavg import FedAvgEngine


class FedProxEngine(FedAvgEngine):
    def __init__(self, trainer, data, cfg, **kw):
        if trainer.prox_mu <= 0.0:
            # never mutate the caller's trainer (it may be shared with a
            # plain-FedAvg engine whose jit traces would pick up the mu)
            trainer = copy.copy(trainer)
            trainer.prox_mu = cfg.prox_mu if cfg.prox_mu > 0 else 0.01
        super().__init__(trainer, data, cfg, **kw)
