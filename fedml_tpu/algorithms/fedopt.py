"""FedOpt — server optimizer applied to the pseudo-gradient.

Reference (fedml_api/distributed/fedopt/FedOptAggregator.py:70-123 and
standalone fedopt_api.py:122-152): weighted-average the client models, form
pseudo-gradient g = w_global - w_avg, install it as .grad, and step a torch
server optimizer whose state persists across rounds.

TPU-native: the server optimizer is an optax transformation and its state is
part of the jitted round's carried server_state — no reflection over
optimizer subclasses (OptRepo, optrepo.py:11-39) needed: optax names map
directly.  FedAvgM = sgd(momentum), FedAdam/FedYogi/FedAdagrad = the matching
optax transforms.
"""
from __future__ import annotations

from typing import Any

import optax

from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.core.pytree import tree_weighted_mean, tree_sub

Pytree = Any


def make_server_optimizer(name: str, lr: float, momentum: float = 0.9):
    name = name.lower()
    if name in ("sgd", "fedavgm"):
        return optax.sgd(lr, momentum=momentum if momentum else None)
    if name in ("adam", "fedadam"):
        return optax.adam(lr, b1=0.9, b2=0.99, eps=1e-3)
    if name in ("yogi", "fedyogi"):
        return optax.yogi(lr)
    if name in ("adagrad", "fedadagrad"):
        return optax.adagrad(lr)
    raise ValueError(f"unknown server optimizer {name!r}")


class FedOptEngine(FedAvgEngine):
    def __init__(self, trainer, data, cfg, **kw):
        self.server_tx = make_server_optimizer(
            cfg.server_optimizer, cfg.server_lr, cfg.server_momentum)
        super().__init__(trainer, data, cfg, **kw)

    def server_init(self, variables: Pytree) -> Pytree:
        return self.server_tx.init(variables["params"])

    def aggregate(self, stacked_variables, weights, global_variables,
                  server_state, rng):
        avg = tree_weighted_mean(stacked_variables, weights)
        # pseudo-gradient: optax minimizes, so g = w_global - w_avg moves
        # params toward the client average at server_lr=1 (reference
        # set_model_global_grads, FedOptAggregator.py:109-123).
        pseudo_grad = tree_sub(global_variables["params"], avg["params"])
        updates, server_state = self.server_tx.update(
            pseudo_grad, server_state, global_variables["params"])
        new_params = optax.apply_updates(global_variables["params"], updates)
        new_vars = dict(avg)      # non-param collections (BN stats): averaged
        new_vars["params"] = new_params
        return new_vars, server_state
