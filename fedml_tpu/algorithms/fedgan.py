"""FedGAN — federated GAN training (generator/discriminator FedAvg).

Parity: fedml_api/distributed/fedgan/ (FedGANAggregator.py:1-164,
MyModelTrainer.py:1-100) — the FedAvg skeleton with a (G, D) model pair:
each client runs local adversarial steps, the server sample-weight-averages
both nets.

TPU-native: one jitted round — vmap over the cohort of (G, D) pairs; the
local loop is a lax.scan of alternating D/G steps; aggregation is the same
weighted tree-mean (a psum on a mesh).
"""
from __future__ import annotations

import functools
import logging
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.pytree import tree_select, tree_weighted_mean
from fedml_tpu.core.sampling import ClientSampler
from fedml_tpu.core.trainer import make_optimizer
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.utils.config import FedConfig

log = logging.getLogger(__name__)
Pytree = Any


def _bce_logits(logits, target_ones, mask):
    y = jnp.ones_like(logits) if target_ones else jnp.zeros_like(logits)
    ls = optax.sigmoid_binary_cross_entropy(logits, y)
    m = mask.astype(ls.dtype)
    return jnp.sum(ls * m) / jnp.maximum(jnp.sum(m), 1.0)


class FedGANEngine:
    def __init__(self, generator, discriminator, data: FederatedData,
                 cfg: FedConfig, latent_dim: int = 64):
        self.gen = generator
        self.disc = discriminator
        self.data = data
        self.cfg = cfg
        self.latent_dim = latent_dim
        self.g_tx = make_optimizer("adam", cfg.lr)
        self.d_tx = make_optimizer("adam", cfg.lr)
        self.sampler = ClientSampler.for_data(data, cfg)
        self.round_fn = jax.jit(self._round)
        self.metrics_history: list[dict] = []

    def init_params(self, rng: Optional[jax.Array] = None):
        rng = rng if rng is not None else jax.random.PRNGKey(self.cfg.seed)
        rg, rd = jax.random.split(rng)
        z = jnp.zeros((1, self.latent_dim))
        x = jnp.asarray(self.data.client_shards["x"][0, 0])
        gp = self.gen.init(rg, z)["params"]
        dp = self.disc.init(rd, x)["params"]
        return {"gen": gp, "disc": dp}

    def _local_train(self, params, shard, rng):
        """Alternating D/G steps over the client's batches × epochs
        (MyModelTrainer.train's inner loop)."""
        g_opt = self.g_tx.init(params["gen"])
        d_opt = self.d_tx.init(params["disc"])

        def batch_step(carry, batch):
            p, go, do, rng = carry
            rng, zk1, zk2 = jax.random.split(rng, 3)
            bs = batch["x"].shape[0]
            m = batch["mask"]

            # D step: real up, fake down
            def d_loss(dp):
                z = jax.random.normal(zk1, (bs, self.latent_dim))
                fake = self.gen.apply({"params": p["gen"]}, z)
                real_logits = self.disc.apply({"params": dp}, batch["x"])
                fake_logits = self.disc.apply({"params": dp}, fake)
                return (_bce_logits(real_logits, True, m)
                        + _bce_logits(fake_logits, False, m))

            dl, dg = jax.value_and_grad(d_loss)(p["disc"])
            du, do2 = self.d_tx.update(dg, do, p["disc"])
            new_disc = optax.apply_updates(p["disc"], du)

            # G step: fool the (updated) D
            def g_loss(gp):
                z = jax.random.normal(zk2, (bs, self.latent_dim))
                fake = self.gen.apply({"params": gp}, z)
                return _bce_logits(
                    self.disc.apply({"params": new_disc}, fake), True, m)

            gl, gg = jax.value_and_grad(g_loss)(p["gen"])
            gu, go2 = self.g_tx.update(gg, go, p["gen"])
            keep = functools.partial(tree_select, jnp.sum(m) > 0)
            new_p = {"gen": keep(optax.apply_updates(p["gen"], gu), p["gen"]),
                     "disc": keep(new_disc, p["disc"])}
            return (new_p, keep(go2, go), keep(do2, do), rng), (dl, gl)

        def epoch(carry, _):
            carry, (dls, gls) = jax.lax.scan(batch_step, carry, shard)
            return carry, (dls.mean(), gls.mean())

        (p, _, _, _), (dls, gls) = jax.lax.scan(
            epoch, (params, g_opt, d_opt, rng), None, length=self.cfg.epochs)
        return p, dls.mean(), gls.mean(), jnp.sum(shard["mask"])

    def _round(self, params, cohort, rng):
        K = cohort["mask"].shape[0]
        rngs = jax.random.split(rng, K)
        ps, dl, gl, ns = jax.vmap(
            lambda s, r: self._local_train(params, s, r))(cohort, rngs)
        new_params = tree_weighted_mean(ps, ns)   # G and D both averaged
        return new_params, {"d_loss": jnp.mean(dl), "g_loss": jnp.mean(gl)}

    def run(self, rounds: Optional[int] = None) -> Pytree:
        cfg = self.cfg
        params = self.init_params()
        rng = jax.random.PRNGKey(cfg.seed + 1)
        rounds = rounds if rounds is not None else cfg.comm_round
        for round_idx in range(rounds):
            t0 = time.time()
            ids = self.sampler.sample(round_idx)
            cohort, _ = self.data.cohort(ids)
            rng, r = jax.random.split(rng)
            params, m = self.round_fn(params, cohort, r)
            stats = {"round": round_idx, "d_loss": float(m["d_loss"]),
                     "g_loss": float(m["g_loss"]),
                     "round_time": time.time() - t0}
            self.metrics_history.append(stats)
            log.info("fedgan round %d: %s", round_idx, stats)
        return params

    def generate(self, params, n: int, rng: Optional[jax.Array] = None):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        z = jax.random.normal(rng, (n, self.latent_dim))
        return self.gen.apply({"params": params["gen"]}, z)
