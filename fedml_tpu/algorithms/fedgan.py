"""FedGAN — federated GAN training (generator/discriminator FedAvg).

Parity: fedml_api/distributed/fedgan/ (FedGANAggregator.py:1-164,
MyModelTrainer.py:1-100) — the FedAvg skeleton with a (G, D) model pair:
each client runs local adversarial steps, the server sample-weight-averages
both nets.

TPU-native: one jitted round — vmap over the cohort of (G, D) pairs; the
local loop is a lax.scan of alternating D/G steps; aggregation is the same
weighted tree-mean (a psum on a mesh).
"""
from __future__ import annotations

import functools
import logging
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.pytree import (tree_select, tree_vary_noop,
                                   tree_weighted_mean)
from fedml_tpu.core.sampling import ClientSampler
from fedml_tpu.core.trainer import make_optimizer
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.utils.config import FedConfig

log = logging.getLogger(__name__)
Pytree = Any


def _bce_logits(logits, target_ones, mask):
    y = jnp.ones_like(logits) if target_ones else jnp.zeros_like(logits)
    ls = optax.sigmoid_binary_cross_entropy(logits, y)
    m = mask.astype(ls.dtype)
    return jnp.sum(ls * m) / jnp.maximum(jnp.sum(m), 1.0)


class FedGANEngine:
    def __init__(self, generator, discriminator, data: FederatedData,
                 cfg: FedConfig, latent_dim: int = 64):
        self.gen = generator
        self.disc = discriminator
        self.data = data
        self.cfg = cfg
        self.latent_dim = latent_dim
        self.g_tx = make_optimizer("adam", cfg.lr)
        self.d_tx = make_optimizer("adam", cfg.lr)
        self.sampler = ClientSampler.for_data(data, cfg)
        self.round_fn = jax.jit(self._round)
        self.metrics_history: list[dict] = []

    def init_params(self, rng: Optional[jax.Array] = None):
        rng = rng if rng is not None else jax.random.PRNGKey(self.cfg.seed)
        rg, rd = jax.random.split(rng)
        z = jnp.zeros((1, self.latent_dim))
        x = jnp.asarray(self.data.client_shards["x"][0, 0])
        gp = self.gen.init(rg, z)["params"]
        dp = self.disc.init(rd, x)["params"]
        return {"gen": gp, "disc": dp}

    def _local_train(self, params, shard, rng):
        """Alternating D/G steps over the client's batches × epochs
        (MyModelTrainer.train's inner loop)."""
        # tree_vary_noop: shard_map vma alignment for the stateful adam
        # states (core/pytree.py)
        g_opt = tree_vary_noop(self.g_tx.init(params["gen"]), shard)
        d_opt = tree_vary_noop(self.d_tx.init(params["disc"]), shard)

        def batch_step(carry, batch):
            p, go, do, rng = carry
            rng, zk1, zk2 = jax.random.split(rng, 3)
            bs = batch["x"].shape[0]
            m = batch["mask"]

            # D step: real up, fake down
            def d_loss(dp):
                z = jax.random.normal(zk1, (bs, self.latent_dim))
                fake = self.gen.apply({"params": p["gen"]}, z)
                real_logits = self.disc.apply({"params": dp}, batch["x"])
                fake_logits = self.disc.apply({"params": dp}, fake)
                return (_bce_logits(real_logits, True, m)
                        + _bce_logits(fake_logits, False, m))

            dl, dg = jax.value_and_grad(d_loss)(p["disc"])
            du, do2 = self.d_tx.update(dg, do, p["disc"])
            new_disc = optax.apply_updates(p["disc"], du)

            # G step: fool the (updated) D
            def g_loss(gp):
                z = jax.random.normal(zk2, (bs, self.latent_dim))
                fake = self.gen.apply({"params": gp}, z)
                return _bce_logits(
                    self.disc.apply({"params": new_disc}, fake), True, m)

            gl, gg = jax.value_and_grad(g_loss)(p["gen"])
            gu, go2 = self.g_tx.update(gg, go, p["gen"])
            keep = functools.partial(tree_select, jnp.sum(m) > 0)
            new_p = {"gen": keep(optax.apply_updates(p["gen"], gu), p["gen"]),
                     "disc": keep(new_disc, p["disc"])}
            return (new_p, keep(go2, go), keep(do2, do), rng), (dl, gl)

        def epoch(carry, _):
            carry, (dls, gls) = jax.lax.scan(batch_step, carry, shard)
            return carry, (dls.mean(), gls.mean())

        (p, _, _, _), (dls, gls) = jax.lax.scan(
            epoch, (params, g_opt, d_opt, rng), None, length=self.cfg.epochs)
        return p, dls.mean(), gls.mean(), jnp.sum(shard["mask"])

    def _round(self, params, cohort, rng):
        K = cohort["mask"].shape[0]
        rngs = jax.random.split(rng, K)
        ps, dl, gl, ns = jax.vmap(
            lambda s, r: self._local_train(params, s, r))(cohort, rngs)
        new_params = tree_weighted_mean(ps, ns)   # G and D both averaged
        return new_params, {"d_loss": jnp.mean(dl), "g_loss": jnp.mean(gl)}

    def _round_args(self, round_idx: int) -> tuple:
        """Round inputs hook (the FedAvgEngine pattern): the mesh variant
        overrides this with the padded-cohort policy."""
        ids = self.sampler.sample(round_idx)
        cohort, _ = self.data.cohort(ids)
        return (cohort,)

    def run(self, rounds: Optional[int] = None) -> Pytree:
        cfg = self.cfg
        params = self.init_params()
        rng = jax.random.PRNGKey(cfg.seed + 1)
        rounds = rounds if rounds is not None else cfg.comm_round
        for round_idx in range(rounds):
            t0 = time.time()
            rng, r = jax.random.split(rng)
            params, m = self.round_fn(params, *self._round_args(round_idx),
                                      r)
            stats = {"round": round_idx, "d_loss": float(m["d_loss"]),
                     "g_loss": float(m["g_loss"]),
                     "round_time": time.time() - t0}
            self.metrics_history.append(stats)
            log.info("fedgan round %d: %s", round_idx, stats)
        return params

    def generate(self, params, n: int, rng: Optional[jax.Array] = None):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        z = jax.random.normal(rng, (n, self.latent_dim))
        return self.gen.apply({"params": params["gen"]}, z)


def make_mesh_fedgan_engine(generator, discriminator, data, cfg,
                            latent_dim: int = 64, mesh=None,
                            chunk: Optional[int] = None):
    """Mesh-sharded FedGAN: the cohort of (G, D) local adversarial
    trainings is sharded over a 1-D client mesh; both nets aggregate via
    one weighted psum each (the fedgan aggregation IS FedAvg over the
    pair, FedGANAggregator.py:1-164).  Factory keeps parallel/ out of
    this module's import graph for single-device users."""
    from jax.sharding import PartitionSpec as P

    from fedml_tpu.parallel.engine import pad_and_chunk
    from fedml_tpu.parallel.mesh import make_mesh, pvary_tree

    class MeshFedGANEngine(FedGANEngine):
        def __init__(self, generator, discriminator, data, cfg,
                     latent_dim=64, mesh=None, chunk=None):
            self.mesh = mesh if mesh is not None else make_mesh()
            self.n_shards = int(np.prod(list(self.mesh.shape.values())))
            self.chunk = chunk
            super().__init__(generator, discriminator, data, cfg,
                             latent_dim)
            self.round_fn = jax.jit(self._mesh_round)

        def _mesh_round(self, params, cohort, wmask, rng):
            mesh, axes = self.mesh, self.mesh.axis_names
            csh = P(axes)
            K = cohort["mask"].shape[0]
            rngs = jax.random.split(rng, K)

            def body(params, cohort, wmask, rngs):
                pv = pvary_tree(params, axes)
                ch_c, ch_w, ch_r = pad_and_chunk(cohort, wmask, rngs,
                                                 self.chunk or 8)

                def chunk_body(carry, xs):
                    num, den, dls, gls, cnt = carry
                    cs, cw, cr = xs
                    ps, dl, gl, ns = jax.vmap(
                        lambda s, r: self._local_train(pv, s, r))(cs, cr)
                    # engine-level pad lanes are masked by wmask; a lane's
                    # own weight is its sample count like the vmap engine
                    w = ns * cw
                    from fedml_tpu.parallel.engine import weighted_acc
                    num = jax.tree.map(weighted_acc(w), num, ps)
                    return (num, den + jnp.sum(w),
                            dls + jnp.sum(dl * cw), gls + jnp.sum(gl * cw),
                            cnt + jnp.sum(cw)), None

                zeros = pvary_tree(jax.tree.map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), params),
                    axes)
                zf = pvary_tree(jnp.float32(0), axes)
                (num, den, dls, gls, cnt), _ = jax.lax.scan(
                    chunk_body, (zeros, zf, zf, zf, zf),
                    (ch_c, ch_w, ch_r))
                num = jax.lax.psum(num, axes)
                den = jax.lax.psum(den, axes)
                new = jax.tree.map(
                    lambda s, ref: (s / den).astype(ref.dtype), num, params)
                cnt = jax.lax.psum(cnt, axes)
                dl = jax.lax.psum(dls, axes) / cnt
                gl = jax.lax.psum(gls, axes) / cnt
                return new, dl, gl

            new, dl, gl = jax.shard_map(
                body, mesh=mesh, in_specs=(P(), csh, csh, csh),
                out_specs=(P(), P(), P()))(params, cohort, wmask, rngs)
            return new, {"d_loss": dl, "g_loss": gl}

        def _round_args(self, round_idx: int) -> tuple:
            from fedml_tpu.parallel.engine import pad_ids
            ids, wmask = pad_ids(self.sampler.sample(round_idx),
                                 self.n_shards)
            cohort, _ = self.data.cohort(ids)
            return (cohort, jnp.asarray(wmask))

    return MeshFedGANEngine(generator, discriminator, data, cfg,
                            latent_dim=latent_dim, mesh=mesh, chunk=chunk)
