"""Byzantine-robust FedAvg.

Reference (fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py:
176-206 + fedml_core/robustness/robust_aggregation.py): per-client norm
-difference clipping before the weighted average, plus optional weak-DP
Gaussian noise on the aggregate.  Additional aggregation rules beyond the
reference (krum, multi-krum, coordinate-median, trimmed-mean) are provided since they
are pure pytree ops on the stacked client axis.

Attack simulation parity: the reference schedules Byzantine clients every
`attack_freq` rounds with poisoned data (FedAvgRobustAggregator.py:221-229);
here `attack_fn` lets tests inject arbitrary update corruption on selected
cohort slots inside the jitted round.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.robust import (add_weak_dp_noise, coordinate_median,
                                   default_multi_krum_m, krum_select,
                                   multi_krum_select, norm_diff_clip,
                                   trimmed_mean)


class FedAvgRobustEngine(FedAvgEngine):
    """defense: "norm_clip" (reference), "krum", "multi_krum", "median",
    "trimmed_mean"."""

    def __init__(self, trainer, data, cfg, defense: str = "norm_clip",
                 n_byzantine: int = 0, multi_krum_m: Optional[int] = None,
                 attack_fn: Optional[Callable] = None, **kw):
        self.defense = defense
        self.n_byzantine = n_byzantine
        self.multi_krum_m = default_multi_krum_m(
            min(cfg.client_num_per_round, data.client_num), n_byzantine,
            multi_krum_m)
        self.attack_fn = attack_fn
        super().__init__(trainer, data, cfg, **kw)

    def aggregate(self, stacked_variables, weights, global_variables,
                  server_state, rng):
        if self.attack_fn is not None:
            stacked_variables = self.attack_fn(stacked_variables)
        params = stacked_variables["params"]
        g = global_variables["params"]
        if self.defense == "norm_clip":
            if self.pallas_agg:
                from fedml_tpu.ops import robust_weighted_mean_pallas
                new_params = robust_weighted_mean_pallas(
                    params, weights, g, self.cfg.norm_bound)
            else:
                clipped = jax.vmap(
                    lambda p: norm_diff_clip(p, g, self.cfg.norm_bound))(params)
                new_params = tree_weighted_mean(clipped, weights)
            if self.cfg.stddev > 0:
                new_params = add_weak_dp_noise(new_params, rng, self.cfg.stddev)
        elif self.defense == "krum":
            i = krum_select(params, self.n_byzantine)
            new_params = jax.tree.map(lambda x: x[i], params)
        elif self.defense == "multi_krum":
            idx = multi_krum_select(params, self.n_byzantine,
                                    self.multi_krum_m)
            new_params = jax.tree.map(
                lambda x: jnp.mean(x[idx].astype(jnp.float32),
                                   axis=0).astype(x.dtype), params)
        elif self.defense == "median":
            new_params = coordinate_median(params)
        elif self.defense == "trimmed_mean":
            new_params = trimmed_mean(params, max(self.n_byzantine, 1))
        else:
            raise ValueError(self.defense)
        new_vars = {k: tree_weighted_mean(v, weights)
                    for k, v in stacked_variables.items() if k != "params"}
        new_vars["params"] = new_params
        return new_vars, server_state

    def evaluate_backdoor(self, variables, poison_shard) -> dict:
        """Backdoor success rate on a triggered test set (the reference's
        poisoned-testset eval, FedAvgRobustAggregator.test :14-111)."""
        shard = jax.tree.map(jnp.asarray, poison_shard)
        sums = self.eval_fn(variables, shard)
        n = max(float(sums["count"]), 1.0)
        return {"backdoor_acc": float(sums["correct"]) / n,
                "backdoor_loss": float(sums["loss_sum"]) / n}
