"""Decentralized gossip learning (server-less).

Reference: fedml_api/distributed/decentralized_framework/ (neighbor
round-robin skeleton) and fedml_api/standalone/decentralized/ (DSGD +
push-sum over a TopologyManager graph for online regret minimization).

TPU-native: every client keeps its own model; the stacked client axis holds
all of them.  One round = local SGD for every client (vmap) followed by the
gossip mixing step  W x  where W is the topology's row-normalized mixing
matrix — a single [C,C]x[C,P] matmul on the MXU instead of C point-to-point
messages.  On a mesh, ring topologies lower to `lax.ppermute`
(parallel/engine.py).  Push-sum (directed graphs) carries the usual scalar
weight alongside the params and de-biases by it.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from fedml_tpu.core.topology import BaseTopologyManager
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.utils.config import FedConfig


class DecentralizedGossipEngine:
    """DSGD (symmetric W) or push-sum (asymmetric/directed W)."""

    def __init__(self, trainer: ClientTrainer, data: FederatedData,
                 cfg: FedConfig, topology: BaseTopologyManager,
                 push_sum: bool = False):
        self.trainer = trainer
        self.data = data
        self.cfg = cfg
        self.W = jnp.asarray(topology.mixing_matrix(), jnp.float32)
        self.push_sum = push_sum
        self.round_fn = jax.jit(self._round, donate_argnums=(0,))
        self.eval_fn = jax.jit(self.trainer.evaluate)
        self._test_shard = jax.tree.map(jnp.asarray, data.test_global)
        self.metrics_history: list[dict] = []

    def init_states(self, rng: Optional[jax.Array] = None):
        rng = rng if rng is not None else jax.random.PRNGKey(self.cfg.seed)
        sample = jnp.asarray(self.data.client_shards["x"][0, 0])
        v0 = self.trainer.init(rng, sample)
        C = self.data.client_num
        stacked = jax.tree.map(lambda x: jnp.broadcast_to(x, (C,) + x.shape), v0)
        weights = jnp.ones((C,), jnp.float32)   # push-sum mass
        return stacked, weights

    def _mix(self, stacked, weights):
        def mix_leaf(leaf):
            flat = leaf.reshape(leaf.shape[0], -1)
            return (self.W @ flat).reshape(leaf.shape)
        mixed = jax.tree.map(mix_leaf, stacked)
        new_w = self.W @ weights
        return mixed, new_w

    def _round(self, stacked_vars, weights, cohort, rng):
        C = cohort["mask"].shape[0]
        client_rngs = jax.random.split(rng, C)
        if self.push_sum:
            # de-bias before local computation: z = x / w
            debiased = jax.tree.map(
                lambda x: x / weights.reshape((-1,) + (1,) * (x.ndim - 1)),
                stacked_vars)
        else:
            debiased = stacked_vars
        new_vars, losses, ns = jax.vmap(
            lambda v, sh, r: self.trainer.local_train(v, sh, r, self.cfg.epochs)
        )(debiased, cohort, client_rngs)
        if self.push_sum:
            new_vars = jax.tree.map(
                lambda x: x * weights.reshape((-1,) + (1,) * (x.ndim - 1)),
                new_vars)
        mixed, new_weights = self._mix(new_vars, weights)
        train_loss = jnp.sum(losses * ns) / jnp.sum(ns)
        return mixed, new_weights, {"train_loss": train_loss}

    def run(self, rounds: Optional[int] = None):
        stacked, weights = self.init_states()
        rng = jax.random.PRNGKey(self.cfg.seed + 1)
        cohort, _ = self.data.device_shards()
        rounds = rounds if rounds is not None else self.cfg.comm_round
        for round_idx in range(rounds):
            rng, rrng = jax.random.split(rng)
            stacked, weights, m = self.round_fn(stacked, weights, cohort, rrng)
            if round_idx % self.cfg.frequency_of_the_test == 0 or round_idx == rounds - 1:
                stats = self.evaluate(stacked, weights)
                stats.update(round=round_idx, train_loss=float(m["train_loss"]))
                self.metrics_history.append(stats)
        return stacked, weights

    def evaluate(self, stacked, weights) -> dict:
        """Evaluate the consensus (mean, de-biased for push-sum) model."""
        if self.push_sum:
            stacked = jax.tree.map(
                lambda x: x / weights.reshape((-1,) + (1,) * (x.ndim - 1)),
                stacked)
        mean_vars = jax.tree.map(lambda x: jnp.mean(x, axis=0), stacked)
        sums = self.eval_fn(mean_vars, self._test_shard)
        cnt = max(float(sums["count"]), 1.0)
        return {"test_acc": float(sums["correct"]) / cnt,
                "test_loss": float(sums["loss_sum"]) / cnt}
