"""SplitNN — split learning across a client/server model cut.

Parity: fedml_api/distributed/split_nn/ (client.py:24-35, server.py:40-72,
SplitNNAPI.py) — the client net computes activations, the server net
computes logits+loss and returns activation gradients; clients take turns
round-robin (`active_node` rotation, server.py:69-72). The reference
crosses an MPI process boundary TWICE PER MINIBATCH (SURVEY.md §3.4) — its
comm stress test.

TPU-native: when both halves live in the mesh, the "split" is structural
(two flax modules) and the per-batch boundary is function composition under
one jit — XLA fuses straight through; the activation/gradient round-trip
costs nothing.  For genuinely remote clients, `SplitNNServerManager` /
`SplitNNClientManager` (comm/split_messaging.py) carry the same per-batch
protocol over the message layer.
"""
from __future__ import annotations

import functools
import logging
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.pytree import tree_select
from fedml_tpu.core.trainer import (make_optimizer, masked_accuracy_sums,
                                    masked_cross_entropy)
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.utils.config import FedConfig

log = logging.getLogger(__name__)
Pytree = Any


class SplitNNEngine:
    """Round-robin split training: client k trains for `epochs` with its
    lower-net params; the server upper-net params persist and are trained on
    every client's traffic (the reference's SplitNN semantics)."""

    def __init__(self, client_model, server_model, data: FederatedData,
                 cfg: FedConfig):
        self.client_model = client_model
        self.server_model = server_model
        self.data = data
        self.cfg = cfg
        self.client_tx = make_optimizer(cfg.client_optimizer, cfg.lr,
                                        cfg.momentum, cfg.wd)
        self.server_tx = make_optimizer(cfg.client_optimizer, cfg.lr,
                                        cfg.momentum, cfg.wd)
        self._fit_client = jax.jit(self._client_phase)
        self._eval = jax.jit(self._eval_sums)
        self.metrics_history: list[dict] = []

    # -- init ---------------------------------------------------------------
    def init_params(self, rng: Optional[jax.Array] = None):
        rng = rng if rng is not None else jax.random.PRNGKey(self.cfg.seed)
        r1, r2 = jax.random.split(rng)
        x = jnp.asarray(self.data.client_shards["x"][0, 0])
        cp = self.client_model.init(r1, x)["params"]
        acts = self.client_model.apply({"params": cp}, x)
        sp = self.server_model.init(r2, acts)["params"]
        return cp, sp

    # -- the split step ------------------------------------------------------
    def _loss(self, client_params, server_params, batch):
        # forward crosses the cut: acts = f_client(x); logits = f_server(acts)
        # (client.py:24-31 'forward_pass' + server.py:40-55). Under jit the
        # cut is invisible to XLA; grads to BOTH halves come from one
        # backward pass (the reference ships acts.grad back by hand,
        # server.py:57-60).
        acts = self.client_model.apply({"params": client_params}, batch["x"])
        logits = self.server_model.apply({"params": server_params}, acts)
        return masked_cross_entropy(logits, batch["y"], batch["mask"])

    def _client_phase(self, client_params, server_params, shard):
        """One client's `epochs` over its shard; both halves update per
        batch (scan over batches x epochs)."""
        c_opt = self.client_tx.init(client_params)
        s_opt = self.server_tx.init(server_params)

        def batch_step(carry, batch):
            cp, sp, co, so = carry
            loss, (cg, sg) = jax.value_and_grad(
                lambda p: self._loss(p[0], p[1], batch))((cp, sp))
            has = jnp.sum(batch["mask"]) > 0
            cu, co2 = self.client_tx.update(cg, co, cp)
            su, so2 = self.server_tx.update(sg, so, sp)
            keep = functools.partial(tree_select, has)
            cp2 = keep(optax.apply_updates(cp, cu), cp)
            sp2 = keep(optax.apply_updates(sp, su), sp)
            return (cp2, sp2, keep(co2, co), keep(so2, so)), loss

        def epoch(carry, _):
            carry, losses = jax.lax.scan(batch_step, carry, shard)
            return carry, losses.mean()

        (cp, sp, _, _), losses = jax.lax.scan(
            epoch, (client_params, server_params, c_opt, s_opt), None,
            length=self.cfg.epochs)
        return cp, sp, losses.mean()

    # -- driver --------------------------------------------------------------
    def run(self, rounds: Optional[int] = None):
        cfg = self.cfg
        client_params, server_params = self.init_params()
        # every client keeps its own lower-net weights (not averaged — split
        # learning semantics, unlike FedAvg)
        per_client = [client_params] * self.data.client_num
        rounds = rounds if rounds is not None else cfg.comm_round
        shards, _ = self.data.device_shards()
        for round_idx in range(rounds):
            t0 = time.time()
            losses = []
            for cid in range(self.data.client_num):   # active_node rotation
                shard = jax.tree.map(lambda a, c=cid: a[c], shards)
                cp, server_params, loss = self._fit_client(
                    per_client[cid], server_params, shard)
                per_client[cid] = cp
                losses.append(float(loss))
            if (round_idx % cfg.frequency_of_the_test == 0
                    or round_idx == rounds - 1):
                stats = self.evaluate(per_client[0], server_params)
                stats.update(round=round_idx,
                             train_loss=float(np.mean(losses)),
                             round_time=time.time() - t0)
                self.metrics_history.append(stats)
                log.info("splitnn round %d: %s", round_idx, stats)
        return per_client, server_params

    def _eval_sums(self, cp, sp, shard):
        def one(batch):
            acts = self.client_model.apply({"params": cp}, batch["x"])
            logits = self.server_model.apply({"params": sp}, acts)
            return masked_accuracy_sums(logits, batch["y"], batch["mask"])
        correct, count = jax.vmap(one)(shard)
        return correct.sum(), count.sum()

    def evaluate(self, client_params, server_params) -> dict:
        shard = jax.tree.map(jnp.asarray, self.data.test_global)
        correct, count = self._eval(client_params, server_params, shard)
        return {"test_acc": float(correct) / max(float(count), 1.0)}
