"""FedSeg — federated semantic segmentation.

Parity: fedml_api/distributed/fedseg/ (FedSegAggregator.py:1-240,
MyModelTrainer.py, utils.py Evaluator) — the FedAvg skeleton with
pixel-wise CE and IoU/accuracy evaluation via a confusion matrix.

The engine reuses FedAvgEngine wholesale (aggregation is unchanged);
only evaluation differs: per-class IoU from a jitted confusion matrix
(core/seg_metrics.py) tracked by an EvaluationMetricsKeeper.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.core.trainer import broadcast_mask
from fedml_tpu.core.seg_metrics import (EvaluationMetricsKeeper,
                                        confusion_matrix,
                                        frequency_weighted_iou, mean_iou,
                                        pixel_accuracy, pixel_accuracy_class)

log = logging.getLogger(__name__)


class SegEvalMixin:
    """Segmentation eval (confusion-matrix IoU/accuracy + metrics keeper)
    shared by the single-device and mesh FedSeg engines.  Replaces the
    classification `evaluate` of whichever FedAvg engine it is mixed
    over."""

    def _init_seg_eval(self):
        self.metrics_keeper = EvaluationMetricsKeeper()
        self._cm_fn = jax.jit(self._shard_confusion)

    def _shard_confusion(self, variables, shard):
        params = variables["params"]
        rest = {k: v for k, v in variables.items() if k != "params"}
        C = self.data.class_num

        def one(batch):
            logits = self.trainer.model.apply(
                {"params": params, **rest}, batch["x"], train=False)
            pred = jnp.argmax(logits, axis=-1)
            m = broadcast_mask(batch["mask"], batch["y"])
            return confusion_matrix(pred, batch["y"], m, C)

        return jax.vmap(one)(shard).sum(axis=0)

    def evaluate(self, variables) -> dict:
        out = {}
        for split, shard in self._eval_shards.items():
            cm = np.asarray(self._cm_fn(variables, shard))
            out[f"{split}_acc"] = pixel_accuracy(cm)
            out[f"{split}_acc_class"] = pixel_accuracy_class(cm)
            out[f"{split}_mIoU"] = mean_iou(cm)
            out[f"{split}_FWIoU"] = frequency_weighted_iou(cm)
        self.metrics_keeper.update(len(self.metrics_history), out)
        return out


class FedSegEngine(SegEvalMixin, FedAvgEngine):
    """FedAvg with segmentation eval. The trainer must be built with
    has_time_axis=True so the per-sample mask broadcasts over H,W."""

    def __init__(self, trainer, data, cfg, **kw):
        super().__init__(trainer, data, cfg, **kw)
        self._init_seg_eval()


def make_mesh_fedseg_engine(trainer, data, cfg, mesh=None, **kw):
    """Mesh-sharded FedSeg: the training round IS MeshFedAvgEngine's (the
    fedseg aggregation is unchanged FedAvg, FedSegAggregator.py:1-240);
    only eval differs, supplied by SegEvalMixin.  Built via a factory to
    keep parallel/ out of this module's import graph for single-device
    users."""
    from fedml_tpu.parallel import MeshFedAvgEngine

    class MeshFedSegEngine(SegEvalMixin, MeshFedAvgEngine):
        def __init__(self, trainer, data, cfg, **kw2):
            super().__init__(trainer, data, cfg, **kw2)
            self._init_seg_eval()

    return MeshFedSegEngine(trainer, data, cfg, mesh=mesh, **kw)
