"""Update admission pipeline — the defense half of ISSUE 9.

PR 8's reliability layer guarantees a frame arrives exactly once and
uncorrupted; nothing yet asks whether its CONTENTS should be trusted.
This module is the defense-in-depth gate at the async server's ONE
insert path (``AsyncServerManager._ingest_row`` and the virtual-time
scheduler's arrival handler): every uplink row passes, in order,

    1. finite canary      — NaN/Inf anywhere in the row quarantines it
                            (one poisoned fold is irreversible: the
                            streaming accumulator has no undo);
    2. norm-bound clip    — the update delta (row − global) is clipped
                            to ``norm_bound`` through THE shared
                            clip definition (core/robust.clip_row ==
                            norm_diff_clip's factor == the pallas
                            clip-agg's), so a boosted model-replacement
                            contributes at most a clean-sized step;
    3. anomaly screen     — robust z-score of the delta norm against an
                            exponentially-weighted running reference
                            of ACCEPTED updates, plus cosine similarity against an
                            EMA of accepted delta directions (sign-flip
                            rides a clean-sized norm; only direction
                            betrays it).  The screen arms after
                            ``screen_warmup`` accepted updates so cold
                            starts cannot quarantine the first honest
                            cohort.

Everything numeric runs in ONE jitted program per arrival (O(P), the
same order as the PR-6 fold itself), so the hot ingest path keeps its
throughput — the ≥0.9x gate is priced by ``bench.py --mode attack``'s
overhead arm.  Rejected rows are quarantined, never folded: counted in
``async_updates_quarantined_total{reason}``, timed into
``defense_screen_seconds``, traced as ``defense.quarantine`` instants
(the flight recorder's ring, so a dump names WHO was rejected and
why).

The DP-FedAvg configuration (ROADMAP item 4's first server transform)
reuses stage 2 as the per-client clip and adds Gaussian noise inside
the bucketed commit (staleness.make_bucket_commit_fn).
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu import obs
from fedml_tpu.obs import programs as obs_programs
from fedml_tpu.core.robust import clip_scale

log = logging.getLogger(__name__)
Pytree = Any

QUARANTINE_REASONS = ("nonfinite", "norm_z", "cosine")


@dataclasses.dataclass
class DefenseConfig:
    """Knobs of the admission pipeline + bucketed robust commit (CLI
    --defense_*).  The degenerate config — buckets=1, combine
    trimmed_mean/trim 0, no clip, no screen, dp off — reproduces the
    PR-6 streaming commit BITWISE (the tentpole's anchor pin)."""
    norm_bound: Optional[float] = None   # admission clip τ (None = off)
    screen: bool = False                 # z/cosine anomaly screen
    z_max: float = 4.0                   # robust z threshold on ‖Δ‖
    cos_min: float = -1.0                # cosine floor vs ref (-1 = off)
    screen_warmup: int = 8               # accepted updates before arming
    ref_ema: float = 0.1                 # EW rate: direction ref + norm stats
    buckets: int = 1                     # B bucket accumulators
    combine: str = "trimmed_mean"        # mean | trimmed_mean | median
    trim_k: int = 0                      # buckets trimmed per side
    dp_clip: Optional[float] = None      # DP-FedAvg per-client clip S
    dp_noise: float = 0.0                # DP noise multiplier z
    seed: int = 0                        # bucket-assignment seed

    def __post_init__(self):
        from fedml_tpu.async_.staleness import BUCKET_COMBINE_MODES
        if self.combine not in BUCKET_COMBINE_MODES:
            raise ValueError(f"unknown bucket combine {self.combine!r} "
                             f"(choose one of {BUCKET_COMBINE_MODES})")
        if self.buckets < 1:
            raise ValueError(f"buckets must be >= 1, got {self.buckets}")
        if self.dp_noise > 0.0 and self.dp_clip is None:
            raise ValueError("dp_noise needs dp_clip: the DP guarantee "
                             "is calibrated to the per-client clip S")

    @property
    def clip_bound(self) -> Optional[float]:
        """The effective per-client clip: DP's S wins when set (the DP
        accounting requires it), else the admission norm bound."""
        return self.dp_clip if self.dp_clip is not None else self.norm_bound

    def active(self) -> bool:
        """Whether any admission stage beyond the finite canary is on."""
        return (self.clip_bound is not None or self.screen
                or self.dp_noise > 0.0)


def make_flatten_fn():
    """Jitted device-side flatten of a variables pytree into the ONE
    flat-row layout (flatten_vars_row's element order: ravel + concat
    in jax leaf order) — the admission screen compares uplink rows
    against the current global in this layout."""
    def flatten(tree):
        leaves = [jnp.ravel(l).astype(jnp.float32)
                  for l in jax.tree.leaves(tree)]
        if not leaves:
            return jnp.zeros((0,), jnp.float32)
        return leaves[0] if len(leaves) == 1 else jnp.concatenate(leaves)
    return jax.jit(flatten)


def _make_stage_fn(cfg: DefenseConfig):
    """THE admission stage math, shared by the standalone screen
    (make_admission_fn) and the fused hot path (make_screened_fold_fn)
    — one definition so the two compiled programs cannot drift:

        stages(row, g, ref, n_acc, mu, m2)
            -> (clipped, ok, reason, new_ref, new_n, new_mu, new_m2)

    Stages: finite canary on the raw row; delta Δ = row − g; clip
    factor via the shared clip_scale (with no clip configured the
    INPUT row passes through untouched — g + 1.0·Δ would not be
    bitwise `row`, and the degenerate-config pin needs exactness);
    ONE-SIDED robust z of ‖Δ‖ vs exponentially-weighted running
    (mu, m2 = EW variance) norm stats; cosine of Δ vs the accepted-
    direction EMA `ref` (python-gated OFF at cos_min <= -1, so the
    disabled stage costs no O(P) passes and `ref` stays frozen).

    Reason codes index QUARANTINE_REASONS + 1 (0 = admitted); the
    canary outranks the z screen outranks cosine, so a NaN row is
    always reported as "nonfinite" even though its z/cos compare
    false too.

    Design notes, all empirically forced (see PERF.md "Adversarial
    robustness"):

    * the clip bound gates TEACHING: the norm stats learn only from
      rows whose raw norm respects the bound — a boosted cohort
      accepted during warmup still folds (clipped, bounded harm) but
      cannot inflate mu/std enough for later boosted rows to slip
      under any z_max;
    * EW stats, not Welford: honest norms drift as training converges;
      an all-history estimator reads the drift as variance or pins mu
      at the warmup level;
    * the norm stats learn from every finite bound-respecting row
      INCLUDING z/cos-rejected ones — accepted-only teaching froze the
      stats whenever the honest distribution shifted faster than the
      EW rate and livelocked the federation quarantining everyone;
    * the z test is one-sided (too-LARGE only): small norms are not an
      attack surface, and honest norms legitimately decay below mu;
      the 10%-of-mean std floor keeps a collapsed variance from
      flagging ordinary fluctuation;
    * the direction reference learns from fully ACCEPTED rows only — a
      sign-flipped cohort (honest-sized norm) must not drag the cosine
      reference toward itself by being rejected."""
    clip_bound = cfg.clip_bound
    z_max = float(cfg.z_max)
    cos_min = float(cfg.cos_min)
    warmup = float(max(1, cfg.screen_warmup))
    ema = float(cfg.ref_ema)
    screen = bool(cfg.screen)
    cos_on = screen and cos_min > -1.0

    def stages(row, g, ref, n_acc, mu, m2):
        d = row - g
        sq = jnp.sum(d * d)
        # the finite canary rides the Σd² reduction instead of paying
        # its own O(P) isfinite pass: any NaN/±Inf element of `row`
        # makes d² non-finite and non-finiteness is absorbing under
        # sum (squares are non-negative, so no cancellation can hide
        # it); an overflowing-but-finite row flags too, which is the
        # right call for a garbage uplink.  The screened fold is the
        # ingest hot path — every pass counts (PERF.md table).
        finite = jnp.isfinite(sq)
        nd = jnp.sqrt(jnp.maximum(sq, 1e-24))
        if clip_bound is not None:
            clipped = g + clip_scale(sq, jnp.float32(clip_bound)) * d
            teaches = nd <= jnp.float32(clip_bound)
        else:
            clipped = row
            teaches = jnp.bool_(True)
        if screen:
            warm = n_acc >= warmup
            std = jnp.sqrt(jnp.maximum(m2, 0.0))
            z = (nd - mu) / jnp.maximum(std, 0.1 * mu + 1e-12)
            ok_z = jnp.logical_or(~warm, z <= z_max)
        else:
            ok_z = jnp.bool_(True)
        if cos_on:
            refn = jnp.sqrt(jnp.sum(ref * ref))
            cos = jnp.sum(d * ref) / (nd * refn + 1e-12)
            ok_cos = jnp.logical_or(n_acc < warmup, cos >= cos_min)
        else:
            ok_cos = jnp.bool_(True)
        ok = finite & ok_z & ok_cos
        reason = jnp.where(
            ~finite, 1, jnp.where(~ok_z, 2, jnp.where(~ok_cos, 3, 0)))
        teach_stats = finite & teaches
        delta = nd - mu
        incr = jnp.float32(ema) * delta
        mu1 = jnp.where(n_acc > 0.0, mu + incr, nd)
        m21 = jnp.where(n_acc > 0.0,
                        (1.0 - jnp.float32(ema)) * (m2 + delta * incr),
                        jnp.float32(0.0))
        new_n = jnp.where(teach_stats, n_acc + 1.0, n_acc)
        new_mu = jnp.where(teach_stats, mu1, mu)
        new_m2 = jnp.where(teach_stats, m21, m2)
        if cos_on:
            ref1 = jnp.where(n_acc > 0.0, (1.0 - ema) * ref + ema * d, d)
            new_ref = jnp.where(ok, ref1, ref)
        else:
            new_ref = ref
        return clipped, ok, reason, new_ref, new_n, new_mu, new_m2

    return stages


def make_admission_fn(cfg: DefenseConfig):
    """Build the standalone jitted admission step (unit tests and
    callers without a streaming buffer; production ingestion uses the
    fused make_screened_fold_fn):

        admit(row [P], g [P], ref [P], n_acc, mu, m2)
            -> (clipped_row [P], admit_flag, reason_code,
                new_ref, new_n_acc, new_mu, new_m2)

    The stage math is _make_stage_fn — ONE definition with the fused
    path.  The reference state (ref, n_acc, mu, m2) is donated."""
    stages = _make_stage_fn(cfg)
    return obs_programs.instrument(
        "async_admission",
        jax.jit(stages, donate_argnums=(2, 3, 4, 5)))


def make_screened_fold_fn(cfg: DefenseConfig, staleness_mode: str,
                          staleness_a: float, staleness_b: float):
    """Fused admission + streaming fold — the production hot path:

        sfold(acc, wsum, row, g, ref, n_acc, mu, m2, weight, staleness)
            -> (acc', wsum', ok, reason, ref', n', mu', m2')

    One jitted dispatch per arrival instead of screen-then-fold: the
    _make_stage_fn stages run fused with the staleness-discounted
    accumulate, and the accumulator update is conditional IN-program
    (``where(ok, acc + w̃·clipped, acc)``), so a quarantined row costs
    the same single dispatch and leaves the accumulator bit-untouched.
    Measured: the unfused two-dispatch pipeline cost ~0.5x of the PR-6
    ingest rate (two serialized O(P) programs + two host syncs under
    the manager lock); fused, the screen rides the fold's pass and the
    ≥0.9x overhead gate holds.  `acc`, `wsum` and the reference state
    are donated — everything updates in place."""
    from fedml_tpu.async_.staleness import staleness_weight
    stages = _make_stage_fn(cfg)

    def sfold(acc, wsum, row, g, ref, n_acc, mu, m2, weight, staleness):
        clipped, ok, reason, new_ref, new_n, new_mu, new_m2 = stages(
            row, g, ref, n_acc, mu, m2)
        # the PR-6 fold, gated: bitwise staleness.make_fold_fn's ops on
        # the accepted path (same λ, same multiply-add)
        lam = staleness_weight(staleness_mode, staleness, staleness_a,
                               staleness_b)
        wt = jnp.asarray(weight, jnp.float32) * lam
        # a quarantined row's (possibly NaN) contribution is computed
        # then discarded by the select — acc stays bit-identical
        acc1 = jnp.where(ok, acc + wt * clipped, acc)
        wsum1 = jnp.where(ok, wsum + wt, wsum)
        return acc1, wsum1, ok, reason, new_ref, new_n, new_mu, new_m2

    # ISSUE 12: the fused screen+fold is its own profile family —
    # its dispatch wall vs async_fold's IS the admission tax, live
    return obs_programs.instrument(
        "async_screened_fold",
        jax.jit(sfold, donate_argnums=(0, 1, 4, 5, 6, 7)))


class UpdateAdmission:
    """Stateful admission gate: wraps the jitted step with the running
    reference, the quarantine accounting, and the obs wiring.  One
    instance per server; callers serialize under the server lock (the
    running-reference state is ordered, like the fold it guards).

    Staleness-aware (the ROADMAP item-4 "stale adversarial updates"
    edge): the gate keeps the last `GLOBAL_WINDOW` committed globals
    (flat rows) and screens each uplink against the global its sender
    TRAINED FROM (the echoed dispatch version) — a stale honest
    update's delta is then its actual local step, not local step plus
    several commits of server drift.  Without this, stale honest
    updates read as norm/direction anomalies (false positives) while
    the drift-inflated statistics let genuinely hostile rows through;
    with it, the accepted-norm distribution stays tight across
    staleness and a boosted row is an unambiguous outlier.  Memory is
    O(GLOBAL_WINDOW·P); versions older than the window fall back to
    the oldest kept global (bounded drift, conservative)."""

    GLOBAL_WINDOW = 16

    def __init__(self, cfg: DefenseConfig, p: int):
        self.cfg = cfg
        self.p = p
        self._admit = make_admission_fn(cfg)
        self._sfold = None               # fused hot path, bound lazily
        self._ref = jnp.zeros((p,), jnp.float32)
        self._n = jnp.zeros((), jnp.float32)
        self._mu = jnp.zeros((), jnp.float32)
        self._m2 = jnp.zeros((), jnp.float32)
        self._globals: "dict[int, jax.Array]" = {}
        self.accepted = 0
        self.quarantined: dict[str, int] = {}
        self.quarantine_log: list[tuple] = []       # (sender, reason)
        self._m_hist = obs.histogram(
            "defense_screen_seconds",
            buckets=obs.metrics.DECODE_SECONDS_BUCKETS)
        self._m_quar = {
            r: obs.counter("async_updates_quarantined_total", reason=r)
            for r in QUARANTINE_REASONS}

    def note_global(self, version: int, global_row) -> None:
        """Record the flat global at `version` (call at init and after
        every commit); evicts beyond GLOBAL_WINDOW."""
        self._globals[int(version)] = global_row
        while len(self._globals) > self.GLOBAL_WINDOW:
            del self._globals[min(self._globals)]

    def _global_for(self, version: Optional[int]):
        if version is not None and int(version) in self._globals:
            return self._globals[int(version)]
        if self._globals:
            # older than the window (or unknown): the oldest kept
            # global bounds the drift better than the newest
            return self._globals[min(self._globals)]
        return jnp.zeros((self.p,), jnp.float32)

    def screen(self, row, global_row=None, sender: int = -1,
               version: Optional[int] = None):
        """Run one row through the pipeline.  Returns (admitted: bool,
        reason: str — "ok" or a QUARANTINE_REASONS entry, clipped_row)
        — clipped_row is a device array ready for the buffer fold
        (None when quarantined).  `version` selects the recorded
        global the sender trained from (preferred); `global_row`
        overrides it explicitly."""
        if global_row is None:
            global_row = self._global_for(version)
        t0 = time.perf_counter()
        with obs.span("defense.screen", sender=sender):
            out_row, ok, reason, self._ref, self._n, self._mu, self._m2 = \
                self._admit(jnp.asarray(row, jnp.float32), global_row,
                            self._ref, self._n, self._mu, self._m2)
            admitted = bool(ok)
        self._m_hist.observe(time.perf_counter() - t0)
        if admitted:
            self.accepted += 1
            return True, "ok", out_row
        return False, self._quarantine(sender, reason), None

    def _quarantine(self, sender: int, reason) -> str:
        """ONE quarantine-accounting path (counter + reason-labeled obs
        + bounded log + flight-recorder instant) for both the
        standalone screen and the fused fold."""
        why = QUARANTINE_REASONS[int(reason) - 1]
        self.quarantined[why] = self.quarantined.get(why, 0) + 1
        if len(self.quarantine_log) < 50_000:
            self.quarantine_log.append((int(sender), why))
        self._m_quar[why].inc()
        # the flight recorder's ring picks this up, so a dump names the
        # quarantined sender and the stage that rejected it
        obs.instant("defense.quarantine", sender=sender, reason=why)
        log.debug("quarantined update from %s: %s", sender, why)
        return why

    def bind_fold(self, staleness_mode: str, staleness_a: float,
                  staleness_b: float) -> None:
        """Build the fused admission+fold program (make_screened_fold_fn)
        for the buffer's staleness family — called once by the server
        that owns both."""
        self._sfold = make_screened_fold_fn(self.cfg, staleness_mode,
                                            staleness_a, staleness_b)

    def screened_fold(self, acc, wsum, row, weight: float,
                      staleness: float, sender: int = -1,
                      version: Optional[int] = None):
        """The fused hot path: one dispatch screens `row` and folds the
        (clipped) accepted contribution into (acc, wsum).  Returns
        (ok, reason, acc', wsum') — on quarantine acc'/wsum' carry the
        UNCHANGED values (in freshly-donated buffers) and the
        accounting mirrors screen()."""
        assert self._sfold is not None, "bind_fold() first"
        g = self._global_for(version)
        t0 = time.perf_counter()
        with obs.span("defense.screen", sender=sender):
            (acc1, wsum1, ok, reason, self._ref, self._n, self._mu,
             self._m2) = self._sfold(
                acc, wsum, jnp.asarray(row, jnp.float32), g, self._ref,
                self._n, self._mu, self._m2, np.float32(weight),
                np.float32(staleness))
            admitted = bool(ok)
        self._m_hist.observe(time.perf_counter() - t0)
        if admitted:
            self.accepted += 1
            return True, "ok", acc1, wsum1
        return False, self._quarantine(sender, reason), acc1, wsum1

    def state(self) -> dict:
        """Checkpointable running-reference snapshot (crash-resume: a
        resumed server keeps its armed screen instead of re-warming
        against a possibly-hostile cohort).  The quarantine counters
        ride along so the attack accounting (reports, bench gates)
        survives a resume too — only the bounded (sender, reason) debug
        log resets."""
        return {"ref": np.asarray(self._ref, np.float32).copy(),
                "n_acc": np.asarray(self._n, np.float32).copy(),
                "mu": np.asarray(self._mu, np.float32).copy(),
                "m2": np.asarray(self._m2, np.float32).copy(),
                "accepted": np.asarray(self.accepted, np.int64),
                "quarantined": np.asarray(
                    [self.quarantined.get(r, 0)
                     for r in QUARANTINE_REASONS], np.int64)}

    def load_state(self, state: dict) -> None:
        ref = np.asarray(state["ref"], np.float32)
        if ref.shape != (self.p,):
            raise ValueError(f"admission state shape mismatch: checkpoint "
                             f"ref {ref.shape} vs configured ({self.p},)")
        # copy=True: the donated admission step must never free orbax's
        # buffer (same alias hazard as AsyncBuffer.load_state)
        self._ref = jnp.array(ref, copy=True)
        self._n = jnp.array(np.asarray(state["n_acc"], np.float32),
                            copy=True)
        self._mu = jnp.array(np.asarray(state["mu"], np.float32), copy=True)
        self._m2 = jnp.array(np.asarray(state["m2"], np.float32), copy=True)
        self.accepted = int(state["accepted"])
        if "quarantined" in state:
            counts = np.asarray(state["quarantined"], np.int64)
            self.quarantined = {
                r: int(c) for r, c in zip(QUARANTINE_REASONS, counts)
                if int(c) > 0}

    def report(self) -> dict:
        return {"accepted": self.accepted,
                "quarantined": dict(self.quarantined),
                "quarantined_total": sum(self.quarantined.values())}
