"""Event-driven async round scheduler — the virtual-time simulation path.

`AsyncFedAvgEngine` simulates a buffered-asynchronous federation
(FedBuff-style: commit on K buffered results or a round deadline;
FedAsync is the K=1 degenerate config) over a SIMULATED clock: client
latencies, crashes, and rejoins come from the seeded lifecycle model
(fedml_tpu/async_/lifecycle.py), dispatch order is a deterministic
event heap, and no thread ever sleeps — a 10,000-commit churn study
runs at compute speed and is bit-reproducible per `--async_seed`
(pinned in tests/test_async.py).  The real-thread/real-socket
counterpart over the comm backends is lifecycle.run_async_messaging.

TPU-native structure: client training happens in DISPATCH WAVES — all
clients handed work at the same moment share one jitted
vmap(local_train) program (the same one_client body the synchronous
FedAvgEngine vmaps), so the simulator keeps the cohort-batched XLA
shape of the rest of the repo instead of decaying into per-client
dispatches.  Results are flattened to f32 buffer rows on device
(flat-carry layout, staleness.flatten_stacked_rows) and surface to the
host once per wave.

The degenerate config — zero latency, zero dropout, buffer_k == cohort,
constant staleness weight, mix 1.0 — reproduces the synchronous FedAvg
engine BITWISE: wave w dispatches exactly sampler.sample(w) with the
sync path's per-round rng derivation, the wave trains at the sync vmap
width, and the mixing-form commit reduces to the same
tree_weighted_mean (see staleness.py).  That pin is what anchors the
async numerics to the rest of the repo.
"""
from __future__ import annotations

import dataclasses
import heapq
import logging
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu import obs
from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.async_.adversary import (AdversarySim, AttackConfig,
                                        apply_data_attack)
from fedml_tpu.async_.defense import (DefenseConfig, UpdateAdmission,
                                      make_flatten_fn)
from fedml_tpu.async_.lifecycle import ClientLifecycle, LifecycleConfig
from fedml_tpu.async_.staleness import (AsyncBuffer, STALENESS_MODES,
                                        flat_dim, flatten_stacked_rows,
                                        make_bucket_commit_fn,
                                        make_commit_fn)
from fedml_tpu.scale import registry as _reg
from fedml_tpu.scale.arrivals import (ArrivalConfig, ArrivalProcess,
                                      make_arrivals)
from fedml_tpu.scale.registry import ClientRegistry

log = logging.getLogger(__name__)
Pytree = Any


class AsyncSchedulerDeadlock(RuntimeError):
    """No event can ever arrive and the buffer can never fill — the
    federation is dead (every client crashed with no rejoin and no
    deadline configured).  A flight dump is written at raise time, so
    the generic engine-error handler must not dump a second copy."""

# event kinds, in tie-break priority at equal virtual time: arrivals
# before rejoins (a rejoin at the same instant joins the NEXT wave)
_ARRIVE, _REJOIN, _DEADLINE = 0, 1, 2


class AsyncFedAvgEngine(FedAvgEngine):
    """Buffered staleness-aware async FedAvg over a simulated clock.

    One `run()` drives `rounds` COMMITS (the async analogue of rounds).
    Client results are staleness-discounted at commit time
    (staleness.make_commit_fn); `mix` is the FedAsync server mixing rate
    α (1.0 installs the discounted buffer average directly).

    `concurrency` clients are in flight at once; freed/rejoined clients
    are redispatched in waves (one wave per commit in steady state),
    each wave sampling its ids through the engine's deterministic
    ClientSampler.  The event trace (`self.trace`) records every
    dispatch/arrival/crash/rejoin/commit with virtual timestamps — the
    seeded-determinism contract is that two engines with equal seeds
    produce equal traces."""

    def __init__(self, trainer, data, cfg, *, buffer_k: Optional[int] = None,
                 concurrency: Optional[int] = None,
                 staleness: str = "constant", staleness_a: float = 0.5,
                 staleness_b: float = 4.0, mix: float = 1.0,
                 round_deadline_s: Optional[float] = None,
                 lifecycle_cfg: Optional[LifecycleConfig] = None,
                 async_seed: Optional[int] = None, donate: bool = True,
                 attack: Optional[AttackConfig] = None,
                 defense: Optional[DefenseConfig] = None,
                 shardstore=None,
                 arrivals: Optional[object] = None):
        if staleness not in STALENESS_MODES:
            raise ValueError(f"unknown staleness mode {staleness!r} "
                             f"(choose one of {STALENESS_MODES})")
        # ISSUE 9: the seeded byzantine cohort (attack) and the update
        # admission + bucketed robust commit (defense).  Data-level
        # attacks poison the byzantine clients' shards BEFORE the engine
        # snapshots the data — the attackers then run the honest
        # protocol on hostile data, exactly the reference's backdoor
        # benchmarking shape.
        self.attack = attack
        self.defense = defense
        self._adversary = None
        if attack is not None and attack.mode != "none":
            self._adversary = AdversarySim(attack, cfg.client_num_in_total)
            data = apply_data_attack(data, attack, self._adversary)
        super().__init__(trainer, data, cfg, donate=donate)
        self.buffer_k = (buffer_k if buffer_k is not None
                         else cfg.client_num_per_round)
        if self.buffer_k < 1:
            raise ValueError(f"buffer_k must be >= 1, got {self.buffer_k}")
        self.concurrency = (concurrency if concurrency is not None
                            else max(self.buffer_k,
                                     cfg.client_num_per_round))
        if self.concurrency < self.buffer_k:
            raise ValueError(
                f"concurrency ({self.concurrency}) must be >= buffer_k "
                f"({self.buffer_k}): a full buffer needs that many "
                f"results in flight")
        self.staleness_mode = staleness
        self.staleness_a = staleness_a
        self.staleness_b = staleness_b
        self.mix = float(mix)
        self.round_deadline_s = round_deadline_s
        self.lifecycle_cfg = (lifecycle_cfg if lifecycle_cfg is not None
                              else LifecycleConfig(
                                  seed=async_seed if async_seed is not None
                                  else cfg.seed))
        if async_seed is not None:
            self.lifecycle_cfg = dataclasses.replace(self.lifecycle_cfg,
                                                     seed=async_seed)
        # wave trainer: the SAME one_client body the sync engine vmaps —
        # variables broadcast (in_axes None), one compile per distinct
        # wave width (waves are buffer_k-sized in steady state)
        self._train_wave = jax.jit(jax.vmap(
            self._one_client, in_axes=(None, 0, 0)))
        # ISSUE 10: the sharded client registry replaces the per-client
        # Python containers (free/dead sets, in_flight dict, the
        # staleness/contribution numpy arrays) — O(cohort) touches per
        # wave, O(1) aggregate reads, checkpointable shards.  An
        # optional ShardStore supplies cohorts on demand (no all-client
        # stack), and an arrival process modulates dispatch turnaround
        # with the load curve (scale/arrivals.py).
        self.registry = ClientRegistry(self.sampler.client_num_in_total)
        self._shardstore = shardstore
        if isinstance(arrivals, ArrivalConfig):
            arrivals = make_arrivals(arrivals)
        self._arrivals: Optional[ArrivalProcess] = arrivals
        self._rows_fn = jax.jit(flatten_stacked_rows)
        self._flat_fn = make_flatten_fn()
        self._commit_fn = None        # built per variables template
        self._admission: Optional[UpdateAdmission] = None
        self._p = None
        self.version = 0
        self.commits_deadline = 0
        self.trace: list[tuple] = []
        self.staleness_committed: list[float] = []
        self.occupancy_at_commit: list[int] = []
        self._m_occupancy = obs.gauge("async_buffer_occupancy")
        self._m_staleness = obs.histogram(
            "async_staleness", buckets=obs.metrics.STALENESS_BUCKETS)
        self._m_commits = obs.counter("async_commits_total")
        self._m_updates = obs.counter("async_updates_committed_total")
        self._m_dispatches = obs.counter("async_dispatches_total")

    def _one_client(self, variables, shard, crng):
        global_params = (variables["params"] if self.trainer.prox_mu > 0
                         else None)
        return self.trainer.local_train(variables, shard, crng,
                                        self.cfg.epochs,
                                        global_params=global_params)

    # -- async server state (checkpoint payload) ------------------------------
    def async_state(self) -> dict:
        """Checkpointable async server state: buffer contents + version +
        the sharded client registry (participation/staleness/quarantine
        counters — utils/checkpoint.py extra_state).  The event
        clock/heap is NOT part of it — a resumed run restarts the
        lifecycle clock but keeps every buffered result and staleness
        statistic.  Defended runs additionally carry the bucket
        accumulators (inside the buffer state) and the admission
        pipeline's running reference, so a resumed screen stays armed."""
        self._ensure_buffer()
        out = {
            "buffer": self._buffer.state(),
            "version": np.asarray(self.version, np.int64),
            "registry": self.registry.state(),
        }
        if self._admission is not None:
            out["defense"] = self._admission.state()
        return out

    def load_async_state(self, state: dict) -> None:
        self._ensure_buffer()
        self._buffer.load_state(state["buffer"])
        self.version = int(state["version"])
        if "registry" in state:
            self.registry.load_state(
                jax.tree.map(np.asarray, state["registry"]))
        elif "client_contribs" in state:
            # pre-PR-10 checkpoint: migrate the two flat per-client
            # arrays into registry counters (last_seen is not
            # reconstructible — defaults to -1)
            contribs = np.asarray(state["client_contribs"], np.int64)
            stale = np.asarray(state["client_last_staleness"], np.float32)
            for cid in np.flatnonzero(contribs):
                s, loc = divmod(int(cid), self.registry.shard_size)
                sh = self.registry._alloc(s)
                sh["participation"][loc] = contribs[cid]
                sh["last_staleness"][loc] = stale[cid]
        else:
            raise ValueError(
                "async checkpoint carries neither 'registry' (PR 10) "
                "nor the legacy per-client arrays — not an async "
                "server state")
        if self._admission is not None and "defense" in state:
            self._admission.load_state(state["defense"])

    def _ensure_buffer(self) -> None:
        if getattr(self, "_buffer", None) is None:
            if self.defense is not None:
                # defended path: streaming bucketed buffer — the robust
                # commit needs B accumulators, and the staleness
                # discount moves into the arrival fold (same λ math;
                # the weights ride the fold instead of the drained
                # commit)
                self._buffer = AsyncBuffer(
                    self.buffer_k, self._flat_dim(), streaming=True,
                    staleness_mode=self.staleness_mode,
                    staleness_a=self.staleness_a,
                    staleness_b=self.staleness_b,
                    buckets=self.defense.buckets,
                    bucket_seed=self.defense.seed)
                self._admission = UpdateAdmission(self.defense,
                                                  self._flat_dim())
                self._admission.bind_fold(self.staleness_mode,
                                          self.staleness_a,
                                          self.staleness_b)
            else:
                self._buffer = AsyncBuffer(self.buffer_k, self._flat_dim())

    def _flat_dim(self) -> int:
        if self._p is None:
            self._p = flat_dim(self.init_variables())
        return self._p

    # -- the event-driven loop ------------------------------------------------
    def run(self, variables: Optional[Pytree] = None,
            rounds: Optional[int] = None, logger=None, ckpt=None,
            ckpt_every: int = 0, resume: bool = False) -> Pytree:
        """Drive `rounds` commits of the async federation.  Mirrors the
        base run() contract (eval cadence, metrics_history, logger,
        checkpoint every N commits); `resume` restores variables AND the
        async server state saved by a previous run's checkpoints."""
        cfg = self.cfg
        variables = (variables if variables is not None
                     else self.init_variables())
        self._p = flat_dim(variables)
        self._ensure_buffer()
        total = rounds if rounds is not None else cfg.comm_round
        start_version = 0
        if ckpt is not None and resume and ckpt.latest_round() is not None:
            step, variables, _ss, extra = ckpt.restore(
                variables, (), extra_template=self.async_state())
            self.load_async_state(extra)
            start_version = self.version
            log.info("async resume: version %d, buffer %d/%d", self.version,
                     self._buffer.count, self.buffer_k)
        if self._commit_fn is None:
            if self.defense is not None:
                d = self.defense
                self._commit_fn = make_bucket_commit_fn(
                    variables, combine=d.combine, trim_k=d.trim_k,
                    dp_noise=d.dp_noise, dp_clip=d.dp_clip or 1.0,
                    donate=self.donate)
            else:
                self._commit_fn = make_commit_fn(
                    variables, mode=self.staleness_mode, a=self.staleness_a,
                    b=self.staleness_b, donate=self.donate)
        variables = jax.tree.map(jnp.asarray, variables)
        # the admission screen and the adversary both compare uplinks
        # against the model the clients trained FROM — one flat device
        # row per version, refreshed at every commit
        g_dev = (self._flat_fn(variables)
                 if (self._admission is not None
                     or self._adversary is not None) else None)
        if self._admission is not None:
            self._admission.note_global(self.version, g_dev)
        dp_rng = (jax.random.PRNGKey(cfg.seed + 17)
                  if self.defense is not None and self.defense.dp_noise > 0
                  else None)
        lifecycle = ClientLifecycle(self.lifecycle_cfg,
                                    self.sampler.client_num_in_total)

        rng_base = jax.random.PRNGKey(cfg.seed + 1)
        heap: list[tuple] = []      # (t, kind, seq, payload)
        seq = 0
        now = 0.0
        wave_idx = self.version     # == start_version on resume; also
        #                             covers a manual load_async_state
        # ISSUE 10: client scheduling state lives in the sharded
        # registry — FREE/IN_FLIGHT/CRASHED/DEAD statuses + the
        # dispatched version per client, no per-client Python objects.
        # A (re)started run re-pools everything transient; counters
        # (participation/staleness/quarantine) survive a resume.
        reg = self.registry
        reg.reset_transient()
        last_commit_t = 0.0
        deadline_armed_version = -1
        t_wall0 = time.perf_counter()

        def push(t, kind, payload):
            nonlocal seq
            heapq.heappush(heap, (t, kind, seq, payload))
            seq += 1

        def dispatch_wave():
            """Hand work to (a sampler draw of) free clients at the
            current version: ONE vmapped train program per wave, results
            flattened to buffer rows on device and scheduled as arrival
            events at their lifecycle latencies."""
            nonlocal wave_idx
            slots = self.concurrency - reg.count_in_flight
            if slots <= 0 or reg.count_free == 0:
                return
            # sample_fast: the non-mutating bitwise twin of the
            # reference draw (core/sampling.py, ISSUE 10) — same
            # cohorts, no global-RNG reseed per wave
            draw = self.sampler.sample_fast(wave_idx)
            ids = draw[reg.status_of(draw) == _reg.FREE][:slots]
            if ids.size == 0:   # the draw missed every free client:
                ids = reg.free_ids(slots)     # take the pool directly
            ids = [int(i) for i in ids]
            w_rng, _ = jax.random.split(
                jax.random.fold_in(rng_base, wave_idx))
            crngs = jax.random.split(w_rng, len(ids))
            store = (self._shardstore if self._shardstore is not None
                     else self.data)
            cohort, _ = store.cohort(np.asarray(ids, np.int64))
            with obs.span("async.wave", wave=wave_idx, clients=len(ids),
                          version=self.version):
                stacked, _losses, ns = self._train_wave(
                    variables, cohort, crngs)
                rows = np.asarray(self._rows_fn(stacked))
                ns = np.asarray(ns)
            g_np = (np.asarray(g_dev) if self._adversary is not None
                    and self._adversary.attacks_model() else None)
            self._m_dispatches.inc(len(ids))
            for lane, cid in enumerate(ids):
                if lifecycle.draw_crash(cid):
                    self.trace.append(("crash", round(now, 9), cid,
                                       self.version))
                    obs.counter("async_dropouts_total").inc()
                    delay = lifecycle.draw_rejoin_delay(cid)
                    reg.note_crash(cid, rejoins=delay is not None)
                    if delay is not None:
                        push(now + delay, _REJOIN, cid)
                    continue
                row = rows[lane]
                if g_np is not None and self._adversary.is_byzantine(cid):
                    # byzantine lanes swap their honest result for the
                    # crafted row — AFTER the crash draw, so a crashed
                    # byzantine dispatch (its uplink never arrives)
                    # neither pays the corruption nor counts as an
                    # injected attack in the trace/counters
                    row = self._adversary.corrupt_row(
                        cid, row, g_np, self.version)
                    self.trace.append(("attack", round(now, 9), cid,
                                       self.version))
                reg.note_dispatch_one(cid, self.version)
                lat = lifecycle.draw_latency(cid)
                if self._arrivals is not None:
                    # ISSUE 10: the arrival process shapes turnaround —
                    # at the trough of the load curve the fleet answers
                    # slower (pure function of virtual time, so seeded
                    # determinism survives)
                    lat *= self._arrivals.slowdown(now)
                if self._adversary is not None:
                    # stale-attack: byzantine uplinks deliberately land
                    # several commits late, where the staleness
                    # discount was supposed to defang them
                    lat += self._adversary.stale_extra_latency(cid)
                self.trace.append(("dispatch", round(now, 9), cid,
                                   self.version))
                push(now + lat, _ARRIVE, (cid, row, float(ns[lane])))
            wave_idx += 1

        def commit(deadline_fired: bool):
            nonlocal variables, last_commit_t, deadline_armed_version, \
                g_dev, dp_rng
            if self.defense is not None:
                accs, wsums, _w, _s, n_real, _raw = \
                    self._buffer.take_stream_buckets()
                self.occupancy_at_commit.append(n_real)
                self._m_occupancy.set(0)
                with obs.span("async.commit", version=self.version,
                              n_results=n_real, deadline=deadline_fired,
                              defended=True):
                    if dp_rng is not None:
                        dp_rng, k = jax.random.split(dp_rng)
                        variables, _stats = self._commit_fn(
                            variables, accs, wsums, jnp.float32(self.mix),
                            jnp.float32(n_real), k)
                    else:
                        variables, _stats = self._commit_fn(
                            variables, accs, wsums, jnp.float32(self.mix))
            else:
                rows, w, s, n_real = self._buffer.drain()
                self.occupancy_at_commit.append(n_real)
                self._m_occupancy.set(0)
                with obs.span("async.commit", version=self.version,
                              n_results=n_real, deadline=deadline_fired):
                    variables, _stats = self._commit_fn(
                        variables, jnp.asarray(rows), jnp.asarray(w),
                        jnp.asarray(s), jnp.float32(self.mix))
            if g_dev is not None:
                g_dev = self._flat_fn(variables)
            self.version += 1
            if self._admission is not None:
                self._admission.note_global(self.version, g_dev)
            last_commit_t = now
            deadline_armed_version = -1
            self._m_commits.inc()
            # ISSUE 12: the SLO pack's committed-updates floor
            self._m_updates.inc(n_real)
            if deadline_fired:
                self.commits_deadline += 1
                obs.counter("async_deadline_commits_total").inc()
            self.trace.append(("commit", round(now, 9), n_real,
                               self.version))
            c = self.version - 1
            if (c % cfg.frequency_of_the_test == 0 or
                    self.version >= total):
                with obs.span("async.eval", version=self.version):
                    stats = self.evaluate(variables)
                stats.update(round=c, commit=c,
                             staleness_mean=float(np.mean(
                                 self.staleness_committed[-n_real:]
                                 or [0.0])),
                             buffer_fill=n_real / self.buffer_k,
                             wall_time=time.perf_counter() - t_wall0)
                self.metrics_history.append(stats)
                if logger is not None:
                    logger.log(stats, step=c)
                log.info("commit %d: %s", c, stats)
            if ckpt is not None and ckpt_every and \
                    self.version % ckpt_every == 0:
                ckpt.save(c, jax.tree.map(np.asarray, variables), (),
                          extra_state=self.async_state())
            if self.version < total:     # no wave past the final commit
                dispatch_wave()

        try:
            with obs.span("async.run", commits=total):
                if self.version < total:   # a resume at/past the budget
                    dispatch_wave()        # must not train a dead wave
                while self.version < total:
                    if not heap:
                        if reg.count_free > 0 and reg.count_in_flight == 0:
                            # crash-starved: every in-flight dispatch
                            # died, but clients rejoined — start a wave
                            dispatch_wave()
                            if heap:
                                continue
                        # nothing can ever arrive: scheduler deadlock
                        obs.dump_flight("async_scheduler_deadlock")
                        raise AsyncSchedulerDeadlock(
                            f"async scheduler deadlock at version "
                            f"{self.version}/{total}: buffer "
                            f"{self._buffer.count}/{self.buffer_k}, "
                            f"{reg.count_dead} clients dead with no "
                            f"rejoin, {reg.count_free} free but "
                            f"undispatchable")
                    t, kind, _s, payload = heapq.heappop(heap)
                    now = max(now, t)
                    if kind == _REJOIN:
                        cid = payload
                        reg.note_rejoin(cid)
                        self.trace.append(("rejoin", round(now, 9), cid,
                                           self.version))
                        obs.counter("async_rejoins_total").inc()
                        if reg.count_in_flight == 0:
                            dispatch_wave()
                        continue
                    if kind == _DEADLINE:
                        armed_version = payload
                        if (self.version == armed_version
                                and self._buffer.count > 0):
                            commit(deadline_fired=True)
                        continue
                    cid, row, n = payload
                    dispatched_v = reg.note_return(cid)
                    staleness = float(self.version - dispatched_v)
                    self.trace.append(("arrive", round(now, 9), cid,
                                       self.version, staleness))
                    if self._admission is not None:
                        # the ISSUE-9 admission gate, fused with the
                        # streaming fold (one jitted dispatch); a
                        # quarantined row never reaches the accumulator
                        # (the client is free again and redispatches
                        # with the next wave)
                        full = False
                        ok, why, full = self._buffer.add_screened(
                            row, n, staleness, self._admission,
                            sender=cid, version=int(dispatched_v))
                        if not ok:
                            reg.note_quarantine(cid)
                            self.trace.append(
                                ("quarantine", round(now, 9), cid, why))
                            continue
                    else:
                        full = self._buffer.add(row, n, staleness)
                    self.staleness_committed.append(staleness)
                    reg.note_contribution(cid, staleness, self.version)
                    self._m_staleness.observe(staleness)
                    self._m_occupancy.set(self._buffer.count)
                    if full:
                        commit(deadline_fired=False)
                    elif (self.round_deadline_s is not None
                          and deadline_armed_version != self.version):
                        deadline_armed_version = self.version
                        push(last_commit_t + self.round_deadline_s,
                             _DEADLINE, self.version)
        except AsyncSchedulerDeadlock:
            raise               # already dumped, with the sharper reason
        except Exception as e:
            obs.dump_flight(f"engine_error:AsyncFedAvgEngine: {e!r}")
            raise
        return variables

    # -- observability rollup -------------------------------------------------
    def timeline_report(self) -> Optional[dict]:
        """Round critical-path attribution over the live tracer's spans
        (fedml_tpu/obs/timeline.py): commit-to-commit windows, per-stage
        seconds (train/commit/eval + wait), p95 straggler attribution.
        None when tracing is disabled (no --obs_dir) — metrics alone
        cannot place spans on a timeline."""
        t = obs.tracer()
        if t is None:
            return None
        from fedml_tpu.obs import timeline
        return timeline.critical_path(t.events())

    def staleness_percentiles(self, qs=(50, 95)) -> dict:
        s = np.asarray(self.staleness_committed or [0.0])
        return {f"p{q}": float(np.percentile(s, q)) for q in qs}

    def async_report(self) -> dict:
        """Headline async numbers for bench.py / profile_bench."""
        occ = np.asarray(self.occupancy_at_commit or [0])
        out = {
            "committed_updates": int(self.version),
            "deadline_commits": int(self.commits_deadline),
            "staleness_p50": self.staleness_percentiles()["p50"],
            "staleness_p95": self.staleness_percentiles()["p95"],
            "staleness_mean": float(np.mean(
                self.staleness_committed or [0.0])),
            "buffer_occupancy_mean": float(occ.mean()),
        }
        if self._admission is not None:
            out.update(self._admission.report())
        if self._adversary is not None:
            out["byzantine_clients"] = len(self._adversary.byzantine)
            # the unbounded counter, not len(events) — the trace list
            # caps at 50k while long runs keep injecting
            out["attacks_injected"] = self._adversary.injected
        return out

    def quarantine_attribution(self) -> dict:
        """{"byzantine": n, "honest": n} quarantine split — the
        false-positive gate's raw numbers (honest must be 0 in the
        clean arm).  Needs both an adversary (who is byzantine) and an
        admission pipeline (who was quarantined)."""
        byz = self._adversary.byzantine if self._adversary else frozenset()
        out = {"byzantine": 0, "honest": 0}
        if self._admission is not None:
            for cid, _why in self._admission.quarantine_log:
                out["byzantine" if cid in byz else "honest"] += 1
        return out
