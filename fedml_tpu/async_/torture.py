"""Concurrent-uplink ingestion torture bench (ISSUE 6).

The Smart-NIC FL study (arXiv:2307.06561) shows the server's
deserialize+aggregate path becomes the bottleneck under concurrent
uplinks — exactly where the PR-5 async server sat: recv threads decoding
wire frames into intermediate pytrees, one manager lock serializing
buffer inserts, and an O(K·P) drained reduction at every commit.  This
harness prices that path: N in-process simulated clients saturate a real
backend (TCP sockets / gRPC channels / the inproc router) with
pre-encoded result frames — no training, no downlinks — while the
server ingests and commits, reporting

    committed-updates/sec    Σ n_real over timed commits / wall
    decode p50/p95           from the comm_decode_seconds histogram
    lock wait                async_lock_wait_seconds growth (contention)

Clients send PRE-ENCODED frames (encode cost would otherwise compete
with the server for cores on small boxes), so the wall measures the
server's ingestion pipeline alone.  `bench.py --mode ingest` wraps this
in the A/B the acceptance gate reads: legacy (inline decode + drain
commit, the PR-5 path) vs decode-into + streaming at pool 1/4/8;
tools/profile_bench.py exp_INGEST queues the same sweep for chip
windows.
"""
from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time
from typing import Optional

import numpy as np

from fedml_tpu import obs
from fedml_tpu.obs import propagate
from fedml_tpu.obs import slo as obs_slo
from fedml_tpu.obs.metrics import quantile_from_cumulative
from fedml_tpu.async_.lifecycle import AsyncMessage, AsyncServerManager
from fedml_tpu.comm import reliability
from fedml_tpu.comm.chaos import ChaosConfig, ChaosPolicy
from fedml_tpu.comm.message import Message, MessageCodec
from fedml_tpu.comm.reliability import BackoffPolicy, ReliableEndpoint
from fedml_tpu.comm.tcp_backend import _read_exact

log = logging.getLogger(__name__)

DEFAULT_P = 262_144          # 1 MiB f32 rows — a small-CNN-sized uplink


def make_template(p: int) -> dict:
    """Synthetic variables pytree of exactly `p` f32 elements, shaped
    like a small model (one matrix + two vectors) so the RowLayout has
    several leaves to tile and the wire frame several buffers."""
    if p < 4:
        return {"params": {"w": np.zeros((p,), np.float32)}}
    cols = 64 if p >= 8192 else 4
    rows = max(1, (p // 2) // cols)
    rest = p - rows * cols
    bias = rest // 2
    return {"params": {
        "dense": {"kernel": np.zeros((rows, cols), np.float32),
                  "bias": np.zeros((bias,), np.float32)},
        "head": np.zeros((rest - bias,), np.float32),
    }}


def _result_frame(template, rank: int, p_seed: int) -> bytes:
    """One pre-encoded C2S result frame from `rank` (version 0 — the
    torture server runs constant staleness weights, so the growing
    staleness is weight-neutral)."""
    import jax
    rs = np.random.RandomState(p_seed)
    vals = jax.tree.map(
        lambda a: rs.randn(*a.shape).astype(np.float32), template)
    msg = Message(AsyncMessage.MSG_TYPE_C2S_ASYNC_RESULT, rank, 0)
    msg.add_params(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS, vals)
    msg.add_params(AsyncMessage.MSG_ARG_KEY_NUM_SAMPLES, 32.0)
    msg.add_params(AsyncMessage.MSG_ARG_KEY_VERSION, 0)
    # under tracing, frames carry the trace block a real uplink would —
    # so the traced-vs-untraced overhead A/B (exp_TRACE) prices the
    # block's decode + note, not just the server-side spans.  Obs off
    # => byte-identical to the untraced build's frames.
    propagate.stamp(msg, rank)
    return MessageCodec.encode(msg)


# ---------------------------------------------------------------------------
# client drivers — raw-transport uplink spammers
# ---------------------------------------------------------------------------

def _tcp_client(host: str, port: int, frame: bytes, stop: threading.Event):
    prefix = struct.pack("<Q", len(frame))
    wire = prefix + frame                  # one buffer, one sendall
    s = socket.create_connection((host, port), timeout=30)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        while not stop.is_set():
            s.sendall(wire)                # kernel bufs = backpressure
    except OSError:
        pass                               # server closed mid-send
    finally:
        s.close()


def _grpc_client(host: str, port: int, frame: bytes, stop: threading.Event):
    import grpc
    from fedml_tpu.comm.grpc_backend import _METHOD, _OPTS
    ch = grpc.insecure_channel(f"{host}:{port}", options=_OPTS)
    stub = ch.unary_unary(_METHOD)
    try:
        while not stop.is_set():
            stub(frame, timeout=60, wait_for_ready=True)
    except grpc.RpcError:
        pass                               # server stopped
    finally:
        ch.close()


def _inproc_client(backend, frame: bytes, stop: threading.Event):
    try:
        while not stop.is_set():
            backend._obs_received(len(frame))
            backend._deliver_frame(frame)
    except Exception:
        pass                               # manager finished mid-frame


# ---------------------------------------------------------------------------
# reliable client drivers (ISSUE 8) — window-limited uplink pushers that
# speak the FMLR envelope: each send gets a fresh per-peer seq, acks
# retire the window, losses/corruption resend on the backoff schedule
# ---------------------------------------------------------------------------

def _reliable_send_loop(ep: ReliableEndpoint, frame: bytes,
                        stop: threading.Event, window: int):
    while not stop.is_set():
        if ep.pending() >= window:
            time.sleep(0.0005)             # acks retire the window
            continue
        ep.send(0, frame)


def _reliable_tcp_client(host: str, port: int, frame: bytes,
                         stop: threading.Event, rank: int,
                         backoff: Optional[BackoffPolicy], window: int):
    s = socket.create_connection((host, port), timeout=30)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    slock = threading.Lock()

    def send_raw(peer: int, wire: bytes) -> None:
        with slock:
            s.sendall(struct.pack("<Q", len(wire)))
            s.sendall(wire)

    ep = ReliableEndpoint(rank, send_raw, policy=backoff,
                          name=f"torture-{rank}")

    def reader():                          # acks ride the same socket
        try:
            while not stop.is_set():
                (n,) = struct.unpack("<Q", _read_exact(s, 8))
                ep.on_wire(_read_exact(s, n))
        except (OSError, ConnectionError, struct.error):
            pass                           # server closed

    threading.Thread(target=reader, daemon=True).start()
    try:
        _reliable_send_loop(ep, frame, stop, window)
    except OSError:
        pass
    finally:
        ep.close()
        s.close()


def _reliable_grpc_client(host: str, port: int, frame: bytes,
                          stop: threading.Event, rank: int,
                          backoff: Optional[BackoffPolicy], window: int):
    import grpc
    from fedml_tpu.comm.grpc_backend import _METHOD, _OPTS
    ch = grpc.insecure_channel(f"{host}:{port}", options=_OPTS)
    stub = ch.unary_unary(_METHOD)

    def send_raw(peer: int, wire: bytes) -> None:
        # the unary response IS the reply channel: the server's ack or
        # nack comes back as the RPC result
        resp = stub(bytes(wire), timeout=60, wait_for_ready=True)
        if resp and bytes(resp[:4]) == reliability.MAGIC:
            ep.on_wire(resp)

    ep = ReliableEndpoint(rank, send_raw, policy=backoff,
                          name=f"torture-{rank}")
    try:
        _reliable_send_loop(ep, frame, stop, window)
    except grpc.RpcError:
        pass                               # server stopped
    finally:
        ep.close()
        ch.close()


def _reliable_inproc_client(backend, frame: bytes, stop: threading.Event,
                            rank: int, backoff: Optional[BackoffPolicy],
                            window: int):
    def send_raw(peer: int, wire: bytes) -> None:
        backend._obs_received(len(wire))
        # reply routes the server's ack straight back into this
        # client's endpoint — the in-memory twin of the TCP reverse
        # channel
        backend._deliver_frame(wire, reply=ep.on_wire)

    ep = ReliableEndpoint(rank, send_raw, policy=backoff,
                          name=f"torture-{rank}")
    try:
        _reliable_send_loop(ep, frame, stop, window)
    except Exception:
        pass                               # manager finished mid-frame
    finally:
        ep.close()


# histogram-delta percentiles: the hand-rolled cumulative-bucket
# interpolation this module used to carry moved into the ONE shared
# definition, obs.metrics.quantile_from_cumulative (Histogram.quantile
# resolves there too) — bitwise-same numbers pinned in tests/test_obs.py

# ---------------------------------------------------------------------------
# the torture run
# ---------------------------------------------------------------------------

def run_ingest_torture(*, n_clients: int = 32, backend: str = "TCP",
                       p: int = DEFAULT_P, buffer_k: int = 8,
                       commits: int = 40, warmup_commits: int = 5,
                       ingest_pool: int = 8, decode_into: bool = True,
                       streaming: bool = True, base_port: int = 53200,
                       timeout_s: float = 300.0,
                       inbox_bound: Optional[int] = None,
                       template: Optional[dict] = None,
                       reliable: bool = False,
                       chaos: Optional[dict] = None, chaos_seed: int = 0,
                       reliable_backoff: Optional[BackoffPolicy] = None,
                       defense=None, window: int = 4) -> dict:
    """Saturate one server with `n_clients` concurrent uplinks until
    `warmup_commits + commits` commits land; returns the ingestion
    report.  `streaming=False, ingest_pool=0, decode_into=False` is the
    PR-5 legacy arm (inline decode on recv threads + drained O(K·P)
    commit) — FAITHFULLY, including its unbounded manager inbox: under
    saturation the recv threads decode into the heap faster than the
    one dispatch thread drains, so that arm measures the queue
    pathology too (and its memory grows for the run's duration — keep
    `commits` moderate).  `inbox_bound` bounds the inbox for sink-less
    (pool 0) configurations, blocking the recv threads when full so
    transport flow control backpressures the senders — the A/B's
    queue-discipline isolation arm.

    Chaos + reliability (ISSUE 8, `bench.py --mode chaos`):
    `reliable=True` swaps the spam clients for window-limited FMLR
    uplink pushers (per-seq envelopes, ack-retired windows, backoff
    resend) and envelopes the server; `chaos` (a dict of
    comm.chaos.ChaosConfig rates, e.g. {"drop": 0.05, "dup": 0.01,
    "corrupt": 0.005}) installs a seeded injector on the server's
    receive path.  The report then carries the injected-event rollup
    plus retry/dedup/quarantine/recv-death counters — the
    goodput-vs-fault-rate curve's raw material."""
    import jax
    import jax.numpy as jnp

    if warmup_commits < 1:
        raise ValueError(
            f"warmup_commits must be >= 1 (the rate window opens at the "
            f"last warmup commit's wall time), got {warmup_commits}")
    backend = backend.upper()
    template = template if template is not None else make_template(p)
    total = warmup_commits + commits
    kw: dict = {}
    if backend == "INPROC":
        from fedml_tpu.comm.inproc import InProcRouter
        kw["router"] = InProcRouter()
    elif backend in ("TCP", "GRPC"):
        kw["ip_config"] = {0: "127.0.0.1"}
        kw["base_port"] = base_port
        if backend == "TCP":
            # the pure-Python transport is the A/B's named spec; the
            # native .so would move decode threading off-harness.  The
            # THREAD transport stays pinned here too (ISSUE 11): the
            # legacy/bounded-inbox arms measure the thread-per-
            # connection pathology by definition, and the decode-into
            # arms keep their PR-6/8/9 bench continuity — the reactor
            # is priced by its own bench, run_connection_torture
            kw["force_python_tcp"] = True
            kw["reactor"] = False

    tracer = obs.tracer()
    # trace watermark: several torture arms share one process tracer
    # (bench --mode ingest) — this run's critical path must only see
    # its own spans
    trace_t0 = tracer._now_us() if tracer is not None else 0.0
    hist = obs.histogram("comm_decode_seconds",
                         buckets=obs.metrics.DECODE_SECONDS_BUCKETS,
                         backend=backend.lower())
    lock_wait = obs.counter("async_lock_wait_seconds")
    recv = obs.counter("comm_received_bytes_total",
                       backend=backend.lower())
    # robustness counters (ISSUE 8): deltas over the run feed the chaos
    # report — process-wide totals (server endpoint + torture clients)
    rob = {name: obs.counter(f"comm_{name}_total") for name in (
        "reliable_retries", "reliable_acks",
        "reliable_dups_suppressed", "frames_quarantined",
        "reliable_abandoned", "recv_thread_deaths")}
    rob0 = {k: c.value for k, c in rob.items()}

    policy = None
    if chaos:
        policy = ChaosPolicy(ChaosConfig(seed=chaos_seed, **chaos))
    # ISSUE 12: one arm = one SLO evaluation window of the default
    # serving-spine pack — primed before the server starts, judged
    # after it quiesces, so the bench's v11 `slo` block attributes
    # breaches (quarantines, evictions, starved commits) per ARM
    slo_eng = obs_slo.SloEngine(obs_slo.default_slo_pack(),
                                dump_min_interval_s=30.0)
    slo_eng.prime()
    server = AsyncServerManager(
        template, total, buffer_k, 0, n_clients + 1, backend,
        staleness_mode="constant", mix=1.0, streaming=streaming,
        ingest_pool=ingest_pool, decode_into=decode_into,
        redispatch=False, reliable=reliable, defense=defense, **kw)
    if policy is not None:
        server.com_manager.install_chaos(policy)
    if inbox_bound is not None and ingest_pool == 0:
        server.com_manager.bound_inbox(inbox_bound)
    server.run_async()

    stop = threading.Event()
    frames = [_result_frame(template, r, r) for r in
              range(1, n_clients + 1)]
    threads = []
    # full-run metric baselines — the fallback window for runs so fast
    # every commit lands before the post-warmup snapshot below is taken
    hist_start, lock_start, recv_start = (hist.cumulative(),
                                          lock_wait.value, recv.value)
    with obs.span("ingest.torture", backend=backend, clients=n_clients,
                  pool=ingest_pool, decode_into=decode_into,
                  streaming=streaming):
        for r, frame in enumerate(frames, start=1):
            if reliable:
                if backend == "TCP":
                    t = threading.Thread(
                        target=_reliable_tcp_client,
                        args=("127.0.0.1", base_port, frame, stop, r,
                              reliable_backoff, window), daemon=True)
                elif backend == "GRPC":
                    t = threading.Thread(
                        target=_reliable_grpc_client,
                        args=("127.0.0.1", base_port, frame, stop, r,
                              reliable_backoff, window), daemon=True)
                else:
                    t = threading.Thread(
                        target=_reliable_inproc_client,
                        args=(server.com_manager, frame, stop, r,
                              reliable_backoff, window), daemon=True)
            elif backend == "TCP":
                t = threading.Thread(target=_tcp_client,
                                     args=("127.0.0.1", base_port, frame,
                                           stop), daemon=True)
            elif backend == "GRPC":
                t = threading.Thread(target=_grpc_client,
                                     args=("127.0.0.1", base_port, frame,
                                           stop), daemon=True)
            else:
                t = threading.Thread(target=_inproc_client,
                                     args=(server.com_manager, frame,
                                           stop), daemon=True)
            t.start()
            threads.append(t)
        # metric baselines at the LAST WARMUP commit, so the decode
        # percentiles / lock wait / ingested bytes measure the same
        # post-warmup regime as the headline rate (jit+codec cold-start
        # and page-cold memcpys land in the excluded warmup window)
        deadline = time.perf_counter() + timeout_s
        while (len(server.commit_walls) < warmup_commits
               and not server.done.is_set()
               and time.perf_counter() < deadline):
            time.sleep(0.005)
        hist0, lock0, recv0 = (hist.cumulative(), lock_wait.value,
                               recv.value)
        finished = server.done.wait(
            timeout=max(0.0, deadline - time.perf_counter()))
        # a client whose transport errored out mid-run died silently
        # (its spam loop just ends) — count survivors BEFORE stop.set()
        # so a rate measured under reduced load is flagged, not silently
        # reported as n_clients' worth of pressure
        clients_alive = sum(1 for t in threads if t.is_alive())
        stop.set()
    if not finished:
        obs.dump_flight("ingest_torture_stall")
        server.finish()
        raise TimeoutError(
            f"ingest torture stalled: {server.version}/{total} commits in "
            f"{timeout_s}s (backend {backend}, {n_clients} clients, "
            f"pool {ingest_pool})")
    server.finish()                 # waits out in-flight decode tasks
    for t in threads:
        t.join(timeout=10)
    # one quiesced snapshot (post pool drain) feeds both percentiles
    # and the lock-wait delta — no straggler can split the windows
    hist1, lock1, recv1 = hist.cumulative(), lock_wait.value, recv.value
    if clients_alive < n_clients:
        log.warning(
            "%d/%d torture clients died before the run ended (transport "
            "timeout/error) — the reported rate was measured under "
            "reduced uplink pressure", n_clients - clients_alive,
            n_clients)
    metric_window = "post_warmup"
    if hist1[-1][1] - hist0[-1][1] <= 0:
        # the whole run landed inside one poll interval of the warmup
        # boundary: fall back to the full-run window rather than report
        # plausible-looking zeros for the percentiles
        metric_window = "full_run"
        hist0, lock0, recv0 = hist_start, lock_start, recv_start

    walls, sizes = server.commit_walls, server.commit_sizes
    dt = walls[-1] - walls[warmup_commits - 1]
    updates = int(sum(sizes[warmup_commits:]))
    frame_bytes = len(frames[0])
    report = {
        "backend": backend,
        "n_clients": n_clients,
        "p": int(sum(int(np.prod(np.shape(l)))
                     for l in jax.tree.leaves(template))),
        "frame_bytes": frame_bytes,
        "buffer_k": buffer_k,
        "ingest_pool": ingest_pool,
        "decode_into": bool(decode_into),
        "streaming": bool(streaming),
        "inbox_bound": inbox_bound,
        "commits": commits,
        "updates_committed": updates,
        "committed_updates_per_sec": updates / dt if dt > 0 else 0.0,
        "commits_per_sec": commits / dt if dt > 0 else 0.0,
        "decode_p50_s": quantile_from_cumulative(hist0, hist1, 0.50),
        "decode_p95_s": quantile_from_cumulative(hist0, hist1, 0.95),
        "decode_samples": int(hist1[-1][1] - hist0[-1][1]),
        "metric_window": metric_window,
        "lock_wait_seconds": lock1 - lock0,
        "ingested_bytes": recv1 - recv0,
        "clients_alive_at_end": clients_alive,
        "staleness_p95": float(np.percentile(
            np.asarray(server.staleness_seen or [0.0]), 95)),
        # ISSUE-8 robustness accounting: injected faults + what the
        # reliability layer did about them (full-run deltas — faults
        # during warmup count too; the goodput ratio compares arms
        # under IDENTICAL accounting, so the window mismatch cancels)
        "reliable": bool(reliable),
        # ISSUE-9 admission accounting: the screen-on overhead arm of
        # `bench.py --mode attack` reads these (honest torture clients
        # must see zero quarantines — the false-positive gate)
        "defense": defense is not None,
        "admission": (server._admission.report()
                      if server._admission is not None else None),
        "chaos": dict(chaos) if chaos else None,
        "chaos_injected": policy.summary() if policy is not None else None,
        "retries": rob["reliable_retries"].value
                   - rob0["reliable_retries"],
        "acks": rob["reliable_acks"].value - rob0["reliable_acks"],
        "dups_suppressed": rob["reliable_dups_suppressed"].value
                           - rob0["reliable_dups_suppressed"],
        "quarantined": rob["frames_quarantined"].value
                       - rob0["frames_quarantined"],
        "abandoned": rob["reliable_abandoned"].value
                     - rob0["reliable_abandoned"],
        "recv_thread_deaths": rob["recv_thread_deaths"].value
                              - rob0["recv_thread_deaths"],
    }
    # the run-scoped SLO verdict (full report + the compact per-arm
    # summary bench.py's v11 `slo` block embeds)
    slo_eng.evaluate()
    report["slo"] = slo_eng.report()
    report["slo_arm"] = slo_eng.arm_summary()
    # the torture server's final variables must be finite — a NaN here
    # means the fold/commit math broke under concurrency
    report["finite"] = bool(all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree.leaves(server.variables)))
    if tracer is not None:
        # commit-to-commit stage attribution (decode/fold/commit + wait
        # on this no-training harness) — the ISSUE-7 critical path,
        # surfaced in bench.py's schema-v6 "critical_path" block
        from fedml_tpu.obs import timeline
        report["critical_path"] = timeline.critical_path(
            [e for e in tracer.events() if e["ts"] >= trace_t0])
    return report


# ---------------------------------------------------------------------------
# the live-connection torture (ISSUE 11) — reactor transport under N live
# sockets, storms, and shedding
# ---------------------------------------------------------------------------

def _swarm_subprocess(cfg, frame: bytes):
    """Launch the swarm as `python -m fedml_tpu.comm.connswarm` so the
    10k arm's client fds live in their own process (the container's
    ulimit -n cannot hold both halves of 10k connections)."""
    import json
    import subprocess
    import sys
    import tempfile
    fd, frame_path = tempfile.mkstemp(prefix="connswarm_", suffix=".bin")
    with os.fdopen(fd, "wb") as f:
        f.write(frame)
    cfg.frame_path = frame_path
    cfd, cfg_path = tempfile.mkstemp(prefix="connswarm_", suffix=".json")
    with os.fdopen(cfd, "w") as f:
        f.write(cfg.to_json())
    proc = subprocess.Popen(
        [sys.executable, "-m", "fedml_tpu.comm.connswarm", cfg_path],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def finish(timeout: float = 15.0) -> dict:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate(timeout=5.0)
        for p in (frame_path, cfg_path):
            try:
                os.unlink(p)
            except OSError:
                pass
        try:
            return json.loads(out.strip().splitlines()[-1])
        except (ValueError, IndexError):
            return {}

    return finish


def run_connection_torture(*, n_connections: int = 256, p: int = 1024,
                           buffer_k: int = 32, commits: int = 30,
                           warmup_commits: int = 3, ingest_pool: int = 4,
                           offered_rate: float = 2000.0,
                           base_port: int = 53600,
                           timeout_s: float = 600.0,
                           storm: bool = False,
                           churn_lifetime_s: float = 0.0,
                           chaos: Optional[dict] = None,
                           chaos_seed: int = 0, seed: int = 0,
                           reactor_config=None,
                           swarm_subprocess: Optional[bool] = None,
                           template: Optional[dict] = None) -> dict:
    """N LIVE connections against one reactor-transport async server
    (ISSUE 11): a selector swarm keeps every socket open with paced
    FMLR-enveloped uplinks at `offered_rate` aggregate frames/sec while
    the server ingests, dedups, acks, and commits.  `storm=True`
    replays a flash crowd as a connection storm (every SYN at once) and
    `churn_lifetime_s` adds reconnect churn (seeded exponential
    lifetimes); `chaos` installs the PR-8 fault injector at the
    server's receive chokepoint.  The report carries the ISSUE-11
    acceptance numbers: sustained committed-updates/sec, p50/p95
    admission latency, peak open connections, every eviction/shed
    counter, recv-thread deaths, and the process FD delta (the
    leak audit).

    `swarm_subprocess=None` auto-selects: in-process below ~4k
    connections, a child process above (both halves of 10k connections
    cannot share one ulimit -n)."""
    import jax
    from fedml_tpu.comm.connswarm import ConnectionSwarm, SwarmConfig
    from fedml_tpu.comm.reactor import (ReactorConfig, open_fd_count,
                                        reactor_default)

    if not reactor_default():
        # the subject under test IS the reactor; silently falling back
        # to the thread transport would bench the wrong thing (and the
        # report's reactor counters would read from a group that does
        # not exist)
        raise RuntimeError(
            "run_connection_torture benches the reactor transport, but "
            "FEDML_TCP_REACTOR=0 pins the thread transport process-wide "
            "— unset it to run the connection bench")
    if swarm_subprocess is None:
        swarm_subprocess = n_connections > 4096
    template = template if template is not None else make_template(p)
    total = warmup_commits + commits
    if reactor_config is None:
        reactor_config = ReactorConfig(
            reactors=max(2, (os.cpu_count() or 2)),
            max_connections=max(n_connections + 64, 256),
            stall_timeout_s=30.0,
            shed_on_pressure=True, shed_after_s=2.0)

    fd_before = open_fd_count()
    policy = None
    if chaos:
        policy = ChaosPolicy(ChaosConfig(seed=chaos_seed, **chaos))
    # ISSUE 12: arm-scoped SLO window, same shape as run_ingest_torture
    slo_eng = obs_slo.SloEngine(obs_slo.default_slo_pack(),
                                dump_min_interval_s=30.0)
    slo_eng.prime()
    server = AsyncServerManager(
        template, total, buffer_k, 0, n_connections + 1, "TCP",
        staleness_mode="constant", mix=1.0, streaming=True,
        ingest_pool=ingest_pool, decode_into=True, redispatch=False,
        ip_config={0: "127.0.0.1"}, base_port=base_port,
        force_python_tcp=True, reactor=True,
        reactor_config=reactor_config)
    if policy is not None:
        server.com_manager.install_chaos(policy)
    server.run_async()

    hist_adm = obs.histogram("comm_admission_seconds")
    hist_lag = obs.histogram("reactor_loop_lag_seconds", backend="tcp")
    evict = {r: obs.counter("comm_connections_evicted_total",
                            backend="tcp", reason=r)
             for r in ("stall", "rate", "shed", "idle", "protocol",
                       "error")}
    shed = obs.counter("comm_uplinks_shed_total", backend="tcp")
    drained = obs.counter("comm_connections_drained_total", backend="tcp")
    deaths = obs.counter("comm_recv_thread_deaths_total")
    dups = obs.counter("comm_reliable_dups_suppressed_total")
    quar = obs.counter("comm_frames_quarantined_total")
    base = {"evict": {r: c.value for r, c in evict.items()},
            "shed": shed.value, "drained": drained.value,
            "deaths": deaths.value, "dups": dups.value,
            "quar": quar.value, "adm": hist_adm.cumulative(),
            "lag": hist_lag.cumulative()}

    # ONE uplink frame shared by the whole swarm (the server's dedup
    # ledger is per-sender seq, so identical payload bytes are fine);
    # constant staleness weights make the version echo weight-neutral
    frame = _result_frame(template, 1, seed)
    scfg = SwarmConfig(
        host="127.0.0.1", port=base_port, n_connections=n_connections,
        offered_rate=offered_rate,
        ramp_s=(0.0 if storm else max(0.5, n_connections / 2000.0)),
        storm=storm, churn_lifetime_s=churn_lifetime_s,
        duration_s=timeout_s + 30.0, seed=seed)
    swarm_stats: dict = {}
    with obs.span("conn.torture", n=n_connections, storm=storm,
                  churn=churn_lifetime_s, chaos=bool(chaos)):
        if swarm_subprocess:
            collect = _swarm_subprocess(scfg, frame)
            swarm = None
        else:
            swarm = ConnectionSwarm(scfg, frame).start()
        deadline = time.perf_counter() + timeout_s
        while (len(server.commit_walls) < warmup_commits
               and not server.done.is_set()
               and time.perf_counter() < deadline):
            time.sleep(0.01)
        adm0 = hist_adm.cumulative()
        lag0 = hist_lag.cumulative()
        finished = server.done.wait(
            timeout=max(0.0, deadline - time.perf_counter()))
        # monotone for the group's lifetime — one read after the wait
        peak = server.com_manager._rg.peak_connections
        if swarm is not None:
            swarm.join()
            swarm_stats = dict(swarm.stats)
        else:
            swarm_stats = collect()
    if not finished:
        obs.dump_flight("connection_torture_stall")
        server.finish()
        raise TimeoutError(
            f"connection torture stalled: {server.version}/{total} "
            f"commits in {timeout_s}s ({n_connections} connections, "
            f"storm={storm})")
    server.finish()
    # teardown quiesce: poll the fd table back to its baseline before
    # the leak audit reads it — straggler closes (shed sockets, the
    # swarm's teardown) land a few hundred ms after finish(), and a
    # fixed sleep mis-read those transients as ±leaks
    deadline = time.perf_counter() + 2.0
    while True:
        fd_after = open_fd_count()
        if fd_after <= fd_before or time.perf_counter() >= deadline:
            break
        time.sleep(0.05)

    adm1, lag1 = hist_adm.cumulative(), hist_lag.cumulative()
    if adm1[-1][1] - adm0[-1][1] <= 0:
        adm0 = base["adm"]          # run outpaced the warmup snapshot
    if lag1[-1][1] - lag0[-1][1] <= 0:
        lag0 = base["lag"]          # same fallback for the lag window
    walls, sizes = server.commit_walls, server.commit_sizes
    dt = walls[-1] - walls[warmup_commits - 1]
    updates = int(sum(sizes[warmup_commits:]))
    report = {
        "n_connections": int(n_connections),
        "p": int(p),
        "buffer_k": int(buffer_k),
        "ingest_pool": int(ingest_pool),
        "offered_rate": float(offered_rate),
        "storm": bool(storm),
        "churn_lifetime_s": float(churn_lifetime_s),
        "chaos": dict(chaos) if chaos else None,
        "chaos_injected": policy.summary() if policy is not None else None,
        "commits": int(commits),
        "updates_committed": updates,
        "committed_updates_per_sec": updates / dt if dt > 0 else 0.0,
        "admission_p50_s": quantile_from_cumulative(adm0, adm1, 0.50),
        "admission_p95_s": quantile_from_cumulative(adm0, adm1, 0.95),
        # post-warmup window, like the admission percentiles — the
        # cold-start/jit iterations must not skew the steady-state gate
        "loop_lag_p95_s": quantile_from_cumulative(lag0, lag1, 0.95),
        "open_connections_peak": int(peak),
        "evicted": {r: evict[r].value - base["evict"][r]
                    for r in evict},
        "uplinks_shed": shed.value - base["shed"],
        "connections_drained": drained.value - base["drained"],
        "recv_thread_deaths": deaths.value - base["deaths"],
        "dups_suppressed": dups.value - base["dups"],
        "quarantined": quar.value - base["quar"],
        "fd_before": fd_before,
        "fd_after": fd_after,
        "fd_leaked": (fd_after - fd_before
                      if fd_before >= 0 and fd_after >= 0 else None),
        "swarm": swarm_stats,
        "seed": int(seed),
    }
    slo_eng.evaluate()
    report["slo"] = slo_eng.report()
    report["slo_arm"] = slo_eng.arm_summary()
    report["finite"] = bool(all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree.leaves(server.variables)))
    return report
