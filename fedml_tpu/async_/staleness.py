"""Staleness-aware buffered aggregation — the async commit math.

The synchronous engines aggregate a whole cohort at once; the async
scheduler (fedml_tpu/async_/scheduler.py) instead accumulates client
results into a bounded buffer and commits whenever K results arrived or
a round deadline fired (FedBuff-style semi-async, arXiv:2106.06639's
shape).  Each buffered result carries a STALENESS s = the number of
server commits since the model version it trained from; the commit
discounts stale results with one of three standard weight families
(FedAsync, arXiv:1903.03934 §5):

    constant      λ(s) = 1
    polynomial    λ(s) = (1 + s)^-a
    hinge         λ(s) = 1 if s <= b else 1 / (a·(s - b) + 1)

Commit rule (mixing form — FedAsync's update, generalized to a buffer):

    w̃_i   = n_i · λ(s_i)                    (samples x staleness discount)
    avg   = Σ w̃_i v_i / Σ w̃_i              (tree_weighted_mean)
    v_new = (1 - α_eff) · v + α_eff · avg

With α_eff = 1, a full buffer (K = cohort), and constant weights this is
EXACTLY the synchronous FedAvg aggregation — `0·v + 1·avg` is bitwise
`avg`, and avg is the same tree_weighted_mean over the same stacked
results — which is what makes the degenerate-config equivalence pin in
tests/test_async.py exact rather than approximate.  K = 1 with a
polynomial/hinge weight is pure FedAsync.

Buffer layout: ONE flat f32 [K, P] row matrix — the flat-carry layout of
parallel/engine.py (flatten_carry_f32: ravel + concat in jax leaf
order), stacked along the buffer axis.  One buffer, one layout, so the
commit program's donated inputs alias cleanly instead of paying a
per-leaf relayout copy; tools/hlo_copy_audit.py audits the compiled
commit program as the `async_commit` family against the pinned ceiling
in benchmarks/hlo_copy_ceilings.json.

Streaming aggregation-on-arrival (the ISSUE-6 ingestion path): instead
of drain-then-reduce, each arrival folds w̃_i·row_i into a running flat
f32 accumulator via a jitted donated fold step (make_fold_fn), and the
commit shrinks to an O(P) mix of the server variables with ONE
accumulator row (make_stream_commit_fn — audited as the
`async_stream_commit` family, 0 copy ops).  The bitwise anchor is
make_drain_fold_fn: a single compiled lax.scan over the drained [K, P]
matrix whose per-lane ops are EXACTLY the arrival fold's — validated
bitwise-equal to the per-arrival folds on this toolchain
(tests/test_async.py), zero-weight pad lanes included, so full and
deadline (partial) commits share one anchor.  NOTE the legacy drain
commit (make_commit_fn) normalizes weights BEFORE the sum
(tree_weighted_mean: Σ v_i·(w̃_i/W)); a streaming partial sum
necessarily divides after (Σ w̃_i·v_i)/W, so the two FAMILIES agree to
float tolerance, not bitwise — the streaming path is pinned against its
own compiled drain twin, and make_commit_fn stays untouched as the
scheduler's sync-FedAvg anchor.
"""
from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.obs import programs as obs_programs

Pytree = Any

STALENESS_MODES = ("constant", "polynomial", "hinge")


def staleness_weight(mode: str, s, a: float = 0.5, b: float = 4.0):
    """λ(s) for a [K] staleness vector (f32 in, f32 out).  `a`/`b` are
    the FedAsync shape parameters (polynomial exponent / hinge knee)."""
    s = jnp.asarray(s, jnp.float32)
    if mode == "constant":
        return jnp.ones_like(s)
    if mode == "polynomial":
        return jnp.power(1.0 + s, -jnp.float32(a))
    if mode == "hinge":
        return jnp.where(s <= b, jnp.float32(1.0),
                         1.0 / (jnp.float32(a) * (s - b) + 1.0))
    raise ValueError(f"unknown staleness mode {mode!r} "
                     f"(choose one of {STALENESS_MODES})")


# ---------------------------------------------------------------------------
# flat rows — the engine flat-carry layout, with a leading buffer axis
# ---------------------------------------------------------------------------

def flat_dim(template: Pytree) -> int:
    """P — total element count of the variables template (the row width
    of the buffer matrix)."""
    return sum(int(np.prod(l.shape)) if np.ndim(l) else 1
               for l in jax.tree.leaves(template))


def flatten_vars_row(tree: Pytree) -> np.ndarray:
    """One variables pytree → [P] f32 HOST row, ravel+concat in jax leaf
    order — the same element order as engine.flatten_carry_f32, so the
    buffer and the chunk-scan carries speak one layout."""
    leaves = [np.asarray(l, np.float32).reshape(-1)
              for l in jax.tree.leaves(tree)]
    if not leaves:
        return np.zeros((0,), np.float32)
    return leaves[0] if len(leaves) == 1 else np.concatenate(leaves)


def flatten_stacked_rows(stacked: Pytree) -> jax.Array:
    """[C, ...]-stacked variables → [C, P] f32 rows (device-side; the
    per-row element order matches flatten_vars_row/flatten_carry_f32).
    The dispatch-wave trainer emits these so buffer inserts are row
    slices, not pytree walks."""
    leaves = jax.tree.leaves(stacked)
    C = leaves[0].shape[0]
    if len(leaves) == 1:
        return leaves[0].reshape(C, -1).astype(jnp.float32)
    return jnp.concatenate(
        [l.reshape(C, -1).astype(jnp.float32) for l in leaves], axis=1)


def unflatten_row(row: jax.Array, template: Pytree) -> Pytree:
    """[P] flat row → pytree of the template's leaf shapes (in-program;
    slices + reshapes only, bit-preserving — the single-row case of
    unflatten_rows, so the offset walk has exactly one definition)."""
    return jax.tree.map(lambda a: a[0], unflatten_rows(row[None, :],
                                                       template))


def unflatten_rows(rows: jax.Array, template: Pytree) -> Pytree:
    """[K, P] rows → [K, ...]-stacked pytree of the template's leaf
    shapes (in-program; slices + reshapes only, so values are
    bit-preserved — the commit's tree_weighted_mean then sees exactly
    the numbers the clients produced)."""
    leaves, treedef = jax.tree.flatten(template)
    K = rows.shape[0]
    out, off = [], 0
    for l in leaves:
        size = int(np.prod(l.shape)) if l.ndim else 1
        out.append(rows[:, off:off + size].reshape((K,) + tuple(l.shape)))
        off += size
    return jax.tree.unflatten(treedef, out)


class RowLayout:
    """Flat-row decode layout for MessageCodec.decode_into: wire-codec
    array path (the codec's "/key/sub/leaf" strings) → (offset, size,
    shape) in the [P] f32 row, in jax leaf order — flatten_vars_row's
    element order, so a frame decoded into the row is bit-identical to
    flatten_vars_row of the decoded pytree (f32/f64/bf16 leaves; int8
    transport dequant reproduces _decode_transport's f64 affine math).

    `key` is the message param the layout tiles (the async uplink's
    model_params); every other param in a frame decodes normally."""

    def __init__(self, template: Pytree, key: str):
        from jax.tree_util import tree_flatten_with_path
        self.key = key
        self.offsets: dict[str, tuple[int, int, tuple]] = {}
        off = 0
        for path, leaf in tree_flatten_with_path(template)[0]:
            parts = []
            for k in path:
                if hasattr(k, "key"):            # DictKey / FlattenedIndexKey
                    parts.append(str(k.key))
                elif hasattr(k, "idx"):          # SequenceKey
                    parts.append(str(k.idx))
                else:                            # GetAttrKey
                    parts.append(str(getattr(k, "name", k)))
            p = "/" + key + ("/" + "/".join(parts) if parts else "")
            shape = tuple(np.shape(leaf))
            size = int(np.prod(shape)) if shape else 1
            self.offsets[p] = (off, size, shape)
            off += size
        self.p = off


# ---------------------------------------------------------------------------
# the jitted commit program
# ---------------------------------------------------------------------------

def make_commit_fn(template: Pytree, mode: str = "constant",
                   a: float = 0.5, b: float = 4.0, donate: bool = True):
    """Build the jitted async commit:

        commit(variables, rows [K,P] f32, weights [K], staleness [K],
               alpha) -> (new_variables, stats)

    `weights` are per-result sample counts with zero-weight pad lanes
    (a deadline commit drains a part-full buffer padded to capacity —
    zero lanes drop out of the weighted mean exactly, so ONE compiled
    program serves full and partial commits).  `alpha` is the server
    mixing rate α_eff; stats carries the effective discount mass for
    observability.  `variables` is donated — the output has its exact
    shapes, so the update aliases in place instead of paying a
    params-sized HBM copy per commit (the rows matrix is NOT donated:
    no output matches its [K, P] shape, so donating it only trips
    XLA's unusable-donation warning)."""
    if mode not in STALENESS_MODES:
        raise ValueError(f"unknown staleness mode {mode!r} "
                         f"(choose one of {STALENESS_MODES})")

    def commit(variables, rows, weights, staleness, alpha):
        lam = staleness_weight(mode, staleness, a, b)
        w = weights * lam
        stacked = unflatten_rows(rows, variables)
        avg = tree_weighted_mean(stacked, w)
        alpha = jnp.asarray(alpha, jnp.float32)
        new = jax.tree.map(
            lambda v, m: ((1.0 - alpha) * v.astype(jnp.float32)
                          + alpha * m).astype(v.dtype),
            variables, avg)
        stats = {"discount_mass": jnp.sum(w) / jnp.maximum(
            jnp.sum(weights), 1e-12)}
        return new, stats

    # ISSUE 12: every dispatch of the legacy drain commit counts
    # into the async_commit profile family (obs/programs.py) —
    # host-side wall + compile attribution, values untouched
    return obs_programs.instrument(
        "async_commit",
        jax.jit(commit, donate_argnums=(0,) if donate else ()))


# ---------------------------------------------------------------------------
# streaming aggregation-on-arrival (the ingestion hot path)
# ---------------------------------------------------------------------------

def make_fold_fn(mode: str = "constant", a: float = 0.5, b: float = 4.0):
    """Jitted arrival fold — the streaming partial sum's one step:

        fold(acc [P], wsum, row [P], weight, staleness)
            -> (acc + w̃·row, wsum + w̃),   w̃ = weight·λ(staleness)

    `acc` and `wsum` are donated: the running accumulator updates in
    place, so an arrival costs one O(P) multiply-add and no buffer-row
    copy at commit time.  λ is computed IN-program (scalar jnp.power ==
    the [K]-vector power of the drain twin bitwise on this toolchain —
    numpy's libm differs, which is why the fold is jitted rather than a
    host numpy loop)."""
    if mode not in STALENESS_MODES:
        raise ValueError(f"unknown staleness mode {mode!r} "
                         f"(choose one of {STALENESS_MODES})")

    def fold(acc, wsum, row, weight, staleness):
        lam = staleness_weight(mode, staleness, a, b)
        wt = jnp.asarray(weight, jnp.float32) * lam
        return acc + wt * row, wsum + wt

    # ISSUE 12: the arrival fold is the ingestion hot path — its
    # per-dispatch wall histogram is the async_fold profile family
    return obs_programs.instrument(
        "async_fold", jax.jit(fold, donate_argnums=(0, 1)))


def make_sparse_fold_fn(mode: str = "constant", a: float = 0.5,
                        b: float = 4.0):
    """Jitted SPARSE twin of the arrival fold (ISSUE 19):

        fold(acc [P], wsum, idx [k], vals [k], weight, staleness)
            -> (acc + w̃·scatter(idx, vals), wsum + w̃)

    Takes the k (index, value) pairs a sparse_topk frame carries
    (comm.message.MessageCodec.decode_sparse) — the dense [P] row never
    materializes on the HOST; it exists only as an in-program scatter
    feeding the IDENTICAL `acc + w̃·row` expression as make_fold_fn.
    That expression sharing is load-bearing for bitwise parity: a
    scatter-ADD of pre-multiplied w̃·vals would round twice where the
    dense fold's fused multiply-add rounds once, putting sparse commits
    one ULP off the dense fold of the densified row (measured on this
    toolchain).  λ is the same in-program power as make_fold_fn.
    `acc`/`wsum` donated, same as the dense fold.  Compiles once per k
    (the fixed-ratio wire keeps k constant per template)."""
    if mode not in STALENESS_MODES:
        raise ValueError(f"unknown staleness mode {mode!r} "
                         f"(choose one of {STALENESS_MODES})")

    def fold(acc, wsum, idx, vals, weight, staleness):
        lam = staleness_weight(mode, staleness, a, b)
        wt = jnp.asarray(weight, jnp.float32) * lam
        row = jnp.zeros_like(acc).at[idx].set(vals)
        return acc + wt * row, wsum + wt

    return obs_programs.instrument(
        "async_sparse_fold", jax.jit(fold, donate_argnums=(0, 1)))


def make_field_fold_fn(prime: int):
    """Jitted INTEGER-FIELD twin of the arrival fold (ISSUE 20) — the
    secure-aggregation data plane's mask-and-fold:

        fold(acc [W] u32, row [W] u32) -> (acc + row) mod prime

    Rides the same flat-row shape as make_fold_fn (a secagg row is the
    flatten_vars_row layout, fixed-point quantized, plus one trailing
    masked weight word), so the server's aggregation-on-arrival
    structure and O(P) commit survive masking unchanged — the field sum
    collapses to an (acc, wsum) pair at the unmask barrier and feeds
    make_stream_commit_fn as-is.

    Arithmetic safety: both operands are field residues < prime
    ≤ 2^31−1, so the u32 sum peaks below 2^32−1 — no wraparound before
    the mod.  The fold is exact integer math end to end, which is what
    makes the masked cohort aggregate BITWISE equal to the plain
    fixed-point sum (the ISSUE-20 anchor pin, tests/test_secagg.py).
    `acc` is donated: the running field accumulator updates in place."""
    p = np.uint32(prime)

    def fold(acc, row):
        return jnp.mod(acc + row, p)

    return obs_programs.instrument(
        "secagg_fold", jax.jit(fold, donate_argnums=(0,)))


def make_drain_fold_fn(mode: str = "constant", a: float = 0.5,
                       b: float = 4.0):
    """ONE compiled drained twin of the arrival fold: lax.scan the same
    per-lane ops over a [K, P] matrix (zero-weight pad lanes are exact
    no-ops, so a capacity-padded deadline drain matches a partial
    streaming fold).  drain(rows, weights, staleness) -> (acc, wsum) —
    bitwise-equal to folding the lanes one arrival at a time through
    make_fold_fn (pinned in tests/test_async.py), which is what makes
    the streaming commit auditable against a drained replay."""
    if mode not in STALENESS_MODES:
        raise ValueError(f"unknown staleness mode {mode!r} "
                         f"(choose one of {STALENESS_MODES})")

    def drain(rows, weights, staleness):
        def body(carry, xs):
            acc, wsum = carry
            row, w, s = xs
            lam = staleness_weight(mode, s, a, b)
            wt = w * lam
            return (acc + wt * row, wsum + wt), None
        init = (jnp.zeros((rows.shape[1],), jnp.float32),
                jnp.zeros((), jnp.float32))
        (acc, wsum), _ = jax.lax.scan(body, init,
                                      (rows, weights, staleness))
        return acc, wsum

    return obs_programs.instrument("async_drain_fold",
                                   jax.jit(drain))


def make_stream_commit_fn(template: Pytree, donate: bool = True):
    """Build the O(P) streaming commit:

        commit(variables, acc [P], wsum, alpha) -> (new_variables, stats)

    The K-wide reduction already happened at arrival time (make_fold_fn),
    so the commit is one divide + the mixing update — no [K, P] matrix
    upload, no O(K·P) reduce.  `variables` AND `wsum` are donated (the
    update aliases in place; the stats passthrough of the consumed
    scalar aliases instead of paying XLA's param-to-output copy); `acc`
    is not (no output shares its [P] shape).  Audited as the
    `async_stream_commit` hlo_copy_audit family with a 0-copy-op
    ceiling."""

    def commit(variables, acc, wsum, alpha):
        avg = unflatten_row(acc / wsum, variables)
        alpha = jnp.asarray(alpha, jnp.float32)
        new = jax.tree.map(
            lambda v, m: ((1.0 - alpha) * v.astype(jnp.float32)
                          + alpha * m).astype(v.dtype),
            variables, avg)
        return new, {"discount_wsum": wsum}

    return obs_programs.instrument(
        "async_stream_commit",
        jax.jit(commit, donate_argnums=(0, 2) if donate else ()))


BUCKET_COMBINE_MODES = ("mean", "trimmed_mean", "median")


def make_bucket_commit_fn(template: Pytree, combine: str = "trimmed_mean",
                          trim_k: int = 0, dp_noise: float = 0.0,
                          dp_clip: float = 1.0, donate: bool = True):
    """Build the O(B·P) bucketed ROBUST streaming commit (ISSUE 9):

        commit(variables, accs [B,P], wsums [B], alpha[, rng])
            -> (new_variables, stats)

    Each arrival folded w̃·row into one of B seeded bucket accumulators
    (AsyncBuffer(buckets=B)); the commit divides each non-empty bucket
    into its discounted mean and combines ACROSS bucket means with a
    robust order statistic — the Karimireddy et al. bucketing recipe
    (arXiv:2006.09365 shape) adapted to the streaming regime: memory
    stays O(B·P), never O(K·P), so the PR-6 aggregation-on-arrival
    property survives the defense.

    Combine families (per coordinate, over the m non-empty buckets,
    empty buckets masked to +inf before the sort so they fall outside
    every rank window):

        mean           trimmed_mean with k_eff = 0
        trimmed_mean   drop the k_eff = min(trim_k, ⌊(m-1)/2⌋) largest
                       and smallest bucket means, average the rest
        median         per-coordinate median of the m bucket means

    DEGENERATE PIN: B = 1, trim 0 (or "mean") reproduces the PR-6
    streaming commit (make_stream_commit_fn) BITWISE — the single
    bucket mean is the same acc/wsum division, the sort over a
    size-1 axis is the identity, and the final /1.0 is exact — pinned
    in tests/test_robustness.py and audited as the
    `async_bucket_commit` hlo_copy_audit family (0 copy ops;
    variables, accs and wsums all donated — accs aliases into the
    stats' bucket_means passthrough).

    DP-FedAvg (ROADMAP item 4's first server transform): `dp_noise`
    > 0 adds Gaussian noise inside the jitted commit — the signature
    grows to commit(variables, accs, wsums, alpha, n_contrib, rng),
    and σ = dp_noise·dp_clip/n_contrib per coordinate on the combined
    mean: the per-client clip (the SAME clip_row definition,
    core/robust.py, applied at admission) bounds each contribution to
    dp_clip, so the n-client average has sensitivity S/n and the
    McMahan et al. 2018 noise-multiplier convention divides by the
    CLIENT count, not the bucket count.  dp_noise = 0 builds the
    noise-free 4-arg program (no dormant ops in the degenerate
    pin)."""
    if combine not in BUCKET_COMBINE_MODES:
        raise ValueError(f"unknown bucket combine {combine!r} "
                         f"(choose one of {BUCKET_COMBINE_MODES})")
    if trim_k < 0:
        raise ValueError(f"trim_k must be >= 0, got {trim_k}")

    def _combine(accs, wsums):
        valid = wsums > 0.0
        m = jnp.sum(valid.astype(jnp.float32))
        safe_w = jnp.where(valid, wsums, 1.0)
        means = accs / safe_w[:, None]
        masked = jnp.where(valid[:, None], means, jnp.inf)
        s = jnp.sort(masked, axis=0)          # invalid rows sort to the top
        if combine == "median":
            mi = m.astype(jnp.int32)
            lo = jnp.take(s, (mi - 1) // 2, axis=0)
            hi = jnp.take(s, mi // 2, axis=0)
            row = 0.5 * (lo + hi)
        else:
            k_eff = (jnp.minimum(jnp.float32(trim_k),
                                 jnp.floor((m - 1.0) / 2.0))
                     if combine == "trimmed_mean" and trim_k > 0
                     else jnp.float32(0.0))
            ranks = jnp.arange(s.shape[0], dtype=jnp.float32)[:, None]
            keep = (ranks >= k_eff) & (ranks < m - k_eff)
            row = (jnp.sum(jnp.where(keep, s, 0.0), axis=0)
                   / (m - 2.0 * k_eff))
        stats = {"bucket_means": jnp.where(valid[:, None], means, 0.0),
                 "n_buckets": m, "bucket_wsum": wsums}
        return row, m, stats

    def _mix(variables, row, alpha):
        avg = unflatten_row(row, variables)
        alpha = jnp.asarray(alpha, jnp.float32)
        return jax.tree.map(
            lambda v, mm: ((1.0 - alpha) * v.astype(jnp.float32)
                           + alpha * mm).astype(v.dtype),
            variables, avg)

    if dp_noise > 0.0:
        def commit(variables, accs, wsums, alpha, n_contrib, rng):
            row, _m, stats = _combine(accs, wsums)
            sigma = (jnp.float32(dp_noise * dp_clip)
                     / jnp.maximum(jnp.asarray(n_contrib, jnp.float32),
                                   1.0))
            row = row + sigma * jax.random.normal(rng, row.shape,
                                                  jnp.float32)
            return _mix(variables, row, alpha), stats
    else:
        def commit(variables, accs, wsums, alpha):
            row, _m, stats = _combine(accs, wsums)
            return _mix(variables, row, alpha), stats

    # variables alias the update in place; accs alias the bucket_means
    # stats passthrough (same [B, P] f32 shape); wsums alias their own
    # passthrough — the 0-copy `async_bucket_commit` audit family
    return obs_programs.instrument(
        "async_bucket_commit",
        jax.jit(commit, donate_argnums=(0, 1, 2) if donate else ()))


# ---------------------------------------------------------------------------
# the bounded aggregation buffer
# ---------------------------------------------------------------------------

class AsyncBuffer:
    """Bounded aggregation buffer, in one of two modes:

    * drain mode (default, the PR-5 layout): [capacity, P] f32 host
      rows plus per-row sample weights and staleness.  `drain()` always
      returns capacity-sized arrays (zero-weight pad lanes beyond
      `count`) so the commit program compiles once; the real-row count
      rides alongside.  One np.copyto per insert, one device_put of the
      matrix at commit.
    * streaming mode (ISSUE-6 aggregation-on-arrival): no row matrix —
      `add` folds w̃·row into a running flat f32 accumulator via the
      jitted donated fold step (make_fold_fn), so `take_stream()` hands
      the commit one [P] accumulator + Σw̃ and the commit is O(P).
      Per-lane weights/staleness are still recorded (stats +
      checkpoint), and a drain-mode checkpoint restores into a
      streaming buffer by REPLAYING its rows through the same fold —
      bitwise the accumulator the arrivals would have built.

    Bucketed mode (ISSUE 9, streaming only): `buckets` = B > 1 keeps B
    independent [P] accumulators instead of one; each arrival folds
    into a SEEDED bucket (block-wise seeded permutations of range(B),
    so every window of B inserts spreads evenly but an attacker cannot
    predict its bucket from its arrival slot — the assignment stream
    is a pure function of `bucket_seed` and the insert sequence, like
    comm/chaos.py's fault streams).  `take_stream_buckets()` hands the
    bucketed robust commit (make_bucket_commit_fn) the stacked
    [B, P] / [B] state; memory stays O(B·P), preserving the PR-6
    streaming regime.  B = 1 keeps the exact PR-6 fields and code
    path.

    Internally thread-safe (ISSUE-6 satellite): `add`, `drain`,
    `take_stream`, `state`, and `load_state` all take the buffer's own
    lock, so a checkpoint snapshot racing a decode-pool insert can
    never see a torn (count, accumulator) pair.  Callers that already
    serialize under a manager lock pay one uncontended acquire."""

    def __init__(self, capacity: int, p: int, *, streaming: bool = False,
                 staleness_mode: str = "constant", staleness_a: float = 0.5,
                 staleness_b: float = 4.0, buckets: int = 1,
                 bucket_seed: int = 0):
        if capacity < 1:
            raise ValueError(f"buffer capacity must be >= 1, got {capacity}")
        if buckets < 1:
            raise ValueError(f"buckets must be >= 1, got {buckets}")
        if buckets > 1 and not streaming:
            raise ValueError("bucketed aggregation needs streaming=True "
                             "(drain mode already holds the full [K, P] "
                             "matrix — bucket it at commit time instead)")
        if buckets > capacity:
            raise ValueError(f"buckets ({buckets}) cannot exceed buffer "
                             f"capacity ({capacity}): a full buffer could "
                             f"never populate every bucket")
        self.capacity = capacity
        self.p = p
        self.streaming = streaming
        self.buckets = int(buckets)
        self._lock = threading.Lock()
        self.weights = np.zeros((capacity,), np.float32)
        self.staleness = np.zeros((capacity,), np.float32)
        self.count = 0
        if streaming:
            self.rows = None
            self._fold = make_fold_fn(staleness_mode, staleness_a,
                                      staleness_b)
            # sparse twin (ISSUE 19), built on first sparse arrival so
            # dense-only buffers never pay the extra jit cache entry
            self._sparse_fold = None
            self._staleness_args = (staleness_mode, staleness_a,
                                    staleness_b)
            if self.buckets > 1:
                self._accs = [jnp.zeros((p,), jnp.float32)
                              for _ in range(self.buckets)]
                self._wsums = [jnp.zeros((), jnp.float32)
                               for _ in range(self.buckets)]
                self._bucket_rng = np.random.default_rng([bucket_seed, 5])
                self._bucket_order: list[int] = []
                self._bucket_draws = 0   # assignment-stream position
            else:
                self.acc = jnp.zeros((p,), jnp.float32)
                self.wsum = jnp.zeros((), jnp.float32)
            self.raw_wsum = 0.0          # un-discounted Σweight (stats)
        else:
            self.rows = np.zeros((capacity, p), np.float32)

    def _next_bucket(self) -> int:
        """Seeded bucket draw (caller holds _lock): refill with a fresh
        permutation of range(B) every B inserts — even spread per
        window, order unpredictable, deterministic per bucket_seed."""
        if not self._bucket_order:
            self._bucket_order = [int(b) for b in
                                  self._bucket_rng.permutation(self.buckets)]
        self._bucket_draws += 1
        return self._bucket_order.pop()

    def _peek_bucket(self) -> int:
        """The bucket the NEXT accepted insert will take, without
        consuming the draw — the screened fold needs the target
        accumulator before admission is decided, and a quarantined row
        must not advance the assignment stream (replaying only the
        accepted rows then reproduces the same assignment)."""
        if not self._bucket_order:
            self._bucket_order = [int(b) for b in
                                  self._bucket_rng.permutation(self.buckets)]
        return self._bucket_order[-1]

    def add_screened(self, row, weight: float, staleness: float,
                     admission, *, sender: int = -1,
                     version: Optional[int] = None):
        """The ISSUE-9 defended insert: ONE fused jitted dispatch
        screens the row (canary -> clip -> anomaly screen) and folds
        the accepted contribution into the (bucketed) accumulator
        (defense.UpdateAdmission.screened_fold).  Returns (admitted,
        reason, full) — a quarantined row leaves the accumulator
        bit-untouched, consumes no buffer slot and no bucket draw.
        Streaming mode only."""
        with self._lock:
            if not self.streaming:
                raise RuntimeError(
                    "add_screened() on a drain-mode AsyncBuffer — the "
                    "admission pipeline rides the streaming fold")
            if self.count >= self.capacity:
                raise RuntimeError("async buffer overflow: commit before add")
            if self.buckets > 1:
                b = self._peek_bucket()
                ok, why, acc1, wsum1 = admission.screened_fold(
                    self._accs[b], self._wsums[b], row, weight, staleness,
                    sender=sender, version=version)
                self._accs[b], self._wsums[b] = acc1, wsum1
                if ok:
                    self._bucket_order.pop()
                    self._bucket_draws += 1
            else:
                ok, why, acc1, wsum1 = admission.screened_fold(
                    self.acc, self.wsum, row, weight, staleness,
                    sender=sender, version=version)
                self.acc, self.wsum = acc1, wsum1
            # no extra sync: screened_fold's host fetch of the admit
            # flag already blocked on the whole fused program (one CPU
            # executable — materializing any output means the fold that
            # may alias the caller's row buffer has completed), so the
            # row-recycling guarantee add() buys with
            # wsum.block_until_ready() is already paid
            if not ok:
                return False, why, False
            i = self.count
            self.weights[i] = np.float32(weight)
            self.staleness[i] = np.float32(staleness)
            self.raw_wsum += float(weight)
            self.count += 1
            return True, why, self.count >= self.capacity

    def add(self, row: np.ndarray, weight: float, staleness: float) -> bool:
        """Insert one result; returns True when the buffer reached
        capacity (the scheduler's commit trigger).  Streaming mode folds
        the row into the accumulator instead of storing it."""
        with self._lock:
            if self.count >= self.capacity:
                raise RuntimeError("async buffer overflow: commit before add")
            i = self.count
            self.weights[i] = np.float32(weight)
            self.staleness[i] = np.float32(staleness)
            if self.streaming:
                if not isinstance(row, jax.Array):
                    # device arrays (the admission pipeline's clipped
                    # rows) feed the fold directly — no host detour
                    row = np.ascontiguousarray(row, np.float32)
                if self.buckets > 1:
                    b = self._next_bucket()
                    self._accs[b], self._wsums[b] = self._fold(
                        self._accs[b], self._wsums[b], row,
                        np.float32(weight), np.float32(staleness))
                    # same row-recycling sync as the B=1 path below
                    self._wsums[b].block_until_ready()
                else:
                    self.acc, self.wsum = self._fold(
                        self.acc, self.wsum, row,
                        np.float32(weight), np.float32(staleness))
                    # jax on CPU may alias `row`'s host buffer zero-copy
                    # and dispatches asynchronously; block before
                    # returning so callers may recycle/overwrite the row
                    # (the decode pool's scratch free-list does exactly
                    # that — an unsynced fold would read a
                    # half-overwritten row)
                    self.wsum.block_until_ready()
                self.raw_wsum += float(weight)
            else:
                np.copyto(self.rows[i], row)
            self.count += 1
            return self.count >= self.capacity

    def add_sparse(self, idx: np.ndarray, vals: np.ndarray,
                   weight: float, staleness: float) -> bool:
        """Insert one SPARSE result (ISSUE 19): scatter-add the k
        (global row index, value) pairs of a sparse_topk frame into the
        streaming accumulator via the jitted sparse fold — the dense
        [P] row never exists on the host.  Streaming B = 1 only: the
        bucketed robust path and the admission screen are defined over
        dense rows (norm screens need the whole row), so sparse uplinks
        compose with neither — route defended/bucketed configs through
        decode_into + add() instead."""
        with self._lock:
            if not self.streaming:
                raise RuntimeError(
                    "add_sparse() on a drain-mode AsyncBuffer — sparse "
                    "arrivals ride the streaming fold")
            if self.buckets > 1:
                raise RuntimeError(
                    "add_sparse() on a bucketed AsyncBuffer — the "
                    "robust bucket screens need dense rows; decode the "
                    "frame via decode_into instead")
            if self.count >= self.capacity:
                raise RuntimeError("async buffer overflow: commit before add")
            if self._sparse_fold is None:
                self._sparse_fold = make_sparse_fold_fn(
                    *self._staleness_args)
            i = self.count
            self.weights[i] = np.float32(weight)
            self.staleness[i] = np.float32(staleness)
            self.acc, self.wsum = self._sparse_fold(
                self.acc, self.wsum,
                np.ascontiguousarray(idx, np.int64),
                np.ascontiguousarray(vals, np.float32),
                np.float32(weight), np.float32(staleness))
            # same row-recycling sync as add(): jax on CPU may alias
            # the pair buffers zero-copy and dispatches asynchronously
            self.wsum.block_until_ready()
            self.raw_wsum += float(weight)
            self.count += 1
            return self.count >= self.capacity

    def drain(self):
        """(rows [K,P], weights [K], staleness [K], n_real) — padded to
        capacity with zero-weight lanes; resets the buffer.  Drain mode
        only (a streaming buffer has no rows to hand back)."""
        with self._lock:
            if self.streaming:
                raise RuntimeError(
                    "drain() on a streaming AsyncBuffer — use take_stream()")
            n = self.count
            out = (self.rows.copy(), self.weights.copy(),
                   self.staleness.copy(), n)
            self.rows[:] = 0.0
            self.weights[:] = 0.0
            self.staleness[:] = 0.0
            self.count = 0
            return out

    def take_stream(self):
        """(acc [P], wsum, weights [K], staleness [K], n_real, raw_wsum)
        — the streaming commit's inputs; resets the buffer.  Streaming
        mode only (B = 1; a bucketed buffer hands back stacked state
        via take_stream_buckets)."""
        with self._lock:
            if not self.streaming:
                raise RuntimeError(
                    "take_stream() on a drain-mode AsyncBuffer — use drain()")
            if self.buckets > 1:
                raise RuntimeError(
                    "take_stream() on a bucketed AsyncBuffer — use "
                    "take_stream_buckets()")
            out = (self.acc, self.wsum, self.weights.copy(),
                   self.staleness.copy(), self.count, self.raw_wsum)
            self.acc = jnp.zeros((self.p,), jnp.float32)
            self.wsum = jnp.zeros((), jnp.float32)
            self.raw_wsum = 0.0
            self.weights[:] = 0.0
            self.staleness[:] = 0.0
            self.count = 0
            return out

    def take_stream_buckets(self):
        """(accs [B,P], wsums [B], weights [K], staleness [K], n_real,
        raw_wsum) — the bucketed robust commit's inputs; resets the
        buffer.  Works for any streaming buffer (B = 1 stacks the PR-6
        accumulator, so the degenerate-config pin runs through the SAME
        bucket commit program it is pinned against)."""
        with self._lock:
            if not self.streaming:
                raise RuntimeError("take_stream_buckets() on a drain-mode "
                                   "AsyncBuffer — use drain()")
            if self.buckets > 1:
                accs = jnp.stack(self._accs)
                wsums = jnp.stack(self._wsums)
                self._accs = [jnp.zeros((self.p,), jnp.float32)
                              for _ in range(self.buckets)]
                self._wsums = [jnp.zeros((), jnp.float32)
                               for _ in range(self.buckets)]
            else:
                accs = self.acc[None, :]
                wsums = self.wsum[None]
                self.acc = jnp.zeros((self.p,), jnp.float32)
                self.wsum = jnp.zeros((), jnp.float32)
            out = (accs, wsums, self.weights.copy(),
                   self.staleness.copy(), self.count, self.raw_wsum)
            self.raw_wsum = 0.0
            self.weights[:] = 0.0
            self.staleness[:] = 0.0
            self.count = 0
            return out

    def state(self) -> dict:
        """Checkpointable snapshot (fedml_tpu/utils/checkpoint.py
        extra_state) — plain arrays, restored by load_state.  Streaming
        mode carries the accumulator fields instead of the row matrix."""
        with self._lock:
            common = {"weights": self.weights.copy(),
                      "staleness": self.staleness.copy(),
                      # 0-d ndarray, not a numpy scalar: orbax
                      # StandardSave rejects np.int64(x) leaves
                      "count": np.asarray(self.count, np.int64)}
            if self.streaming:
                if self.buckets > 1:
                    # bucketed crash-resume (ISSUE 9): the stacked
                    # accumulators ARE the round state — restore refuses
                    # on a bucket-count change like the shape checks
                    # below.  bucket_draws is the assignment stream's
                    # position: a resumed buffer replays that many
                    # seeded draws, so post-resume inserts continue the
                    # SAME permutation schedule the crashed run was on
                    common.update(
                        acc=np.stack([np.asarray(a, np.float32)
                                      for a in self._accs]),
                        wsum=np.stack([np.asarray(w, np.float32)
                                       for w in self._wsums]),
                        raw_wsum=np.asarray(self.raw_wsum, np.float64),
                        bucket_draws=np.asarray(self._bucket_draws,
                                                np.int64))
                else:
                    common.update(
                        acc=np.asarray(self.acc, np.float32).copy(),
                        wsum=np.asarray(self.wsum, np.float32).copy(),
                        raw_wsum=np.asarray(self.raw_wsum, np.float64))
            else:
                common["rows"] = self.rows.copy()
            return common

    def load_state(self, state: dict) -> None:
        with self._lock:
            w = np.asarray(state["weights"], np.float32)
            if w.shape != self.weights.shape:
                raise ValueError(
                    f"async buffer shape mismatch: checkpoint weights "
                    f"{w.shape} vs configured {self.weights.shape} "
                    f"(buffer_k changed)")
            np.copyto(self.weights, w)
            np.copyto(self.staleness,
                      np.asarray(state["staleness"], np.float32))
            self.count = int(state["count"])
            if self.streaming:
                if "acc" in state and self.buckets > 1:
                    acc = np.asarray(state["acc"], np.float32)
                    if acc.shape != (self.buckets, self.p):
                        raise ValueError(
                            f"async buffer shape mismatch: checkpoint acc "
                            f"{acc.shape} vs configured "
                            f"({self.buckets}, {self.p}) (buckets or "
                            f"model changed)")
                    wsum = np.asarray(state["wsum"], np.float32)
                    # copy=True for the same donation-safety reason as
                    # the B=1 branch below
                    self._accs = [jnp.array(acc[b], copy=True)
                                  for b in range(self.buckets)]
                    self._wsums = [jnp.array(wsum[b], copy=True)
                                   for b in range(self.buckets)]
                    self.raw_wsum = float(state.get(
                        "raw_wsum", float(np.sum(self.weights))))
                    # resume the assignment stream where the crashed
                    # run left it — without the replay, post-resume
                    # inserts would redraw a window the interrupted
                    # permutation had already part-consumed
                    for _ in range(int(state.get("bucket_draws", 0))):
                        self._next_bucket()
                    self._bucket_draws = int(state.get("bucket_draws", 0))
                elif "acc" in state:
                    acc = np.asarray(state["acc"], np.float32)
                    if acc.shape != (self.p,):
                        raise ValueError(
                            f"async buffer shape mismatch: checkpoint acc "
                            f"{acc.shape} vs configured ({self.p},) "
                            f"(model changed)")
                    # copy=True, NOT asarray: on CPU jax may alias the
                    # numpy/orbax buffer zero-copy, and the next add()
                    # DONATES acc to the jitted fold — donating memory
                    # jax does not own corrupts the heap (empirically: a
                    # deferred glibc abort in a later commit on this
                    # toolchain, surfaced by the crash-resume e2e)
                    self.acc = jnp.array(acc, copy=True)
                    self.wsum = jnp.array(
                        np.asarray(state["wsum"], np.float32), copy=True)
                    self.raw_wsum = float(state.get(
                        "raw_wsum", float(np.sum(self.weights))))
                elif "rows" in state:
                    # drain-mode checkpoint into a streaming buffer:
                    # replay the saved rows through the fold — bitwise
                    # the accumulator those arrivals would have built
                    # (bucketed buffers replay through their own seeded
                    # assignment stream, exactly as live arrivals would)
                    rows = np.asarray(state["rows"], np.float32)
                    if rows.shape[1] != self.p:
                        raise ValueError(
                            f"async buffer shape mismatch: checkpoint rows "
                            f"{rows.shape} vs row width {self.p}")
                    if self.buckets > 1:
                        self._accs = [jnp.zeros((self.p,), jnp.float32)
                                      for _ in range(self.buckets)]
                        self._wsums = [jnp.zeros((), jnp.float32)
                                       for _ in range(self.buckets)]
                        for i in range(self.count):
                            b = self._next_bucket()
                            self._accs[b], self._wsums[b] = self._fold(
                                self._accs[b], self._wsums[b], rows[i],
                                self.weights[i], self.staleness[i])
                    else:
                        self.acc = jnp.zeros((self.p,), jnp.float32)
                        self.wsum = jnp.zeros((), jnp.float32)
                        for i in range(self.count):
                            self.acc, self.wsum = self._fold(
                                self.acc, self.wsum, rows[i],
                                self.weights[i], self.staleness[i])
                    self.raw_wsum = float(np.sum(self.weights[:self.count]))
                else:
                    raise ValueError(
                        "async buffer checkpoint carries neither 'acc' nor "
                        "'rows'")
            else:
                if "rows" not in state:
                    raise ValueError(
                        "streaming-buffer checkpoint cannot restore into a "
                        "drain-mode AsyncBuffer: the row matrix is not "
                        "reconstructible from the accumulator")
                rows = np.asarray(state["rows"], np.float32)
                if rows.shape != self.rows.shape:
                    raise ValueError(
                        f"async buffer shape mismatch: checkpoint "
                        f"{rows.shape} vs configured {self.rows.shape} "
                        f"(buffer_k or model changed)")
                np.copyto(self.rows, rows)
