"""Staleness-aware buffered aggregation — the async commit math.

The synchronous engines aggregate a whole cohort at once; the async
scheduler (fedml_tpu/async_/scheduler.py) instead accumulates client
results into a bounded buffer and commits whenever K results arrived or
a round deadline fired (FedBuff-style semi-async, arXiv:2106.06639's
shape).  Each buffered result carries a STALENESS s = the number of
server commits since the model version it trained from; the commit
discounts stale results with one of three standard weight families
(FedAsync, arXiv:1903.03934 §5):

    constant      λ(s) = 1
    polynomial    λ(s) = (1 + s)^-a
    hinge         λ(s) = 1 if s <= b else 1 / (a·(s - b) + 1)

Commit rule (mixing form — FedAsync's update, generalized to a buffer):

    w̃_i   = n_i · λ(s_i)                    (samples x staleness discount)
    avg   = Σ w̃_i v_i / Σ w̃_i              (tree_weighted_mean)
    v_new = (1 - α_eff) · v + α_eff · avg

With α_eff = 1, a full buffer (K = cohort), and constant weights this is
EXACTLY the synchronous FedAvg aggregation — `0·v + 1·avg` is bitwise
`avg`, and avg is the same tree_weighted_mean over the same stacked
results — which is what makes the degenerate-config equivalence pin in
tests/test_async.py exact rather than approximate.  K = 1 with a
polynomial/hinge weight is pure FedAsync.

Buffer layout: ONE flat f32 [K, P] row matrix — the flat-carry layout of
parallel/engine.py (flatten_carry_f32: ravel + concat in jax leaf
order), stacked along the buffer axis.  One buffer, one layout, so the
commit program's donated inputs alias cleanly instead of paying a
per-leaf relayout copy; tools/hlo_copy_audit.py audits the compiled
commit program as the `async_commit` family against the pinned ceiling
in benchmarks/hlo_copy_ceilings.json.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.pytree import tree_weighted_mean

Pytree = Any

STALENESS_MODES = ("constant", "polynomial", "hinge")


def staleness_weight(mode: str, s, a: float = 0.5, b: float = 4.0):
    """λ(s) for a [K] staleness vector (f32 in, f32 out).  `a`/`b` are
    the FedAsync shape parameters (polynomial exponent / hinge knee)."""
    s = jnp.asarray(s, jnp.float32)
    if mode == "constant":
        return jnp.ones_like(s)
    if mode == "polynomial":
        return jnp.power(1.0 + s, -jnp.float32(a))
    if mode == "hinge":
        return jnp.where(s <= b, jnp.float32(1.0),
                         1.0 / (jnp.float32(a) * (s - b) + 1.0))
    raise ValueError(f"unknown staleness mode {mode!r} "
                     f"(choose one of {STALENESS_MODES})")


# ---------------------------------------------------------------------------
# flat rows — the engine flat-carry layout, with a leading buffer axis
# ---------------------------------------------------------------------------

def flat_dim(template: Pytree) -> int:
    """P — total element count of the variables template (the row width
    of the buffer matrix)."""
    return sum(int(np.prod(l.shape)) if np.ndim(l) else 1
               for l in jax.tree.leaves(template))


def flatten_vars_row(tree: Pytree) -> np.ndarray:
    """One variables pytree → [P] f32 HOST row, ravel+concat in jax leaf
    order — the same element order as engine.flatten_carry_f32, so the
    buffer and the chunk-scan carries speak one layout."""
    leaves = [np.asarray(l, np.float32).reshape(-1)
              for l in jax.tree.leaves(tree)]
    if not leaves:
        return np.zeros((0,), np.float32)
    return leaves[0] if len(leaves) == 1 else np.concatenate(leaves)


def flatten_stacked_rows(stacked: Pytree) -> jax.Array:
    """[C, ...]-stacked variables → [C, P] f32 rows (device-side; the
    per-row element order matches flatten_vars_row/flatten_carry_f32).
    The dispatch-wave trainer emits these so buffer inserts are row
    slices, not pytree walks."""
    leaves = jax.tree.leaves(stacked)
    C = leaves[0].shape[0]
    if len(leaves) == 1:
        return leaves[0].reshape(C, -1).astype(jnp.float32)
    return jnp.concatenate(
        [l.reshape(C, -1).astype(jnp.float32) for l in leaves], axis=1)


def unflatten_rows(rows: jax.Array, template: Pytree) -> Pytree:
    """[K, P] rows → [K, ...]-stacked pytree of the template's leaf
    shapes (in-program; slices + reshapes only, so values are
    bit-preserved — the commit's tree_weighted_mean then sees exactly
    the numbers the clients produced)."""
    leaves, treedef = jax.tree.flatten(template)
    K = rows.shape[0]
    out, off = [], 0
    for l in leaves:
        size = int(np.prod(l.shape)) if l.ndim else 1
        out.append(rows[:, off:off + size].reshape((K,) + tuple(l.shape)))
        off += size
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# the jitted commit program
# ---------------------------------------------------------------------------

def make_commit_fn(template: Pytree, mode: str = "constant",
                   a: float = 0.5, b: float = 4.0, donate: bool = True):
    """Build the jitted async commit:

        commit(variables, rows [K,P] f32, weights [K], staleness [K],
               alpha) -> (new_variables, stats)

    `weights` are per-result sample counts with zero-weight pad lanes
    (a deadline commit drains a part-full buffer padded to capacity —
    zero lanes drop out of the weighted mean exactly, so ONE compiled
    program serves full and partial commits).  `alpha` is the server
    mixing rate α_eff; stats carries the effective discount mass for
    observability.  `variables` is donated — the output has its exact
    shapes, so the update aliases in place instead of paying a
    params-sized HBM copy per commit (the rows matrix is NOT donated:
    no output matches its [K, P] shape, so donating it only trips
    XLA's unusable-donation warning)."""
    if mode not in STALENESS_MODES:
        raise ValueError(f"unknown staleness mode {mode!r} "
                         f"(choose one of {STALENESS_MODES})")

    def commit(variables, rows, weights, staleness, alpha):
        lam = staleness_weight(mode, staleness, a, b)
        w = weights * lam
        stacked = unflatten_rows(rows, variables)
        avg = tree_weighted_mean(stacked, w)
        alpha = jnp.asarray(alpha, jnp.float32)
        new = jax.tree.map(
            lambda v, m: ((1.0 - alpha) * v.astype(jnp.float32)
                          + alpha * m).astype(v.dtype),
            variables, avg)
        stats = {"discount_mass": jnp.sum(w) / jnp.maximum(
            jnp.sum(weights), 1e-12)}
        return new, stats

    return jax.jit(commit, donate_argnums=(0,) if donate else ())


# ---------------------------------------------------------------------------
# the bounded aggregation buffer
# ---------------------------------------------------------------------------

class AsyncBuffer:
    """Bounded host-side aggregation buffer: [capacity, P] f32 rows plus
    per-row sample weights and staleness.  `drain()` always returns
    capacity-sized arrays (zero-weight pad lanes beyond `count`) so the
    commit program compiles once; the real-row count rides alongside.

    Host-side by design: results arrive from the comm FSM as numpy
    payloads (wire codec) or from the in-process scheduler as device
    rows fetched once per dispatch wave — either way one np.copyto per
    insert, and the commit uploads the matrix in one device_put."""

    def __init__(self, capacity: int, p: int):
        if capacity < 1:
            raise ValueError(f"buffer capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.rows = np.zeros((capacity, p), np.float32)
        self.weights = np.zeros((capacity,), np.float32)
        self.staleness = np.zeros((capacity,), np.float32)
        self.count = 0

    def add(self, row: np.ndarray, weight: float, staleness: float) -> bool:
        """Insert one result; returns True when the buffer reached
        capacity (the scheduler's commit trigger)."""
        if self.count >= self.capacity:
            raise RuntimeError("async buffer overflow: commit before add")
        i = self.count
        np.copyto(self.rows[i], row)
        self.weights[i] = np.float32(weight)
        self.staleness[i] = np.float32(staleness)
        self.count += 1
        return self.count >= self.capacity

    def drain(self):
        """(rows [K,P], weights [K], staleness [K], n_real) — padded to
        capacity with zero-weight lanes; resets the buffer."""
        n = self.count
        out = (self.rows.copy(), self.weights.copy(),
               self.staleness.copy(), n)
        self.rows[:] = 0.0
        self.weights[:] = 0.0
        self.staleness[:] = 0.0
        self.count = 0
        return out

    def state(self) -> dict:
        """Checkpointable snapshot (fedml_tpu/utils/checkpoint.py
        extra_state) — plain arrays, restored by load_state."""
        return {"rows": self.rows.copy(), "weights": self.weights.copy(),
                "staleness": self.staleness.copy(),
                # 0-d ndarray, not a numpy scalar: orbax StandardSave
                # rejects np.int64(x) leaves
                "count": np.asarray(self.count, np.int64)}

    def load_state(self, state: dict) -> None:
        rows = np.asarray(state["rows"], np.float32)
        if rows.shape != self.rows.shape:
            raise ValueError(
                f"async buffer shape mismatch: checkpoint {rows.shape} vs "
                f"configured {self.rows.shape} (buffer_k or model changed)")
        np.copyto(self.rows, rows)
        np.copyto(self.weights, np.asarray(state["weights"], np.float32))
        np.copyto(self.staleness, np.asarray(state["staleness"], np.float32))
        self.count = int(state["count"])
