"""Seeded client-lifecycle simulator + the async messaging FSM pair.

Cross-device federations are defined by client churn: heavy-tailed
device latencies, dropouts mid-round, rejoins minutes later (the FedML
paper's "millions of intermittent clients" regime, arXiv:2007.13518 §2).
`ClientLifecycle` is the ONE seeded source of that behavior, shared by
both async execution paths:

* the virtual-time scheduler (fedml_tpu/async_/scheduler.py) draws
  latency/crash/rejoin per dispatch and advances a simulated clock —
  deterministic per seed, so two runs with the same `--async_seed`
  produce identical event traces (pinned in tests/test_async.py);
* the REAL-thread FSM pair below (AsyncServerManager /
  AsyncClientManager) applies the same draws as actual sleeps and
  dropped replies over any comm backend (INPROC for tests, TCP/GRPC
  across machines) — so the async path exercises the real wire codec,
  the per-backend byte/message counters, and redispatch under loss.

Latency families (per dispatch, scaled by a per-client speed factor
drawn once at construction — persistent stragglers, not iid noise):

    lognormal   scale · exp(sigma·N(0,1))          (bulk + mild tail)
    pareto      scale · (1 + Pareto(alpha))        (heavy tail)
    none        0                                  (the degenerate pin)
"""
from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import Any, Optional

import numpy as np

from fedml_tpu import obs
from fedml_tpu.obs import propagate
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message, MessageCodec
from fedml_tpu.async_.adversary import (AdversarySim, AttackConfig,
                                        apply_data_attack)
from fedml_tpu.async_.defense import (DefenseConfig, UpdateAdmission,
                                      make_flatten_fn)
from fedml_tpu.async_.staleness import (AsyncBuffer, RowLayout, flat_dim,
                                        flatten_vars_row,
                                        make_bucket_commit_fn,
                                        make_commit_fn,
                                        make_stream_commit_fn,
                                        unflatten_rows)
from fedml_tpu.scale.registry import BANNED as _REG_BANNED
from fedml_tpu.scale.registry import ClientRegistry
from fedml_tpu.secure.secagg import (SecAggBelowThreshold, SecAggConfig,
                                     SecureAggregator)

log = logging.getLogger(__name__)
Pytree = Any

LATENCY_MODES = ("none", "lognormal", "pareto")


@dataclasses.dataclass
class LifecycleConfig:
    """Knobs of the seeded client-lifecycle model (CLI --async_*)."""
    latency: str = "none"            # none | lognormal | pareto
    latency_scale: float = 1.0       # seconds (virtual or real)
    latency_sigma: float = 0.5       # lognormal spread
    pareto_alpha: float = 2.0        # pareto tail index (>1 for finite mean)
    heterogeneity: float = 0.0       # per-client speed-factor lognormal sigma
    dropout_prob: float = 0.0        # P(crash mid-round) per dispatch
    rejoin_prob: float = 1.0         # P(a crashed client ever rejoins)
    rejoin_delay_s: float = 5.0      # mean rejoin delay (exponential)
    seed: int = 0

    def __post_init__(self):
        if self.latency not in LATENCY_MODES:
            raise ValueError(f"unknown latency mode {self.latency!r} "
                             f"(choose one of {LATENCY_MODES})")
        if not 0.0 <= self.dropout_prob <= 1.0:
            raise ValueError(f"dropout_prob must be in [0, 1], got "
                             f"{self.dropout_prob}")


class ClientLifecycle:
    """Seeded per-client draw source.  All randomness flows through ONE
    np.random.Generator in call order, so a scheduler that processes
    events deterministically gets a deterministic fault schedule."""

    def __init__(self, cfg: LifecycleConfig, n_clients: int):
        self.cfg = cfg
        self.n_clients = n_clients
        self._rng = np.random.default_rng(cfg.seed)
        # the virtual-time scheduler draws in deterministic event order;
        # the messaging FSM draws from concurrent client threads — the
        # lock keeps the shared Generator coherent there (determinism is
        # only promised for the single-threaded scheduler path)
        self._lock = threading.Lock()
        # persistent per-client speed factors: the straggler identity of
        # a device does not re-roll every round
        if cfg.heterogeneity > 0.0:
            self.speed = np.exp(cfg.heterogeneity
                                * self._rng.standard_normal(n_clients))
        else:
            self.speed = np.ones(n_clients)

    def draw_latency(self, client_id: int) -> float:
        c = self.cfg
        if c.latency == "none":
            return 0.0
        with self._lock:
            if c.latency == "lognormal":
                base = c.latency_scale * float(
                    np.exp(c.latency_sigma * self._rng.standard_normal()))
            else:                                # pareto
                base = c.latency_scale * float(1.0 + self._rng.pareto(
                    c.pareto_alpha))
        return base * float(self.speed[client_id])

    def draw_crash(self, client_id: int) -> bool:
        """Crash-mid-round fault injection: the dispatch trains (or not)
        but its result never reaches the server."""
        if self.cfg.dropout_prob <= 0.0:
            return False
        with self._lock:
            return bool(self._rng.random() < self.cfg.dropout_prob)

    def draw_rejoin_delay(self, client_id: int) -> Optional[float]:
        """Seconds until a crashed client rejoins the dispatchable pool;
        None = the client is gone for good."""
        with self._lock:
            if self._rng.random() >= self.cfg.rejoin_prob:
                return None
            return float(self._rng.exponential(self.cfg.rejoin_delay_s))


# ---------------------------------------------------------------------------
# async messaging FSM (real threads over the comm backends)
# ---------------------------------------------------------------------------

class AsyncMessage:
    """Message-type constants of the async federation protocol (disjoint
    from fedavg_messaging.MyMessage's 1-4 so a mixed deployment cannot
    cross-dispatch)."""
    MSG_TYPE_S2C_ASYNC_TRAIN = 11
    MSG_TYPE_C2S_ASYNC_RESULT = 12
    MSG_TYPE_S2C_ASYNC_STOP = 13

    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_VERSION = "model_version"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    # ISSUE 20: marker param of a masked secagg uplink ({"round": v}) —
    # explicit so a NON-secure server quarantines masked words by name
    # instead of folding uint32 garbage, and a secure server rejects
    # plain uplinks symmetrically
    MSG_ARG_KEY_SECAGG = "secagg"


class AsyncServerManager(ServerManager):
    """Buffered staleness-aware async server over any comm backend.

    No round barrier: every inbound result lands in the AsyncBuffer with
    staleness = current_version − the version echoed by the client; a
    commit fires when the buffer reaches `buffer_k` OR the deadline
    timer (armed at the first buffered result after a commit) expires
    with a part-full buffer.  Contributing clients are redispatched at
    the new version immediately; on a deadline commit, clients whose
    outstanding dispatch is older than the previous version are
    presumed crashed and redispatched too (counted in
    `async_redispatch_total` — the lifecycle's rejoin path).

    Ingestion hot path (ISSUE 6).  Three orthogonal knobs:

    * `streaming` (default True): aggregation-on-arrival — each result
      folds w̃·row into the buffer's running flat f32 accumulator
      (staleness.make_fold_fn), so the commit is the O(P)
      make_stream_commit_fn mix instead of the O(K·P) drained
      reduction.  `streaming=False` keeps the PR-5 drain path — the
      perf A/B's legacy arm, and bitwise-anchored to sync FedAvg.
    * `ingest_pool` (default 0): a bounded decode pool fed RAW frames
      by the backend's frame sink (comm/base.py), so wire decode runs
      off the transport recv threads; zlib and the numpy cast/copy hot
      spots release the GIL, so decodes of concurrent uplinks overlap.
      Saturation blocks the sink — transport flow control is the
      backpressure.  0 = decode inline in the recv path (the FSM
      route).
    * `decode_into` (default True, pool only): decode v2/v1 frames
      straight into preallocated scratch rows at the RowLayout offsets
      (MessageCodec.decode_into) — no intermediate pytree, one pass
      per leaf.  False decodes zero-copy (copy="never") and
      re-flattens, isolating the decode-into win in the A/B.

    `redispatch=False` (torture-bench mode) never sends downlinks:
    clients push uplinks at their own rate and the server only ingests
    and commits.

    Robustness (ISSUE 8).  `reliable=True` envelopes the transport
    (comm/reliability.py) — the receive chokepoint's (sender, seq)
    dedup ledger then guards `_ingest_row`: a retried or duplicated
    uplink is suppressed BEFORE decode, so the streaming accumulator
    under a dup-storm is bitwise the clean run's (pinned in
    tests/test_chaos.py).  `min_quorum` makes deadline commits
    partition-aware: a deadline with fewer than `min_quorum` buffered
    results redispatches and re-arms instead of committing a
    near-empty buffer; commits that do fire below capacity are counted
    in `async_degraded_commits_total`.  `checkpoint_dir` +
    `checkpoint_every` save (version, variables, buffer state,
    counters) through orbax after every Nth commit, and `resume=True`
    restores the latest checkpoint at construction — the
    crash-resume path: kill the server mid-round, rebuild it with
    `resume=True` on the same port, `send_start()` re-handshakes every
    client at the restored version and the run completes (pinned over
    real TCP in tests/test_async_messaging.py)."""

    def __init__(self, init_variables: Pytree, total_commits: int,
                 buffer_k: int, rank: int = 0, size: int = 1,
                 backend: str = "INPROC", staleness_mode: str = "constant",
                 staleness_a: float = 0.5, staleness_b: float = 4.0,
                 mix: float = 1.0, deadline_s: Optional[float] = None,
                 streaming: bool = True, ingest_pool: int = 0,
                 decode_into: bool = True, sparse_uplink: bool = False,
                 redispatch: bool = True,
                 reliable: bool = False, min_quorum: int = 1,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1, resume: bool = False,
                 defense: Optional[DefenseConfig] = None,
                 secure=None, **kw):
        super().__init__(rank, size, backend, **kw)
        import jax
        if reliable:
            self.com_manager.enable_reliability()
        if secure is not None:
            # ISSUE 20: a secure round is a cohort barrier — pairwise
            # masks cancel only within ONE round's full pair set, so
            # the free-running staleness machinery cannot apply
            if not streaming:
                raise ValueError(
                    "secure aggregation rides the jitted field fold "
                    "(secagg needs streaming=True) — the drain path "
                    "holds plaintext rows, the exact thing masking "
                    "removes")
            if defense is not None:
                raise ValueError(
                    "the admission screen reads PLAINTEXT rows and is "
                    "blinded by pairwise masks — --secure_agg composes "
                    "with defense=None only; the private mode's DP "
                    "rides the CLIENT side (SecAggConfig.dp_clip/"
                    "dp_noise), and only the quantizer's norm-bound "
                    "enforcement survives masking")
            if sparse_uplink:
                raise ValueError(
                    "sparse_topk drops coordinates per client, so "
                    "pairwise masks could never cancel — secagg and "
                    "sparse_uplink are mutually exclusive")
            if staleness_mode != "constant":
                raise ValueError(
                    f"secagg forces staleness_mode='constant': a masked "
                    f"uplink is only foldable at the round it was "
                    f"dispatched for (got {staleness_mode!r})")
            if buffer_k != size - 1:
                raise ValueError(
                    f"secagg commits on the FULL cohort (or its deadline "
                    f"survivor set): buffer_k must equal the cohort size "
                    f"{size - 1}, got {buffer_k}")
        if defense is not None and not streaming:
            raise ValueError(
                "the admission pipeline rides the streaming fold "
                "(defense needs streaming=True) — the drain path holds "
                "the full [K, P] matrix and has the sync-side robust "
                "aggregators instead")
        if sparse_uplink and (not streaming or defense is not None):
            raise ValueError(
                "sparse_uplink rides the streaming sparse fold and the "
                "admission screen needs dense rows — sparse_topk frames "
                "compose with streaming=True and defense=None only "
                "(defended configs densify via decode_into instead)")
        self.sparse_uplink = bool(sparse_uplink)
        self.defense = defense
        # ISSUE 20: the secure-aggregation seam — a shared
        # SecureAggregator instance (INPROC: the clients hold the same
        # object) or a SecAggConfig this server expands itself
        # (multi-process: every rank rebuilds the keyring from the
        # seed).  Secure round state is NOT checkpointed: masks are
        # round-keyed, so a restarted server re-dispatches at the
        # restored version and stragglers from the dead round
        # quarantine on the version mismatch.
        self._secure: Optional[SecureAggregator] = None
        if isinstance(secure, SecAggConfig):
            self._secure = SecureAggregator(
                secure, range(1, size), flat_dim(init_variables))
        elif secure is not None:
            self._secure = secure
        self.secure_below_threshold = 0       # named round failures
        self.variables = jax.tree.map(np.asarray, init_variables)
        self.total_commits = total_commits
        self.buffer_k = buffer_k
        self.mix = float(mix)
        self.deadline_s = deadline_s
        self.streaming = streaming
        self.decode_into = decode_into
        self.redispatch = redispatch
        self.min_quorum = max(1, int(min_quorum))
        self.ingest_pool = int(ingest_pool)
        self.version = 0
        self.partial_commits = 0
        self.degraded_commits = 0            # deadline commits below K
        self.updates_committed = 0
        self.staleness_seen: list[float] = []
        self.commit_walls: list[float] = []      # perf_counter per commit
        self.commit_sizes: list[int] = []        # n_real per commit
        p = flat_dim(self.variables)
        self.buffer = AsyncBuffer(
            buffer_k, p, streaming=streaming,
            staleness_mode=staleness_mode, staleness_a=staleness_a,
            staleness_b=staleness_b,
            buckets=(defense.buckets if defense is not None else 1),
            bucket_seed=(defense.seed if defense is not None else 0))
        # ISSUE 9: the admission pipeline + bucketed robust commit.  The
        # admission gate sits at _ingest_row (the ONE insert path);
        # defense=None keeps the PR-6 programs untouched, and the
        # defended degenerate config (B=1, no screen/clip) is pinned
        # bitwise against them in tests/test_robustness.py.
        self._admission: Optional[UpdateAdmission] = None
        self._dp_rng = None
        self._flat_fn = make_flatten_fn()
        self._g_dev = None
        if defense is not None:
            self._admission = UpdateAdmission(defense, p)
            self._admission.bind_fold(staleness_mode, staleness_a,
                                      staleness_b)
            self._g_dev = self._flat_fn(self.variables)
            self._admission.note_global(0, self._g_dev)
            if defense.dp_noise > 0.0:
                self._dp_rng = jax.random.PRNGKey(defense.seed + 17)
        if streaming and defense is not None:
            self._commit = make_bucket_commit_fn(
                self.variables, combine=defense.combine,
                trim_k=defense.trim_k, dp_noise=defense.dp_noise,
                dp_clip=defense.dp_clip or 1.0, donate=False)
        elif streaming:
            self._commit = make_stream_commit_fn(self.variables,
                                                 donate=False)
        else:
            self._commit = make_commit_fn(self.variables,
                                          mode=staleness_mode,
                                          a=staleness_a, b=staleness_b,
                                          donate=False)
        self._lock = threading.Lock()
        self._watchdog: Optional[threading.Timer] = None
        # ISSUE 10: per-rank dispatch/participation state lives in the
        # sharded client registry (scale/registry.py) instead of the
        # PR-5 `_outstanding` dict — the `outstanding` field carries
        # the in-flight version (-1 idle), participation/staleness/
        # quarantine counters ride the same shards, and the whole thing
        # checkpoints through _ckpt_state like the reliability ledger.
        self.registry = ClientRegistry(size)
        self.done = threading.Event()
        self._m_occupancy = obs.gauge("async_buffer_occupancy")
        self._m_staleness = obs.histogram(
            "async_staleness", buckets=obs.metrics.STALENESS_BUCKETS)
        self._m_commits = obs.counter("async_commits_total")
        self._m_updates = obs.counter("async_updates_committed_total")
        self._m_deadline = obs.counter("async_deadline_commits_total")
        self._m_degraded = obs.counter("async_degraded_commits_total")
        self._m_redispatch = obs.counter("async_redispatch_total")
        self._m_lock_wait = obs.counter("async_lock_wait_seconds")
        self._m_pool_depth = obs.gauge("async_ingest_pool_depth")
        self._m_decode = obs.histogram(
            "comm_decode_seconds",
            buckets=obs.metrics.DECODE_SECONDS_BUCKETS,
            backend=self.com_manager.backend_name)
        # ISSUE 11: admission latency — wall from the transport handing
        # over a reassembled frame to the row landing in the buffer
        # (pool queueing + decode + screen + lock wait); the connection
        # bench's p95 gate reads its histogram delta
        self._m_admission = obs.histogram(
            "comm_admission_seconds",
            buckets=obs.metrics.DECODE_SECONDS_BUCKETS)
        # crash-resume (ISSUE 8): per-commit orbax checkpoints of the
        # full server round state — restore happens BEFORE the ingest
        # pool exists, so no frame can race the rebuild
        self._ckpt = None
        self.checkpoint_every = max(1, int(checkpoint_every))
        if checkpoint_dir:
            from fedml_tpu.utils.checkpoint import FedCheckpointManager
            self._ckpt = FedCheckpointManager(checkpoint_dir, max_to_keep=3)
            if resume and self._ckpt.latest_round() is not None:
                step, variables, _s, extra = self._ckpt.restore(
                    self.variables, (), extra_template=self._ckpt_state())
                self.version = int(step)
                self.variables = jax.tree.map(np.asarray, variables)
                self.buffer.load_state(
                    jax.tree.map(np.asarray, extra["buffer"]))
                self.updates_committed = int(extra["updates_committed"])
                self.partial_commits = int(extra["partial_commits"])
                self.degraded_commits = int(extra["degraded_commits"])
                rel = self.com_manager._rel_ep
                if rel is not None and "reliable" in extra:
                    rel.import_seq_state(
                        jax.tree.map(np.asarray, extra["reliable"]))
                if "registry" in extra:
                    # per-rank participation/staleness/quarantine
                    # counters survive the crash; in-flight markers are
                    # transient (send_start() re-dispatches everyone)
                    self.registry.load_state(
                        jax.tree.map(np.asarray, extra["registry"]))
                    self.registry.reset_transient()
                if self._admission is not None:
                    if "defense" in extra:
                        # the screen resumes ARMED: its running
                        # reference survives the crash, so a restart
                        # cannot be exploited as a fresh cold-start
                        # warmup window
                        self._admission.load_state(
                            jax.tree.map(np.asarray, extra["defense"]))
                    self._g_dev = self._flat_fn(self.variables)
                    self._admission.note_global(self.version, self._g_dev)
                log.info("async server resumed from checkpoint: version "
                         "%d, %d updates committed, buffer %d/%d",
                         self.version, self.updates_committed,
                         self.buffer.count, self.buffer_k)
        self._layout = RowLayout(self.variables,
                                 AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS)
        self._pool = None
        if self.ingest_pool > 0:
            # the pool only sees traffic on backends that route raw
            # frames through _deliver_frame; MQTT speaks broker JSON and
            # a no-encode inproc router hands Message objects across —
            # fall back to inline decode loudly instead of building an
            # idle pool that an A/B would silently mislabel
            cm = self.com_manager
            if not cm.supports_frame_sink:
                log.warning(
                    "ingest_pool=%d has no effect on the %s backend "
                    "(frames never reach the raw-frame sink) — decoding "
                    "inline instead", self.ingest_pool, cm.backend_name)
                self.ingest_pool = 0
        if self.ingest_pool > 0:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self.ingest_pool,
                thread_name_prefix="async-ingest")
            # scratch rows sized to the in-flight bound: tasks hold at
            # most 2x pool rows (the semaphore's submit bound), so the
            # free-list never starves and never grows
            self._scratch: "queue.Queue[np.ndarray]" = queue.Queue()
            for _ in range(2 * self.ingest_pool):
                self._scratch.put(np.empty((p,), np.float32))
            self._ingest_sem = threading.BoundedSemaphore(
                2 * self.ingest_pool)
            self.com_manager.set_frame_sink(self._ingest_frame)
            # ISSUE 11: non-blocking admission probe for reactor
            # transports — while the pool is at its in-flight bound the
            # reactor suspends the peer's READ interest (kernel-buffer
            # backpressure) instead of blocking a shared loop thread in
            # the semaphore the way a recv thread harmlessly does.  The
            # gauge is maintained exactly at the semaphore edges, so
            # the probe races at most one task-width — a transient
            # block bounded by one decode, never a stall.
            pool_cap = float(2 * self.ingest_pool)
            self.com_manager.set_ingest_pressure(
                lambda: self._m_pool_depth.value >= pool_cap)

    # -- crash-resume --------------------------------------------------------
    def _ckpt_state(self) -> dict:
        """extra_state pytree for FedCheckpointManager: the buffer's
        own checkpointable snapshot (accumulator or rows — the PR-5/6
        state), the round counters (0-d ndarrays for orbax), and the
        reliability endpoint's per-peer seq/ledger state — without it a
        resumed server would re-fold an uplink whose ACK died with the
        crash (double-count) and its re-handshake downlinks would be
        suppressed as replays by the surviving clients' ledgers."""
        rel = self.com_manager._rel_ep
        rel_state = (rel.export_seq_state(self.size) if rel is not None
                     else {"seq": np.zeros((self.size,), np.int64),
                           "seen": np.full((self.size,), -1, np.int64)})
        out = {"buffer": self.buffer.state(),
               "updates_committed": np.asarray(self.updates_committed,
                                               np.int64),
               "partial_commits": np.asarray(self.partial_commits,
                                             np.int64),
               "degraded_commits": np.asarray(self.degraded_commits,
                                              np.int64),
               "reliable": rel_state,
               # ISSUE 10: registry shards (participation/staleness/
               # quarantine/outstanding per rank) ride the checkpoint —
               # shape-stable stacked arrays, orbax-friendly
               "registry": self.registry.state()}
        if self._admission is not None:
            # bucket accumulators ride the buffer state above; the
            # admission pipeline's running reference rides here
            out["defense"] = self._admission.state()
        return out

    def _save_checkpoint_locked(self) -> None:
        with obs.span("async.checkpoint", version=self.version):
            self._ckpt.save(self.version, self.variables, (),
                            extra_state=self._ckpt_state())

    def crash(self) -> None:
        """Chaos/test hook: die abruptly — no STOP broadcast, no final
        commit.  The deadline watchdog is cancelled and the transport
        torn down mid-round; clients keep running against a dead
        server (their reliable resends carry the gap) until a new
        server constructed with `resume=True` re-handshakes them."""
        with self._lock:
            if self._watchdog is not None:
                self._watchdog.cancel()
                self._watchdog = None
            self.done.set()             # sink + _ingest_row drop frames
        log.warning("async server CRASH at version %d (buffer %d/%d)",
                    self.version, self.buffer.count, self.buffer_k)
        self.finish()

    # -- dispatch ------------------------------------------------------------
    def send_start(self) -> None:
        for rank in range(1, self.size):
            self._dispatch(rank)
        with self._lock:
            if self.deadline_s is not None:
                self._arm_watchdog(self.version)

    def _dispatch(self, rank: int) -> None:
        msg = Message(AsyncMessage.MSG_TYPE_S2C_ASYNC_TRAIN, self.rank, rank)
        msg.add_params(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS, self.variables)
        msg.add_params(AsyncMessage.MSG_ARG_KEY_CLIENT_INDEX, rank - 1)
        msg.add_params(AsyncMessage.MSG_ARG_KEY_VERSION, self.version)
        if self._secure is not None:
            # escrow the client's key shares AT DISPATCH (ISSUE 20): if
            # this client dies mid-round, the surviving threshold set
            # already holds what the unmask barrier needs
            self._secure.escrow(rank)
        if self.registry.contains(rank):
            self.registry.note_dispatch_one(rank, self.version)
        self.send_message(msg)

    # -- FSM -----------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            AsyncMessage.MSG_TYPE_C2S_ASYNC_RESULT, self._handle_result)

    def _handle_result(self, msg: Message) -> None:
        """FSM route (ingest_pool=0): the backend decoded the frame
        inline; flatten and fold/insert.  Secure mode routes masked
        uplinks to the field fold; the marker param keeps the two
        worlds from silently folding each other's rows."""
        t0 = time.perf_counter()
        marker = msg.get(AsyncMessage.MSG_ARG_KEY_SECAGG)
        if self._secure is not None:
            if marker is None:
                self.com_manager._m_quarantined.inc()
                log.warning(
                    "secure server: PLAIN uplink from rank %d quarantined "
                    "(client not running --secure_agg? config skew)",
                    msg.get_sender_id())
                return
            words = np.ascontiguousarray(
                msg.get(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS), np.uint32)
            self._ingest_secure(
                msg.get_sender_id(), words,
                int(msg.get(AsyncMessage.MSG_ARG_KEY_VERSION)))
            self._m_admission.observe(time.perf_counter() - t0)
            return
        if marker is not None:
            # masked words reached a plain server: uint32 garbage to
            # every fold — quarantine BY NAME, never ingest
            self.com_manager._m_quarantined.inc()
            log.warning(
                "plain server: MASKED secagg uplink from rank %d "
                "quarantined (server missing --secure_agg? config skew)",
                msg.get_sender_id())
            return
        row = flatten_vars_row(msg.get(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS))
        self._ingest_row(
            msg.get_sender_id(), row,
            float(msg.get(AsyncMessage.MSG_ARG_KEY_NUM_SAMPLES)),
            int(msg.get(AsyncMessage.MSG_ARG_KEY_VERSION)))
        self._m_admission.observe(time.perf_counter() - t0)

    # -- parallel ingest (frame sink + decode pool) --------------------------
    def _ingest_frame(self, payload) -> Optional[Message]:
        """Frame sink, called on the backend's recv threads with RAW
        undecoded frames.  Bounded hand-off to the decode pool: when
        2x pool tasks are already in flight, the acquire blocks this
        recv thread and the transport's flow control backpressures the
        sender — the pool can saturate, never the heap."""
        if self.done.is_set() or self._closed:
            return None                       # shutdown: drop late frames
        self._ingest_sem.acquire()
        self._m_pool_depth.inc()
        try:
            self._pool.submit(self._ingest_task, payload,
                              time.perf_counter())
        except RuntimeError:                  # pool torn down mid-flight
            self._ingest_sem.release()
            self._m_pool_depth.dec()
        return None

    def _ingest_task(self, payload, t_arrive: Optional[float] = None) -> None:
        """Decode-pool worker: decode one frame into a scratch row
        (zlib + numpy casts release the GIL, so tasks overlap), then
        fold it into the buffer."""
        row = self._scratch.get()
        try:
            t0 = time.perf_counter()
            if self._secure is not None:
                # ISSUE 20: masked uplinks decode through the secagg
                # twin (raw u32 words, no dequantization possible);
                # anything else is control traffic or a plain uplink —
                # the latter quarantines by name, never folds
                with obs.span("ingest.decode", nbytes=len(payload),
                              into=False):
                    try:
                        msg, words, _enc = MessageCodec.decode_secagg(
                            payload,
                            AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS,
                            self._secure.words)
                    except ValueError:
                        msg = None
                    if msg is None:
                        try:
                            full = MessageCodec.decode(payload,
                                                       copy="never")
                        except Exception as e:
                            self.com_manager._m_quarantined.inc()
                            log.warning(
                                "ingest pool: undecodable frame (%d "
                                "bytes) quarantined: %s", len(payload), e)
                            return
                        if (full.get_type()
                                != AsyncMessage.MSG_TYPE_C2S_ASYNC_RESULT):
                            self.com_manager._note_frame(full)
                            self.com_manager._on_message(full)
                            return
                        self.com_manager._m_quarantined.inc()
                        log.warning(
                            "secure server: PLAIN uplink from rank %d "
                            "quarantined (client not running "
                            "--secure_agg? config skew)",
                            full.get_sender_id())
                        return
                self._m_decode.observe(time.perf_counter() - t0)
                self.com_manager._note_frame(msg)
                self._ingest_secure(
                    msg.get_sender_id(), words,
                    int(msg.get(AsyncMessage.MSG_ARG_KEY_VERSION)))
                if t_arrive is not None:
                    self._m_admission.observe(
                        time.perf_counter() - t_arrive)
                return
            msg = None
            pairs = None
            with obs.span("ingest.decode", nbytes=len(payload),
                          into=self.decode_into):
                if self.sparse_uplink:
                    # sparse fast path (ISSUE 19): pull the (index,
                    # value) pairs without densifying; dense/mixed
                    # frames fall through to decode_into unchanged
                    try:
                        msg, sidx, svals = MessageCodec.decode_sparse(
                            payload, self._layout)
                        pairs = (sidx, svals)
                    except ValueError:
                        msg = None            # dense frame / skew
                if msg is None and self.decode_into:
                    try:
                        msg = MessageCodec.decode_into(payload, row,
                                                       self._layout)
                    except ValueError:
                        msg = None            # not a result frame / skew
                if msg is None:
                    # fallback (or the decode-into A/B's legacy arm):
                    # zero-copy views + immediate re-flatten.  An
                    # undecodable (corrupt/alien) frame QUARANTINES —
                    # the same counter + semantics as the sink-less
                    # inline path in comm/base.py; before ISSUE 12 a
                    # pool-path corrupt frame died as a generic "ingest
                    # task failed" log, invisible to the quarantine
                    # accounting the chaos bench and the SLO pack read
                    try:
                        full = MessageCodec.decode(payload, copy="never")
                    except Exception as e:
                        self.com_manager._m_quarantined.inc()
                        log.warning(
                            "ingest pool: undecodable frame (%d bytes) "
                            "quarantined: %s", len(payload), e)
                        return
                    if (full.get_type()
                            != AsyncMessage.MSG_TYPE_C2S_ASYNC_RESULT):
                        # control traffic: hand to the FSM dispatch loop
                        self.com_manager._note_frame(full)
                        self.com_manager._on_message(full)
                        return
                    if full.get(AsyncMessage.MSG_ARG_KEY_SECAGG) is not None:
                        # masked words on a plain server: quarantine BY
                        # NAME (ISSUE 20) — folding u32 residues as f32
                        # would silently poison the accumulator
                        self.com_manager._m_quarantined.inc()
                        log.warning(
                            "plain server: MASKED secagg uplink from "
                            "rank %d quarantined (server missing "
                            "--secure_agg? config skew)",
                            full.get_sender_id())
                        return
                    np.copyto(row, flatten_vars_row(
                        full.get(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS)))
                    msg = full
            self._m_decode.observe(time.perf_counter() - t0)
            # trace block + piggybacked client metrics delta: the sink
            # path bypasses _deliver_frame's inline-decode note, so the
            # pool worker strips/accounts them here (clock offsets,
            # trace.recv digest instant, cohort metrics fold)
            self.com_manager._note_frame(msg)
            self._ingest_row(
                msg.get_sender_id(), row,
                float(msg.get(AsyncMessage.MSG_ARG_KEY_NUM_SAMPLES)),
                int(msg.get(AsyncMessage.MSG_ARG_KEY_VERSION)),
                sparse=pairs)
            if t_arrive is not None:
                # admission latency: sink hand-off -> buffer insert
                # (pool queue + decode + screen + lock), the ISSUE-11
                # p95 gate's raw series
                self._m_admission.observe(time.perf_counter() - t_arrive)
        except Exception:                     # never kill a pool worker
            log.exception("ingest task failed (%d bytes)", len(payload))
        finally:
            self._scratch.put(row)
            self._ingest_sem.release()
            self._m_pool_depth.dec()
            # wake any reactor loop holding pressure-paused peers: a
            # slot just freed (ISSUE 11 — resume is event-driven, the
            # housekeeping scan is only the fallback)
            self.com_manager._notify_ingest_ready()

    def _ingest_row(self, sender: int, row: np.ndarray, weight: float,
                    dispatched: int, *, sparse=None) -> None:
        """The ONE insert path (FSM route and decode pool both land
        here): staleness accounting, buffer fold/insert, commit
        trigger.  Lock acquisition is timed into
        async_lock_wait_seconds — the contention signal of the
        concurrent-uplink regime.  `sparse` (ISSUE 19) carries the
        (global-index, value) pairs of a sparse_topk frame; when set,
        `row` is untouched scratch and the insert rides the jitted
        sparse scatter fold (AsyncBuffer.add_sparse)."""
        t0 = time.perf_counter()
        self._lock.acquire()
        self._m_lock_wait.inc(time.perf_counter() - t0)
        last = False
        try:
            if self.done.is_set():
                return                      # late straggler after shutdown
            staleness = float(self.version - dispatched)
            known = self.registry.contains(sender)
            if self._admission is not None:
                # ISSUE-9 admission gate at the ONE insert path: finite
                # canary -> shared-definition norm clip -> z/cosine
                # anomaly screen, FUSED with the streaming fold into a
                # single jitted dispatch (the hot path keeps its PR-6
                # throughput).  A quarantined row never reaches the
                # accumulator; its sender is redispatched like any
                # contributing client, so an attacker cannot starve the
                # round by getting itself rejected.
                with obs.span("ingest.fold", sender=sender):
                    ok, _why, full = self.buffer.add_screened(
                        row, weight, staleness, self._admission,
                        sender=sender, version=dispatched)
                if not ok:
                    banned = False
                    if known:
                        self.registry.note_return(sender)
                        # True when the quarantine counter crossed the
                        # registry's ban threshold — a banned sender
                        # must NOT be redispatched (the ban contract)
                        banned = self.registry.note_quarantine(sender)
                    if self.redispatch and not banned:
                        self._redispatch_locked([sender])
                    return
            else:
                with obs.span("ingest.fold", sender=sender):
                    if sparse is not None:
                        full = self.buffer.add_sparse(
                            sparse[0], sparse[1], weight, staleness)
                    else:
                        full = self.buffer.add(row, weight, staleness)
            # shared post-insert bookkeeping: only ADMITTED results
            # count toward the staleness statistics (a quarantined
            # row's staleness returned above)
            self.staleness_seen.append(staleness)
            self._m_staleness.observe(staleness)
            self._m_occupancy.set(self.buffer.count)
            if known:
                self.registry.note_return(sender)
                self.registry.note_contribution(sender, staleness,
                                                self.version)
            if not full:
                # the contributing client would idle until the next
                # commit; async has no barrier, so hand it work now
                if self.redispatch:
                    self._redispatch_locked([sender])
                return
            last = self._commit_locked(deadline_fired=False)
        finally:
            self._lock.release()
        if last:
            self.stop_all()

    def _ingest_secure(self, sender: int, words: np.ndarray,
                       dispatched: int) -> None:
        """Secure twin of _ingest_row (ISSUE 20): the round-version
        check, the jitted mask-and-fold, and the cohort-full commit
        trigger.  The VERSION is the secure round index AND the mask
        PRG counter — an uplink masked for any other round can never
        cancel against this one's pair set, so a version mismatch
        quarantines by name and redispatches at the current round
        instead of folding unerasable mask noise."""
        t0 = time.perf_counter()
        self._lock.acquire()
        self._m_lock_wait.inc(time.perf_counter() - t0)
        last = False
        try:
            if self.done.is_set():
                return                      # late straggler after shutdown
            known = self.registry.contains(sender)
            if dispatched != self.version:
                self.com_manager._m_quarantined.inc()
                log.warning(
                    "secure round %d: stale masked uplink from rank %d "
                    "(masked for round %d) quarantined — masks are "
                    "round-keyed and cannot cancel across rounds",
                    self.version, sender, dispatched)
                if known:
                    self.registry.note_return(sender)
                if self.redispatch:
                    self._redispatch_locked([sender])
                return
            with obs.span("ingest.fold", sender=sender, secure=True):
                n = self._secure.fold(sender, words)
            self.staleness_seen.append(0.0)
            self._m_staleness.observe(0.0)
            self._m_occupancy.set(n)
            if known:
                self.registry.note_return(sender)
                self.registry.note_contribution(sender, 0.0, self.version)
            if n < self.buffer_k:
                # cohort barrier: contributors WAIT for the round to
                # close (no mid-round redispatch — a re-dispatch at the
                # same version would just replace this row)
                return
            last = self._commit_locked(deadline_fired=False)
        finally:
            self._lock.release()
        if last:
            self.stop_all()

    def _arm_watchdog(self, armed_version: int) -> None:
        """Deadline heartbeat: armed at start and re-armed after every
        commit (and after an empty-buffer retry sweep), so progress
        never depends on a result arriving first — the crash-starved
        case (every in-flight client dropped) is exactly when nothing
        else would wake the server."""
        self._watchdog = threading.Timer(
            self.deadline_s, self._on_deadline, args=(armed_version,))
        self._watchdog.daemon = True
        self._watchdog.start()

    def _on_deadline(self, armed_version: int) -> None:
        with self._lock:
            self._watchdog = None
            if self.done.is_set() or self.version != armed_version:
                return                      # committed normally meanwhile
            arrived = (self._secure.count if self._secure is not None
                       else self.buffer.count)
            if arrived < self.min_quorum:
                # not enough arrived a whole deadline long (empty, or
                # below the partition quorum): presume the outstanding
                # dispatches crashed/partitioned, retry them all (the
                # lifecycle's rejoin path), keep the heartbeat alive —
                # committing a sub-quorum buffer would let one surviving
                # client steer the model during a partition
                if self.redispatch:
                    self._redispatch_locked(
                        [int(r) for r in self.registry.outstanding_ids()])
                self._arm_watchdog(self.version)
                return
            last = self._commit_locked(deadline_fired=True)
        if last:
            self.stop_all()

    def _commit_locked(self, deadline_fired: bool) -> bool:
        """Jitted commit + redispatch; caller holds _lock.  Streaming
        mode: O(P) mix of the server variables with the arrival
        accumulator (the [K, P] reduction already happened at arrival
        time).  Drain mode (legacy A/B arm): drain + the O(K·P)
        tree_weighted_mean commit.  Returns True when this was the
        last commit."""
        import jax
        import jax.numpy as jnp
        if self._watchdog is not None:
            self._watchdog.cancel()
            self._watchdog = None
        with obs.span("async.commit", version=self.version,
                      streaming=self.streaming,
                      n_results=(self._secure.count
                                 if self._secure is not None
                                 else self.buffer.count),
                      deadline=deadline_fired):
            if self._secure is not None:
                # ISSUE 20: the unmask barrier.  Survivors = who
                # answered THIS round; a deadline commit with absent
                # cohort members reconstructs their masks from the
                # escrowed shares (the elastic dropout recovery), and a
                # below-threshold set fails the round BY NAME — the
                # arrived rows are kept, the missing ranks are
                # redispatched at the SAME round, and the next deadline
                # (or late arrivals) retries the barrier.
                survivors = self._secure.arrived
                try:
                    acc_np, wsum, included = self._secure.commit(
                        self.version, survivors)
                except SecAggBelowThreshold as e:
                    self.secure_below_threshold += 1
                    log.warning(
                        "secure round %d did not commit: %s",
                        self.version, e)
                    if self.redispatch:
                        self._redispatch_locked(
                            [int(r) for r
                             in self.registry.outstanding_ids()])
                    if self.deadline_s is not None:
                        self._arm_watchdog(self.version)
                    return False
                n_real = len(included)
                self._m_occupancy.set(0)
                new_vars, _stats = self._commit(
                    jax.tree.map(jnp.asarray, self.variables),
                    jnp.asarray(acc_np), jnp.float32(wsum),
                    jnp.float32(self.mix))
            elif self.streaming and self.defense is not None:
                # bucketed robust streaming commit (ISSUE 9): O(B·P)
                accs, wsums, _w, _s, n_real, _raw = \
                    self.buffer.take_stream_buckets()
                self._m_occupancy.set(0)
                if self._dp_rng is not None:
                    self._dp_rng, k = jax.random.split(self._dp_rng)
                    new_vars, _stats = self._commit(
                        jax.tree.map(jnp.asarray, self.variables),
                        accs, wsums, jnp.float32(self.mix),
                        jnp.float32(n_real), k)
                else:
                    new_vars, _stats = self._commit(
                        jax.tree.map(jnp.asarray, self.variables),
                        accs, wsums, jnp.float32(self.mix))
            elif self.streaming:
                acc, wsum, _w, _s, n_real, _raw = self.buffer.take_stream()
                self._m_occupancy.set(0)
                new_vars, _stats = self._commit(
                    jax.tree.map(jnp.asarray, self.variables),
                    acc, wsum, jnp.float32(self.mix))
            else:
                rows, w, s, n_real = self.buffer.drain()
                self._m_occupancy.set(0)
                new_vars, _stats = self._commit(
                    jax.tree.map(jnp.asarray, self.variables),
                    jnp.asarray(rows), jnp.asarray(w), jnp.asarray(s),
                    jnp.float32(self.mix))
            self.variables = jax.tree.map(np.asarray, new_vars)
            if self._g_dev is not None:
                # the admission reference global moves with every commit
                self._g_dev = self._flat_fn(self.variables)
        self.version += 1
        if self._admission is not None:
            self._admission.note_global(self.version, self._g_dev)
        self.updates_committed += n_real
        self.commit_walls.append(time.perf_counter())
        self.commit_sizes.append(n_real)
        self._m_commits.inc()
        # ISSUE 12: the SLO pack's committed-updates/sec floor reads
        # this counter — the throughput signal as a metric, not just
        # the report's post-hoc arithmetic
        self._m_updates.inc(n_real)
        if deadline_fired:
            self.partial_commits += 1
            self._m_deadline.inc()
            if n_real < self.buffer_k:
                # quorum-degraded: the round committed with fewer than
                # a full buffer (partition / mass crash) — visible in
                # the rollup, not silent
                self.degraded_commits += 1
                self._m_degraded.inc()
        if self._ckpt is not None and (
                self.version % self.checkpoint_every == 0
                or self.version >= self.total_commits):
            self._save_checkpoint_locked()
        if self.version >= self.total_commits:
            self.done.set()
            return True
        # redispatch everyone idle; on a deadline commit also retry
        # ranks whose outstanding dispatch predates the PREVIOUS
        # version — two commits without a reply reads as a crash
        if self.redispatch:
            ranks = np.arange(1, self.size, dtype=np.int64)
            out = self.registry.outstanding_of(ranks)
            retry = [int(r) for r, v in zip(ranks, out)
                     if v < 0 or (deadline_fired
                                  and v < self.version - 1)]
            self._redispatch_locked(retry)
        if self.deadline_s is not None:
            self._arm_watchdog(self.version)
        return False

    def _redispatch_locked(self, ranks) -> None:
        for r in ranks:
            if (self.registry.contains(r) and int(
                    self.registry.status_of([r])[0]) == _REG_BANNED):
                continue        # banned = never dispatched again (all
                #                 call sites funnel through here)
            self._m_redispatch.inc()
            self._dispatch(r)

    def stop_all(self) -> None:
        """Broadcast STOP and close this manager (never under _lock —
        finish() joins the receive thread, which may be waiting on it).
        A no-downlink (redispatch=False) server skips the broadcast:
        its torture clients have no listeners to stop."""
        if self.redispatch:
            for rank in range(1, self.size):
                try:
                    self.send_message(Message(
                        AsyncMessage.MSG_TYPE_S2C_ASYNC_STOP, self.rank,
                        rank))
                except Exception:              # a dead client's transport
                    log.warning("stop broadcast to rank %d failed", rank,
                                exc_info=True)
        self.finish()

    def finish(self) -> None:
        """Tear down the decode pool before the base shutdown: done is
        set (or the manager closed) so the sink drops new frames, and
        in-flight tasks fall through _ingest_row's done guard.  The
        shutdown WAITS for the in-flight tasks (bounded: the semaphore
        caps them at 2x pool, none can block — scratch rows are sized
        to the same bound) so callers reading the decode/lock-wait
        metrics after finish() see a quiesced pool, not stragglers
        still observing into the histograms — EXCEPT when finish() is
        itself running on a pool worker (the final commit's
        _ingest_row -> stop_all chain), where waiting would self-join;
        there the pool drains on its own and an external finish()
        (idempotent) does the quiescing join."""
        if self._pool is not None:
            on_worker = threading.current_thread().name.startswith(
                "async-ingest")
            self._pool.shutdown(wait=not on_worker)
        super().finish()
        if self._ckpt is not None:
            # release the orbax manager (its background machinery must
            # not linger on a directory a resumed successor reopens)
            try:
                self._ckpt.close()
            except Exception:
                log.warning("checkpoint manager close failed",
                            exc_info=True)
            self._ckpt = None


class AsyncClientManager(ClientManager):
    """One lifecycle-simulated device: on a train dispatch, draw this
    dispatch's fate from the seeded lifecycle — a crash swallows the
    result (the server's deadline path carries on without it); otherwise
    sleep the drawn latency (REAL seconds — keep latency_scale small in
    tests) and upload the trained model with the dispatch version echoed
    for staleness accounting."""

    def __init__(self, trainer, data, epochs: int, rank: int, size: int,
                 backend: str = "INPROC",
                 lifecycle: Optional[ClientLifecycle] = None,
                 reliable: bool = False,
                 adversary: Optional[AdversarySim] = None,
                 secure: Optional[SecureAggregator] = None, **kw):
        super().__init__(rank, size, backend, **kw)
        import jax
        self.adversary = adversary
        # ISSUE 20: this client's view of the secure data plane —
        # client_row only reads the (deterministic, seed-derived)
        # keyring, so INPROC ranks can share the server's instance and
        # multi-process ranks rebuild an identical one from the config
        self._secure = secure
        self.secagg_rejected = 0       # uplinks the quantizer refused
        if reliable:
            # enveloped uplinks: a server restart mid-upload is carried
            # by the endpoint's backoff resend instead of an exception
            # killing this client's handler thread
            self.com_manager.enable_reliability()
        self.trainer = trainer
        self.data = data
        self.epochs = epochs
        self.lifecycle = lifecycle
        self.crashes = 0
        self.done = threading.Event()
        self._local_train = jax.jit(
            lambda v, shard, rng: trainer.local_train(
                v, shard, rng, self.epochs))
        self._rng = jax.random.PRNGKey(2000 + rank)
        # mergeable-telemetry baseline: each uplink ships the registry
        # delta since the previous uplink (obs/propagate.py), so the
        # server's rollup sees client-side counters without a scrape
        self._m_ship_state: Optional[dict] = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            AsyncMessage.MSG_TYPE_S2C_ASYNC_TRAIN, self._handle_train)
        self.register_message_receive_handler(
            AsyncMessage.MSG_TYPE_S2C_ASYNC_STOP, self._handle_stop)

    def _handle_train(self, msg: Message) -> None:
        import jax
        import jax.numpy as jnp
        if self.done.is_set() or self._closed:
            return      # dispatch raced shutdown: the server is gone
        client_idx = int(msg.get(AsyncMessage.MSG_ARG_KEY_CLIENT_INDEX))
        if self.lifecycle is not None:
            if self.lifecycle.draw_crash(client_idx):
                # crash mid-round: the work is lost, nothing is sent —
                # the server's deadline/redispatch path is the rejoin
                self.crashes += 1
                obs.counter("async_dropouts_total").inc()
                return
            lat = self.lifecycle.draw_latency(client_idx)
            if self.adversary is not None:
                # stale-attack: the byzantine uplink deliberately lands
                # commits late (REAL seconds here — keep stale_lag small
                # in tests, like latency_scale)
                lat += self.adversary.stale_extra_latency(client_idx)
            if lat > 0.0:
                time.sleep(lat)
        variables = msg.get(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS)
        shard = jax.tree.map(lambda a: jnp.asarray(a[client_idx]),
                             self.data.client_shards)
        self._rng, rng = jax.random.split(self._rng)
        with obs.span("async.local_train", rank=self.rank,
                      client=client_idx):
            new_vars, _loss, n = self._local_train(
                jax.tree.map(jnp.asarray, variables), shard, rng)
        upload = jax.tree.map(np.asarray, new_vars)
        if self.adversary is not None:
            # byzantine clients corrupt what they UPLOAD (semantically
            # valid frames — the wire layer has no reason to reject
            # them; that is exactly the admission pipeline's job)
            upload = self.adversary.corrupt_update(
                client_idx, upload, variables,
                int(msg.get(AsyncMessage.MSG_ARG_KEY_VERSION)))
        out = Message(AsyncMessage.MSG_TYPE_C2S_ASYNC_RESULT, self.rank, 0)
        ver = int(msg.get(AsyncMessage.MSG_ARG_KEY_VERSION))
        if self._secure is not None:
            # ISSUE 20: quantize + pairwise-mask the weighted flat row
            # (DP clip+noise first when the private mode is on).  The
            # sample weight rides as the row's masked trailing word, so
            # NUM_SAMPLES ships a constant 1.0 — per-client sample
            # counts never cross the wire in the clear.  A row the
            # quantizer refuses (fixed-point field overflow — the one
            # screen masking cannot blind) is DROPPED, not sent: the
            # server's deadline path treats this client as dead.
            try:
                masked = self._secure.client_row(
                    self.rank, ver,
                    np.asarray(flatten_vars_row(upload), np.float64),
                    float(n))
            except ValueError as e:
                self.secagg_rejected += 1
                obs.counter("secagg_rejected_uplinks_total").inc()
                log.warning(
                    "secagg client %d: uplink for round %d refused at "
                    "quantization (norm-bound enforcement): %s",
                    self.rank, ver, e)
                return
            out.add_params(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS, masked)
            out.add_params(AsyncMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0)
            out.add_params(AsyncMessage.MSG_ARG_KEY_SECAGG, {"round": ver})
            out.set_wire_transport(
                AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS, "secagg",
                scale=self._secure.cfg.scale, p=self._secure.cfg.prime)
        else:
            out.add_params(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS, upload)
            out.add_params(AsyncMessage.MSG_ARG_KEY_NUM_SAMPLES, float(n))
        out.add_params(AsyncMessage.MSG_ARG_KEY_VERSION, ver)
        if self.done.is_set() or self._closed:
            return      # STOP landed during the latency sleep / train
        if obs.enabled():
            # piggyback this client's metrics delta on the uplink —
            # compact (only what moved since the last ship), folded
            # into the server registry as a cohort rollup under
            # origin="remote" (propagate.note; delta_snapshot excludes
            # already-merged origin-labeled series, so a shared
            # in-process registry cannot echo the rollup back into
            # itself).  Obs off => the frame stays byte-identical to
            # the untraced build.  (In the in-process sim every rank
            # shares one registry, so the shipped delta is the PROCESS
            # delta — the per-client precision only exists in real
            # multi-process deployments.)
            delta, self._m_ship_state = obs.registry().delta_snapshot(
                self._m_ship_state)
            out.add_params(propagate.METRICS_KEY, delta)
        self.send_message(out)

    def _handle_stop(self, msg: Message) -> None:
        self.done.set()
        self.finish()


def run_async_messaging(trainer, data, cfg, *, buffer_k: int,
                        total_commits: Optional[int] = None,
                        backend: str = "INPROC",
                        worker_num: Optional[int] = None,
                        lifecycle_cfg: Optional[LifecycleConfig] = None,
                        lifecycle: Optional[ClientLifecycle] = None,
                        staleness_mode: str = "constant",
                        staleness_a: float = 0.5, staleness_b: float = 4.0,
                        mix: float = 1.0, deadline_s: Optional[float] = None,
                        streaming: bool = True, ingest_pool: int = 0,
                        decode_into: bool = True, reliable: bool = False,
                        chaos=None, min_quorum: int = 1,
                        attack: Optional[AttackConfig] = None,
                        defense: Optional[DefenseConfig] = None,
                        secure: Optional[SecAggConfig] = None,
                        timeout_s: float = 600.0, **backend_kw):
    """Launch the async server + one lifecycle-simulated client per rank
    (threads for INPROC; for TCP/GRPC run one rank per process and call
    the managers directly).  Returns (variables, server) after
    `total_commits` commits.  A stall past `timeout_s` dumps the flight
    recorder — the scheduler-deadlock artifact — before raising.

    `reliable=True` envelopes every manager's transport (exactly-once
    ingestion under retries/duplicates); `chaos` installs a
    comm.chaos.ChaosPolicy on the SERVER's backend (uplink faults —
    the torture direction); `min_quorum` gates deadline commits under
    partition.

    ISSUE 9: `attack` builds one seeded AdversarySim shared by every
    client manager (byzantine uplink corruption; data-level attacks
    poison the shared dataset before the clients snapshot it) and
    `defense` installs the admission pipeline + bucketed robust commit
    on the server."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.comm.inproc import InProcRouter

    worker_num = worker_num or cfg.client_num_per_round
    size = worker_num + 1
    total_commits = (total_commits if total_commits is not None
                     else cfg.comm_round)
    router = backend_kw.pop("router", None)
    if backend.upper() == "INPROC" and router is None:
        router = InProcRouter()
    kw = dict(backend_kw)
    if router is not None:
        kw["router"] = router

    if lifecycle is None and lifecycle_cfg is not None:
        lifecycle = ClientLifecycle(lifecycle_cfg, worker_num)
    adversary = None
    if attack is not None and attack.mode != "none":
        adversary = AdversarySim(attack, worker_num)
        data = apply_data_attack(data, attack, adversary)
    init_vars = trainer.init(jax.random.PRNGKey(cfg.seed),
                             jnp.asarray(data.client_shards["x"][0, 0]))
    secagg = None
    if secure is not None:
        # one shared SecureAggregator: the server folds/unmasks, the
        # clients only read the keyring (deterministic from the seed,
        # so multi-process ranks could rebuild it identically)
        secagg = SecureAggregator(secure, range(1, size),
                                  flat_dim(init_vars))
    server = AsyncServerManager(
        init_vars, total_commits, buffer_k, 0, size, backend,
        staleness_mode=staleness_mode, staleness_a=staleness_a,
        staleness_b=staleness_b, mix=mix, deadline_s=deadline_s,
        streaming=streaming, ingest_pool=ingest_pool,
        decode_into=decode_into, reliable=reliable,
        min_quorum=min_quorum, defense=defense, secure=secagg, **kw)
    if chaos is not None:
        server.com_manager.install_chaos(chaos)
    clients = [AsyncClientManager(trainer, data, cfg.epochs, r, size,
                                  backend, lifecycle=lifecycle,
                                  reliable=reliable, adversary=adversary,
                                  secure=secagg, **kw)
               for r in range(1, size)]
    threads = [c.run_async() for c in clients] + [server.run_async()]
    server.send_start()
    if not server.done.wait(timeout=timeout_s):
        obs.dump_flight("async_scheduler_deadlock")
        for c in clients:
            c.finish()
        server.finish()
        raise TimeoutError(
            f"async federation stalled: {server.version}/{total_commits} "
            f"commits in {timeout_s}s (buffer {server.buffer.count}/"
            f"{buffer_k}; all in-flight clients may have crashed with no "
            f"deadline set)")
    for c in clients:
        c.finish()
    for t in threads:
        t.join(timeout=10)
    # the final commit may have run finish() ON a pool worker (where it
    # cannot self-join); this external idempotent finish() is the
    # quiescing join the pool contract promises, so callers reading the
    # ingest metrics (pool depth, decode walls) see a drained pool
    server.finish()
    return jax.tree.map(jnp.asarray, server.variables), server
