"""Seeded client-lifecycle simulator + the async messaging FSM pair.

Cross-device federations are defined by client churn: heavy-tailed
device latencies, dropouts mid-round, rejoins minutes later (the FedML
paper's "millions of intermittent clients" regime, arXiv:2007.13518 §2).
`ClientLifecycle` is the ONE seeded source of that behavior, shared by
both async execution paths:

* the virtual-time scheduler (fedml_tpu/async_/scheduler.py) draws
  latency/crash/rejoin per dispatch and advances a simulated clock —
  deterministic per seed, so two runs with the same `--async_seed`
  produce identical event traces (pinned in tests/test_async.py);
* the REAL-thread FSM pair below (AsyncServerManager /
  AsyncClientManager) applies the same draws as actual sleeps and
  dropped replies over any comm backend (INPROC for tests, TCP/GRPC
  across machines) — so the async path exercises the real wire codec,
  the per-backend byte/message counters, and redispatch under loss.

Latency families (per dispatch, scaled by a per-client speed factor
drawn once at construction — persistent stragglers, not iid noise):

    lognormal   scale · exp(sigma·N(0,1))          (bulk + mild tail)
    pareto      scale · (1 + Pareto(alpha))        (heavy tail)
    none        0                                  (the degenerate pin)
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Optional

import numpy as np

from fedml_tpu import obs
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.async_.staleness import (AsyncBuffer, flat_dim,
                                        flatten_vars_row, make_commit_fn,
                                        unflatten_rows)

log = logging.getLogger(__name__)
Pytree = Any

LATENCY_MODES = ("none", "lognormal", "pareto")


@dataclasses.dataclass
class LifecycleConfig:
    """Knobs of the seeded client-lifecycle model (CLI --async_*)."""
    latency: str = "none"            # none | lognormal | pareto
    latency_scale: float = 1.0       # seconds (virtual or real)
    latency_sigma: float = 0.5       # lognormal spread
    pareto_alpha: float = 2.0        # pareto tail index (>1 for finite mean)
    heterogeneity: float = 0.0       # per-client speed-factor lognormal sigma
    dropout_prob: float = 0.0        # P(crash mid-round) per dispatch
    rejoin_prob: float = 1.0         # P(a crashed client ever rejoins)
    rejoin_delay_s: float = 5.0      # mean rejoin delay (exponential)
    seed: int = 0

    def __post_init__(self):
        if self.latency not in LATENCY_MODES:
            raise ValueError(f"unknown latency mode {self.latency!r} "
                             f"(choose one of {LATENCY_MODES})")
        if not 0.0 <= self.dropout_prob <= 1.0:
            raise ValueError(f"dropout_prob must be in [0, 1], got "
                             f"{self.dropout_prob}")


class ClientLifecycle:
    """Seeded per-client draw source.  All randomness flows through ONE
    np.random.Generator in call order, so a scheduler that processes
    events deterministically gets a deterministic fault schedule."""

    def __init__(self, cfg: LifecycleConfig, n_clients: int):
        self.cfg = cfg
        self.n_clients = n_clients
        self._rng = np.random.default_rng(cfg.seed)
        # the virtual-time scheduler draws in deterministic event order;
        # the messaging FSM draws from concurrent client threads — the
        # lock keeps the shared Generator coherent there (determinism is
        # only promised for the single-threaded scheduler path)
        self._lock = threading.Lock()
        # persistent per-client speed factors: the straggler identity of
        # a device does not re-roll every round
        if cfg.heterogeneity > 0.0:
            self.speed = np.exp(cfg.heterogeneity
                                * self._rng.standard_normal(n_clients))
        else:
            self.speed = np.ones(n_clients)

    def draw_latency(self, client_id: int) -> float:
        c = self.cfg
        if c.latency == "none":
            return 0.0
        with self._lock:
            if c.latency == "lognormal":
                base = c.latency_scale * float(
                    np.exp(c.latency_sigma * self._rng.standard_normal()))
            else:                                # pareto
                base = c.latency_scale * float(1.0 + self._rng.pareto(
                    c.pareto_alpha))
        return base * float(self.speed[client_id])

    def draw_crash(self, client_id: int) -> bool:
        """Crash-mid-round fault injection: the dispatch trains (or not)
        but its result never reaches the server."""
        if self.cfg.dropout_prob <= 0.0:
            return False
        with self._lock:
            return bool(self._rng.random() < self.cfg.dropout_prob)

    def draw_rejoin_delay(self, client_id: int) -> Optional[float]:
        """Seconds until a crashed client rejoins the dispatchable pool;
        None = the client is gone for good."""
        with self._lock:
            if self._rng.random() >= self.cfg.rejoin_prob:
                return None
            return float(self._rng.exponential(self.cfg.rejoin_delay_s))


# ---------------------------------------------------------------------------
# async messaging FSM (real threads over the comm backends)
# ---------------------------------------------------------------------------

class AsyncMessage:
    """Message-type constants of the async federation protocol (disjoint
    from fedavg_messaging.MyMessage's 1-4 so a mixed deployment cannot
    cross-dispatch)."""
    MSG_TYPE_S2C_ASYNC_TRAIN = 11
    MSG_TYPE_C2S_ASYNC_RESULT = 12
    MSG_TYPE_S2C_ASYNC_STOP = 13

    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_VERSION = "model_version"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"


class AsyncServerManager(ServerManager):
    """Buffered staleness-aware async server over any comm backend.

    No round barrier: every inbound result lands in the AsyncBuffer with
    staleness = current_version − the version echoed by the client; a
    commit fires when the buffer reaches `buffer_k` OR the deadline
    timer (armed at the first buffered result after a commit) expires
    with a part-full buffer.  Contributing clients are redispatched at
    the new version immediately; on a deadline commit, clients whose
    outstanding dispatch is older than the previous version are
    presumed crashed and redispatched too (counted in
    `async_redispatch_total` — the lifecycle's rejoin path)."""

    def __init__(self, init_variables: Pytree, total_commits: int,
                 buffer_k: int, rank: int = 0, size: int = 1,
                 backend: str = "INPROC", staleness_mode: str = "constant",
                 staleness_a: float = 0.5, staleness_b: float = 4.0,
                 mix: float = 1.0,
                 deadline_s: Optional[float] = None, **kw):
        super().__init__(rank, size, backend, **kw)
        import jax
        self.variables = jax.tree.map(np.asarray, init_variables)
        self.total_commits = total_commits
        self.buffer_k = buffer_k
        self.mix = float(mix)
        self.deadline_s = deadline_s
        self.version = 0
        self.partial_commits = 0
        self.staleness_seen: list[float] = []
        self.buffer = AsyncBuffer(buffer_k, flat_dim(self.variables))
        self._commit = make_commit_fn(self.variables, mode=staleness_mode,
                                      a=staleness_a, b=staleness_b,
                                      donate=False)
        self._lock = threading.Lock()
        self._watchdog: Optional[threading.Timer] = None
        # rank -> version of its outstanding dispatch (None = idle)
        self._outstanding: dict[int, Optional[int]] = {
            r: None for r in range(1, size)}
        self.done = threading.Event()
        self._m_occupancy = obs.gauge("async_buffer_occupancy")
        self._m_staleness = obs.histogram(
            "async_staleness", buckets=obs.metrics.STALENESS_BUCKETS)
        self._m_commits = obs.counter("async_commits_total")
        self._m_deadline = obs.counter("async_deadline_commits_total")
        self._m_redispatch = obs.counter("async_redispatch_total")

    # -- dispatch ------------------------------------------------------------
    def send_start(self) -> None:
        for rank in range(1, self.size):
            self._dispatch(rank)
        with self._lock:
            if self.deadline_s is not None:
                self._arm_watchdog(self.version)

    def _dispatch(self, rank: int) -> None:
        msg = Message(AsyncMessage.MSG_TYPE_S2C_ASYNC_TRAIN, self.rank, rank)
        msg.add_params(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS, self.variables)
        msg.add_params(AsyncMessage.MSG_ARG_KEY_CLIENT_INDEX, rank - 1)
        msg.add_params(AsyncMessage.MSG_ARG_KEY_VERSION, self.version)
        self._outstanding[rank] = self.version
        self.send_message(msg)

    # -- FSM -----------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            AsyncMessage.MSG_TYPE_C2S_ASYNC_RESULT, self._handle_result)

    def _handle_result(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        dispatched = int(msg.get(AsyncMessage.MSG_ARG_KEY_VERSION))
        variables = msg.get(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS)
        n = float(msg.get(AsyncMessage.MSG_ARG_KEY_NUM_SAMPLES))
        row = flatten_vars_row(variables)
        with self._lock:
            if self.done.is_set():
                return                      # late straggler after shutdown
            staleness = float(self.version - dispatched)
            self.staleness_seen.append(staleness)
            self._m_staleness.observe(staleness)
            full = self.buffer.add(row, n, staleness)
            self._m_occupancy.set(self.buffer.count)
            self._outstanding[sender] = None
            if not full:
                # the contributing client would idle until the next
                # commit; async has no barrier, so hand it work now
                self._redispatch_locked([sender])
                return
            last = self._commit_locked(deadline_fired=False)
        if last:
            self.stop_all()

    def _arm_watchdog(self, armed_version: int) -> None:
        """Deadline heartbeat: armed at start and re-armed after every
        commit (and after an empty-buffer retry sweep), so progress
        never depends on a result arriving first — the crash-starved
        case (every in-flight client dropped) is exactly when nothing
        else would wake the server."""
        self._watchdog = threading.Timer(
            self.deadline_s, self._on_deadline, args=(armed_version,))
        self._watchdog.daemon = True
        self._watchdog.start()

    def _on_deadline(self, armed_version: int) -> None:
        with self._lock:
            self._watchdog = None
            if self.done.is_set() or self.version != armed_version:
                return                      # committed normally meanwhile
            if self.buffer.count == 0:
                # nothing arrived a whole deadline long: presume every
                # outstanding dispatch crashed, retry them all (the
                # lifecycle's rejoin path), keep the heartbeat alive
                self._redispatch_locked(
                    [r for r, v in self._outstanding.items()
                     if v is not None])
                self._arm_watchdog(self.version)
                return
            last = self._commit_locked(deadline_fired=True)
        if last:
            self.stop_all()

    def _commit_locked(self, deadline_fired: bool) -> bool:
        """Drain + jitted commit + redispatch; caller holds _lock.
        Returns True when this was the last commit."""
        import jax
        import jax.numpy as jnp
        if self._watchdog is not None:
            self._watchdog.cancel()
            self._watchdog = None
        rows, w, s, n_real = self.buffer.drain()
        self._m_occupancy.set(0)
        with obs.span("async.commit", version=self.version,
                      n_results=n_real, deadline=deadline_fired):
            new_vars, _stats = self._commit(
                jax.tree.map(jnp.asarray, self.variables),
                jnp.asarray(rows), jnp.asarray(w), jnp.asarray(s),
                jnp.float32(self.mix))
            self.variables = jax.tree.map(np.asarray, new_vars)
        self.version += 1
        self._m_commits.inc()
        if deadline_fired:
            self.partial_commits += 1
            self._m_deadline.inc()
        if self.version >= self.total_commits:
            self.done.set()
            return True
        # redispatch everyone idle; on a deadline commit also retry
        # ranks whose outstanding dispatch predates the PREVIOUS
        # version — two commits without a reply reads as a crash
        retry = [r for r, v in self._outstanding.items()
                 if v is None or (deadline_fired and v < self.version - 1)]
        self._redispatch_locked(retry)
        if self.deadline_s is not None:
            self._arm_watchdog(self.version)
        return False

    def _redispatch_locked(self, ranks) -> None:
        for r in ranks:
            self._m_redispatch.inc()
            self._dispatch(r)

    def stop_all(self) -> None:
        """Broadcast STOP and close this manager (never under _lock —
        finish() joins the receive thread, which may be waiting on it)."""
        for rank in range(1, self.size):
            try:
                self.send_message(Message(
                    AsyncMessage.MSG_TYPE_S2C_ASYNC_STOP, self.rank, rank))
            except Exception:                  # a dead client's transport
                log.warning("stop broadcast to rank %d failed", rank,
                            exc_info=True)
        self.finish()


class AsyncClientManager(ClientManager):
    """One lifecycle-simulated device: on a train dispatch, draw this
    dispatch's fate from the seeded lifecycle — a crash swallows the
    result (the server's deadline path carries on without it); otherwise
    sleep the drawn latency (REAL seconds — keep latency_scale small in
    tests) and upload the trained model with the dispatch version echoed
    for staleness accounting."""

    def __init__(self, trainer, data, epochs: int, rank: int, size: int,
                 backend: str = "INPROC",
                 lifecycle: Optional[ClientLifecycle] = None, **kw):
        super().__init__(rank, size, backend, **kw)
        import jax
        self.trainer = trainer
        self.data = data
        self.epochs = epochs
        self.lifecycle = lifecycle
        self.crashes = 0
        self.done = threading.Event()
        self._local_train = jax.jit(
            lambda v, shard, rng: trainer.local_train(
                v, shard, rng, self.epochs))
        self._rng = jax.random.PRNGKey(2000 + rank)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            AsyncMessage.MSG_TYPE_S2C_ASYNC_TRAIN, self._handle_train)
        self.register_message_receive_handler(
            AsyncMessage.MSG_TYPE_S2C_ASYNC_STOP, self._handle_stop)

    def _handle_train(self, msg: Message) -> None:
        import jax
        import jax.numpy as jnp
        if self.done.is_set() or self._closed:
            return      # dispatch raced shutdown: the server is gone
        client_idx = int(msg.get(AsyncMessage.MSG_ARG_KEY_CLIENT_INDEX))
        if self.lifecycle is not None:
            if self.lifecycle.draw_crash(client_idx):
                # crash mid-round: the work is lost, nothing is sent —
                # the server's deadline/redispatch path is the rejoin
                self.crashes += 1
                obs.counter("async_dropouts_total").inc()
                return
            lat = self.lifecycle.draw_latency(client_idx)
            if lat > 0.0:
                time.sleep(lat)
        variables = msg.get(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS)
        shard = jax.tree.map(lambda a: jnp.asarray(a[client_idx]),
                             self.data.client_shards)
        self._rng, rng = jax.random.split(self._rng)
        with obs.span("async.local_train", rank=self.rank,
                      client=client_idx):
            new_vars, _loss, n = self._local_train(
                jax.tree.map(jnp.asarray, variables), shard, rng)
        out = Message(AsyncMessage.MSG_TYPE_C2S_ASYNC_RESULT, self.rank, 0)
        out.add_params(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS,
                       jax.tree.map(np.asarray, new_vars))
        out.add_params(AsyncMessage.MSG_ARG_KEY_NUM_SAMPLES, float(n))
        out.add_params(AsyncMessage.MSG_ARG_KEY_VERSION,
                       int(msg.get(AsyncMessage.MSG_ARG_KEY_VERSION)))
        if self.done.is_set() or self._closed:
            return      # STOP landed during the latency sleep / train
        self.send_message(out)

    def _handle_stop(self, msg: Message) -> None:
        self.done.set()
        self.finish()


def run_async_messaging(trainer, data, cfg, *, buffer_k: int,
                        total_commits: Optional[int] = None,
                        backend: str = "INPROC",
                        worker_num: Optional[int] = None,
                        lifecycle_cfg: Optional[LifecycleConfig] = None,
                        lifecycle: Optional[ClientLifecycle] = None,
                        staleness_mode: str = "constant",
                        staleness_a: float = 0.5, staleness_b: float = 4.0,
                        mix: float = 1.0, deadline_s: Optional[float] = None,
                        timeout_s: float = 600.0, **backend_kw):
    """Launch the async server + one lifecycle-simulated client per rank
    (threads for INPROC; for TCP/GRPC run one rank per process and call
    the managers directly).  Returns (variables, server) after
    `total_commits` commits.  A stall past `timeout_s` dumps the flight
    recorder — the scheduler-deadlock artifact — before raising."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.comm.inproc import InProcRouter

    worker_num = worker_num or cfg.client_num_per_round
    size = worker_num + 1
    total_commits = (total_commits if total_commits is not None
                     else cfg.comm_round)
    router = backend_kw.pop("router", None)
    if backend.upper() == "INPROC" and router is None:
        router = InProcRouter()
    kw = dict(backend_kw)
    if router is not None:
        kw["router"] = router

    if lifecycle is None and lifecycle_cfg is not None:
        lifecycle = ClientLifecycle(lifecycle_cfg, worker_num)
    init_vars = trainer.init(jax.random.PRNGKey(cfg.seed),
                             jnp.asarray(data.client_shards["x"][0, 0]))
    server = AsyncServerManager(
        init_vars, total_commits, buffer_k, 0, size, backend,
        staleness_mode=staleness_mode, staleness_a=staleness_a,
        staleness_b=staleness_b, mix=mix, deadline_s=deadline_s, **kw)
    clients = [AsyncClientManager(trainer, data, cfg.epochs, r, size,
                                  backend, lifecycle=lifecycle, **kw)
               for r in range(1, size)]
    threads = [c.run_async() for c in clients] + [server.run_async()]
    server.send_start()
    if not server.done.wait(timeout=timeout_s):
        obs.dump_flight("async_scheduler_deadlock")
        for c in clients:
            c.finish()
        server.finish()
        raise TimeoutError(
            f"async federation stalled: {server.version}/{total_commits} "
            f"commits in {timeout_s}s (buffer {server.buffer.count}/"
            f"{buffer_k}; all in-flight clients may have crashed with no "
            f"deadline set)")
    for c in clients:
        c.finish()
    for t in threads:
        t.join(timeout=10)
    return jax.tree.map(jnp.asarray, server.variables), server
