"""Async federation subsystem — buffered staleness-aware aggregation.

Four layers (module docstrings have the full design):

  staleness.py   staleness-discount weight families (constant /
                 polynomial / hinge), the flat-carry [K, P] buffer —
                 drain mode and streaming aggregation-on-arrival (the
                 jitted donated fold + O(P) stream commit, ISSUE 6) —
                 and the RowLayout the decode-into fast path targets
  scheduler.py   AsyncFedAvgEngine — event-driven virtual-time
                 scheduler (FedBuff semi-async; FedAsync at K=1) with
                 dispatch-wave vmapped training
  lifecycle.py   seeded client-lifecycle simulator (latency / dropout /
                 rejoin / crash) + the AsyncServerManager /
                 AsyncClientManager FSM pair over the comm backends,
                 with the bounded parallel-decode ingest pool
  torture.py     concurrent-uplink ingestion torture bench
                 (bench.py --mode ingest / profile_bench exp_INGEST)
"""
from fedml_tpu.async_.lifecycle import (AsyncClientManager, AsyncMessage,
                                        AsyncServerManager, ClientLifecycle,
                                        LifecycleConfig,
                                        run_async_messaging)
from fedml_tpu.async_.scheduler import AsyncFedAvgEngine
from fedml_tpu.async_.staleness import (AsyncBuffer, RowLayout,
                                        STALENESS_MODES, make_commit_fn,
                                        make_drain_fold_fn, make_fold_fn,
                                        make_stream_commit_fn,
                                        staleness_weight)
from fedml_tpu.async_.torture import run_ingest_torture

__all__ = [
    "AsyncBuffer", "AsyncClientManager", "AsyncFedAvgEngine",
    "AsyncMessage", "AsyncServerManager", "ClientLifecycle",
    "LifecycleConfig", "RowLayout", "STALENESS_MODES", "make_commit_fn",
    "make_drain_fold_fn", "make_fold_fn", "make_stream_commit_fn",
    "run_async_messaging", "run_ingest_torture", "staleness_weight",
]
