"""Async federation subsystem — buffered staleness-aware aggregation.

Six layers (module docstrings have the full design):

  staleness.py   staleness-discount weight families (constant /
                 polynomial / hinge), the flat-carry [K, P] buffer —
                 drain mode and streaming aggregation-on-arrival (the
                 jitted donated fold + O(P) stream commit, ISSUE 6),
                 the SEEDED bucketed robust streaming commit (ISSUE 9)
                 — and the RowLayout the decode-into fast path targets
  adversary.py   seeded adversarial client simulator (ISSUE 9):
                 sign-flip / boosted model-replacement / gaussian /
                 label-flip / backdoor / colluding / stale-timed
                 byzantine cohorts riding the PR-5 lifecycle
  defense.py     update admission pipeline at the ONE insert path:
                 finite canary -> shared-definition norm clip ->
                 z/cosine anomaly screen, quarantine accounting, and
                 the DP-FedAvg configuration
  scheduler.py   AsyncFedAvgEngine — event-driven virtual-time
                 scheduler (FedBuff semi-async; FedAsync at K=1) with
                 dispatch-wave vmapped training
  lifecycle.py   seeded client-lifecycle simulator (latency / dropout /
                 rejoin / crash) + the AsyncServerManager /
                 AsyncClientManager FSM pair over the comm backends,
                 with the bounded parallel-decode ingest pool
  torture.py     concurrent-uplink ingestion torture bench
                 (bench.py --mode ingest / profile_bench exp_INGEST)
"""
from fedml_tpu.async_.adversary import (ATTACK_MODES, AdversarySim,
                                        AttackConfig, apply_data_attack)
from fedml_tpu.async_.defense import (DefenseConfig, QUARANTINE_REASONS,
                                      UpdateAdmission)
from fedml_tpu.async_.lifecycle import (AsyncClientManager, AsyncMessage,
                                        AsyncServerManager, ClientLifecycle,
                                        LifecycleConfig,
                                        run_async_messaging)
from fedml_tpu.async_.scheduler import AsyncFedAvgEngine
from fedml_tpu.async_.staleness import (AsyncBuffer, BUCKET_COMBINE_MODES,
                                        RowLayout, STALENESS_MODES,
                                        make_bucket_commit_fn,
                                        make_commit_fn, make_drain_fold_fn,
                                        make_fold_fn, make_stream_commit_fn,
                                        staleness_weight)
from fedml_tpu.async_.torture import run_ingest_torture

__all__ = [
    "ATTACK_MODES", "AdversarySim", "AsyncBuffer", "AsyncClientManager",
    "AsyncFedAvgEngine", "AsyncMessage", "AsyncServerManager",
    "AttackConfig", "BUCKET_COMBINE_MODES", "ClientLifecycle",
    "DefenseConfig", "LifecycleConfig", "QUARANTINE_REASONS", "RowLayout",
    "STALENESS_MODES", "UpdateAdmission", "apply_data_attack",
    "make_bucket_commit_fn", "make_commit_fn", "make_drain_fold_fn",
    "make_fold_fn", "make_stream_commit_fn", "run_async_messaging",
    "run_ingest_torture", "staleness_weight",
]
