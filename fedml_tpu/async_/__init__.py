"""Async federation subsystem — buffered staleness-aware aggregation.

Three layers (module docstrings have the full design):

  staleness.py   staleness-discount weight families (constant /
                 polynomial / hinge), the flat-carry [K, P] buffer, and
                 the jitted donation-friendly commit program
  scheduler.py   AsyncFedAvgEngine — event-driven virtual-time
                 scheduler (FedBuff semi-async; FedAsync at K=1) with
                 dispatch-wave vmapped training
  lifecycle.py   seeded client-lifecycle simulator (latency / dropout /
                 rejoin / crash) + the AsyncServerManager /
                 AsyncClientManager FSM pair over the comm backends
"""
from fedml_tpu.async_.lifecycle import (AsyncClientManager, AsyncMessage,
                                        AsyncServerManager, ClientLifecycle,
                                        LifecycleConfig,
                                        run_async_messaging)
from fedml_tpu.async_.scheduler import AsyncFedAvgEngine
from fedml_tpu.async_.staleness import (AsyncBuffer, STALENESS_MODES,
                                        make_commit_fn, staleness_weight)

__all__ = [
    "AsyncBuffer", "AsyncClientManager", "AsyncFedAvgEngine",
    "AsyncMessage", "AsyncServerManager", "ClientLifecycle",
    "LifecycleConfig", "STALENESS_MODES", "make_commit_fn",
    "run_async_messaging", "staleness_weight",
]
