"""Seeded adversarial client simulator — the attack half of ISSUE 9.

PR 8 made the transport survive a hostile NETWORK; this module makes
clients hostile at the SEMANTIC level: a byzantine cohort rides the
PR-5 ``ClientLifecycle`` (same dispatch path, same latencies) but
corrupts what it uploads — the FedML paper's attack benchmarking
surface (arXiv:2007.13518 §3.4) brought to the async path, where
ROADMAP item 4 calls stale adversarial updates "an open research
edge".

Attack families, applied to the flat f32 uplink row (the
``flatten_vars_row`` layout both async paths speak):

    signflip    row' = g − (row − g)          (reversed update direction)
    boost       row' = g + β·(row − g)        (scaled model replacement,
                                               Bagdasaryan et al. 2020's
                                               train-and-scale)
    gaussian    row' = row + σ·N(0, I)        (additive noise)
    labelflip   honest protocol, poisoned DATA (data/poison.py label
                flip — trigger_fn=None semantics)
    backdoor    honest protocol, pixel-trigger backdoor shards
                (data/poison.py BadNets-style corner patch)
    mixed       boost + labelflip together — the acceptance arm's shape

Orthogonal modifiers:

* ``collude``: every byzantine client at the same model version sends
  the IDENTICAL crafted row (a shared direction from a cohort stream),
  defeating per-client outlier screens — the case bucketed robust
  aggregation exists for;
* ``stale``: byzantine uplinks are timed to land at high staleness
  (``stale_lag`` extra latency per dispatch), so the attack hides in
  the staleness-discount regime the async path tolerates by design.

Determinism (the comm/chaos.py contract): the byzantine set is a
seeded choice, and every per-client corruption stream is a pure
function of ``[seed, client_id]`` (colluding draws of ``[seed,
version]``) — two sims with the same seed corrupt identically
(identical ``events`` traces, pinned in tests/test_robustness.py), two
seeds differ.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Optional

import numpy as np

from fedml_tpu import obs

log = logging.getLogger(__name__)
Pytree = Any

ATTACK_MODES = ("none", "signflip", "boost", "gaussian", "labelflip",
                "backdoor", "mixed")
# modes that corrupt the uplink row (vs. poisoning the training data)
_MODEL_ATTACKS = ("signflip", "boost", "gaussian", "mixed")
# modes that poison the attacker clients' shards
_DATA_ATTACKS = ("labelflip", "backdoor", "mixed")

_MAX_EVENTS = 50_000


@dataclasses.dataclass
class AttackConfig:
    """Knobs of the seeded adversarial cohort (CLI --attack_*)."""
    mode: str = "none"
    frac: float = 0.2                # byzantine fraction of the fleet
    boost: float = 10.0              # model-replacement scale β
    noise_std: float = 1.0           # gaussian attack σ
    target_label: int = 0            # label-flip / backdoor target
    poison_frac: float = 0.5         # poisoned fraction of attacker data
    collude: bool = False            # identical crafted rows per version
    stale: bool = False              # time uplinks to land stale
    stale_lag: float = 3.0           # extra latency (sim/real seconds)
    seed: int = 0

    def __post_init__(self):
        if self.mode not in ATTACK_MODES:
            raise ValueError(f"unknown attack mode {self.mode!r} "
                             f"(choose one of {ATTACK_MODES})")
        if not 0.0 <= self.frac <= 1.0:
            raise ValueError(f"attack frac must be in [0, 1], got "
                             f"{self.frac}")


class AdversarySim:
    """Seeded byzantine cohort.  Thread-safe (the messaging FSM corrupts
    from concurrent client threads); per-client streams are lazily
    created np.Generators keyed [seed, 7, client_id], so one client's
    corruption trace never depends on another's interleaving."""

    def __init__(self, cfg: AttackConfig, n_clients: int):
        self.cfg = cfg
        self.n_clients = n_clients
        self._lock = threading.Lock()
        self._streams: dict[int, np.random.Generator] = {}
        self.events: list[tuple] = []
        self.injected = 0                # unbounded (events list is capped)
        self._m_corrupted = obs.counter("async_attacks_injected_total")
        rng = np.random.default_rng([cfg.seed, 6])
        n_byz = int(round(cfg.frac * n_clients)) if cfg.mode != "none" else 0
        self.byzantine = frozenset(
            int(c) for c in rng.choice(n_clients, size=n_byz,
                                       replace=False)) if n_byz else frozenset()

    def is_byzantine(self, client_id: int) -> bool:
        return int(client_id) in self.byzantine

    def attacks_model(self) -> bool:
        return self.cfg.mode in _MODEL_ATTACKS

    def attacks_data(self) -> bool:
        return self.cfg.mode in _DATA_ATTACKS

    def _stream(self, client_id: int) -> np.random.Generator:
        with self._lock:
            st = self._streams.get(client_id)
            if st is None:
                st = self._streams[client_id] = np.random.default_rng(
                    [self.cfg.seed, 7, int(client_id)])
            return st

    def _record(self, kind: str, client_id: int, version: int) -> None:
        with self._lock:
            self.injected += 1
            if len(self.events) < _MAX_EVENTS:
                self.events.append((kind, int(client_id), int(version)))
        self._m_corrupted.inc()
        obs.instant(f"attack.{kind}", client=client_id, version=version)

    def trace(self) -> list[tuple]:
        with self._lock:
            return list(self.events)

    def stale_extra_latency(self, client_id: int) -> float:
        """Extra dispatch latency for a stale-attacking byzantine client
        (0 otherwise) — lands its uplink several commits late, where
        the staleness discount is supposed to defang it."""
        if self.cfg.stale and self.is_byzantine(client_id):
            return float(self.cfg.stale_lag)
        return 0.0

    def corrupt_row(self, client_id: int, row: np.ndarray,
                    global_row: np.ndarray, version: int = 0) -> np.ndarray:
        """The model-level attack on one flat uplink row.  `global_row`
        is the model the client trained FROM (the attacker legitimately
        holds it); honest clients and data-only attacks pass through
        unchanged.  Always returns a fresh array — callers may hold
        read-only views of device buffers."""
        c = self.cfg
        if not self.is_byzantine(client_id) or not self.attacks_model():
            return row
        row = np.asarray(row, np.float32)
        g = np.asarray(global_row, np.float32)
        if c.collude:
            # every colluder at this version sends the same crafted
            # model: g + β·σ·(shared unit direction) — per-VERSION
            # stream, so the cohort agrees without communicating
            rng = np.random.default_rng([c.seed, 8, int(version)])
            d = rng.standard_normal(row.shape[0]).astype(np.float32)
            d *= np.float32(c.noise_std) / np.float32(
                max(np.linalg.norm(d), 1e-12))
            out = g + np.float32(c.boost) * d
            self._record("collude", client_id, version)
            return out
        if c.mode == "signflip":
            out = g - (row - g)
            self._record("signflip", client_id, version)
            return out
        if c.mode in ("boost", "mixed"):
            out = g + np.float32(c.boost) * (row - g)
            self._record("boost", client_id, version)
            return out
        # gaussian
        noise = self._stream(client_id).standard_normal(
            row.shape[0]).astype(np.float32)
        self._record("gaussian", client_id, version)
        return row + np.float32(c.noise_std) * noise

    def corrupt_update(self, client_id: int, new_vars: Pytree,
                       base_vars: Pytree, version: int = 0) -> Pytree:
        """Pytree form of corrupt_row for the messaging FSM (the client
        holds variables, not rows): flatten both through the ONE
        flatten_vars_row layout, corrupt, unflatten back to numpy."""
        if not self.is_byzantine(client_id) or not self.attacks_model():
            return new_vars
        import jax
        from fedml_tpu.async_.staleness import flatten_vars_row
        row = self.corrupt_row(client_id, flatten_vars_row(new_vars),
                               flatten_vars_row(base_vars), version)
        leaves, treedef = jax.tree.flatten(new_vars)
        out, off = [], 0
        for leaf in leaves:
            size = int(np.prod(np.shape(leaf))) if np.ndim(leaf) else 1
            out.append(np.asarray(
                row[off:off + size], np.float32).reshape(np.shape(leaf)))
            off += size
        return jax.tree.unflatten(treedef, out)


def apply_data_attack(data, cfg: AttackConfig, adversary: AdversarySim):
    """Poison the byzantine clients' shards for the data-level attacks
    (labelflip/backdoor/mixed), through the existing data/poison.py
    machinery: label-flip = poison_federated_data with trigger_fn=None,
    backdoor = the BadNets pixel trigger.  Identity for model-only
    attacks."""
    if not adversary.attacks_data() or not adversary.byzantine:
        return data
    from fedml_tpu.data.poison import pixel_trigger, poison_federated_data
    trigger = pixel_trigger if cfg.mode == "backdoor" else None
    return poison_federated_data(
        data, sorted(adversary.byzantine), cfg.target_label,
        poison_frac=cfg.poison_frac, trigger_fn=trigger, seed=cfg.seed)
