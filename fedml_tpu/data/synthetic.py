"""Synthetic federated datasets.

Two roles:
1. The FedProx-paper synthetic(alpha, beta) generator — a real benchmark
   config of the reference (benchmark/README.md:14; reference ships only the
   pre-generated JSONs under fedml_api/data_preprocessing/synthetic_*).
   Implemented from the published process: per-client model W_k,b_k ~
   N(u_k, 1), u_k ~ N(0, alpha); inputs x ~ N(v_k, Sigma),
   v_k ~ N(B_k, 1), B_k ~ N(0, beta); labels y = argmax(W x + b).
2. Deterministic stand-ins for datasets whose files are not on disk (this
   image has zero network egress) — same shapes, dtypes, vocab sizes and
   client counts as the real thing, so every pipeline runs end-to-end and
   perf numbers are valid; accuracy numbers then measure the synthetic task.
"""
from __future__ import annotations

import numpy as np


def synthetic_fedprox(alpha: float, beta: float, n_clients: int = 30,
                      dim: int = 60, n_classes: int = 10, seed: int = 0):
    """Returns (x [N, dim] f32, y [N] i64, net_dataidx_map)."""
    rng = np.random.RandomState(seed)
    # power-law client sizes, as in the FedProx paper (lognormal sizes)
    sizes = (rng.lognormal(4, 2, n_clients).astype(int) + 50)
    diag = np.array([(j + 1) ** -1.2 for j in range(dim)])
    xs, ys, idx_map, off = [], [], {}, 0
    for k in range(n_clients):
        u_k = rng.normal(0, alpha)
        B_k = rng.normal(0, beta)
        W = rng.normal(u_k, 1, (dim, n_classes))
        b = rng.normal(u_k, 1, n_classes)
        v_k = rng.normal(B_k, 1, dim)
        x = rng.multivariate_normal(v_k, np.diag(diag), sizes[k]).astype(np.float32)
        y = np.argmax(x @ W + b, axis=1).astype(np.int64)
        xs.append(x); ys.append(y)
        idx_map[k] = np.arange(off, off + sizes[k])
        off += sizes[k]
    return np.concatenate(xs), np.concatenate(ys), idx_map


def synthetic_classification_images(n: int, hw: tuple[int, int], channels: int,
                                    n_classes: int, seed: int = 0,
                                    flat: bool = False):
    """Learnable synthetic image task: class templates + noise, so accuracy
    oracles (federated == centralized) remain meaningful without real data."""
    rng = np.random.RandomState(seed)
    h, w = hw
    shape = (h * w * channels,) if flat else (h, w, channels)
    templates = rng.normal(0, 1, (n_classes,) + shape).astype(np.float32)
    y = rng.randint(0, n_classes, n).astype(np.int64)
    x = templates[y] * 0.5 + rng.normal(0, 1, (n,) + shape).astype(np.float32)
    return x.astype(np.float32), y


def synthetic_segmentation(n: int, hw: tuple[int, int], n_classes: int,
                           seed: int = 0, void_frac: float = 0.02,
                           void_id: int = 255):
    """Learnable synthetic segmentation task (pascal_voc stand-in): each
    pixel's class is a deterministic function of local color thresholds,
    with a sprinkle of void (ignore-index 255) pixels like real VOC
    boundary bands."""
    rng = np.random.RandomState(seed)
    h, w = hw
    x = rng.rand(n, h, w, 3).astype(np.float32)
    # class = number of channels above 0.5, capped — smooth, learnable
    y = np.minimum((x > 0.5).sum(axis=-1), n_classes - 1).astype(np.int64)
    void = rng.rand(n, h, w) < void_frac
    y[void] = void_id
    return x, y


def synthetic_sequences(n: int, seq_len: int, vocab: int, seed: int = 0):
    """Markov-chain token sequences for LM tasks (shakespeare/stackoverflow
    stand-in): x = seq[:-1], y = seq[1:].

    Sampling inverts each row's CDF with searchsorted, GROUPED BY CURRENT
    TOKEN: the historical formulation gathered a full [rows, vocab]
    float64 cum matrix per step — ~1 TB of memory traffic (and 985 s) at
    the reference's 342k-client stackoverflow scale (684,954 rows ×
    10,004 vocab) — while grouping touches each state's cum row once per
    step and binary-searches the group's uniforms against it.  The rng
    stream and the math are unchanged ((r > cum).sum() == searchsorted
    (cum, r, 'left') for sorted cum), so the output is BIT-IDENTICAL to
    the historical version (pinned by tests/test_data_extended.py)."""
    rng = np.random.RandomState(seed)
    # sparse transition matrix => learnable structure (at small vocab;
    # see synthetic_sequences_classed for why this reverts to noise at
    # large vocab)
    trans = rng.dirichlet(np.full(vocab, 0.05), size=vocab)
    cumt = np.cumsum(trans, axis=1)       # precompute rows once
    del trans
    # identity state->row mapping: each token owns its transition row
    seqs = _sample_grouped_markov(rng, n, seq_len, vocab,
                                  np.arange(vocab), cumt)
    return seqs[:, :-1].astype(np.int32), seqs[:, 1:].astype(np.int64)


def _sample_grouped_markov(rng, n: int, seq_len: int, vocab: int,
                           key_of_state: np.ndarray,
                           cum_rows: np.ndarray) -> np.ndarray:
    """Shared Markov sampler: grouped inverse-CDF over `cum_rows`,
    where state s uses row `key_of_state[s]`.  Grouping touches each
    row once per step and binary-searches the group's uniforms against
    it; the rng stream and math match the historical per-row gather
    formulation bit-exactly ((r > cum).sum() == searchsorted(cum, r,
    'left') for sorted cum — pinned by tests/test_data_extended.py)."""
    seqs = np.zeros((n, seq_len + 1), np.int32)
    seqs[:, 0] = rng.randint(0, vocab, n)
    for t in range(seq_len):
        r = rng.rand(n)                   # same stream as the row loop
        keys = key_of_state[seqs[:, t]]
        order = np.argsort(keys, kind="stable")
        uniq, starts = np.unique(keys[order], return_index=True)
        ends = np.append(starts[1:], n)
        nxt = np.empty(n, np.int64)
        for i, k in enumerate(uniq):
            sel = order[starts[i]:ends[i]]
            nxt[sel] = np.searchsorted(cum_rows[k], r[sel], side="left")
        seqs[:, t + 1] = np.clip(nxt, 0, vocab - 1)
    return seqs


def synthetic_sequences_classed(n: int, seq_len: int, vocab: int,
                                n_classes: int = 64, seed: int = 0,
                                row_alpha_total: float = 10.0):
    """Low-rank learnable Markov sequences for LARGE-vocab LM tasks.

    `synthetic_sequences` draws every state's transition row i.i.d.
    Dirichlet — a full-rank random [V, V] matrix.  At vocab 404 a
    d=96 embedding model captures a usable fraction of it (rank/V ~
    1/4, the CPU smoke learns); at the stackoverflow vocab of 10,004
    the same model is rank-limited to ~1% of the structure and every
    curve flat-lines at ln(V) — measured on chip 2026-08-01, and
    expected: random matrices are not low-rank, but natural language
    (the real task) is.  This variant makes the stand-in learnable at
    any vocab by construction: tokens are randomly assigned to
    `n_classes` classes and the transition row depends only on the
    CURRENT TOKEN'S CLASS — a rank-`n_classes` chain, exactly
    representable by any model whose embedding width >= n_classes
    (infer the class from the token, emit the class's row).

    Row sharpness must be vocab-INVARIANT or large vocabs silently
    revert to noise: a fixed per-coordinate Dirichlet alpha makes the
    effective concentration alpha*V grow with vocab (alpha=0.05 at
    V=10,004 spreads each row over ~500 tokens — oracle_top1 measured
    0.0102, so even a perfect model sits at 1%).  `row_alpha_total` is
    the TOTAL concentration: per-coordinate alpha = row_alpha_total /
    vocab, so every class's next-token distribution concentrates on
    ~row_alpha_total tokens at any vocab (default 10 -> oracle ~0.2,
    measured 0.205/0.194/0.192 at V=404/2004/10004).

    Same grouped inverse-CDF sampling as synthetic_sequences; x =
    seq[:-1], y = seq[1:].  Returns (x, y, oracle_top1): oracle_top1
    is the Bayes accuracy (mean max-prob of the class rows under the
    chain's empirical state distribution) — the ceiling a perfect
    model would hit, recorded in convergence artifacts for context."""
    rng = np.random.RandomState(seed)
    cls = rng.randint(0, n_classes, vocab)
    rows = rng.dirichlet(np.full(vocab, row_alpha_total / vocab),
                         size=n_classes)
    seqs = _sample_grouped_markov(rng, n, seq_len, vocab, cls,
                                  np.cumsum(rows, axis=1))
    # Bayes ceiling: P(correct) when always predicting the current
    # class-row's argmax, weighted by how often each class is the state
    state_cls = cls[seqs[:, :-1]]
    freq = np.bincount(state_cls.ravel(), minlength=n_classes)
    oracle_top1 = float((rows.max(axis=1) * freq).sum() / freq.sum())
    return seqs[:, :-1].astype(np.int32), seqs[:, 1:].astype(np.int64), \
        oracle_top1


def synthetic_multilabel(n: int, dim: int, n_tags: int, seed: int = 0):
    """Bag-of-words -> tag multi-label task (stackoverflow_lr stand-in)."""
    rng = np.random.RandomState(seed)
    proj = rng.normal(0, 1, (dim, n_tags)).astype(np.float32)
    x = (rng.rand(n, dim) < 0.05).astype(np.float32)
    logits = x @ proj
    y = (logits > np.percentile(logits, 90, axis=1, keepdims=True)).astype(np.float32)
    return x, y


def synthetic_tabular(n: int, dim: int, seed: int = 0, n_classes: int = 2):
    """Gaussian-blob tabular task (UCI SUSY / room-occupancy / lending-club
    stand-in): linearly separable with noise, so accuracy climbs."""
    rng = np.random.RandomState(seed)
    w = rng.normal(0, 1, (dim, n_classes)).astype(np.float32)
    x = rng.normal(0, 1, (n, dim)).astype(np.float32)
    y = np.argmax(x @ w + rng.normal(0, 0.5, (n, n_classes)), axis=1)
    return x, y.astype(np.int64)
