"""Backdoor-poisoned federated datasets for robust-FL evaluation.

Parity: reference fedml_api/data_preprocessing/edge_case_examples/
(data_loader.py:283+, `load_poisoned_dataset`) — attacker clients train on
samples relabeled to an attacker-chosen target; the defense is scored on
(a) clean accuracy and (b) backdoor success rate on a poisoned test set.
The reference ships fixed poisoned image packs (southwest/ardis/greencar);
this build poisons any loaded dataset structurally instead: a pixel
trigger (classic BadNets-style corner patch) or label-flip ("edge case"
without trigger), applied to the stacked client shards — so the pipeline
works on real files and synthetic stand-ins alike.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import numpy as np

from fedml_tpu.data.federated import FederatedData

# the "How To Backdoor FL" green-car CIFAR-10 train indices (reference
# data_loader.py:158-161 / 563-566 — published constants of the attack):
# 27 in-train pool images + 3 held out as the fallback test pool
GREEN_CAR_TRAIN_IDX = [
    874, 49163, 34287, 21422, 48003, 47001, 48030, 22984, 37533, 41336,
    3678, 37365, 19165, 34385, 41861, 39824, 561, 49588, 4528, 3378,
    38658, 38735, 19500, 9744, 47026, 1605, 389]
GREEN_CAR_TEST_IDX = [32941, 36005, 40138]


def pixel_trigger(x: np.ndarray, strength: float = 3.0) -> np.ndarray:
    """Stamp a high-contrast 3×3 checkerboard in the bottom-right corner.
    Works for NHWC images and flat vectors (last 9 features)."""
    x = x.copy()
    pat = strength * (np.indices((3, 3)).sum(axis=0) % 2 * 2 - 1)
    # image iff the trailing axes look like (H, W, C): channel dim ≤ 4.
    # Flat feature vectors (e.g. batched MNIST [..., 784]) take the
    # last-9-features branch regardless of batch ndim.
    if x.ndim >= 3 and x.shape[-1] <= 4:
        x[..., -3:, -3:, :] = pat[..., None].astype(x.dtype)
    else:
        # narrow tabular inputs (e.g. room_occupancy's 5 features) take a
        # truncated patch instead of a broadcast error
        k = min(9, x.shape[-1])
        x[..., -k:] = pat.reshape(-1)[:k].astype(x.dtype)
    return x


def poison_federated_data(data: FederatedData,
                          attacker_ids: Sequence[int],
                          target_label: int,
                          poison_frac: float = 0.5,
                          trigger_fn: Optional[Callable] = pixel_trigger,
                          seed: int = 0) -> FederatedData:
    """Return a copy of `data` where `poison_frac` of each attacker client's
    real samples carry the trigger and the target label.

    trigger_fn=None gives a pure label-flip attack (the reference's
    edge-case semantics: naturally-plausible inputs, wrong label)."""
    rs = np.random.RandomState(seed)
    shards = {k: np.array(v, copy=True) for k, v in data.client_shards.items()}
    C, B, bs = shards["mask"].shape
    for cid in attacker_ids:
        real = np.argwhere(shards["mask"][cid].reshape(-1) > 0).reshape(-1)
        n_poison = int(len(real) * poison_frac)
        if n_poison == 0:
            continue
        chosen = rs.choice(real, n_poison, replace=False)
        bi, si = np.unravel_index(chosen, (B, bs))
        if trigger_fn is not None:
            shards["x"][cid, bi, si] = trigger_fn(shards["x"][cid, bi, si])
        shards["y"][cid, bi, si] = target_label
    # fresh _device_cache: dataclasses.replace would otherwise SHARE the
    # mutable cache dict with the source data — whichever object uploads
    # its stack first would silently serve it to BOTH (a poisoned run
    # reading clean tensors, or worse, a clean run reading poisoned ones)
    return dataclasses.replace(data, client_shards=shards, _device_cache={})


def load_edge_case_pool(data_dir: Optional[str], poison_type: str,
                        image_shape: Sequence[int] = (32, 32, 3),
                        n_fallback: int = 784, seed: int = 7):
    """Edge-case example pool (reference `load_poisoned_dataset`,
    edge_case_examples/data_loader.py:283-420): naturally-plausible inputs
    from OUTSIDE the task distribution that the attacker relabels.

    Real packs when present:
      southwest: southwest_images_new_train.pkl / southwest_images_new_test.pkl
                 (pickled uint8 [N,32,32,3] CIFAR-shaped airline images)
      ardis:     ARDIS/ardis_train_dataset.pt / ardis_test_dataset.pt
                 (torch-saved MNIST-shaped digit images)
      greencar:  the pool's TRAIN images are 27 fixed green-car images
                 drawn from CIFAR-10's own train set by index
                 (data_loader.py:563-565 sampled_indices_train; the "How
                 To Backdoor FL" set) read from
                 data_dir/cifar-10-batches-py; the TEST pool is the
                 shipped greencar_cifar10/green_car_transformed_test.pkl
                 (already normalized, :585-587), falling back to the 3
                 held-out train indices (:566).
    Fallback (zero-egress image): a tight off-distribution Gaussian cluster
    with the same shapes — edge-case semantics (plausible, consistent,
    unseen) without the real pixels.

    Returns (x_train [N,...], x_test [M,...]) float32 in the dataset's
    input scale."""
    import os
    import pickle
    if poison_type in ("greencar-neo", "howto"):   # reference aliases
        poison_type = "greencar"
    if poison_type not in ("southwest", "ardis", "greencar"):
        raise ValueError(f"unknown edge-case poison {poison_type!r}")
    try:
        if poison_type == "greencar":
            from fedml_tpu.data.loaders import CIFAR10_MEAN, CIFAR10_STD
            from fedml_tpu.data.readers import read_cifar_pickles
            x_all, _, _, _ = read_cifar_pickles(
                os.path.join(data_dir or "", "cifar-10-batches-py"))
            mean = np.asarray(CIFAR10_MEAN, np.float32)
            std = np.asarray(CIFAR10_STD, np.float32)
            x_tr = (x_all[GREEN_CAR_TRAIN_IDX] - mean) / std
            te_pkl = os.path.join(data_dir or "", "greencar_cifar10",
                                  "green_car_transformed_test.pkl")
            if os.path.isfile(te_pkl):
                with open(te_pkl, "rb") as f:
                    x_te = np.asarray(pickle.load(f), np.float32)
                if x_te.ndim == 4 and x_te.shape[1] == 3:   # NCHW pack
                    x_te = x_te.transpose(0, 2, 3, 1)
            else:
                x_te = (x_all[GREEN_CAR_TEST_IDX] - mean) / std
        elif poison_type == "southwest":
            from fedml_tpu.data.loaders import CIFAR10_MEAN, CIFAR10_STD
            base = os.path.join(data_dir or "", "southwest_cifar10")
            with open(os.path.join(base, "southwest_images_new_train.pkl"),
                      "rb") as f:
                x_tr = pickle.load(f)
            with open(os.path.join(base, "southwest_images_new_test.pkl"),
                      "rb") as f:
                x_te = pickle.load(f)
            # same normalize transform the task data gets (reference applies
            # transform_train to the southwest pack, data_loader.py:330+) —
            # an un-normalized pool would make the backdoor a trivial
            # pixel-scale artifact
            mean = np.asarray(CIFAR10_MEAN, np.float32)
            std = np.asarray(CIFAR10_STD, np.float32)
            x_tr = (np.asarray(x_tr, np.float32) / 255.0 - mean) / std
            x_te = (np.asarray(x_te, np.float32) / 255.0 - mean) / std
        else:
            import torch
            base = os.path.join(data_dir or "", "ARDIS")
            # the packs are pickled Dataset objects (arbitrary classes), so
            # weights_only loading (torch>=2.6 default) cannot apply
            tr = torch.load(os.path.join(base, "ardis_train_dataset.pt"),
                            weights_only=False)
            te = torch.load(os.path.join(base, "ardis_test_dataset.pt"),
                            weights_only=False)
            # EMNIST normalization, as the reference's transform applies
            x_tr = (np.asarray(tr.data, np.float32) / 255.0 - 0.1307) / 0.3081
            x_te = (np.asarray(te.data, np.float32) / 255.0 - 0.1307) / 0.3081
            if x_tr.ndim == 3:
                x_tr, x_te = x_tr[..., None], x_te[..., None]
        return x_tr, x_te
    except (FileNotFoundError, OSError, ImportError):
        # ImportError: torch absent for the ardis .pt packs — same
        # fall-back contract as a missing file
        rs = np.random.RandomState(seed)
        shape = tuple(image_shape)
        # one coherent off-distribution prototype + small jitter: the
        # "edge case" property is that the examples resemble each OTHER,
        # not the training data
        proto = rs.normal(2.5, 0.3, shape).astype(np.float32)
        n_te = max(n_fallback // 4, 1)
        x = proto + rs.normal(0, 0.2, (n_fallback + n_te,) + shape)
        return (x[:n_fallback].astype(np.float32),
                x[n_fallback:].astype(np.float32))


def poison_edge_case(data: FederatedData, attacker_ids: Sequence[int],
                     target_label: int, pool: np.ndarray,
                     poison_frac: float = 0.5,
                     seed: int = 0) -> FederatedData:
    """Replace `poison_frac` of each attacker's real samples with edge-case
    pool images labeled `target_label` (data_loader.py mixing semantics:
    the attacker's shard is a clean/edge mixture)."""
    rs = np.random.RandomState(seed)
    shards = {k: np.array(v, copy=True) for k, v in data.client_shards.items()}
    C, B, bs = shards["mask"].shape
    for cid in attacker_ids:
        real = np.argwhere(shards["mask"][cid].reshape(-1) > 0).reshape(-1)
        n_poison = int(len(real) * poison_frac)
        if n_poison == 0:
            continue
        chosen = rs.choice(real, n_poison, replace=False)
        picks = rs.randint(0, len(pool), n_poison)
        bi, si = np.unravel_index(chosen, (B, bs))
        shards["x"][cid, bi, si] = pool[picks].astype(shards["x"].dtype)
        shards["y"][cid, bi, si] = target_label
    # fresh _device_cache — same shared-cache hazard as poison_federated_data
    return dataclasses.replace(data, client_shards=shards, _device_cache={})


def edge_case_test_shard(pool_test: np.ndarray, target_label: int,
                         batch_size: int = 64) -> dict:
    """Backdoor-success eval shard: every edge-case test image, labeled with
    the attacker's target (targetted_task_test_loader parity)."""
    n = len(pool_test)
    B = (n + batch_size - 1) // batch_size
    pad = B * batch_size - n
    x = np.concatenate([pool_test,
                        np.zeros((pad,) + pool_test.shape[1:],
                                 pool_test.dtype)])
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    y = np.full(B * batch_size, target_label, np.int64)
    return {"x": x.reshape((B, batch_size) + pool_test.shape[1:]),
            "y": y.reshape(B, batch_size),
            "mask": mask.reshape(B, batch_size)}


def backdoor_test_shard(data: FederatedData, target_label: int,
                        trigger_fn: Callable = pixel_trigger) -> dict:
    """Poisoned test set for the backdoor-success metric: every non-target
    test sample gets the trigger and the target label; originally-target
    samples are masked out (they would inflate the success rate)."""
    shard = {k: np.array(v, copy=True) for k, v in data.test_global.items()}
    shard["x"] = trigger_fn(shard["x"])
    not_target = (shard["y"] != target_label).astype(shard["mask"].dtype)
    shard["mask"] = shard["mask"] * not_target
    shard["y"] = np.full_like(shard["y"], target_label)
    return shard
