"""Backdoor-poisoned federated datasets for robust-FL evaluation.

Parity: reference fedml_api/data_preprocessing/edge_case_examples/
(data_loader.py:283+, `load_poisoned_dataset`) — attacker clients train on
samples relabeled to an attacker-chosen target; the defense is scored on
(a) clean accuracy and (b) backdoor success rate on a poisoned test set.
The reference ships fixed poisoned image packs (southwest/ardis/greencar);
this build poisons any loaded dataset structurally instead: a pixel
trigger (classic BadNets-style corner patch) or label-flip ("edge case"
without trigger), applied to the stacked client shards — so the pipeline
works on real files and synthetic stand-ins alike.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import numpy as np

from fedml_tpu.data.federated import FederatedData


def pixel_trigger(x: np.ndarray, strength: float = 3.0) -> np.ndarray:
    """Stamp a high-contrast 3×3 checkerboard in the bottom-right corner.
    Works for NHWC images and flat vectors (last 9 features)."""
    x = x.copy()
    pat = strength * (np.indices((3, 3)).sum(axis=0) % 2 * 2 - 1)
    # image iff the trailing axes look like (H, W, C): channel dim ≤ 4.
    # Flat feature vectors (e.g. batched MNIST [..., 784]) take the
    # last-9-features branch regardless of batch ndim.
    if x.ndim >= 3 and x.shape[-1] <= 4:
        x[..., -3:, -3:, :] = pat[..., None].astype(x.dtype)
    else:
        # narrow tabular inputs (e.g. room_occupancy's 5 features) take a
        # truncated patch instead of a broadcast error
        k = min(9, x.shape[-1])
        x[..., -k:] = pat.reshape(-1)[:k].astype(x.dtype)
    return x


def poison_federated_data(data: FederatedData,
                          attacker_ids: Sequence[int],
                          target_label: int,
                          poison_frac: float = 0.5,
                          trigger_fn: Optional[Callable] = pixel_trigger,
                          seed: int = 0) -> FederatedData:
    """Return a copy of `data` where `poison_frac` of each attacker client's
    real samples carry the trigger and the target label.

    trigger_fn=None gives a pure label-flip attack (the reference's
    edge-case semantics: naturally-plausible inputs, wrong label)."""
    rs = np.random.RandomState(seed)
    shards = {k: np.array(v, copy=True) for k, v in data.client_shards.items()}
    C, B, bs = shards["mask"].shape
    for cid in attacker_ids:
        real = np.argwhere(shards["mask"][cid].reshape(-1) > 0).reshape(-1)
        n_poison = int(len(real) * poison_frac)
        if n_poison == 0:
            continue
        chosen = rs.choice(real, n_poison, replace=False)
        bi, si = np.unravel_index(chosen, (B, bs))
        if trigger_fn is not None:
            shards["x"][cid, bi, si] = trigger_fn(shards["x"][cid, bi, si])
        shards["y"][cid, bi, si] = target_label
    return dataclasses.replace(data, client_shards=shards)


def backdoor_test_shard(data: FederatedData, target_label: int,
                        trigger_fn: Callable = pixel_trigger) -> dict:
    """Poisoned test set for the backdoor-success metric: every non-target
    test sample gets the trigger and the target label; originally-target
    samples are masked out (they would inflate the success rate)."""
    shard = {k: np.array(v, copy=True) for k, v in data.test_global.items()}
    shard["x"] = trigger_fn(shard["x"])
    not_target = (shard["y"] != target_label).astype(shard["mask"].dtype)
    shard["mask"] = shard["mask"] * not_target
    shard["y"] = np.full_like(shard["y"], target_label)
    return shard
