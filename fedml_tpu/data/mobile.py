"""Mobile per-device dataset splitter.

Parity: fedml_api/data_preprocessing/MNIST/mnist_mobile_preprocessor.py —
pre-computes, for each of `client_num_per_round` devices, the client ids it
will play across `comm_round` rounds (the SAME deterministic
np.random.seed(round_idx) sampler as training) and writes per-device LEAF
JSONs: `<out>/<device>/train/train.json` and `<out>/<device>/test/test.json`
with `users` / `num_samples` / `user_data` restricted to those clients.
The mobile runtime then ships one small JSON per device instead of the full
federation.
"""
from __future__ import annotations

import json
import os

import numpy as np

from fedml_tpu.core.sampling import ClientSampler
from fedml_tpu.data.readers import read_leaf_dir


def _subset(users, user_data, picked_ids):
    # a user can be missing from one split (LEAF test jsons are not
    # guaranteed to mirror train) — ship an empty record, don't crash
    sel_users = [users[i] for i in picked_ids]
    empty = {"x": [], "y": []}
    return {
        "users": sel_users,
        "num_samples": [len(user_data.get(u, empty)["y"])
                        for u in sel_users],
        "user_data": {u: user_data.get(u, empty) for u in sel_users},
    }


def split_mobile_devices(data_dir: str, out_dir: str,
                         client_num_per_round: int, comm_round: int,
                         client_num_in_total: int | None = None) -> list[str]:
    """Write per-device train/test JSONs; returns the device dirs.

    Device d plays sampled client `sample_list[d]` each round
    (mnist_mobile_preprocessor.py:99-103: worker.client_sample_list).
    """
    users, train_data = read_leaf_dir(os.path.join(data_dir, "train"))
    _, test_data = read_leaf_dir(os.path.join(data_dir, "test"))
    total = min(client_num_in_total or len(users), len(users))
    sampler = ClientSampler(total, client_num_per_round)
    per_device: list[list[int]] = [[] for _ in range(client_num_per_round)]
    for round_idx in range(comm_round):
        picks = np.asarray(sampler.sample(round_idx))
        for d in range(client_num_per_round):
            per_device[d].append(int(picks[d]))
    out_paths = []
    for d, ids in enumerate(per_device):
        dev = os.path.join(out_dir, str(d))
        for split, data in (("train", train_data), ("test", test_data)):
            path = os.path.join(dev, split, f"{split}.json")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump(_subset(users, data, sorted(set(ids))), f)
        out_paths.append(dev)
    return out_paths
