"""uint8 cohort quantization — the transfer-compression storage format.

The round-5 chip sessions proved the large-cohort paths are
transfer-bound (PERF.md: C4096B ran at exactly tunnel upload bandwidth
for 10.5 GB of bf16 H2D).  Image inputs are natively uint8 — 4x smaller
than the f32 stacks the loaders build and 2x smaller than the bf16
`--stack_dtype` floor — so the biggest remaining byte lever is to keep
cohorts in uint8 through host gather, prefetch, and `device_put`, and
dequantize ON DEVICE as the first op of the jitted round program
(engine.py `_dequant_chunk_x`, fused into the block/chunk scan).

A `DequantSpec` is the per-dataset affine that turns stored uint8 back
into the float values training expects:

    x_float = u.astype(f32) * scale + offset

Two constructions:

* `spec_from_normalize(mean, std)` — EXACT for loaders that normalize
  raw uint8 pixels with `(u/255 - mean)/std` (cifar10/100/cinic10):
  scale = 1/(255*std), offset = -mean/std per channel, so storing the
  raw pixels loses nothing — the dequantized values are the same
  formula the f32 loader computed.
* `spec_from_minmax(x)` — generic fallback for float sources without a
  known uint8 origin (synthetic stand-ins, engine-side quantization of
  an already-float stack): one affine over the tensor's [min, max]
  range, worst-case error scale/2 = (max-min)/510 per element.

scale/offset are float32 arrays broadcastable over a SAMPLE's trailing
dims (per-channel [c] for images, scalars otherwise) — they broadcast
against [C, B, bs, h, w, c] stacks and single-sample slices alike.
"""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class DequantSpec:
    """Affine dequantization params: x = u * scale + offset (f32)."""
    scale: np.ndarray    # f32, broadcastable over trailing sample dims
    offset: np.ndarray

    def __post_init__(self):
        object.__setattr__(self, "scale",
                           np.asarray(self.scale, np.float32))
        object.__setattr__(self, "offset",
                           np.asarray(self.offset, np.float32))


def spec_from_normalize(mean, std) -> DequantSpec:
    """Exact spec for `(u/255 - mean)/std`-normalized uint8 sources
    (readers.normalize_image): dequantizing the raw pixels reproduces
    the normalized float values bit-for-bit up to f32 rounding of the
    same formula."""
    std = np.asarray(std, np.float32)
    mean = np.asarray(mean, np.float32)
    return DequantSpec(scale=1.0 / (255.0 * std), offset=-mean / std)


def spec_from_minmax(x: np.ndarray) -> DequantSpec:
    """Generic per-tensor affine over [min, max] of a float array.
    Degenerate (constant / empty) inputs get scale 1 so the round trip
    stays finite."""
    x = np.asarray(x)
    if x.size == 0:
        return DequantSpec(scale=np.float32(1.0), offset=np.float32(0.0))
    mn = np.float32(np.min(x))
    mx = np.float32(np.max(x))
    if not (np.isfinite(mn) and np.isfinite(mx)):
        raise ValueError("cannot quantize a non-finite array to uint8")
    scale = (mx - mn) / np.float32(255.0)
    if scale <= 0:
        scale = np.float32(1.0)
    return DequantSpec(scale=scale, offset=mn)


def quantize_uint8(x: np.ndarray, spec: DequantSpec) -> np.ndarray:
    """Float -> uint8 under `spec` (round-to-nearest, clipped).  For a
    spec_from_normalize spec applied to normalize_image output this
    recovers the original raw pixels exactly."""
    q = np.rint((np.asarray(x, np.float32) - spec.offset) / spec.scale)
    return np.clip(q, 0, 255).astype(np.uint8)


def dequantize(u: np.ndarray, spec: DequantSpec) -> np.ndarray:
    """Host-side inverse (the device-side twin lives inside the engine's
    jitted round program — engine.py `_dequant_chunk_x`)."""
    return np.asarray(u, np.float32) * spec.scale + spec.offset
