"""Jit-compatible training-time image augmentation.

Parity: the reference's torchvision pipeline — RandomCrop(32, padding=4) +
RandomHorizontalFlip + Cutout(16) (cifar10/data_loader.py:57-98) — runs on
CPU workers per sample.  TPU-native, augmentation is a pure batched
function of (rng, x) executed INSIDE the jitted train step: per-sample
crop offsets via vmapped dynamic_slice, flips and cutout as masked selects.
XLA fuses the whole thing into the input pipeline of the first conv —
zero host round-trips, reproducible from the client rng.

Eval paths never call this (ClientTrainer applies it only under
train=True), so augmentation is a no-op at eval by construction.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def random_crop(rng: jax.Array, x: jax.Array, padding: int = 4) -> jax.Array:
    """RandomCrop(H, padding): zero-pad then take a random HxW window per
    sample.  x: [bs, H, W, C]."""
    bs, h, w, c = x.shape
    xp = jnp.pad(x, ((0, 0), (padding, padding), (padding, padding), (0, 0)))
    ry, rx = jax.random.split(rng)
    ys = jax.random.randint(ry, (bs,), 0, 2 * padding + 1)
    xs = jax.random.randint(rx, (bs,), 0, 2 * padding + 1)

    def one(img, y0, x0):
        return jax.lax.dynamic_slice(img, (y0, x0, 0), (h, w, c))

    return jax.vmap(one)(xp, ys, xs)


def random_flip(rng: jax.Array, x: jax.Array) -> jax.Array:
    """RandomHorizontalFlip (p=0.5) per sample."""
    flip = jax.random.bernoulli(rng, 0.5, (x.shape[0],))
    return jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)


def cutout(rng: jax.Array, x: jax.Array, length: int = 16) -> jax.Array:
    """Cutout(length): zero a length x length square at a uniform center,
    clipped at the borders (data_loader.py:57-77 semantics: the center is
    uniform over the image, so edge squares are partially cut)."""
    bs, h, w, _ = x.shape
    ry, rx = jax.random.split(rng)
    cy = jax.random.randint(ry, (bs, 1, 1), 0, h)
    cx = jax.random.randint(rx, (bs, 1, 1), 0, w)
    yy = jnp.arange(h)[None, :, None]
    xx = jnp.arange(w)[None, None, :]
    inside = ((yy >= cy - length // 2) & (yy < cy + length // 2)
              & (xx >= cx - length // 2) & (xx < cx + length // 2))
    return x * (~inside)[..., None].astype(x.dtype)


def make_augment_fn(crop_padding: int = 4, flip: bool = True,
                    cutout_length: Optional[int] = 16):
    """Compose the reference CIFAR pipeline as one (rng, x) -> x function.
    Set cutout_length=None to disable cutout (the reference only applies it
    to CIFAR-10/100-style sets)."""

    def augment(rng: jax.Array, x: jax.Array) -> jax.Array:
        r1, r2, r3 = jax.random.split(rng, 3)
        if crop_padding:
            x = random_crop(r1, x, crop_padding)
        if flip:
            x = random_flip(r2, x)
        if cutout_length:
            x = cutout(r3, x, cutout_length)
        return x

    return augment
