from fedml_tpu.data.federated import (
    FederatedData,
    build_client_shards,
    build_eval_shard,
    pad_to_batches,
)
from fedml_tpu.data.loaders import load_data

__all__ = ["FederatedData", "build_client_shards", "build_eval_shard",
           "pad_to_batches", "load_data"]
