from fedml_tpu.data.federated import (
    FederatedData,
    build_client_shards,
    build_eval_shard,
    pad_to_batches,
)
from fedml_tpu.data.loaders import load_data, load_vfl_data
from fedml_tpu.data.poison import (backdoor_test_shard, pixel_trigger,
                                   poison_federated_data)

__all__ = ["FederatedData", "build_client_shards", "build_eval_shard",
           "pad_to_batches", "load_data", "load_vfl_data",
           "poison_federated_data", "backdoor_test_shard", "pixel_trigger"]
