"""Dataset registry + `load_data` dispatch.

Mirrors the reference's per-entry-point dataset dispatch
(fedml_experiments/distributed/fedavg/main_fedavg.py:138-356) as one
function.  Every loader returns a `FederatedData` whose client shards are
stacked padded arrays (see data/federated.py).  When the real files are
absent (zero-egress image), a deterministic synthetic stand-in with the same
shapes/vocab/client counts is generated and `synthetic=True` is recorded.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

from fedml_tpu.core.partition import (partition_dirichlet, partition_homo,
                                      partition_power_law)
from fedml_tpu.data.federated import (FederatedData, build_client_shards,
                                      build_eval_shard)
from fedml_tpu.data import readers, synthetic, text

CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)
CIFAR10_STD = (0.2470, 0.2435, 0.2616)
CIFAR100_MEAN = (0.5071, 0.4866, 0.4409)
CIFAR100_STD = (0.2673, 0.2564, 0.2762)


@dataclass
class DatasetSpec:
    n_clients_default: int
    class_num: int
    batch_size_default: int


SPECS = {
    "mnist": DatasetSpec(1000, 10, 10),
    "femnist": DatasetSpec(3400, 62, 20),
    "fed_cifar100": DatasetSpec(500, 100, 20),
    "shakespeare": DatasetSpec(715, 90, 4),
    "fed_shakespeare": DatasetSpec(715, 90, 4),
    # 342,477 = the full TFF StackOverflow user base, the reference's
    # benchmark client count (benchmark/README.md:57); pass
    # client_num_in_total for smaller slices
    "stackoverflow_nwp": DatasetSpec(342_477, 10004, 16),
    "stackoverflow_lr": DatasetSpec(342_477, 500, 16),
    "cifar10": DatasetSpec(10, 10, 64),
    "cifar100": DatasetSpec(10, 100, 64),
    "cinic10": DatasetSpec(10, 10, 64),
    "synthetic_0_0": DatasetSpec(30, 10, 10),
    "synthetic_0.5_0.5": DatasetSpec(30, 10, 10),
    "synthetic_1_1": DatasetSpec(30, 10, 10),
    "imagenet": DatasetSpec(100, 1000, 32),
    "gld23k": DatasetSpec(233, 203, 32),
    "gld160k": DatasetSpec(1262, 2028, 32),
    "susy": DatasetSpec(30, 2, 32),
    "room_occupancy": DatasetSpec(30, 2, 32),
    # segmentation (fedseg; 21 = VOC classes incl. background, void=255)
    "pascal_voc": DatasetSpec(4, 21, 8),
}

# feature dims for the tabular/streaming UCI tasks (reference
# UCI/data_loader_for_susy_and_ro.py)
_TABULAR_DIMS = {"susy": 18, "room_occupancy": 5}


def _partition(labels, n_clients, method, alpha, seed, data_dir=""):
    if method == "homo":
        return partition_homo(len(labels), n_clients, seed)
    if method == "hetero":
        return partition_dirichlet(labels, n_clients, alpha, seed=seed)
    if method == "power_law":
        return partition_power_law(labels, n_clients, seed)
    if method == "hetero-fix":
        # precomputed map (reference cifar10/data_loader.py:150-156);
        # falls back to hetero when the txt is absent
        try:
            m = readers.read_net_dataidx_map(
                os.path.join(data_dir or "", "net_dataidx_map.txt"))
        except FileNotFoundError:
            import logging
            logging.getLogger(__name__).warning(
                "hetero-fix requested but %s/net_dataidx_map.txt is absent; "
                "falling back to a Dirichlet(alpha=%s) partition — this is "
                "NOT the precomputed reference split", data_dir, alpha)
            return partition_dirichlet(labels, n_clients, alpha, seed=seed)
        if sorted(m) != list(range(n_clients)):
            raise ValueError(
                f"net_dataidx_map.txt holds clients {sorted(m)[:5]}..."
                f"(n={len(m)}), but client_num_in_total={n_clients}; the "
                "sampler would train the wrong cohort")
        return m
    raise ValueError(f"unknown partition {method!r}")


def _make(x_tr, y_tr, x_te, y_te, idx_map, batch_size, class_num,
          max_batches=None, test_idx_map=None, seed=0, synthetic=False):
    if synthetic and len(idx_map) > 100_000:
        # reference-contract client counts (stackoverflow: 342,477) make
        # the synthetic stand-in a multi-minute, multi-GB host build —
        # worth a heads-up when it was reached by DEFAULT
        import logging
        logging.getLogger(__name__).warning(
            "building a synthetic stand-in for %d clients (measured: "
            "18 s / 2.6 GB RSS at 342,477); pass client_num_in_total "
            "for a smaller slice", len(idx_map))
    shards = build_client_shards(x_tr, y_tr, idx_map, batch_size,
                                 max_batches=max_batches, shuffle_seed=seed)
    sizes = np.array([min(len(idx_map[i]),
                          shards["mask"].shape[1] * shards["mask"].shape[2])
                      for i in range(len(idx_map))], np.float32)
    test_shards = None
    if test_idx_map is not None:
        test_shards = build_client_shards(x_te, y_te, test_idx_map, batch_size,
                                          max_batches=max_batches)
    return FederatedData(
        train_data_num=int(len(y_tr)),
        test_data_num=int(len(y_te)),
        train_global=build_eval_shard(x_tr, y_tr, max(batch_size, 64)),
        test_global=build_eval_shard(x_te, y_te, max(batch_size, 64)),
        client_shards=shards,
        client_num_samples=sizes,
        test_client_shards=test_shards,
        class_num=class_num,
        synthetic=synthetic,
    )


def load_data(dataset: str,
              data_dir: Optional[str] = None,
              client_num_in_total: Optional[int] = None,
              batch_size: Optional[int] = None,
              partition_method: str = "hetero",
              partition_alpha: float = 0.5,
              max_batches_per_client: Optional[int] = None,
              seed: int = 0,
              synthetic_scale: float = 1.0,
              store_uint8: bool = False) -> FederatedData:
    """Load (or synthesize) a federated dataset.

    `synthetic_scale` < 1 shrinks synthetic stand-ins for fast tests.

    `store_uint8` keeps the TRAIN client stack's input leaf in uint8
    with a `DequantSpec` on `FederatedData.x_dequant` (data/quant.py) —
    the transfer-compression storage the mesh engines dequantize
    on-device (`--stack_dtype uint8`): 4x fewer host RAM / H2D bytes
    than f32 stacks.  For the normalize_image datasets (cifar10/100,
    cinic10) the stored bytes ARE the raw pixels (exact round trip);
    elsewhere a per-tensor min/max affine is used.  Eval shards
    (train_global/test_global/test_client_shards) always stay float —
    only the cohort path pays transfer at scale.
    """
    fd = _load_data(dataset, data_dir, client_num_in_total, batch_size,
                    partition_method, partition_alpha,
                    max_batches_per_client, seed, synthetic_scale)
    if store_uint8:
        from fedml_tpu.data import quant
        spec = None
        if not fd.synthetic:
            # normalize_image datasets: dequant spec derived from the
            # normalization constants, so the uint8 storage is exactly
            # the raw pixels (lossless round trip)
            if dataset in ("cifar10", "cinic10"):
                spec = quant.spec_from_normalize(CIFAR10_MEAN, CIFAR10_STD)
            elif dataset == "cifar100":
                spec = quant.spec_from_normalize(CIFAR100_MEAN,
                                                 CIFAR100_STD)
        x = fd.client_shards.get("x")
        if x is not None and np.issubdtype(np.asarray(x).dtype,
                                           np.floating):
            spec = spec or quant.spec_from_minmax(x)
            fd.client_shards["x"] = quant.quantize_uint8(x, spec)
            fd.x_dequant = spec
        else:
            import logging
            logging.getLogger(__name__).warning(
                "store_uint8 ignored for %s: the input leaf is %s "
                "(integer token ids must not be quantized)", dataset,
                None if x is None else np.asarray(x).dtype)
    return fd


def _load_data(dataset: str,
               data_dir: Optional[str] = None,
               client_num_in_total: Optional[int] = None,
               batch_size: Optional[int] = None,
               partition_method: str = "hetero",
               partition_alpha: float = 0.5,
               max_batches_per_client: Optional[int] = None,
               seed: int = 0,
               synthetic_scale: float = 1.0) -> FederatedData:
    if dataset not in SPECS:
        raise ValueError(f"unknown dataset {dataset!r}; known: {sorted(SPECS)}")
    spec = SPECS[dataset]
    data_dir = data_dir or ""
    C = client_num_in_total or spec.n_clients_default
    bs = batch_size or spec.batch_size_default
    sc = lambda n: max(C * 2, int(n * synthetic_scale))

    if dataset == "mnist":
        try:
            users, user_data = readers.read_leaf_dir(os.path.join(data_dir or "", "train"))
            users_te, user_data_te = readers.read_leaf_dir(os.path.join(data_dir, "test"))
            x_tr, y_tr, idx_map = readers.leaf_to_arrays(users[:C], user_data)
            x_te, y_te, te_map = readers.leaf_to_arrays(users_te[:C], user_data_te)
            x_tr = x_tr.reshape(-1, 28 * 28); x_te = x_te.reshape(-1, 28 * 28)
            synth = False
        except FileNotFoundError:
            synth = True
            x, y = synthetic.synthetic_classification_images(
                sc(60000), (28, 28), 1, 10, seed=seed, flat=True)
            n_te = max(C, sc(60000) // 6)
            x_tr, y_tr, x_te, y_te = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
            idx_map = _partition(y_tr, C, "power_law", partition_alpha, seed)
            te_map = None
        return _make(x_tr, y_tr, x_te, y_te, idx_map, bs, 10,
                     max_batches_per_client, te_map, seed, synthetic=synth)

    if dataset == "femnist":
        try:
            h5 = readers.read_tff_h5(os.path.join(data_dir or "", "fed_emnist_train.h5"),
                                     ("pixels", "label"))
            h5t = readers.read_tff_h5(os.path.join(data_dir, "fed_emnist_test.h5"),
                                      ("pixels", "label"))
            cids = sorted(h5.keys())[:C]
            xs, ys, idx_map, off = [], [], {}, 0
            for i, cid in enumerate(cids):
                px = h5[cid]["pixels"].astype(np.float32)[..., None]
                lb = h5[cid]["label"].astype(np.int64)
                xs.append(px); ys.append(lb)
                idx_map[i] = np.arange(off, off + len(lb)); off += len(lb)
            x_tr, y_tr = np.concatenate(xs), np.concatenate(ys)
            xt = np.concatenate([h5t[c]["pixels"].astype(np.float32)[..., None]
                                 for c in sorted(h5t.keys())[:C]])
            yt = np.concatenate([h5t[c]["label"].astype(np.int64)
                                 for c in sorted(h5t.keys())[:C]])
            te_map = None
            synth = False
        except FileNotFoundError:
            synth = True
            x, y = synthetic.synthetic_classification_images(
                sc(80000), (28, 28), 1, 62, seed=seed)
            n_te = sc(80000) // 8
            x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
            idx_map = _partition(y_tr, C, "power_law", partition_alpha, seed)
            te_map = None
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, 62,
                     max_batches_per_client, te_map, seed, synthetic=synth)

    if dataset == "fed_cifar100":
        try:
            h5 = readers.read_tff_h5(os.path.join(data_dir or "", "fed_cifar100_train.h5"),
                                     ("image", "label"))
            cids = sorted(h5.keys())[:C]
            xs, ys, idx_map, off = [], [], {}, 0
            for i, cid in enumerate(cids):
                im = h5[cid]["image"].astype(np.float32) / 255.0
                lb = h5[cid]["label"].astype(np.int64)
                xs.append(im); ys.append(lb)
                idx_map[i] = np.arange(off, off + len(lb)); off += len(lb)
            x_tr, y_tr = np.concatenate(xs), np.concatenate(ys)
            h5t = readers.read_tff_h5(os.path.join(data_dir, "fed_cifar100_test.h5"),
                                      ("image", "label"))
            xt = np.concatenate([h5t[c]["image"].astype(np.float32) / 255.0
                                 for c in sorted(h5t.keys())])
            yt = np.concatenate([h5t[c]["label"].astype(np.int64)
                                 for c in sorted(h5t.keys())])
            synth = False
        except FileNotFoundError:
            synth = True
            x, y = synthetic.synthetic_classification_images(
                sc(50000), (32, 32), 3, 100, seed=seed)
            n_te = sc(50000) // 5
            x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
            idx_map = _partition(y_tr, C, "hetero", partition_alpha, seed)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, 100,
                     max_batches_per_client, None, seed, synthetic=synth)

    if dataset == "shakespeare":
        # LEAF JSON text: 80-char windows -> next char (reference
        # shakespeare/data_loader.py:11-87, language_utils.py:31-55)
        seq_len, vocab = text.SHAKESPEARE_SEQ_LEN, text.SHAKESPEARE_VOCAB_SIZE
        try:
            users, user_data = readers.read_leaf_dir(
                os.path.join(data_dir or "", "train"))
            users_te, user_data_te = readers.read_leaf_dir(
                os.path.join(data_dir, "test"))
            x_tr, y_tr, idx_map = text.leaf_shakespeare_to_arrays(
                users[:C], user_data)
            xt, yt, te_map = text.leaf_shakespeare_to_arrays(
                users_te[:C], user_data_te)
            synth = False
        except FileNotFoundError:
            synth, te_map = True, None
            x, y = synthetic.synthetic_sequences(sc(16000), seq_len, vocab,
                                                 seed=seed)
            n_te = sc(16000) // 8
            x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
            # next-char task: label = last-position next token
            y_tr, yt = y_tr[:, -1], yt[:, -1]
            idx_map = partition_homo(len(y_tr), C, seed)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, vocab,
                     max_batches_per_client, te_map, seed, synthetic=synth)

    if dataset == "fed_shakespeare":
        # TFF h5 snippets -> 80-token shifted sequences (reference
        # fed_shakespeare/utils.py:53-82, data_loader.py:24-69)
        seq_len, vocab = text.SHAKESPEARE_SEQ_LEN, text.SHAKESPEARE_VOCAB_SIZE
        try:
            h5 = readers.read_tff_h5(
                os.path.join(data_dir or "", "shakespeare_train.h5"),
                ("snippets",))
            h5t = readers.read_tff_h5(
                os.path.join(data_dir, "shakespeare_test.h5"), ("snippets",))
            xs, ys, idx_map, off = [], [], {}, 0
            for i, cid in enumerate(sorted(h5)[:C]):
                sx, sy = text.tff_snippets_to_sequences(
                    text._decode(h5[cid]["snippets"]), seq_len)
                xs.append(sx); ys.append(sy)
                idx_map[i] = np.arange(off, off + len(sy)); off += len(sy)
            x_tr, y_tr = np.concatenate(xs), np.concatenate(ys)
            parts = [text.tff_snippets_to_sequences(
                text._decode(h5t[c]["snippets"]), seq_len) for c in sorted(h5t)]
            xt = np.concatenate([p[0] for p in parts])
            yt = np.concatenate([p[1] for p in parts])
            synth = False
        except FileNotFoundError:
            synth = True
            x, y = synthetic.synthetic_sequences(sc(16000), seq_len, vocab,
                                                 seed=seed)
            n_te = sc(16000) // 8
            x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
            idx_map = partition_homo(len(y_tr), C, seed)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, vocab,
                     max_batches_per_client, None, seed, synthetic=synth)

    if dataset == "stackoverflow_nwp":
        # TFF h5 word streams + stackoverflow.word_count vocabulary
        # (reference stackoverflow_nwp/utils.py:27-86, dataset.py:45-51)
        seq_len, vocab_len = 20, 10004
        try:
            words = text.read_word_count_vocab(
                os.path.join(data_dir or "", "stackoverflow.word_count"))
            wv = text.WordVocab(words)
            h5 = readers.read_tff_h5(
                os.path.join(data_dir, "stackoverflow_train.h5"), ("tokens",))
            h5t = readers.read_tff_h5(
                os.path.join(data_dir, "stackoverflow_test.h5"), ("tokens",))
            x_tr, y_tr, idx_map = text.stackoverflow_nwp_arrays(
                h5, wv, seq_len, max_clients=C)
            xt, yt, te_map = text.stackoverflow_nwp_arrays(
                h5t, wv, seq_len, max_clients=C)
            vocab_len = wv.vocab_len
            synth = False
        except FileNotFoundError:
            synth, te_map = True, None
            # classed (rank-64) chain, NOT synthetic_sequences: a
            # full-rank random [V, V] chain at vocab 10,004 is
            # unlearnable by embedding models AND near-noise even for
            # an oracle (measured oracle_top1 = 0.0102 — see
            # synthetic_sequences_classed's docstring), which broke the
            # "learnable stand-in" contract this module documents.
            # Also ~150x lighter to generate (64 rows vs a [V, V]
            # matrix).
            x, y, _ = synthetic.synthetic_sequences_classed(
                sc(20000), seq_len, vocab_len, seed=seed)
            n_te = sc(20000) // 8
            x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
            idx_map = partition_homo(len(y_tr), C, seed)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, vocab_len,
                     max_batches_per_client, te_map, seed, synthetic=synth)

    if dataset == "stackoverflow_lr":
        # bag-of-words -> multi-hot tags, vocab/tag files + h5
        # (reference stackoverflow_lr/utils.py:33-131, dataset.py:54-62)
        dim, n_tags = 10000, 500
        try:
            words = text.BagOfWordsVocab(text.read_word_count_vocab(
                os.path.join(data_dir or "", "stackoverflow.word_count"), dim))
            tags = text.TagVocab(text.read_tag_count_vocab(
                os.path.join(data_dir, "stackoverflow.tag_count"), n_tags))
            h5 = readers.read_tff_h5(
                os.path.join(data_dir, "stackoverflow_train.h5"),
                ("tokens", "title", "tags"))
            h5t = readers.read_tff_h5(
                os.path.join(data_dir, "stackoverflow_test.h5"),
                ("tokens", "title", "tags"))
            x_tr, y_tr, idx_map = text.stackoverflow_lr_arrays(
                h5, words, tags, max_clients=C)
            xt, yt, te_map = text.stackoverflow_lr_arrays(
                h5t, words, tags, max_clients=C)
            dim, n_tags = words.dim, tags.dim
            synth = False
        except FileNotFoundError:
            synth, te_map = True, None
            x, y = synthetic.synthetic_multilabel(sc(20000), dim, n_tags,
                                                  seed=seed)
            n_te = sc(20000) // 8
            x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
            idx_map = partition_homo(len(y_tr), C, seed)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, n_tags,
                     max_batches_per_client, te_map, seed, synthetic=synth)

    if dataset in ("cifar10", "cifar100", "cinic10"):
        n_classes = 100 if dataset == "cifar100" else 10
        mean, std = ((CIFAR100_MEAN, CIFAR100_STD) if dataset == "cifar100"
                     else (CIFAR10_MEAN, CIFAR10_STD))
        try:
            if dataset == "cinic10":
                x_tr, y_tr, xt, yt = readers.read_image_folder(data_dir)
            else:
                sub = {"cifar10": "cifar-10-batches-py",
                       "cifar100": "cifar-100-python"}[dataset]
                x_tr, y_tr, xt, yt = readers.read_cifar_pickles(
                    os.path.join(data_dir, sub),
                    cifar100=(dataset == "cifar100"))
            x_tr = readers.normalize_image(x_tr, mean, std)
            xt = readers.normalize_image(xt, mean, std)
            synth = False
        except FileNotFoundError:
            synth = True
            n = sc(50000 if dataset != "cinic10" else 90000)
            x, y = synthetic.synthetic_classification_images(
                n, (32, 32), 3, n_classes, seed=seed)
            n_te = n // 5
            x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
        idx_map = _partition(y_tr, C, partition_method, partition_alpha,
                             seed, data_dir)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, n_classes,
                     max_batches_per_client, None, seed, synthetic=synth)

    if dataset == "imagenet":
        # reference ImageNet/data_loader.py:1-300 (per-client index maps over
        # ILSVRC2012; hdf5 pack variant datasets_hdf5.py:13-40).  Synthetic
        # stand-in uses 64×64 (memory-sane shape proxy; the loader path and
        # partition semantics are identical).
        try:
            h5p = os.path.join(data_dir or "", "imagenet.hdf5")
            if os.path.isfile(h5p):
                x_tr, y_tr, xt, yt = readers.read_imagenet_h5(h5p)
            else:
                x_tr, y_tr, xt, yt = readers.read_image_folder(data_dir)
            synth = False
            idx_map = _partition(y_tr, C, partition_method, partition_alpha,
                                 seed, data_dir)
        except FileNotFoundError:
            synth = True
            n = sc(4000)
            x, y = synthetic.synthetic_classification_images(
                n, (64, 64), 3, 1000, seed=seed)
            n_te = n // 5
            x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
            idx_map = _partition(y_tr, C, "homo", partition_alpha, seed)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, 1000,
                     max_batches_per_client, None, seed, synthetic=synth)

    if dataset in ("gld23k", "gld160k"):
        # Google Landmarks federated split (Landmarks/data_loader.py:1-285):
        # natural per-user partition from the CSV mapping.
        n_classes = spec.class_num
        try:
            split_csv = ("mini_gld_train_split.csv" if dataset == "gld23k"
                         else "federated_train.csv")
            x_tr, y_tr, idx_map = readers.read_landmarks_csv(
                data_dir, split_csv)
            test_csv = ("mini_gld_test.csv" if dataset == "gld23k"
                        else "test.csv")
            xt, yt, _ = readers.read_landmarks_csv(data_dir, test_csv)
            synth = False
        except FileNotFoundError:
            synth = True
            n = sc(23080 if dataset == "gld23k" else 164172)
            x, y = synthetic.synthetic_classification_images(
                n, (64, 64), 3, n_classes, seed=seed)
            n_te = n // 8
            x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
            idx_map = _partition(y_tr, C, "power_law", partition_alpha, seed)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, n_classes,
                     max_batches_per_client, None, seed, synthetic=synth)

    if dataset in _TABULAR_DIMS:
        # UCI SUSY / Room-Occupancy streaming tabular tasks for the
        # decentralized online learners (UCI/data_loader_for_susy_and_ro.py).
        dim = _TABULAR_DIMS[dataset]
        fname = {"susy": "SUSY.csv",
                 "room_occupancy": "datatraining.txt"}[dataset]
        try:
            if dataset == "susy":
                label_col, feat_cols, hdr = 0, None, False
            else:   # datatraining.txt: "id","date",T,H,Light,CO2,HR,Occupancy
                label_col, feat_cols, hdr = -1, [2, 3, 4, 5, 6], True
            x, y = readers.read_csv_tabular(
                os.path.join(data_dir or "", fname), label_col=label_col,
                feature_cols=feat_cols, skip_header=hdr)
            synth = False
        except FileNotFoundError:
            synth = True
            x, y = synthetic.synthetic_tabular(sc(20000), dim, seed=seed)
        n_te = len(y) // 8
        x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
        # standardize with TRAIN statistics only (no test leakage)
        mu, sd = x_tr.mean(axis=0), x_tr.std(axis=0) + 1e-8
        x_tr, xt = (x_tr - mu) / sd, (xt - mu) / sd
        idx_map = _partition(y_tr, C, "homo", partition_alpha, seed)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, 2,
                     max_batches_per_client, None, seed, synthetic=synth)

    if dataset == "pascal_voc":
        # fedseg's segmentation data: VOC-layout folders when present,
        # synthetic threshold-mask task otherwise.  Labels are [H, W] int
        # maps with void=255 (the trainer's train_ignore_id).  The
        # fallback triggers ONLY on a missing SegmentationClass dir; a
        # present-but-broken dataset (e.g. a label png without its jpg)
        # raises instead of silently training on synthetic data.
        if os.path.isdir(os.path.join(data_dir or "", "SegmentationClass")):
            x, y = readers.read_voc_pairs(data_dir)
            synth = False
        else:
            x, y = synthetic.synthetic_segmentation(
                sc(512), (32, 32), spec.class_num, seed=seed)
            synth = True
        n_te = max(C, len(y) // 8)
        x_tr, y_tr, xt, yt = x[n_te:], y[n_te:], x[:n_te], y[:n_te]
        # partition on the images' DOMINANT class (LDA needs one label
        # per sample; reference fedseg partitions image lists the same way)
        dom = np.array([np.bincount(
            m[m != 255].ravel(), minlength=spec.class_num).argmax()
            if (m != 255).any() else 0 for m in y_tr])
        idx_map = _partition(dom, C, partition_method, partition_alpha,
                             seed, data_dir)
        return _make(x_tr, y_tr, xt, yt, idx_map, bs, spec.class_num,
                     max_batches_per_client, None, seed, synthetic=synth)

    if dataset.startswith("synthetic_"):
        ab = dataset.split("_")[1:]
        alpha, beta = float(ab[0]), float(ab[1])
        # real path: the reference SHIPS these datasets as pre-generated
        # LEAF JSONs (data/synthetic_1_1/{train/mytrain,test/mytest}.json;
        # fedml_api/data_preprocessing/synthetic_1_1/data_loader.py:14-15).
        # Only probed when data_dir is EXPLICIT: unlike the named-dataset
        # loaders, synthetic_* encodes generation parameters in its name,
        # and stray ./train ./test dirs must not shadow the generator.
        if data_dir:
            try:
                u_tr, ud_tr = readers.read_leaf_dir(
                    os.path.join(data_dir, "train"))
                u_te, ud_te = readers.read_leaf_dir(
                    os.path.join(data_dir, "test"))
                x_tr, y_tr, tr_map = readers.leaf_to_arrays(u_tr[:C], ud_tr)
                xt, yt, _ = readers.leaf_to_arrays(u_te[:C], ud_te)
                return _make(x_tr, y_tr, xt, yt, tr_map, bs, 10,
                             max_batches_per_client, None, seed,
                             synthetic=False)
            except FileNotFoundError:
                pass
        x, y, idx_map = synthetic.synthetic_fedprox(alpha, beta, C, seed=seed)
        n = len(y)
        # 90/10 train/test split inside each client, reference-style
        tr_map, te_idx = {}, []
        for k, idx in idx_map.items():
            cut = max(1, int(0.9 * len(idx)))
            tr_map[k] = idx[:cut]; te_idx.append(idx[cut:])
        te_idx = np.concatenate(te_idx)
        return _make(x, y, x[te_idx], y[te_idx], tr_map, bs, 10,
                     max_batches_per_client, None, seed)

    raise ValueError(f"unknown dataset {dataset!r}")


# ---------------------------------------------------------------------------
# Vertical-FL datasets: party-split features over shared samples
# ---------------------------------------------------------------------------

# (total feature dim, default per-party split) — reference NUS_WIDE
# (634 image features + 1000 text tags, nus_wide_dataset.py:1-260) and
# lending_club (lending_club_loan/, guest/host feature columns)
_VFL_SPECS = {
    "nus_wide": (1634, (634, 1000)),
    "lending_club": (60, (30, 30)),
}


def load_vfl_data(dataset: str, data_dir: Optional[str] = None,
                  n_samples: int = 4000, seed: int = 0):
    """Load a vertical-FL task: returns (x [n, D], y [n] binary,
    feature_splits) where feature_splits[p] is party p's slice width
    (guest = party 0).  Real CSVs when present, synthetic stand-in
    otherwise — the VFLEngine consumes either identically."""
    if dataset not in _VFL_SPECS:
        raise ValueError(f"unknown VFL dataset {dataset!r}; "
                         f"known: {sorted(_VFL_SPECS)}")
    dim, splits = _VFL_SPECS[dataset]
    try:
        fname = {"nus_wide": "nus_wide_features.csv",
                 "lending_club": "loan_processed.csv"}[dataset]
        x, y = readers.read_csv_tabular(
            os.path.join(data_dir or "", fname), label_col=-1)
        y = (y > 0).astype(np.int64)
    except FileNotFoundError:
        x, y = synthetic.synthetic_tabular(n_samples, dim, seed=seed)
    mu, sd = x.mean(axis=0), x.std(axis=0) + 1e-8
    x = (x - mu) / sd
    return x.astype(np.float32), y, list(splits)
