"""Federated dataset container — the TPU-native replacement for the
reference's dict-of-DataLoaders 8-tuple contract
(e.g. cifar10/data_loader.py:235-269).

Instead of per-client torch DataLoaders pulled by Python loops, all client
shards live as ONE stacked, padded array set

    x    [C, B, bs, ...]    C = clients, B = batches/client, bs = batch size
    y    [C, B, bs, ...]
    mask [C, B, bs]         1.0 for real samples, 0.0 for padding

resident in HBM (or sharded over a mesh axis).  A round's cohort is a
`jnp.take` along axis 0 — so client selection, local training, and
aggregation all happen device-side with static shapes (SURVEY.md §7 hard
part #1: unequal client sizes become padding+masking, not control flow).

`as_8tuple()` provides the reference-shaped view for API parity.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def pad_to_batches(x: np.ndarray, y: np.ndarray, batch_size: int,
                   n_batches: Optional[int] = None):
    """Pad (x, y) up to n_batches full batches; returns (x, y, mask) with
    leading shape [B, bs]."""
    n = x.shape[0]
    need = n_batches if n_batches is not None else max(1, -(-n // batch_size))
    total = need * batch_size
    pad = total - n
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    if pad > 0:
        x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        y = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
    rs = lambda a: a.reshape((need, batch_size) + a.shape[1:])
    return rs(x), rs(y), mask.reshape(need, batch_size)


def build_client_shards(x: np.ndarray, y: np.ndarray,
                        net_dataidx_map: dict[int, np.ndarray],
                        batch_size: int,
                        max_batches: Optional[int] = None,
                        shuffle_seed: Optional[int] = None) -> dict[str, np.ndarray]:
    """Stack every client's padded shard into one array set [C, B, bs, ...].

    B = max batches over clients (optionally capped at `max_batches`; clients
    with more data are truncated to B*bs samples — cap consciously).

    Vectorized as one [C, B*bs] index matrix + one gather: per-client
    Python assembly costs ~7.5 ms/client, which at reference cross-device
    scale (342,477 stackoverflow clients, benchmark/README.md:57) is ~40
    minutes; this path builds the same stack in seconds.  The per-client
    rng draws happen in the same order as the historical loop, so the
    output is bit-identical for any shuffle_seed.
    """
    n_clients = len(net_dataidx_map)
    sizes = np.fromiter((len(net_dataidx_map[i]) for i in range(n_clients)),
                        np.int64, n_clients)
    B = max(1, int(np.max(-(-sizes // batch_size))))
    if max_batches is not None:
        B = min(B, max_batches)
    cap = B * batch_size
    keep = np.minimum(sizes, cap)
    rng = (np.random.RandomState(shuffle_seed)
           if shuffle_seed is not None else None)
    idx = np.zeros((n_clients, cap), np.int64)
    for i in range(n_clients):          # cheap: index bookkeeping only
        ci = np.asarray(net_dataidx_map[i])
        if rng is not None:
            ci = ci[rng.permutation(len(ci))]
        idx[i, :keep[i]] = ci[:keep[i]]
    mask = (np.arange(cap)[None, :] < keep[:, None])
    gx = x[idx.reshape(-1)].reshape((n_clients, cap) + x.shape[1:])
    gy = y[idx.reshape(-1)].reshape((n_clients, cap) + y.shape[1:])
    # padding rows pointed at sample 0 for the gather; zero them to match
    # pad_to_batches' zero padding
    gx[~mask] = 0
    gy[~mask] = 0
    rs = lambda a: a.reshape((n_clients, B, batch_size) + a.shape[2:])
    return {"x": rs(gx), "y": rs(gy),
            "mask": rs(mask.astype(np.float32))}


def build_eval_shard(x: np.ndarray, y: np.ndarray, batch_size: int) -> dict[str, np.ndarray]:
    """Single padded shard [B, bs, ...] for global eval."""
    cx, cy, cm = pad_to_batches(x, y, batch_size)
    return {"x": cx, "y": cy, "mask": cm}


@dataclasses.dataclass
class FederatedData:
    """All state the algorithms need; mirrors the reference 8-tuple."""
    train_data_num: int
    test_data_num: int
    train_global: dict[str, np.ndarray]      # padded eval shard
    test_global: dict[str, np.ndarray]       # padded eval shard
    client_shards: dict[str, np.ndarray]     # stacked [C, B, bs, ...]
    client_num_samples: np.ndarray           # [C] true sample counts
    test_client_shards: Optional[dict[str, np.ndarray]]  # [C, Bt, bs, ...] or None
    class_num: int
    synthetic: bool = False   # True when a stand-in replaced missing files
    # set when client_shards["x"] is stored uint8 (data/quant.py): the
    # affine spec (x_f32 = u*scale + offset) the mesh engines fuse into
    # the jitted round program as its first op.  Eval shards
    # (train_global/test_global/test_client_shards) always stay float.
    x_dequant: Optional[object] = None
    _device_cache: dict = dataclasses.field(default_factory=dict, repr=False)

    @property
    def client_num(self) -> int:
        return int(self.client_shards["mask"].shape[0])

    def device_shards(self) -> tuple[dict, jnp.ndarray]:
        """Client shards + weights as device arrays, uploaded ONCE and cached
        (HBM-resident; per-round cohort gather is then device-side)."""
        if "shards" not in self._device_cache:
            self._device_cache["shards"] = {
                k: jnp.asarray(v) for k, v in self.client_shards.items()}
            self._device_cache["weights"] = jnp.asarray(self.client_num_samples)
        return self._device_cache["shards"], self._device_cache["weights"]

    def cohort(self, client_indices: np.ndarray) -> tuple[dict, jnp.ndarray]:
        """Gather a round's cohort: ({x,y,mask} [K, B, bs, ...], weights [K]).
        A `jnp.take` on the cached device-resident stack — no host↔device
        traffic beyond the index vector."""
        shards, weights = self.device_shards()
        idx = jnp.asarray(client_indices)
        return ({k: jnp.take(v, idx, axis=0) for k, v in shards.items()},
                jnp.take(weights, idx))

    def as_8tuple(self):
        """Reference-shaped view (train_data_num, test_data_num, train_global,
        test_global, local_num_dict, train_local_dict, test_local_dict,
        class_num) — cifar10/data_loader.py:235-269."""
        C = self.client_num
        local_num = {i: int(self.client_num_samples[i]) for i in range(C)}
        train_local = {i: jax.tree.map(lambda v, i=i: v[i], self.client_shards)
                       for i in range(C)}
        if self.test_client_shards is not None:
            test_local = {i: jax.tree.map(lambda v, i=i: v[i], self.test_client_shards)
                          for i in range(C)}
        else:
            test_local = {i: None for i in range(C)}
        return (self.train_data_num, self.test_data_num, self.train_global,
                self.test_global, local_num, train_local, test_local,
                self.class_num)
