"""Text preprocessing for the NLP federated benchmarks.

Parity targets (reference fedml_api/data_preprocessing/*):
  - shakespeare (LEAF JSON):   language_utils.py:1-55, shakespeare/
    data_loader.py:54-61 — 80-char windows -> next-char, char ids via
    ALL_LETTERS.find, VOCAB_SIZE = 86 + 4.
  - fed_shakespeare (TFF h5):  fed_shakespeare/utils.py:15-82 — snippets
    tokenized as [bos] + chars + [eos], padded to 81-multiples, chunked to
    81, x = seq[:-1], y = seq[1:].
  - stackoverflow_nwp (TFF h5): stackoverflow_nwp/utils.py:56-86 — space
    tokenizer, top-10k word vocab from `stackoverflow.word_count`,
    [bos] + ids (+[eos]) + pad to 21, x/y shifted.
  - stackoverflow_lr (TFF h5): stackoverflow_lr/utils.py:66-131 — mean
    bag-of-words features (10,000-dim) from tokens+title, multi-hot tag
    targets (500-dim) from `stackoverflow.tag_count`.

Everything is vectorized numpy (byte-LUT for chars, dict lookups batched per
client) — the output feeds straight into build_client_shards.
"""
from __future__ import annotations

import json
import os
from typing import Iterable, Optional

import numpy as np

# Public vocabulary from the TFF text-generation tutorial (same constant the
# reference re-uses, language_utils.py:12-14 / fed_shakespeare/utils.py:18-20).
SHAKESPEARE_CHARS = (
    "dhlptx@DHLPTX $(,048cgkoswCGKOSW[_#'/37;?bfjnrvzBFJNRVZ\"&*.26:"
    "\naeimquyAEIMQUY]!%)-159\r"
)
SHAKESPEARE_VOCAB_SIZE = len(SHAKESPEARE_CHARS) + 4      # 90: +pad/bos/eos/oov
SHAKESPEARE_SEQ_LEN = 80

PAD, BOS, EOS = "<pad>", "<bos>", "<eos>"


def _char_lut(offset: int, oov_id: int) -> np.ndarray:
    """256-entry byte -> id lookup table. ids are `offset + position` in
    SHAKESPEARE_CHARS; any byte outside the vocabulary maps to oov_id."""
    lut = np.full(256, oov_id, np.int32)
    for i, ch in enumerate(SHAKESPEARE_CHARS):
        lut[ord(ch)] = offset + i
    return lut


# LEAF convention: ids are raw ALL_LETTERS positions (0..85). The reference
# leaves OOV at find()'s -1 (language_utils.py:37); we use the first reserved
# slot (86) so ids index cleanly into the 90-wide embedding.
_LEAF_LUT = _char_lut(offset=0, oov_id=len(SHAKESPEARE_CHARS))
# TFF convention (fed_shakespeare/utils.py:23-50): pad=0, chars 1..86,
# bos=87, eos=88, oov=89.
_TFF_PAD = 0
_TFF_BOS = len(SHAKESPEARE_CHARS) + 1                    # 87
_TFF_EOS = len(SHAKESPEARE_CHARS) + 2                    # 88
_TFF_OOV = len(SHAKESPEARE_CHARS) + 3                    # 89
_TFF_LUT = _char_lut(offset=1, oov_id=_TFF_OOV)


def chars_to_ids(strings: Iterable[str], lut: np.ndarray = _LEAF_LUT,
                 width: Optional[int] = None) -> np.ndarray:
    """Vectorized char -> id for equal-length strings; returns [n, width].

    Non-latin-1 characters are OOV by construction (they can't be a vocab
    byte), encoded with errors="replace" so the LUT sees a valid byte.
    """
    rows = [np.frombuffer(s.encode("latin-1", errors="replace"), np.uint8)
            for s in strings]
    if width is None:
        width = max((len(r) for r in rows), default=0)
    # byte 0 is never in the vocabulary, so short strings pad to OOV ids
    out = np.zeros((len(rows), width), np.uint8)
    for i, r in enumerate(rows):
        r = r[:width]
        out[i, :len(r)] = r
    return lut[out]


def leaf_shakespeare_to_arrays(users: list[str], user_data: dict):
    """LEAF shakespeare: x = 80-char strings, y = single next chars
    (shakespeare/data_loader.py:54-61).  Returns (x [n,80] i32, y [n] i64,
    idx_map) with the LEAF char-id convention."""
    xs, ys, idx_map, off = [], [], {}, 0
    for i, u in enumerate(users):
        ux = chars_to_ids(user_data[u]["x"], _LEAF_LUT, SHAKESPEARE_SEQ_LEN)
        uy = chars_to_ids([c[0] for c in user_data[u]["y"]], _LEAF_LUT, 1)[:, 0]
        xs.append(ux.astype(np.int32))
        ys.append(uy.astype(np.int64))
        idx_map[i] = np.arange(off, off + len(uy))
        off += len(uy)
    return np.concatenate(xs), np.concatenate(ys), idx_map


def tff_snippets_to_sequences(snippets: Iterable[str],
                              seq_len: int = SHAKESPEARE_SEQ_LEN):
    """fed_shakespeare preprocess (utils.py:53-82): each snippet becomes
    [bos] + char-ids + [eos], padded to a multiple of (seq_len+1), chunked;
    returns (x [n,seq_len] i32, y [n,seq_len] i64)."""
    chunks = []
    for s in snippets:
        ids = _TFF_LUT[np.frombuffer(
            s.encode("latin-1", errors="replace"), np.uint8)]
        tok = np.concatenate([[_TFF_BOS], ids, [_TFF_EOS]])
        pad = (-len(tok)) % (seq_len + 1)
        if pad:
            tok = np.concatenate([tok, np.full(pad, _TFF_PAD)])
        chunks.append(tok.reshape(-1, seq_len + 1))
    if not chunks:
        return (np.zeros((0, seq_len), np.int32),
                np.zeros((0, seq_len), np.int64))
    seq = np.concatenate(chunks)
    return seq[:, :-1].astype(np.int32), seq[:, 1:].astype(np.int64)


# ---------------------------------------------------------------------------
# StackOverflow word vocabulary
# ---------------------------------------------------------------------------

def read_word_count_vocab(path: str, vocab_size: int = 10000) -> list[str]:
    """Top-N words from `stackoverflow.word_count` ("word count" per line,
    already frequency-sorted — stackoverflow_nwp/utils.py:27-31)."""
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    words = []
    with open(path) as f:
        for line in f:
            words.append(line.split()[0])
            if len(words) >= vocab_size:
                break
    return words


def read_tag_count_vocab(path: str, tag_size: int = 500) -> list[str]:
    """Top-N tags from the `stackoverflow.tag_count` JSON dict (insertion-
    ordered by frequency — stackoverflow_lr/utils.py:40-44)."""
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    with open(path) as f:
        return list(json.load(f).keys())[:tag_size]


class WordVocab:
    """NWP word dict: pad=0, words 1..N, bos=N+1, eos=N+2, oov=N+3
    (stackoverflow_nwp/utils.py:34-42 with the single-OOV-bucket default).
    vocab_len = N + 4 matches RNNStackOverflow's 10004."""

    def __init__(self, words: list[str]):
        self.word_to_id = {w: i + 1 for i, w in enumerate(words)}
        self.pad_id = 0
        self.bos_id = len(words) + 1
        self.eos_id = len(words) + 2
        self.oov_id = len(words) + 3
        self.vocab_len = len(words) + 4

    def sentence_to_ids(self, sentence: str, max_seq_len: int = 20) -> np.ndarray:
        """[bos] + ids (+[eos] when short) + pad, to max_seq_len+1 tokens."""
        toks = [self.word_to_id.get(w, self.oov_id)
                for w in sentence.split(" ")[:max_seq_len]]
        if len(toks) < max_seq_len:
            toks.append(self.eos_id)
        toks = [self.bos_id] + toks
        toks += [self.pad_id] * (max_seq_len + 1 - len(toks))
        return np.asarray(toks[:max_seq_len + 1], np.int32)

    def sentences_to_xy(self, sentences: Iterable[str],
                        max_seq_len: int = 20):
        seqs = np.stack([self.sentence_to_ids(s, max_seq_len)
                         for s in sentences])
        return seqs[:, :-1].astype(np.int32), seqs[:, 1:].astype(np.int64)


class BagOfWordsVocab:
    """LR featureizer: mean bag-of-words over the top-10k vocab (OOV column
    dropped — stackoverflow_lr/utils.py:78-85, 107-124)."""

    def __init__(self, words: list[str]):
        self.word_to_id = {w: i for i, w in enumerate(words)}
        self.dim = len(words)

    def sentences_to_features(self, sentences: Iterable[str]) -> np.ndarray:
        out = []
        for s in sentences:
            toks = s.split(" ")
            v = np.zeros(self.dim, np.float32)
            for t in toks:
                i = self.word_to_id.get(t)
                if i is not None:
                    v[i] += 1.0
            out.append(v / max(len(toks), 1))
        return np.stack(out) if out else np.zeros((0, self.dim), np.float32)


class TagVocab:
    """Multi-hot tag targets over the top-500 tags; '|'-separated raw tags,
    OOV column dropped (stackoverflow_lr/utils.py:88-104)."""

    def __init__(self, tags: list[str]):
        self.tag_to_id = {t: i for i, t in enumerate(tags)}
        self.dim = len(tags)

    def tags_to_targets(self, raw_tags: Iterable[str]) -> np.ndarray:
        out = []
        for raw in raw_tags:
            v = np.zeros(self.dim, np.float32)
            for t in raw.split("|"):
                i = self.tag_to_id.get(t)
                if i is not None:
                    v[i] = 1.0
            out.append(v)
        return np.stack(out) if out else np.zeros((0, self.dim), np.float32)


def _decode(arr) -> list[str]:
    """h5py string datasets arrive as bytes; tolerate str too."""
    return [a.decode("utf-8", errors="replace") if isinstance(a, bytes)
            else str(a) for a in np.asarray(arr).ravel()]


def stackoverflow_nwp_arrays(client_data: dict, vocab: WordVocab,
                             max_seq_len: int = 20, max_clients=None):
    """{cid: {"tokens": [...]}} (read_tff_h5 output) -> stacked NWP arrays.
    Returns (x [n,T] i32, y [n,T] i64, idx_map)."""
    xs, ys, idx_map, off = [], [], {}, 0
    for i, cid in enumerate(sorted(client_data)[:max_clients]):
        sents = _decode(client_data[cid]["tokens"])
        x, y = vocab.sentences_to_xy(sents, max_seq_len)
        xs.append(x); ys.append(y)
        idx_map[i] = np.arange(off, off + len(y)); off += len(y)
    return np.concatenate(xs), np.concatenate(ys), idx_map


def stackoverflow_lr_arrays(client_data: dict, words: BagOfWordsVocab,
                            tags: TagVocab, max_clients=None):
    """{cid: {"tokens","title","tags"}} -> (x [n,10000] f32 bag-of-words over
    tokens+title, y [n,500] f32 multi-hot, idx_map). Reference joins tokens
    and title with a space (stackoverflow_lr/dataset.py:57-60)."""
    xs, ys, idx_map, off = [], [], {}, 0
    for i, cid in enumerate(sorted(client_data)[:max_clients]):
        d = client_data[cid]
        sents = [" ".join(p) for p in zip(_decode(d["tokens"]),
                                          _decode(d["title"]))]
        x = words.sentences_to_features(sents)
        y = tags.tags_to_targets(_decode(d["tags"]))
        xs.append(x); ys.append(y)
        idx_map[i] = np.arange(off, off + len(y)); off += len(y)
    return np.concatenate(xs), np.concatenate(ys), idx_map
