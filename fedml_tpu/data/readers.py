"""On-disk format readers for the real federated datasets.

Parity with reference fedml_api/data_preprocessing/*:
  - LEAF JSON  (MNIST/data_loader.py:9-49, shakespeare): dirs of
    ``{"users": [...], "user_data": {uid: {"x": ..., "y": ...}}}``
  - TFF HDF5   (FederatedEMNIST, fed_cifar100, fed_shakespeare,
    stackoverflow_*): ``examples/<client_id>/<feature>`` groups
  - CIFAR python pickles (cifar10/100); CINIC-10 image folders
    (read_image_folder, requires PIL only when files are present).

All readers return host numpy; partitioning metadata comes from the file's
natural per-user split. Missing files raise FileNotFoundError — the loader
layer catches it and substitutes the synthetic stand-in.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Callable, Optional

import numpy as np


def read_leaf_dir(data_dir: str) -> tuple[list[str], dict]:
    """Read every *.json in a LEAF split dir; returns (users, user_data)."""
    if not os.path.isdir(data_dir):
        raise FileNotFoundError(data_dir)
    users, user_data = [], {}
    files = sorted(f for f in os.listdir(data_dir) if f.endswith(".json"))
    if not files:
        raise FileNotFoundError(f"no LEAF json in {data_dir}")
    for f in files:
        with open(os.path.join(data_dir, f)) as fh:
            blob = json.load(fh)
        users.extend(blob["users"])
        user_data.update(blob["user_data"])
    return users, user_data


def leaf_to_arrays(users: list[str], user_data: dict,
                   xform: Optional[Callable] = None):
    """Flatten LEAF per-user data to (x, y, idx_map)."""
    xs, ys, idx_map, off = [], [], {}, 0
    for i, u in enumerate(users):
        ux = np.asarray(user_data[u]["x"], np.float32)
        uy = np.asarray(user_data[u]["y"], np.int64)
        if xform is not None:
            ux, uy = xform(ux, uy)
        xs.append(ux); ys.append(uy)
        idx_map[i] = np.arange(off, off + len(uy))
        off += len(uy)
    return np.concatenate(xs), np.concatenate(ys), idx_map


def read_tff_h5(path: str, feature_keys: tuple[str, ...]):
    """Read a TFF-style h5: returns {client_id: {key: np.ndarray}}."""
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    import h5py  # after the existence check: absent file must fall back
                 # to synthetic even when h5py isn't installed
    out = {}
    with h5py.File(path, "r") as f:
        ex = f["examples"]
        for cid in ex.keys():
            out[cid] = {k: np.asarray(ex[cid][k]) for k in feature_keys}
    return out


def read_cifar_pickles(data_dir: str, cifar100: bool = False):
    """CIFAR-10/100 python-version pickles -> (x_train, y_train, x_test,
    y_test) in NHWC float32 [0,1]."""
    if cifar100:
        tf, sf, lk = ["train"], "test", b"fine_labels"
    else:
        tf = [f"data_batch_{i}" for i in range(1, 6)]
        sf, lk = "test_batch", b"labels"
    def _load(name):
        p = os.path.join(data_dir, name)
        if not os.path.isfile(p):
            raise FileNotFoundError(p)
        with open(p, "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x.astype(np.float32) / 255.0, np.asarray(d[lk], np.int64)
    parts = [_load(n) for n in tf]
    x_tr = np.concatenate([p[0] for p in parts])
    y_tr = np.concatenate([p[1] for p in parts])
    x_te, y_te = _load(sf)
    return x_tr, y_tr, x_te, y_te


def normalize_image(x: np.ndarray, mean, std) -> np.ndarray:
    return (x - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)


def read_image_folder(data_dir: str, splits=("train", "test"),
                      max_per_class: Optional[int] = None):
    """CINIC-10-style image folders: <split>/<class_name>/*.png.
    Returns (x_train, y_train, x_test, y_test) NHWC float32 in [0,1]."""
    if not os.path.isdir(os.path.join(data_dir, splits[0])):
        raise FileNotFoundError(os.path.join(data_dir, splits[0]))
    from PIL import Image  # after existence check (same fallback contract
                           # as read_tff_h5)
    out = []
    for split in splits:
        root = os.path.join(data_dir, split)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        xs, ys = [], []
        for ci, cname in enumerate(classes):
            files = sorted(os.listdir(os.path.join(root, cname)))
            if max_per_class:
                files = files[:max_per_class]
            for f in files:
                with Image.open(os.path.join(root, cname, f)) as im:
                    xs.append(np.asarray(im.convert("RGB"), np.float32) / 255.0)
                ys.append(ci)
        out += [np.stack(xs), np.asarray(ys, np.int64)]
    return tuple(out)


def read_voc_pairs(data_dir: str, hw: int = 32,
                   max_images: Optional[int] = None):
    """Pascal-VOC-layout segmentation pairs: JPEGImages/<id>.jpg +
    SegmentationClass/<id>.png (palette PNG whose pixel VALUES are class
    ids, 255 = void).  Returns (x [N,hw,hw,3] f32, y [N,hw,hw] i64) with
    nearest-neighbor label resize (never interpolate class ids)."""
    img_dir = os.path.join(data_dir, "JPEGImages")
    lbl_dir = os.path.join(data_dir, "SegmentationClass")
    if not os.path.isdir(lbl_dir):
        raise FileNotFoundError(lbl_dir)
    from PIL import Image
    ids = sorted(os.path.splitext(f)[0] for f in os.listdir(lbl_dir)
                 if f.endswith(".png"))
    if not ids:
        raise FileNotFoundError(f"no label pngs in {lbl_dir}")
    if max_images:
        ids = ids[:max_images]
    xs, ys = [], []
    for i in ids:
        jpg = os.path.join(img_dir, i + ".jpg")
        if not os.path.isfile(jpg):
            jpg = os.path.join(img_dir, i + ".png")   # tolerate png images
        with Image.open(jpg) as im:
            im = im.convert("RGB").resize((hw, hw), Image.BILINEAR)
            xs.append(np.asarray(im, np.float32) / 255.0)
        with Image.open(os.path.join(lbl_dir, i + ".png")) as lm:
            lm = lm.resize((hw, hw), Image.NEAREST)
            ys.append(np.asarray(lm, np.int64))
    return np.stack(xs), np.stack(ys)


def read_landmarks_csv(data_dir: str, split_csv: str, image_dir: str = "images",
                       hw: int = 64):
    """Google Landmarks federated CSV split (reference
    Landmarks/data_loader.py:1-285): rows of (user_id, image_id, class).
    Returns (x, y, net_dataidx_map) with images resized to hw×hw."""
    import csv
    path = os.path.join(data_dir, split_csv)
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    from PIL import Image
    rows = []
    with open(path) as f:
        for row in csv.DictReader(f):
            rows.append((row["user_id"], row["image_id"], int(row["class"])))
    xs, ys, idx_map = [], [], {}
    users = sorted({u for u, _, _ in rows})
    uid_of = {u: i for i, u in enumerate(users)}
    for u, image_id, cls in rows:
        p = os.path.join(data_dir, image_dir, f"{image_id}.jpg")
        try:
            with Image.open(p) as im:
                im = im.convert("RGB").resize((hw, hw))
                xs.append(np.asarray(im, np.float32) / 255.0)
        except FileNotFoundError as e:
            # the split CSV exists, so the dataset IS present — a missing
            # image is a partial download, not "fall back to synthetic"
            raise RuntimeError(
                f"landmarks dataset is partially downloaded: {p}") from e
        idx_map.setdefault(uid_of[u], []).append(len(ys))
        ys.append(cls)
    return (np.stack(xs), np.asarray(ys, np.int64),
            {k: np.asarray(v) for k, v in idx_map.items()})


def read_net_dataidx_map(path: str) -> dict[int, "np.ndarray"]:
    """Precomputed non-IID partition map ('hetero-fix'), reference
    cifar10/data_loader.py:32-43: a pretty-printed python-dict txt of
    {client: [idx, ...]}."""
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    out, key = {}, None
    with open(path) as f:
        for line in f:
            if not line.strip() or line[0] in "{}]":
                continue
            head = line.split(":")
            if head[-1].strip() == "[":
                key = int(head[0])
                out[key] = []
            else:
                out[key].extend(int(t.strip().rstrip("]"))
                                for t in line.split(",") if t.strip("] \n"))
    return {k: np.asarray(v, np.int64) for k, v in out.items()}


def read_data_distribution(path: str) -> dict[int, dict[int, int]]:
    """Companion per-client class-count file (cifar10/data_loader.py:15-29)."""
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    out, key = {}, None
    with open(path) as f:
        for line in f:
            if not line.strip() or line[0] in "{}":
                continue
            head, tail = line.split(":", 1)
            if tail.strip() == "{":
                key = int(head)
                out[key] = {}
            else:
                out[key][int(head)] = int(tail.strip().rstrip(","))
    return out


def read_imagenet_h5(path: str):
    """ImageNet hdf5 pack (reference ImageNet/datasets_hdf5.py:13-40):
    datasets train_img/train_labels/val_img/val_labels.  Returns
    (x_tr, y_tr, x_te, y_te) NHWC float32 in [0,1]."""
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    import h5py

    def _img(ds):
        # decide /255 from the STORED dtype (O(1)) and scale during the
        # float32 conversion — not a full-array max() after a 4x f32 blow-up
        arr = np.asarray(ds)
        if np.issubdtype(arr.dtype, np.integer):
            return arr.astype(np.float32) / 255.0
        return arr.astype(np.float32)

    with h5py.File(path, "r") as f:
        x_tr = _img(f["train_img"])
        y_tr = np.asarray(f["train_labels"], np.int64)
        x_te = _img(f["val_img"])
        y_te = np.asarray(f["val_labels"], np.int64)
    return x_tr, y_tr, x_te, y_te


def read_csv_tabular(path: str, label_col: int, feature_cols=None,
                     skip_header: bool = True, max_rows: Optional[int] = None):
    """Plain-CSV tabular reader (UCI SUSY / Room-Occupancy / lending-club,
    reference UCI/data_loader_for_susy_and_ro.py:1-143).  Returns
    (x float32 [n,d], y int64 [n])."""
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    data = np.genfromtxt(path, delimiter=",",
                         skip_header=1 if skip_header else 0,
                         max_rows=max_rows)
    y = data[:, label_col].astype(np.int64)
    if feature_cols is None:
        feature_cols = [c for c in range(data.shape[1]) if c != label_col]
    x = data[:, feature_cols].astype(np.float32)
    x = np.nan_to_num(x)
    return x, y
