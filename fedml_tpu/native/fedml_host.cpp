// fedml_host — native host-side message transport for fedml_tpu.
//
// The reference's native transport work all lives in external libraries
// (mpi4py→libmpi, grpcio→gRPC C-core, torch.distributed.rpc→TensorPipe;
// SURVEY.md §2.0 — no in-tree native code).  This library is the
// TPU-framework equivalent: a length-prefixed TCP message fabric for the
// control plane (cross-silo/edge participants outside the device mesh),
// bound into Python with ctypes (comm/native_tcp.py).  The dense data
// plane stays on XLA collectives — this carries Messages, not tensors.
//
// Wire format (identical to the pure-Python TcpBackend, the behavioral
// spec): 8-byte little-endian payload length ‖ payload bytes.
//
// C ABI (ctypes-friendly, no exceptions cross the boundary):
//   fh_server_create(port)            -> handle (listen + accept loop)
//   fh_recv(h, &buf, &len, timeout)   -> 0 ok / -1 timeout / -2 closed
//   fh_buf_free(buf)
//   fh_connect(host, port)            -> conn handle (nullptr on failure)
//   fh_send(conn, buf, len)           -> 0 ok / -1 error
//   fh_conn_close(conn), fh_server_close(h)

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

bool read_exact(int fd, uint8_t* dst, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r = ::recv(fd, dst + off, n - off, 0);
    if (r <= 0) return false;
    off += static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const uint8_t* src, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t w = ::send(fd, src + off, n - off, MSG_NOSIGNAL);
    if (w <= 0) return false;
    off += static_cast<size_t>(w);
  }
  return true;
}

struct Server {
  int listen_fd = -1;
  std::atomic<bool> alive{true};
  std::thread accept_thread;
  std::vector<std::thread> recv_threads;
  std::vector<int> conn_fds;       // for shutdown-on-close (unblocks recv)
  std::mutex conn_mu;              // guards recv_threads/conn_fds growth
  std::mutex mu;                   // guards inbox
  std::condition_variable cv;
  std::deque<std::vector<uint8_t>> inbox;

  void recv_loop(int fd) {
    for (;;) {
      uint8_t hdr[8];
      if (!alive.load() || !read_exact(fd, hdr, 8)) break;
      uint64_t len = 0;
      std::memcpy(&len, hdr, 8);   // little-endian hosts only (x86/arm)
      if (len > (1ull << 30)) break;   // 1 GiB cap (matches the reference's
                                       // gRPC max-message, §2.1) — a corrupt
                                       // header must not OOM the process
      std::vector<uint8_t> payload;
      try {
        payload.resize(len);
      } catch (const std::bad_alloc&) {
        break;                         // drop the connection, keep serving
      }
      if (!read_exact(fd, payload.data(), len)) break;
      {
        std::lock_guard<std::mutex> g(mu);
        inbox.emplace_back(std::move(payload));
      }
      cv.notify_one();
    }
    {
      // deregister before close so a later fh_server_close cannot
      // shutdown() a kernel-reused fd belonging to another socket
      std::lock_guard<std::mutex> g(conn_mu);
      for (auto it = conn_fds.begin(); it != conn_fds.end(); ++it) {
        if (*it == fd) { conn_fds.erase(it); break; }
      }
    }
    ::close(fd);
  }

  void accept_loop() {
    while (alive.load()) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (!alive.load()) return;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(conn_mu);
      conn_fds.push_back(fd);
      recv_threads.emplace_back([this, fd] { recv_loop(fd); });
    }
  }
};

struct Conn {
  int fd = -1;
  std::mutex mu;                   // serialize frames on one connection
};

}  // namespace

extern "C" {

void* fh_server_create(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  auto* s = new Server();
  s->listen_fd = fd;
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

// 0 = ok (buf/len set, caller frees via fh_buf_free); -1 = timeout; -2 closed
int fh_recv(void* handle, uint8_t** out, long* out_len, int timeout_ms) {
  auto* s = static_cast<Server*>(handle);
  std::unique_lock<std::mutex> lk(s->mu);
  auto ready = [&] { return !s->inbox.empty() || !s->alive.load(); };
  if (timeout_ms < 0) {
    s->cv.wait(lk, ready);
  } else if (!s->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                             ready)) {
    return -1;
  }
  if (s->inbox.empty()) return -2;   // woken by shutdown
  std::vector<uint8_t> msg = std::move(s->inbox.front());
  s->inbox.pop_front();
  lk.unlock();
  auto* buf = static_cast<uint8_t*>(::malloc(msg.size()));
  std::memcpy(buf, msg.data(), msg.size());
  *out = buf;
  *out_len = static_cast<long>(msg.size());
  return 0;
}

void fh_buf_free(uint8_t* buf) { ::free(buf); }

// non-blocking connect with timeout (the pure-Python spec used
// create_connection(timeout=30); kernel-default connect can block minutes)
void* fh_connect_timeout(const char* host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  std::snprintf(portstr, sizeof(portstr), "%d", port);
  if (::getaddrinfo(host, portstr, &hints, &res) != 0 || res == nullptr)
    return nullptr;
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) {
    ::freeaddrinfo(res);
    return nullptr;
  }
  int flags = ::fcntl(fd, F_GETFL, 0);
  ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = ::connect(fd, res->ai_addr, res->ai_addrlen);
  ::freeaddrinfo(res);
  if (rc != 0) {
    if (errno != EINPROGRESS) {
      ::close(fd);
      return nullptr;
    }
    pollfd pfd{fd, POLLOUT, 0};
    if (::poll(&pfd, 1, timeout_ms) != 1) {
      ::close(fd);
      return nullptr;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    if (::getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 || err != 0) {
      ::close(fd);
      return nullptr;
    }
  }
  ::fcntl(fd, F_SETFL, flags);   // back to blocking for send()
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Conn();
  c->fd = fd;
  return c;
}

void* fh_connect(const char* host, int port) {
  return fh_connect_timeout(host, port, 30000);
}

int fh_send(void* conn, const uint8_t* data, long len) {
  auto* c = static_cast<Conn*>(conn);
  uint64_t n = static_cast<uint64_t>(len);
  uint8_t hdr[8];
  std::memcpy(hdr, &n, 8);
  std::lock_guard<std::mutex> g(c->mu);
  if (!write_exact(c->fd, hdr, 8)) return -1;
  if (!write_exact(c->fd, data, n)) return -1;
  return 0;
}

void fh_conn_close(void* conn) {
  auto* c = static_cast<Conn*>(conn);
  ::shutdown(c->fd, SHUT_RDWR);
  ::close(c->fd);
  delete c;
}

void fh_server_close(void* handle) {
  auto* s = static_cast<Server*>(handle);
  s->alive.store(false);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  s->cv.notify_all();
  if (s->accept_thread.joinable()) s->accept_thread.join();
  std::vector<std::thread> threads;
  {
    // shutdown live fds under the lock, but join OUTSIDE it — exiting
    // recv_loops take conn_mu to deregister their fd
    std::lock_guard<std::mutex> g(s->conn_mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);  // unblock recv()
    threads.swap(s->recv_threads);
  }
  for (auto& t : threads)
    if (t.joinable()) t.join();
  delete s;
}

}  // extern "C"
