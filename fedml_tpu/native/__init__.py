"""Native (C++) components — build + ctypes loading.

`load_library()` returns the ctypes handle for libfedml_host.so, compiling
it with g++ on first use (cached beside the source).  Returns None when no
toolchain is available; callers fall back to the pure-Python paths.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libfedml_host.so")
_SRC = os.path.join(_DIR, "fedml_host.cpp")
_lock = threading.Lock()
_lib = None
_tried = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.fh_server_create.restype = ctypes.c_void_p
    lib.fh_server_create.argtypes = [ctypes.c_int]
    lib.fh_recv.restype = ctypes.c_int
    lib.fh_recv.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                            ctypes.POINTER(ctypes.c_long), ctypes.c_int]
    lib.fh_buf_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
    lib.fh_connect.restype = ctypes.c_void_p
    lib.fh_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.fh_send.restype = ctypes.c_int
    lib.fh_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long]
    lib.fh_conn_close.argtypes = [ctypes.c_void_p]
    lib.fh_server_close.argtypes = [ctypes.c_void_p]
    return lib


def library_built() -> bool:
    """True iff the .so already exists — cheap check, never compiles."""
    return os.path.exists(_SO)


def load_library():
    """Build (once) and load the native transport; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            try:
                subprocess.run(
                    ["g++", "-O2", "-fPIC", "-std=c++17", "-pthread",
                     "-Wall", "-shared", "-o", _SO, _SRC],
                    check=True, capture_output=True, text=True, timeout=120)
                log.info("built %s", _SO)
            except (OSError, subprocess.SubprocessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                log.warning("native transport build failed: %s", detail)
                return None
        try:
            _lib = _configure(ctypes.CDLL(_SO))
        except OSError as e:
            log.warning("native transport load failed: %s", e)
            _lib = None
        return _lib
