"""Native (C++) components — build + ctypes loading.

`load_library()` returns the ctypes handle for libfedml_host.so, compiling
it with g++ on first use (cached beside the source).  Returns None when no
toolchain is available; callers fall back to the pure-Python paths.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fedml_host.cpp")


def _so_path() -> str:
    # build OUTSIDE the source tree (VERDICT r4: no binaries in the repo),
    # keyed on the SOURCE CONTENT hash — two checkouts at different
    # versions sharing ~/.cache can never load each other's symbols, and
    # an mtime-rolled-back checkout can't pass a staleness check into a
    # newer binary.  Fall back beside the source if the cache dir is
    # unwritable.
    import hashlib
    try:
        with open(_SRC, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        tag = "nosrc"
    cache = os.path.join(os.path.expanduser("~"), ".cache", "fedml_tpu")
    try:
        os.makedirs(cache, exist_ok=True)
    except OSError:
        cache = _DIR       # unwritable cache: build beside the source
    # the content tag rides BOTH paths — staleness is impossible by name
    return os.path.join(cache, f"libfedml_host-{tag}.so")


_SO = _so_path()
_lock = threading.Lock()
_lib = None
_tried = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.fh_server_create.restype = ctypes.c_void_p
    lib.fh_server_create.argtypes = [ctypes.c_int]
    lib.fh_recv.restype = ctypes.c_int
    lib.fh_recv.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                            ctypes.POINTER(ctypes.c_long), ctypes.c_int]
    lib.fh_buf_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
    lib.fh_connect.restype = ctypes.c_void_p
    lib.fh_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.fh_send.restype = ctypes.c_int
    lib.fh_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long]
    lib.fh_conn_close.argtypes = [ctypes.c_void_p]
    lib.fh_server_close.argtypes = [ctypes.c_void_p]
    return lib


def library_built() -> bool:
    """True iff the .so already exists — cheap check, never compiles."""
    return os.path.exists(_SO)


def load_library():
    """Build (once) and load the native transport; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO):
            # the content-hashed name makes staleness impossible; build
            # to a unique temp path + atomic rename so concurrent
            # builders (parallel test sessions) never CDLL a half-
            # written file
            tmp = f"{_SO}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    ["g++", "-O2", "-fPIC", "-std=c++17", "-pthread",
                     "-Wall", "-shared", "-o", tmp, _SRC],
                    check=True, capture_output=True, text=True, timeout=120)
                os.replace(tmp, _SO)
                log.info("built %s", _SO)
            except (OSError, subprocess.SubprocessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                log.warning("native transport build failed: %s", detail)
                return None
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        try:
            _lib = _configure(ctypes.CDLL(_SO))
        except (OSError, AttributeError) as e:
            # AttributeError = symbol mismatch in _configure: fall back
            # to the pure-Python transport rather than crash the caller
            log.warning("native transport load failed: %s", e)
            _lib = None
        return _lib
