"""Pairwise-mask additive secure aggregation (ISSUE 20).

The protocol shape is Bonawitz-style pairwise masking over the
TurboAggregate field primitives (core/mpc.py):

* every ordered client pair (i, j) owns a DH shared secret
  ``shared_key(pk_j, sk_i) == shared_key(pk_i, sk_j)`` which seeds a
  counter-mode PRG stream (numpy Philox: key = the pairwise secret,
  counter HIGH word = the round index, so per-round streams are 2^192
  blocks apart and can never overlap for any row length) of field
  elements;
* client i uploads ``quantize(weight·update) + Σ_{j>i} m_ij −
  Σ_{j<i} m_ij  (mod p)`` — every pair's mask appears once with each
  sign, so the COHORT SUM cancels every mask exactly in the integer
  field and the masked aggregate is BITWISE the plain fixed-point sum
  (the anchor pin, tests/test_secagg.py);
* the sample weight rides as ONE EXTRA masked field word appended to
  the row, so sample-weighted FedAvg survives masking without leaking
  per-client sample counts in the clear;
* dropout recovery: each client's DH secret key is BGW-shared across
  the cohort (threshold = the round's minimum survivor count) and
  escrowed at dispatch.  At the commit barrier the surviving set
  reconstructs a dead client's ``sk`` from ≥ threshold shares, replays
  its pairwise streams, and subtracts the uncancelled masks; a round
  with fewer survivors than the threshold fails BY NAME
  (:class:`SecAggBelowThreshold`) instead of committing garbage.

Trust model (simulation-grade, stated precisely): the keyring draws
every client's secret key from one seeded generator and the server
process holds the escrowed shares directly.  That preserves the
protocol ARITHMETIC — mask cancellation, threshold reconstruction,
below-threshold failure — which is what the tests pin, but not the
cryptographic trust boundary of a real deployment (where each share
would travel encrypted to its holder and only return at the barrier,
and keys would never co-reside).  Multi-process deployments rebuild
the same keyring from ``SecAggConfig.seed`` on every rank.

What masking costs the defense stack: the PR-9 admission screen
(norm z-score, cosine direction) reads PLAINTEXT rows and is therefore
BLINDED through masks — a masked byzantine row is indistinguishable
from an honest one at ingest.  The only per-update enforcement that
survives is the norm bound built into quantization itself:
``mpc.quantize`` raises on any row whose fixed-point magnitude exceeds
the field's signed half-range, so a boosted model-replacement larger
than ±(p−1)/(2·scale) cannot even be encoded.  ``bench.py --mode
secure`` measures exactly this (the masked × byzantine arm).

Arithmetic bounds (ENFORCED at quantization, see mpc.quantize and
client_row): every per-client word and the K-client field SUM must
stay within ±(p−1)//2, i.e. K·max|weight·x|·scale ≤ (p−1)//2.
client_row passes ``max_abs=(p−1)//(2K)`` so each client's slice of
that budget is checked a priori — the sum cannot alias, and the check
cannot be deferred to commit because a wrapped field value is
indistinguishable from a legitimate one post hoc.  With the default
scale 2^16, p = 2^31−1 and a 5-client cohort that is
|weight·x| < 3276.8 per coordinate per client.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Iterable, Optional

import numpy as np

from fedml_tpu.core import mpc

log = logging.getLogger(__name__)

DEFAULT_SCALE = 2 ** 16


class SecAggBelowThreshold(RuntimeError):
    """A secure round's surviving set fell below the share-reconstruction
    threshold: the uncancelled masks of the dead clients cannot be
    rebuilt, so the round fails by name instead of committing a
    mask-polluted aggregate."""


@dataclasses.dataclass
class SecAggConfig:
    """Knobs of the secure-aggregation data plane (CLI --secure_*).

    threshold: minimum SURVIVING clients for a round to commit — also
    the BGW share count needed to reconstruct a dead client's key
    (polynomial degree threshold−1).  0 = majority of the cohort.
    dp_clip/dp_noise: the end-to-end private mode (--secure_agg --dp):
    each client clips its weighted update to dp_clip (the shared
    norm-clip definition) and adds Gaussian noise sigma = dp_noise ·
    dp_clip BEFORE quantize+mask, so the server only ever sees masked
    words of an already-noised update."""
    threshold: int = 0
    scale: int = DEFAULT_SCALE
    prime: int = mpc.DEFAULT_PRIME
    seed: int = 0
    dp_clip: Optional[float] = None
    dp_noise: float = 0.0

    def __post_init__(self):
        if self.threshold < 0:
            raise ValueError(f"threshold must be >= 0, got {self.threshold}")
        if self.scale < 2:
            raise ValueError(f"scale must be >= 2, got {self.scale}")
        if self.dp_noise > 0.0 and self.dp_clip is None:
            raise ValueError("dp_noise needs dp_clip: the noise sigma is "
                             "calibrated to the per-client clip")

    def resolve_threshold(self, n_clients: int) -> int:
        t = self.threshold if self.threshold > 0 else n_clients // 2 + 1
        if not 1 <= t <= n_clients:
            raise ValueError(
                f"secagg threshold {t} outside [1, {n_clients}] for a "
                f"{n_clients}-client cohort")
        return t


def pairwise_mask(pair_key: int, round_idx: int, n_words: int,
                  p: int = mpc.DEFAULT_PRIME) -> np.ndarray:
    """Counter-mode PRG stream of `n_words` field elements for one
    ordered pair at one round: Philox keyed by the DH pairwise secret
    with the round index in the counter's HIGH (most-significant) word.
    Generating a W-word row advances the 256-bit counter ~W/8 blocks
    from the LOW word up, so rounds that start 2^192 blocks apart can
    never overlap for any row length — with the round in the low word,
    round r+1's stream was round r's shifted by 8 words, and the
    difference of one client's consecutive masked uplinks leaked
    plaintext quantized-update deltas.  Same (key, round) → same
    stream, which is exactly what dropout recovery replays from a
    reconstructed secret key.  Returns int64 residues in [0, p)."""
    key = int(pair_key)
    bg = np.random.Philox(key=np.array([key & 0xFFFFFFFFFFFFFFFF,
                                        0x5EC466], dtype=np.uint64),
                          counter=np.array([0, 0, 0, int(round_idx)],
                                           dtype=np.uint64))
    return np.random.Generator(bg).integers(0, p, size=n_words,
                                            dtype=np.int64)


class SecAggKeyring:
    """Per-cohort DH key material + the escrowed seed shares.

    Client ids are the federation ranks (1..N).  ``escrow(cid)``
    materializes the BGW shares of that client's secret key — called at
    dispatch time, which is when a real deployment would ship each
    share to its holder.  ``reconstruct_sk(dead, survivors)`` rebuilds
    a dead client's key from the survivors' shares and raises
    :class:`SecAggBelowThreshold` by name below the threshold."""

    def __init__(self, client_ids: Iterable[int], threshold: int,
                 cfg: SecAggConfig):
        self.cfg = cfg
        self.ids = sorted(int(c) for c in client_ids)
        if len(set(self.ids)) != len(self.ids):
            raise ValueError(f"duplicate client ids in {self.ids}")
        self.threshold = int(threshold)
        p = cfg.prime
        rs = np.random.RandomState(cfg.seed)
        # secret keys in [2, p-2]: exclude the degenerate exponents
        self.sk = {c: int(rs.randint(2, p - 1)) for c in self.ids}
        self.pk = {c: mpc.pk_gen(self.sk[c], p=p) for c in self.ids}
        # escrowed BGW shares of sk, by owner: shares[owner][slot] where
        # slot k belongs to self.ids[k] (lazy — built at dispatch)
        self._shares: dict[int, np.ndarray] = {}
        self._pos = {c: k for k, c in enumerate(self.ids)}

    def pair_key(self, a: int, b: int) -> int:
        """The symmetric DH pairwise secret of clients a and b."""
        return mpc.shared_key(self.pk[b], self.sk[a], self.cfg.prime)

    def escrow(self, cid: int) -> None:
        """Materialize the BGW shares of `cid`'s secret key (threshold−1
        degree polynomial: any `threshold` shares reconstruct, fewer
        cannot).  Idempotent; seeded off (cfg.seed, cid) so every rank
        of a multi-process deployment escrows identical shares."""
        if cid in self._shares:
            return
        self._shares[cid] = mpc.BGW_encoding(
            np.array([self.sk[cid]], np.int64), len(self.ids),
            self.threshold - 1, self.cfg.prime,
            seed=(self.cfg.seed * 1_000_003 + cid) % (2 ** 31))

    def reconstruct_sk(self, dead: int, survivors: Iterable[int]) -> int:
        """Rebuild a dead client's secret key from the surviving set's
        escrowed shares.  Fails by name below the threshold."""
        self.escrow(dead)
        surv = sorted(int(s) for s in set(survivors) if s != dead
                      and s in self._pos)
        if len(surv) < self.threshold:
            raise SecAggBelowThreshold(
                f"cannot reconstruct client {dead}'s pairwise masks: "
                f"{len(surv)} survivors hold shares, threshold is "
                f"{self.threshold} — the round must not commit")
        idx = np.array([self._pos[s] for s in surv[:self.threshold]],
                       np.int64)
        shares = self._shares[dead][idx]
        return int(mpc.BGW_decoding(shares, idx, self.cfg.prime)[0])


class SecureAggregator:
    """THE aggregation-stage seam of the secure data plane — one object
    serving both the async server (AsyncServerManager, masked uplinks
    on the live wire) and the sync FSM (fedavg_messaging's aggregate
    barrier), plus the in-process clients of either path.

    Client side: :meth:`client_row` quantizes the weighted flat update
    (flatten_vars_row layout) plus the weight word and adds the
    pairwise masks.  Server side: :meth:`fold` is the jitted
    mask-and-fold at arrival (staleness.make_field_fold_fn — mod-p adds
    on the u32 row, O(W) per uplink like the plain streaming fold);
    the arrived row is also retained until the barrier, because
    excluding an uploaded-then-died client from a pure running sum is
    otherwise impossible.  :meth:`commit` runs the unmask barrier:
    subtract excluded uploaders' retained rows, reconstruct every
    non-included client's masks from escrowed shares, dequantize, and
    hand back the (acc, wsum) pair the existing O(P) stream commit
    consumes unchanged."""

    def __init__(self, cfg: SecAggConfig, client_ids: Iterable[int],
                 flat_dim: int):
        self.cfg = cfg
        self.dim = int(flat_dim)
        self.words = self.dim + 1            # + the masked weight word
        self.ids = sorted(int(c) for c in client_ids)
        self.threshold = cfg.resolve_threshold(len(self.ids))
        self.keyring = SecAggKeyring(self.ids, self.threshold, cfg)
        self._fold_fn = None                 # jitted, built lazily
        self._acc = None                     # device u32 running field sum
        self._rows: dict[int, np.ndarray] = {}   # unmask-window retention
        self._lock = threading.Lock()
        self.below_threshold_rounds = 0
        self.recovered_rounds = 0            # commits that rebuilt masks

    # -- client side ---------------------------------------------------------
    def client_row(self, cid: int, round_idx: int, flat: np.ndarray,
                   weight: float) -> np.ndarray:
        """One client's masked uplink row: [quantize(weight·flat),
        quantize(weight)] + pairwise masks, as uint32 field words.
        The DP stage (end-to-end private mode) clips and noises the
        weighted update BEFORE quantization, so no un-noised value ever
        reaches the field encoding; the noise generator is derived per
        (seed, client, round), so draws are thread-safe and
        byte-deterministic no matter how concurrent uploads interleave.
        Quantization enforces the per-client slice of the aggregate
        bound, |q| ≤ (p−1)//(2K) for a K-client cohort, so the folded
        field SUM can never cross the signed half-range and alias at
        dequantize — aliasing is undetectable post hoc, so the guard
        must run a priori, here."""
        p = self.cfg.prime
        x = np.asarray(flat, np.float64) * float(weight)
        if x.shape != (self.dim,):
            raise ValueError(f"client_row expects a [{self.dim}] flat "
                             f"row, got {x.shape}")
        if self.cfg.dp_clip is not None:
            nrm = float(np.linalg.norm(x))
            if nrm > self.cfg.dp_clip:
                x = x * (self.cfg.dp_clip / nrm)
            if self.cfg.dp_noise > 0.0:
                rng = np.random.default_rng(
                    (self.cfg.seed, 41, int(cid), int(round_idx)))
                x = x + rng.normal(
                    0.0, self.cfg.dp_noise * self.cfg.dp_clip, x.shape)
        head = (p - 1) // (2 * len(self.ids))
        q = np.empty((self.words,), np.int64)
        q[:self.dim] = mpc.quantize(x, self.cfg.scale, p, max_abs=head)
        q[self.dim] = mpc.quantize(np.array([float(weight)]),
                                   self.cfg.scale, p, max_abs=head)[0]
        for j in self.ids:
            if j == cid:
                continue
            m = pairwise_mask(self.keyring.pair_key(cid, j), round_idx,
                              self.words, p)
            q = (q + m) % p if cid < j else (q - m) % p
        return q.astype(np.uint32)

    # -- server side ---------------------------------------------------------
    @property
    def arrived(self) -> list[int]:
        with self._lock:
            return sorted(self._rows)

    @property
    def count(self) -> int:
        return len(self._rows)

    def escrow(self, cid: int) -> None:
        """Dispatch-time share escrow (see SecAggKeyring.escrow)."""
        self.keyring.escrow(cid)

    def fold(self, cid: int, row: np.ndarray) -> int:
        """Jitted mask-and-fold at arrival; returns the arrived count.
        A client's re-upload within one round replaces its retained row
        (the duplicate is backed out of the field sum first — exactly
        once semantics at the aggregation stage)."""
        import jax.numpy as jnp
        from fedml_tpu.async_.staleness import make_field_fold_fn
        row = np.ascontiguousarray(row, np.uint32)
        if row.shape != (self.words,):
            raise ValueError(f"secagg row must be [{self.words}] u32 "
                             f"words, got {row.shape}")
        if int(cid) not in self.keyring._pos:
            raise ValueError(f"unknown secagg client id {cid} "
                             f"(cohort is {self.ids})")
        with self._lock:
            if self._fold_fn is None:
                self._fold_fn = make_field_fold_fn(self.cfg.prime)
            if self._acc is None:
                self._acc = jnp.zeros((self.words,), jnp.uint32)
            prev = self._rows.pop(int(cid), None)
            if prev is not None:
                # additive inverse in the field: acc + (p - prev) mod p
                inv = ((self.cfg.prime - prev.astype(np.int64))
                       % self.cfg.prime).astype(np.uint32)
                self._acc = self._fold_fn(self._acc, jnp.asarray(inv))
            self._acc = self._fold_fn(self._acc, jnp.asarray(row))
            self._rows[int(cid)] = row.copy()
            return len(self._rows)

    def field_sum(self, round_idx: int,
                  survivors: Iterable[int]) -> tuple[np.ndarray, list[int]]:
        """The unmask barrier in the integer field: returns (words i64
        in [0, p), included ids).  Included = arrived ∩ survivors; an
        uploaded-then-died client's retained row is subtracted whole,
        then every non-included cohort member's pairwise masks against
        the included set are reconstructed (escrowed shares → sk →
        replayed PRG streams) and backed out.  What remains is exactly
        Σ_{i∈included} quantize(w_i·x_i) mod p — bitwise the maskless
        fixed-point sum.  Raises SecAggBelowThreshold by name when the
        surviving set cannot reconstruct."""
        p = self.cfg.prime
        with self._lock:
            rows = dict(self._rows)
            acc = (np.zeros((self.words,), np.int64) if self._acc is None
                   else np.asarray(self._acc, np.uint32).astype(np.int64))
        survivors = sorted(int(s) for s in set(survivors))
        included = sorted(set(rows) & set(survivors))
        if len(survivors) < self.threshold:
            self.below_threshold_rounds += 1
            raise SecAggBelowThreshold(
                f"secure round {round_idx}: {len(survivors)} survivors "
                f"< threshold {self.threshold} — refusing to commit a "
                f"mask-polluted aggregate")
        for d in set(rows) - set(included):
            # uploaded then excluded (died pre-commit): back the whole
            # masked row out, leaving only survivor-side pair residues
            acc = (acc - rows[d].astype(np.int64)) % p
        dead = [c for c in self.ids if c not in included]
        if dead and included:
            self.recovered_rounds += 1
        for d in dead:
            # the included rows each carry one uncancelled mask for the
            # pair (i, d); replay d's streams from the reconstructed key
            sk_d = self.keyring.reconstruct_sk(d, survivors)
            for i in included:
                s = mpc.shared_key(self.keyring.pk[i], sk_d, p)
                m = pairwise_mask(s, round_idx, self.words, p)
                # client i applied +m if i < d else −m; subtract that
                acc = (acc - m) % p if i < d else (acc + m) % p
        return acc, included

    def commit(self, round_idx: int, survivors: Iterable[int],
               reset: bool = True) -> tuple[np.ndarray, float, list[int]]:
        """Unmask + dequantize: returns (acc f32 [dim] = Σ w_i·x_i,
        wsum = Σ w_i, included ids) — the exact (acc, wsum) shape
        make_stream_commit_fn consumes, so the O(P) commit program is
        untouched by masking.  `reset` clears the round window."""
        words, included = self.field_sum(round_idx, survivors)
        total = mpc.dequantize(words, self.cfg.scale, self.cfg.prime)
        acc = total[:self.dim].astype(np.float32)
        wsum = float(total[self.dim])
        if reset:
            self.reset()
        return acc, wsum, included

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()
            self._acc = None

    def report(self) -> dict:
        return {"cohort": len(self.ids), "threshold": self.threshold,
                "below_threshold_rounds": self.below_threshold_rounds,
                "recovered_rounds": self.recovered_rounds}
