"""Secure-aggregation data plane (ISSUE 20).

`core/mpc.py` holds the finite-field control plane (BGW shares, LCC,
DH key agreement, fixed-point quantization); this package is the data
plane that wires those primitives into the federation's aggregation
path: pairwise-mask uplinks, elastic dropout recovery at the commit
barrier, and the `transport=secagg` wire frames.
"""
from fedml_tpu.secure.secagg import (SecAggBelowThreshold, SecAggConfig,
                                     SecAggKeyring, SecureAggregator,
                                     pairwise_mask)

__all__ = ["SecAggBelowThreshold", "SecAggConfig", "SecAggKeyring",
           "SecureAggregator", "pairwise_mask"]
