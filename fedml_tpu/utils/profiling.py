"""Profiling/tracing — first-class replacement for the reference's coarse
wall-clock timers (SURVEY.md §5: aggregation timers FedAVGAggregator.py:60,
TRPC latency microbench).

`trace(dir)` captures a full XLA/TPU profile viewable in TensorBoard or
Perfetto; `annotate(name)` scopes a named region inside it; `StepTimer`
gives the reference-style wall-clock numbers (rounds/sec, per-phase means)
without any profiler overhead.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Iterator

import jax


def repin_jax_platforms() -> None:
    """Re-assert an explicit JAX_PLATFORMS env choice over the image's
    sitecustomize, which force-sets jax_platforms to "axon,cpu"
    regardless of the env var (see tests/conftest.py): without this, a
    JAX_PLATFORMS=cpu dev/CI run still attaches (or blocks on) the
    tunnel TPU backend.  No-op when the env var is unset — the normal
    chip path keeps the sitecustomize default.  Call before the first
    device use (bench.py, tools/)."""
    import os
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """jax.profiler trace of everything inside the block (device + host)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (shows up on the TraceMe timeline)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Accumulates wall-clock per named phase; blocking-safe (call `stop`
    after block_until_ready for honest device timings)."""

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self._open: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def mean(self, name: str) -> float:
        return self.totals[name] / max(self.counts[name], 1)

    def report(self) -> dict[str, float]:
        return {f"{k}_mean_s": self.mean(k) for k in self.totals}
