"""Profiling/tracing — first-class replacement for the reference's coarse
wall-clock timers (SURVEY.md §5: aggregation timers FedAVGAggregator.py:60,
TRPC latency microbench).

`trace(dir)` captures a full XLA/TPU profile viewable in TensorBoard or
Perfetto; `annotate(name)` scopes a named region inside it; `StepTimer`
gives the reference-style wall-clock numbers (rounds/sec, per-phase means)
without any profiler overhead.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Iterator, Optional

import jax

from fedml_tpu import obs


def repin_jax_platforms() -> None:
    """Re-assert an explicit JAX_PLATFORMS env choice over the image's
    sitecustomize, which force-sets jax_platforms to "axon,cpu"
    regardless of the env var (see tests/conftest.py): without this, a
    JAX_PLATFORMS=cpu dev/CI run still attaches (or blocks on) the
    tunnel TPU backend.  No-op when the env var is unset — the normal
    chip path keeps the sitecustomize default.  Call before the first
    device use (bench.py, tools/)."""
    import os
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """jax.profiler trace of everything inside the block (device + host)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (shows up on the TraceMe timeline)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Accumulates wall-clock per named phase; blocking-safe (call `stop`
    after block_until_ready for honest device timings)."""

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self._open: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def mean(self, name: str) -> float:
        return self.totals[name] / max(self.counts[name], 1)

    def report(self) -> dict[str, float]:
        return {f"{k}_mean_s": self.mean(k) for k in self.totals}


def _overlap_fraction(upload_wall: float, wait_wall: float) -> float:
    """Fraction of the upload wall that hid behind compute.  1.0 when
    there were no uploads (nothing left unhidden — bench's resident
    cohort path reports this by definition)."""
    if upload_wall <= 0.0:
        return 1.0
    return max(0.0, min(1.0, (upload_wall - wait_wall) / upload_wall))


class TransferOverlapStats:
    """Host→device transfer vs compute overlap accounting for the
    streaming/block-stream engine paths (the PR-1 prefetch pipeline).

    Producers — whichever thread runs the host gather + cast +
    `jax.device_put` — time each upload with `uploading()`; the round
    loop times its blocking prefetch waits with `waiting()` and
    brackets each round with `round_start()`/`round_end()`.  Per round
    (and cumulatively since `reset()`):

        upload_wall_s     Σ wall of upload calls, any thread
        wait_wall_s       wall the round loop spent blocked on uploads
        round_wall_s      wall of the whole round
        compute_wall_s    round_wall_s − wait_wall_s (dispatch + device)
        overlap_fraction  (upload_wall − wait_wall)/upload_wall ∈ [0, 1]

    With perfect overlap the loop never waits for a transfer
    (overlap 1.0); a fully transfer-bound round waits out almost every
    upload (overlap ≈ compute/upload).  Uploads are attributed to the
    round window they occur in by wall time (a next-round prefetch that
    starts during round r lands in r's window); the cumulative numbers
    are window-free.  Thread-safe; overhead is two perf_counter calls
    per event, so it stays on for every streaming round
    (PERF.md §"Prefetch pipeline" has the measurement recipe).

    The metrics registry (fedml_tpu/obs) is the exported system of
    record: every upload/wait/round event writes through to the shared
    engine_* counters and histograms below, so a Prometheus snapshot
    carries the same walls this object reports.  The instance keeps its
    own cumulative state too — per-engine round windows (and `reset()`)
    must not be corrupted by another engine in the same process, and
    prometheus counters never reset."""

    def __init__(self):
        self._lock = threading.Lock()
        # write-through registry handles (shared across engines; the
        # per-instance fields below stay the per-engine view)
        self._m_upload_total = obs.counter(
            "engine_upload_wall_seconds_total")
        self._m_wait_total = obs.counter("engine_wait_wall_seconds_total")
        self._m_rounds = obs.counter("engine_rounds_total")
        # per-event histograms: upload tail = the straggler blocks of a
        # block-streamed round; round wall = the cohort wall-time
        self._h_upload = obs.histogram("engine_upload_wall_seconds")
        self._h_wait = obs.histogram("engine_wait_wall_seconds")
        self._h_round = obs.histogram("engine_round_wall_seconds")
        self._h_overlap = obs.histogram(
            "engine_round_overlap_fraction",
            buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0))
        # byte accounting (transfer-compression layer): every host
        # buffer the engine hands to device_put counts here, so the
        # stack-dtype tiers (f32/bf16/uint8) are comparable as BYTES,
        # not just walls — bench.py surfaces h2d_bytes_per_round from
        # this, and the registry counter is the Prometheus view
        self._m_h2d_bytes = obs.counter("engine_h2d_bytes_total")
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._upload_wall = 0.0
            self._wait_wall = 0.0
            self._h2d_bytes = 0
            self._round_t0: Optional[float] = None
            self._snap = (0.0, 0.0, 0)
            self.rounds: list[dict] = []

    def add_h2d_bytes(self, nbytes: int) -> None:
        """Record host→device payload bytes (called by the engine upload
        paths where the host buffer sizes are known — any thread)."""
        n = int(nbytes)
        with self._lock:
            self._h2d_bytes += n
        self._m_h2d_bytes.inc(n)

    @property
    def h2d_bytes(self) -> int:
        """Cumulative H2D payload bytes since reset() (per-engine view;
        engine_h2d_bytes_total is the process-wide counter)."""
        with self._lock:
            return self._h2d_bytes

    @contextlib.contextmanager
    def uploading(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._upload_wall += dt
            self._m_upload_total.inc(dt)
            self._h_upload.observe(dt)

    @contextlib.contextmanager
    def waiting(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._wait_wall += dt
            self._m_wait_total.inc(dt)
            self._h_wait.observe(dt)

    def round_start(self) -> None:
        """Open a round window (auto-closes a window left open).  The
        block-stream rounds bracket themselves with round_start/
        round_end (try/finally); the per-round streaming path records
        cumulative walls only — its round body runs in the base run()
        loop, outside the engine hooks' sight."""
        if self._round_t0 is not None:
            self.round_end()
        with self._lock:
            self._snap = (self._upload_wall, self._wait_wall,
                          self._h2d_bytes)
        self._round_t0 = time.perf_counter()

    def round_end(self) -> Optional[dict]:
        """Close the open round window and record it; no-op when none
        is open."""
        if self._round_t0 is None:
            return None
        wall = time.perf_counter() - self._round_t0
        self._round_t0 = None
        with self._lock:
            up = self._upload_wall - self._snap[0]
            wait = self._wait_wall - self._snap[1]
            h2d = self._h2d_bytes - self._snap[2]
        rec = {"round_wall_s": wall, "upload_wall_s": up,
               "wait_wall_s": wait,
               "compute_wall_s": max(wall - wait, 0.0),
               "overlap_fraction": _overlap_fraction(up, wait),
               "h2d_bytes": h2d}
        self.rounds.append(rec)
        self._m_rounds.inc()
        self._h_round.observe(wall)
        self._h_overlap.observe(rec["overlap_fraction"])
        return rec

    def overlap_fraction(self) -> float:
        with self._lock:
            return _overlap_fraction(self._upload_wall, self._wait_wall)

    def report(self) -> dict:
        with self._lock:
            up, wait = self._upload_wall, self._wait_wall
            h2d = self._h2d_bytes
        return {"upload_wall_s": up, "wait_wall_s": wait,
                "overlap_fraction": _overlap_fraction(up, wait),
                "h2d_bytes": h2d, "rounds": len(self.rounds)}
