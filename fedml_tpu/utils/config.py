"""Unified run configuration.

The reference scatters ~20 argparse flags per entry point plus three sidecar
files (gpu_mapping.yaml, grpc_ipconfig.csv, trpc_master_config.csv —
SURVEY.md §5).  Here one dataclass covers the canonical flag set
(main_fedavg.py:46-135) and is consumed by every algorithm and entry point;
`from_args` adapts an argparse namespace.
"""
from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class FedConfig:
    # task
    model: str = "lr"
    dataset: str = "mnist"
    data_dir: Optional[str] = None
    partition_method: str = "hetero"
    partition_alpha: float = 0.5
    # federation
    client_num_in_total: int = 10
    client_num_per_round: int = 10
    comm_round: int = 10
    epochs: int = 1                      # local epochs E
    batch_size: int = 10
    # client optimizer
    client_optimizer: str = "sgd"
    lr: float = 0.03
    momentum: float = 0.0
    wd: float = 0.0
    # per-local-round LR schedule (reference fedseg LR_Scheduler parity):
    # None | "poly" | "cos" | "step"; step decays 0.1x every lr_step epochs
    lr_scheduler: Optional[str] = None
    lr_step: int = 0
    warmup_epochs: int = 0
    # loss override (None = dataset-derived) and segmentation void label
    loss_type: Optional[str] = None
    train_ignore_id: Optional[int] = None
    # server optimizer (FedOpt)
    server_optimizer: str = "sgd"
    server_lr: float = 1.0
    server_momentum: float = 0.0
    # fedprox
    prox_mu: float = 0.0
    # unroll factor of the local batch scan (perf knob; 8 measured -2.5%
    # on the v5e bench round at chunk 2 — PERF.md L2U rows)
    batch_unroll: int = 1
    # robust aggregation
    norm_bound: float = 5.0
    stddev: float = 0.0
    # eval cadence
    frequency_of_the_test: int = 5
    # observability: flight-recorder dump when one round overruns this
    # many seconds (needs --obs_dir; None = no watchdog — fedml_tpu/obs)
    round_deadline_s: Optional[float] = None
    # auto per-client test eval during evaluate() (the reference's
    # _local_test_on_all_clients); opt out to skip its upload + cost
    local_test_eval: bool = True
    # compute precision: "float32" | "bfloat16" (bf16 = the MXU fast path;
    # masters/aggregation stay f32)
    train_dtype: str = "float32"
    # training-time image augmentation (crop+flip+cutout inside the jitted
    # train step, data/augment.py; reference cifar10/data_loader.py:57-98)
    augment: bool = False
    # misc
    seed: int = 0
    max_batches_per_client: Optional[int] = None
    synthetic_scale: float = 1.0
    ci: bool = False

    @classmethod
    def from_args(cls, args) -> "FedConfig":
        """None-valued namespace entries fall back to the dataclass
        default — the CLI uses default=None as an "unset" sentinel for
        flags (server_*) whose effective default depends on the
        algorithm; a command line cannot express an explicit None."""
        known = {f.name for f in dataclasses.fields(cls)}
        defaults = {f.name: f.default for f in dataclasses.fields(cls)}
        return cls(**{k: (defaults[k] if v is None else v)
                      for k, v in vars(args).items() if k in known})
