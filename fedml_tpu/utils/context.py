"""Run-level error/cleanup context managers + sweep coordination glue.

Parity: fedml_api/utils/context.py (raise_MPI_error aborts COMM_WORLD on
any exception — we tear down comm managers instead of nuking the world)
and fedml_api/distributed/fedavg/utils.py:19-27
(post_complete_message_to_sweep_process — wandb-sweep agents block on a
named pipe until the training process reports completion).
"""
from __future__ import annotations

import logging
import os
import traceback
from contextlib import contextmanager

log = logging.getLogger(__name__)


@contextmanager
def graceful_abort(*managers, reraise: bool = True):
    """Run a deployment block; on ANY exception, log the traceback and
    finish() every comm manager so sockets/threads shut down instead of
    hanging the peer ranks (the reference calls MPI Abort; we close the
    transports we own).  `reraise=False` mirrors
    raise_error_without_process."""
    try:
        yield
    except BaseException as e:      # incl. KeyboardInterrupt/SystemExit:
        log.error("aborting run:\n%s", traceback.format_exc())
        for m in managers:
            try:
                m.finish()
            except Exception:       # teardown must not mask the real error
                log.exception("manager %r failed to finish", m)
        # Ctrl-C / sys.exit always propagate; reraise=False only swallows
        # ordinary Exceptions (raise_error_without_process parity)
        if reraise or not isinstance(e, Exception):
            raise


def post_complete_message_to_sweep_process(args,
                                           pipe_path: str = "./tmp/fedml",
                                           wait_for_reader: float = 2.0):
    """Notify a sweep coordinator over a named pipe (reference
    fedavg/utils.py:19-27).  Waits up to `wait_for_reader` seconds for a
    coordinator to attach, then drops the message with a warning — the
    reference instead blocks forever when run outside a sweep."""
    import time
    os.makedirs(os.path.dirname(pipe_path) or ".", exist_ok=True)
    if not os.path.exists(pipe_path):
        try:
            os.mkfifo(pipe_path)
        except OSError:             # plain file already there, etc.
            pass
    deadline = time.monotonic() + wait_for_reader
    while True:
        try:
            pipe_fd = os.open(pipe_path, os.O_WRONLY | os.O_NONBLOCK)
            break
        except OSError:             # ENXIO: no reader attached yet
            if time.monotonic() >= deadline:
                log.warning("no sweep coordinator reading %s; completion "
                            "message dropped", pipe_path)
                return
            time.sleep(0.05)
    with os.fdopen(pipe_fd, "w") as pipe:
        pipe.write(f"training is finished! \n{args}\n")
