"""Round-level checkpoint/resume via orbax.

The reference has essentially no FL-state checkpointing (SURVEY.md §5:
FedGKT saves a server .pth.tar, DARTS saves genotypes, nothing resumes a
round).  Here any engine's (variables, server_state, round_idx) checkpoints
atomically every N rounds and training resumes exactly — the deterministic
per-round client sampler (np.random.seed(round_idx)) makes a resumed run
bitwise-identical to an uninterrupted one.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

Pytree = Any

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:                      # pragma: no cover
    _HAVE_ORBAX = False


class FedCheckpointManager:
    """Save/restore (round_idx, variables, server_state) under `directory`.

    Thin wrapper over orbax's CheckpointManager: keeps `max_to_keep`
    newest rounds, atomic renames, async-safe.  `server_state` may be any
    pytree (optax states included); restore needs the matching template
    structure, which every engine can produce via server_init."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        if not _HAVE_ORBAX:
            raise RuntimeError("orbax is not available in this environment")
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, round_idx: int, variables: Pytree,
             server_state: Pytree = (),
             extra_state: Optional[Pytree] = None) -> None:
        """`extra_state` carries engine-specific round state beyond the
        (variables, server_state) pair — the async engine checkpoints
        its aggregation-buffer contents and per-client staleness
        counters through it (fedml_tpu/async_/scheduler.py
        async_state()).  Only written when provided, so synchronous
        checkpoints keep their existing on-disk structure."""
        state = {"variables": variables,
                 "server_state": _wrap_empty(server_state)}
        if extra_state is not None:
            state["extra_state"] = extra_state
        self._mgr.save(round_idx, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_round(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, variables_template: Pytree,
                server_state_template: Pytree = (),
                round_idx: Optional[int] = None,
                extra_template: Optional[Pytree] = None):
        """Returns (round_idx, variables, server_state); templates define
        the pytree structure/dtypes (pass engine.init_variables() /
        engine.server_init(v)).  With `extra_template` the checkpoint's
        extra_state is restored too and a 4-tuple is returned — only
        for checkpoints that were saved with one."""
        step = round_idx if round_idx is not None else self.latest_step_or_raise()
        template = {"variables": variables_template,
                    "server_state": _wrap_empty(server_state_template)}
        if extra_template is not None:
            template["extra_state"] = extra_template
        out = self._mgr.restore(step, args=ocp.args.StandardRestore(template))
        if extra_template is not None:
            return (step, out["variables"],
                    _unwrap_empty(out["server_state"]), out["extra_state"])
        return step, out["variables"], _unwrap_empty(out["server_state"])

    def latest_step_or_raise(self) -> int:
        step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        return step

    def close(self):
        self._mgr.close()


def _wrap_empty(tree: Pytree):
    # orbax rejects totally-empty pytrees (e.g. FedAvg's () server state);
    # carry a sentinel leaf alongside
    return {"state": tree, "_nonempty": np.zeros((1,), np.int32)}


def _unwrap_empty(wrapped):
    return wrapped["state"]
