from fedml_tpu.utils.config import FedConfig
