from fedml_tpu.utils.config import FedConfig
from fedml_tpu.utils.metrics import RunLogger
from fedml_tpu.utils.profiling import StepTimer, annotate, trace

__all__ = ["FedConfig", "RunLogger", "StepTimer", "annotate", "trace"]
