"""Local run logger — the wandb-parity metrics system of record.

The reference logs Train/Acc, Train/Loss, Test/Acc, Test/Loss keyed by
round to wandb (FedAVGAggregator.py:137-162) and its CI reads results back
out of wandb-summary.json (CI-script-fedavg.sh:42-47).  Zero-egress
equivalent: a per-run directory with

  history.jsonl   one JSON line per log() call (step-keyed)
  summary.json    last value per key — same contract the CI oracle reads

If wandb is importable AND configured, mirror to it; never required.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Optional


class RunLogger:
    def __init__(self, root: str = "./runs", project: str = "fedml_tpu",
                 name: Optional[str] = None, config: Optional[dict] = None,
                 use_wandb: bool = False):
        stamp = name or time.strftime("run-%Y%m%d-%H%M%S")
        self.dir = os.path.join(root, project, stamp)
        os.makedirs(self.dir, exist_ok=True)
        self.summary: dict[str, Any] = {}
        self._hist = open(os.path.join(self.dir, "history.jsonl"), "a")
        self._wandb = None
        if use_wandb:
            try:                        # optional, absent in this image
                import wandb
                self._wandb = wandb.init(project=project, name=name,
                                         config=config or {})
            except Exception:
                self._wandb = None
        if config:
            with open(os.path.join(self.dir, "config.json"), "w") as f:
                json.dump(config, f, indent=2, default=str)

    def log(self, metrics: dict, step: Optional[int] = None) -> None:
        if self._hist.closed:
            raise ValueError("RunLogger is closed (log after close())")
        rec = {"_step": step, "_time": time.time(), **metrics}
        self._hist.write(json.dumps(rec, default=float) + "\n")
        # flush per log(): a killed run keeps every line it logged —
        # history.jsonl is the post-mortem record, not a best-effort one
        self._hist.flush()
        self.summary.update(metrics)
        with open(os.path.join(self.dir, "summary.json"), "w") as f:
            json.dump(self.summary, f, default=float)
        if self._wandb is not None:
            self._wandb.log(metrics, step=step)

    def finish(self) -> None:
        """Close the history handle and the wandb mirror.  Idempotent —
        every exit path (cli main, context-manager __exit__, an
        engine's own cleanup) may call it."""
        if not self._hist.closed:
            self._hist.close()
        if self._wandb is not None:
            self._wandb.finish()
            self._wandb = None

    # close()/with-statement aliases: `with RunLogger(...) as logger:`
    # guarantees the wandb mirror and the history handle are released on
    # ANY exit, including an exception mid-run
    close = finish

    def __enter__(self) -> "RunLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    @staticmethod
    def read_summary(run_dir: str) -> dict:
        """The CI-oracle read path (reference reads
        wandb/latest-run/files/wandb-summary.json)."""
        with open(os.path.join(run_dir, "summary.json")) as f:
            return json.load(f)
