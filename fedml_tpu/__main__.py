"""`python -m fedml_tpu` — the unified launcher (cli.py)."""
import sys

from fedml_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
