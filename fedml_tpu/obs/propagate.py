"""Cross-process trace propagation — the federation-wide observability
glue (ISSUE 7).

A federated round's wall time is split across processes: client train,
uplink encode, transport, decode-into, streaming fold, commit.  Each
process's SpanTracer only sees its own slice; this module carries the
connective tissue on the wire frames themselves, at the comm layer's
send/`_deliver_frame` chokepoints (fedml_tpu/comm/base.py):

* ``stamp(msg, rank, clock)`` — attach a compact **trace block**
  (``__fedml_trace__`` param: sender rank, send wall-clock, sender
  trace-relative timestamp, round/version id, span digest, clock echo)
  to an outbound Message.  ONLY when tracing is enabled: with obs
  disabled the param is never added and frames stay BYTE-IDENTICAL to
  the untraced build (pinned in tests/test_wire_codec.py).
* ``note(msg, backend, clock)`` — pop the trace block (and a
  piggybacked metrics delta, ``__fedml_metrics__``) off an inbound
  Message before the FSM sees it: feed the per-peer clock-offset
  estimator, record a ``trace.recv`` instant carrying the peer's span
  digest (the "shipped client spans" tools/trace_timeline.py merges),
  and fold the metrics delta into this process's registry under
  ``origin="remote"`` — a cohort rollup, never per-client labels.

Clock alignment is the piggybacked **handshake echo**: every receive
observes ``delta = t_recv(mine) − t_send(theirs) = offset + transit``;
every send echoes back the minimum delta observed FROM the receiver.
With both directions' minima the peer offset is the classic symmetric
estimate ``(delta − echo) / 2`` and transit ``(delta + echo) / 2`` —
no extra messages, accuracy bounded by transit asymmetry.  One-way-only
peers fall back to ``min(delta)`` (an upper bound: transit ≥ 0).
`ClockSync` state is bounded (``max_peers``) so a million-client server
cannot grow an unbounded peer map.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Optional

from fedml_tpu import obs

TRACE_KEY = "__fedml_trace__"
METRICS_KEY = "__fedml_metrics__"

# process-wide view of live ClockSyncs so obs.export() can write the
# peer-offset table tools/trace_timeline.py aligns traces with.  Weak
# refs: each comm manager owns its clock, and a long-lived process that
# constructs managers per run/connection must not pin every dead
# manager's peer map here forever.
_registry_lock = threading.Lock()
_clock_syncs: list["weakref.ref[ClockSync]"] = []


class ClockSync:
    """Per-peer clock-offset estimator fed by piggybacked timestamps.

    ``offset(peer)`` is the estimated seconds to ADD to the peer's
    wall-clock timestamps to land on this process's clock.  Memory is
    capped at `max_peers` (overflow peers are counted, not tracked) —
    the million-client constraint."""

    def __init__(self, backend: str, max_peers: int = 4096):
        self.backend = backend
        self.rank: Optional[int] = None      # learned at first stamp
        self.max_peers = max_peers
        self.peers_skipped = 0
        self._lock = threading.Lock()
        self._delta_min: dict[int, float] = {}   # min(t_recv − t_send)
        self._echo: dict[int, float] = {}        # peer's min for OUR sends
        self._m_transit = obs.histogram(
            "trace_transit_seconds",
            buckets=obs.metrics.DECODE_SECONDS_BUCKETS, backend=backend)
        self._m_offset = obs.gauge("trace_clock_offset_seconds",
                                   backend=backend)
        # cached handle: note() runs per received frame — the registry
        # get-or-create lookup must not
        self._m_frames = obs.counter("trace_frames_total",
                                     backend=backend)

    def observe(self, peer: int, delta: float,
                echo: Optional[float]) -> None:
        with self._lock:
            if peer not in self._delta_min and \
                    len(self._delta_min) >= self.max_peers:
                self.peers_skipped += 1
                return
            d = self._delta_min.get(peer)
            self._delta_min[peer] = delta if d is None else min(d, delta)
            if echo is not None:
                e = self._echo.get(peer)
                self._echo[peer] = echo if e is None else min(e, echo)
            off, transit = self._estimate(peer)
        self._m_offset.set(off)
        if transit is not None:
            self._m_transit.observe(max(0.0, transit))

    def _estimate(self, peer: int):
        """(offset, transit) under _lock; transit None without echo."""
        d = self._delta_min[peer]
        e = self._echo.get(peer)
        if e is None:
            return d, None            # one-way bound: transit >= 0
        return (d - e) / 2.0, (d + e) / 2.0

    def delta_for(self, peer: int) -> Optional[float]:
        """Min observed delta FROM `peer` — the echo a frame bound for
        that peer carries."""
        with self._lock:
            return self._delta_min.get(peer)

    def offsets(self) -> dict[int, float]:
        """{peer_rank: offset_seconds} — add to peer timestamps to map
        onto this clock."""
        with self._lock:
            return {p: self._estimate(p)[0] for p in self._delta_min}

    def export(self) -> dict:
        with self._lock:
            return {
                "backend": self.backend,
                "rank": self.rank,
                "offsets_s": {str(p): self._estimate(p)[0]
                              for p in self._delta_min},
                "echoed": sorted(self._echo),
                "peers_skipped": self.peers_skipped,
            }


def make_clock(backend: str) -> ClockSync:
    """ClockSync factory that registers the instance for export()."""
    c = ClockSync(backend)
    with _registry_lock:
        _clock_syncs.append(weakref.ref(c))
        # prune refs whose manager (and clock) died — a long-lived
        # process creating managers per run must not grow this list
        _clock_syncs[:] = [r for r in _clock_syncs if r() is not None]
    return c


def clock_exports() -> list[dict]:
    with _registry_lock:
        syncs = [c for c in (r() for r in _clock_syncs) if c is not None]
    return [c.export() for c in syncs if c._delta_min or c.rank is not None]


def reset_clocks() -> None:
    """Test hook (obs.reset() calls through)."""
    with _registry_lock:
        _clock_syncs.clear()


def stamp(msg, rank: int, clock: Optional[ClockSync] = None) -> None:
    """Attach the trace block to an outbound Message — a no-op (and
    byte-neutral) unless tracing is enabled."""
    t = obs.tracer()
    if t is None:
        return
    blk = {
        "r": int(rank),
        "t": time.time(),             # send wall-clock (offset source)
        "m": t._now_us(),             # sender trace-relative, us
        "d": t.digest(),
    }
    rd = msg.get("model_version", msg.get("round_idx"))
    if rd is not None:
        blk["rd"] = int(rd)
    if clock is not None:
        clock.rank = int(rank)
        e = clock.delta_for(msg.get_receiver_id())
        if e is not None:
            blk["e"] = e
    msg.add_params(TRACE_KEY, blk)


def note(msg, backend: str = "",
         clock: Optional[ClockSync] = None) -> None:
    """Strip + account the trace block and metrics delta of an inbound
    Message (the receive chokepoint's twin of stamp()).  Always safe to
    call: both params are absent on untraced frames."""
    params = msg.msg_params
    mblk = params.pop(METRICS_KEY, None)
    if mblk is not None:
        # cohort rollup: ONE origin label, never the sender's id
        obs.registry().merge_delta(mblk, origin="remote")
    blk = params.pop(TRACE_KEY, None)
    if blk is None:
        return
    now = time.time()
    peer = int(blk.get("r", -1))
    delta = now - float(blk.get("t", now))
    if clock is not None:
        clock.observe(peer, delta, blk.get("e"))
        clock._m_frames.inc()
    else:
        obs.counter("trace_frames_total", backend=backend).inc()
    t = obs.tracer()
    if t is not None:
        t.instant("trace.recv", peer=peer, backend=backend,
                  round=blk.get("rd"), delta_s=round(delta, 6),
                  send_unix=blk.get("t"), send_ts_us=blk.get("m"),
                  digest=blk.get("d"))
