"""Stdlib-HTTP introspection endpoint — poke a long run without shell
access to its pid (ISSUE 7).

SIGUSR1 flight dumps (fedml_tpu/obs/flight.py) assume an operator can
signal the process; a torture run inside a container, a driver-launched
bench, or a remote async server often cannot be signaled.  One daemon
ThreadingHTTPServer (zero dependencies) serves:

    /metrics   Prometheus text exposition (the always-on registry)
    /rollup    obs.rollup() JSON — headline counters + artifact paths
    /healthz   200 + {status, pid, uptime_s} — the liveness probe
    /slo       the installed SLO engine's pack report (obs/slo.py);
               503 until one is installed (cli --slo or SloEngine.start).
               Carries ``scope: local|cluster`` (ISSUE 17): "local"
               means ONE rank's view — a dashboard must not read a
               worker's green as the cluster's
    /cluster   the cluster observatory (obs/cluster.py): per-rank
               liveness/epoch/last-fold age, the barrier straggler
               summary, the cluster SLO view, top counters — real
               cluster-wide data only on the coordinator
               (scope == "cluster")
    /flight    POST: trigger a flight-recorder dump, return its path.
               GET: return the LAST dump's path WITHOUT triggering —
               a metrics scraper or browser prefetch walking the
               endpoints must never mutate (the ISSUE-12 fix: the old
               ``do_POST = do_GET`` alias made every GET a dump)

Enable with ``FEDML_OBS_HTTP_PORT=<port>`` (picked up by
``obs.configure``/``configure_from_env``), the CLI's
``--obs_http_port``, or programmatically via ``obs.serve_http(port)``.
Port 0 binds an ephemeral port — the chosen one is on
``ObsHttpServer.port`` and in ``obs.rollup()``.  Binds 127.0.0.1 only:
this is an operator loopback hatch, not a service."""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class ObsHttpServer:
    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        from fedml_tpu import obs
        from fedml_tpu.obs import cluster as cluster_mod
        from fedml_tpu.obs import slo as slo_mod
        started = time.monotonic()

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, doc) -> None:
                self._send(code, json.dumps(doc).encode(),
                           "application/json")

            def do_GET(self):                        # noqa: N802 (stdlib)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    self._send(200,
                               obs.registry().to_prometheus().encode(),
                               "text/plain; version=0.0.4")
                elif path == "/rollup":
                    self._json(200, obs.rollup())
                elif path == "/healthz":
                    self._json(200, {"status": "ok", "pid": os.getpid(),
                                     "uptime_s": round(
                                         time.monotonic() - started, 3)})
                elif path == "/slo":
                    eng = slo_mod.active()
                    if eng is None:
                        self._json(503, {"error": "no SLO engine "
                                                  "installed (cli --slo "
                                                  "or SloEngine.start)"})
                    else:
                        doc = eng.report()
                        # scope marks whose truth this is: "local" =
                        # this rank only; "cluster" = the coordinator's
                        # folded view (ISSUE 17 satellite)
                        doc["scope"] = cluster_mod.scope()
                        self._json(200, doc)
                elif path == "/cluster":
                    self._json(200, cluster_mod.cluster_report())
                elif path == "/flight":
                    # READ-ONLY: report the last dump, never trigger —
                    # GETs must stay safe (scrapers, prefetchers)
                    f = obs.flight()
                    dumps = list(f.dumps) if f is not None else []
                    self._json(200, {"last_dump": (dumps[-1] if dumps
                                                   else None),
                                     "dumps": len(dumps),
                                     "trigger": "POST /flight"})
                else:
                    self._json(404, {"error": "unknown path"})

            def do_POST(self):                       # noqa: N802 (stdlib)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/flight":
                    dump = obs.dump_flight("http_trigger")
                    body = {"dump": dump,
                            "error": (None if dump is not None
                                      else "obs not configured "
                                           "(no --obs_dir)")}
                    self._json(200 if dump is not None else 503, body)
                else:
                    # every other endpoint is a read — POST falls
                    # through to the same representation
                    self.do_GET()

            def log_message(self, *a):               # silence stderr spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-http",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
