"""SLO engine — the judgment layer over the always-on metrics registry
(ISSUE 12).

PRs 2/7/11 made the federation emit rich raw telemetry; nothing yet
JUDGES it — "is this run healthy" was a human reading PERF.md.  This
module evaluates declarative SLO specs as LOW-OVERHEAD windowed deltas
over the existing :class:`MetricsRegistry`:

* a spec names a metric (name + label subset), an objective kind, a
  target, an evaluation window and a burn budget;
* evaluation reuses the registry's existing collection path — counter
  values and ``Histogram.cumulative()`` snapshots diffed per window,
  percentiles through the ONE shared ``quantile_from_cumulative``
  definition.  No new observation path, no per-event cost: the entire
  engine runs at evaluation time (a handful of snapshot diffs per
  window), which is how the <=1% overhead gate is met by construction;
* a breach increments ``slo_breaches_total{slo}``, sets
  ``slo_healthy{slo}`` to 0, fires a THROTTLED flight-recorder dump
  (one per ``dump_min_interval_s`` across all specs — a breach storm
  must not turn the recorder into the incident), and surfaces through
  ``obs.rollup()``, the Prometheus exporter, and the httpd ``/slo``
  endpoint.

Burn budget (the burn-rate idea at windowed-delta granularity): a spec
with ``burn_windows = n`` only FIRES after n consecutive breaching
evaluation windows — transient spikes spend budget, sustained burn
pages.  ``burn_windows = 1`` (the default) fires immediately.

The default pack (:func:`default_slo_pack`) encodes the serving spine's
health contract — committed-updates/sec floor, admission-latency p95,
reactor loop-lag p95, zero quarantines/evictions/sheds, zero
recv-thread deaths — with targets green on the clean ingest/connection
bench arms and breached by the chaos/storm arms (the ISSUE-12
acceptance shape; bench.py's schema-v11 ``slo`` block records the
per-arm verdicts).
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Optional, Sequence

from fedml_tpu.obs.metrics import (MetricsRegistry,
                                   quantile_from_cumulative)

SLO_KINDS = ("rate_min", "rate_max", "delta_max", "quantile_max",
             "gauge_max")

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One declarative objective over one metric family.

    ``metric`` + ``labels`` select series: every registry series with
    that name whose labels are a SUPERSET of ``labels`` contributes
    (counters/histograms merge across the matching label sets — a
    per-backend counter family evaluates as its federation-wide sum).

    Kinds (all evaluated on the delta since the previous evaluation
    window, except ``gauge_max`` which reads the live value):

        rate_min       counter delta / window_s  >= target
        rate_max       counter delta / window_s  <= target
        delta_max      counter delta              <= target
                       (target 0 == "this must never happen")
        quantile_max   windowed histogram q-quantile <= target
        gauge_max      current gauge value        <= target
    """
    name: str
    metric: str
    kind: str
    target: float
    labels: tuple = ()                  # (("k", "v"), ...) subset match
    q: float = 0.95                     # quantile_max only
    burn_windows: int = 1               # consecutive breaches to fire
    description: str = ""

    def __post_init__(self):
        if self.kind not in SLO_KINDS:
            raise ValueError(f"unknown SLO kind {self.kind!r} "
                             f"(choose one of {SLO_KINDS})")
        if self.burn_windows < 1:
            raise ValueError(
                f"burn_windows must be >= 1, got {self.burn_windows}")
        if self.kind == "quantile_max" and not (0.0 <= self.q <= 1.0):
            raise ValueError(f"q must be in [0, 1], got {self.q}")
        # labels arrive as a dict from callers; freeze to a sorted tuple
        # so the spec stays hashable/dataclass-frozen
        if isinstance(self.labels, dict):
            object.__setattr__(
                self, "labels",
                tuple(sorted((str(k), str(v))
                             for k, v in self.labels.items())))


def spec(name: str, metric: str, kind: str, target: float,
         labels: Optional[dict] = None, **kw) -> SloSpec:
    """Terse constructor (labels as a dict)."""
    return SloSpec(name=name, metric=metric, kind=kind, target=target,
                   labels=tuple(sorted((str(k), str(v))
                                       for k, v in (labels or {}).items())),
                   **kw)


def default_slo_pack() -> list[SloSpec]:
    """The serving spine's default health contract (ISSUE 12).

    Calibrated against the 2-core bench arms: every target is GREEN on
    the clean ingest/connections arms and at least one spec breaches on
    every chaos/storm arm —

    * chaos arms corrupt frames => ``no_quarantines`` breaches (the
      0.5% corrupt rate quarantines dozens of frames per arm);
    * storm arms shed/evict connections => ``no_evictions`` /
      ``no_sheds`` breach (the admission ceiling sheds by design under
      a storm — the SLO says an operator should LOOK, not that the
      server misbehaved);
    * a wedged server starves commits => ``committed_updates_floor``;
    * ``no_recv_thread_deaths`` is the PR-8 zero-deaths gate as a
      standing objective.

    Latency targets (admission p95, loop-lag p95) are deliberately
    loose operational ceilings (well above the clean arms' sub-ms
    steady state, below a pathological stall) — they page on collapse,
    not on box-load jitter."""
    return [
        spec("committed_updates_floor", "async_updates_committed_total",
             "rate_min", 1.0, burn_windows=3,
             description="the server must keep committing: >= 1 "
                         "update/sec sustained.  burn_windows=3 — a "
                         "single idle window between rounds spends "
                         "budget, three consecutive starved windows "
                         "page (and a one-evaluate bench arm judges "
                         "the whole arm as one window, where commits "
                         "always landed or the bench itself timed "
                         "out)"),
        spec("admission_p95", "comm_admission_seconds",
             "quantile_max", 1.0, q=0.95,
             description="transport hand-off -> buffer insert p95 "
                         "under 1 s (clean arms run sub-ms; a stalled "
                         "decode pool or reactor pushes seconds)"),
        spec("reactor_loop_lag_p95", "reactor_loop_lag_seconds",
             "quantile_max", 0.5, q=0.95,
             description="reactor event-loop iterations must not hold "
                         "the loop > 500 ms at p95"),
        spec("no_quarantines", "comm_frames_quarantined_total",
             "delta_max", 0.0,
             description="wire-level quarantines (CRC/undecodable) are "
                         "an incident signal, not steady state"),
        spec("no_update_quarantines", "async_updates_quarantined_total",
             "delta_max", 0.0,
             description="admission-screen quarantines mean an active "
                         "anomaly (attack or drift) — page an operator"),
        spec("no_evictions", "comm_connections_evicted_total",
             "delta_max", 0.0,
             description="stall/rate/shed evictions counted by the "
                         "reactor transport"),
        spec("no_sheds", "comm_uplinks_shed_total", "delta_max", 0.0,
             description="load-shedding engaged — capacity, not "
                         "correctness, but an operator should know"),
        spec("no_recv_thread_deaths", "comm_recv_thread_deaths_total",
             "delta_max", 0.0,
             description="recv-thread deaths == 0, the PR-8 gate as a "
                         "standing objective"),
    ]


DEFAULT_PACK_NAME = "serving_spine_default"


class SloEngine:
    """Evaluates a pack of :class:`SloSpec` over windowed registry
    deltas.  One instance = one evaluation scope (a bench arm primes a
    fresh engine; a long-running server starts one periodic engine).

    Thread-safe for the intended shapes: `evaluate()` serializes under
    the engine lock; the background `start()` thread is just a caller
    of `evaluate()`."""

    def __init__(self, specs: Sequence[SloSpec],
                 registry: Optional[MetricsRegistry] = None, *,
                 pack_name: str = DEFAULT_PACK_NAME,
                 dump_min_interval_s: float = 30.0):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names in pack: {names}")
        self.specs = list(specs)
        self.pack_name = pack_name
        self.dump_min_interval_s = float(dump_min_interval_s)
        self._registry = registry          # None = resolve obs.registry()
        self._lock = threading.Lock()
        self._state: dict[str, dict] = {}  # spec -> per-series snapshots
        self._t_prev: Optional[float] = None
        self._last_dump = -float("inf")
        self._breaches = {s.name: 0 for s in self.specs}
        self._burn = {s.name: 0 for s in self.specs}
        self._last = {s.name: {"status": "no_data", "value": None}
                      for s in self.specs}
        self._windows = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- registry access -----------------------------------------------------

    def _reg(self) -> MetricsRegistry:
        if self._registry is not None:
            return self._registry
        from fedml_tpu import obs
        return obs.registry()

    def _matching(self, s: SloSpec) -> list:
        want = set(s.labels)
        out = []
        for m in self._reg().metrics():
            if m.name == s.metric and want.issubset(set(m.labels)):
                out.append(m)
        return out

    def _snapshot(self, s: SloSpec) -> dict:
        """Per-series raw state for the spec's metric family."""
        snap = {}
        for m in self._matching(s):
            key = m.labels
            if m.kind == "histogram":
                snap[key] = m.cumulative()
            else:
                snap[key] = m.value
        return snap

    # -- evaluation ----------------------------------------------------------

    def prime(self) -> None:
        """Open the first evaluation window: snapshot every spec's
        series so the next `evaluate()` measures deltas from HERE, not
        from process birth."""
        with self._lock:
            for s in self.specs:
                self._state[s.name] = self._snapshot(s)
            self._t_prev = time.perf_counter()

    def _measure(self, s: SloSpec, prev: dict, cur: dict,
                 window_s: float):
        """(value, status) for one spec over one window, judged from
        the SAME `cur` snapshot that becomes the next window's baseline
        — an increment landing mid-evaluation is judged either this
        window or the next, never dropped between two reads.  Series
        absent from the registry => ("no_data", healthy): the default
        pack spans subsystems a given run may not exercise.
        Histogram series snapshot as cumulative lists, counters/gauges
        as floats."""
        if not cur:
            return None, "no_data"
        if s.kind == "gauge_max":
            vals = [v for v in cur.values() if not isinstance(v, list)]
            if not vals:
                return None, "no_data"
            value = max(vals)
            return value, ("breach" if value > s.target else "ok")
        if s.kind == "quantile_max":
            # merge windowed deltas across matching series bucket-wise
            # (same canonical ladder per name); a series whose ladder
            # mismatches the first one is skipped with a WARNING — a
            # partially-merged percentile must not pass silently as the
            # federation-wide one
            total_after, total_before = None, None
            for labels, after in cur.items():
                if not isinstance(after, list):
                    continue
                before = prev.get(labels)
                if not isinstance(before, list):
                    before = [(le, 0) for le, _ in after]
                if total_after is None:
                    total_after = [list(x) for x in after]
                    total_before = [list(x) for x in before]
                elif len(after) == len(total_after) and all(
                        a[0] == t[0] for a, t in zip(after, total_after)):
                    for i in range(len(after)):
                        total_after[i][1] += after[i][1]
                        total_before[i][1] += before[i][1]
                else:
                    log.warning(
                        "slo %s: series %s of %s has a different bucket "
                        "ladder — skipped from the merged quantile",
                        s.name, dict(labels), s.metric)
            if total_after is None or (total_after[-1][1]
                                       - total_before[-1][1]) <= 0:
                return None, "no_data"       # empty window: nothing to judge
            value = quantile_from_cumulative(
                [tuple(x) for x in total_before],
                [tuple(x) for x in total_after], s.q)
            return value, ("breach" if value > s.target else "ok")
        # counter kinds
        delta = 0.0
        for labels, v in cur.items():
            if isinstance(v, list):
                continue                     # kind/metric mismatch: skip
            p = prev.get(labels, 0.0)
            delta += v - (0.0 if isinstance(p, list) else float(p))
        if s.kind == "delta_max":
            return delta, ("breach" if delta > s.target else "ok")
        rate = delta / window_s if window_s > 0 else 0.0
        if s.kind == "rate_min":
            return rate, ("breach" if rate < s.target else "ok")
        return rate, ("breach" if rate > s.target else "ok")  # rate_max

    def evaluate(self) -> dict:
        """One evaluation pass over every spec (the window = time since
        prime()/the previous evaluate()).  Fires breach side effects and
        returns the report."""
        from fedml_tpu import obs
        with self._lock:
            now = time.perf_counter()
            if self._t_prev is None:
                # evaluate() without prime(): all-time window (counters
                # since birth) — still well-defined, window = 0 guards
                # the rate division
                self._t_prev = now
            window_s = max(0.0, now - self._t_prev)
            fired = []
            for s in self.specs:
                prev = self._state.get(s.name, {})
                cur = self._snapshot(s)      # ONE read: judged AND kept
                value, status = self._measure(s, prev, cur, window_s)
                if status == "breach":
                    self._burn[s.name] += 1
                    if self._burn[s.name] >= s.burn_windows:
                        self._breaches[s.name] += 1
                        fired.append((s, value))
                else:
                    self._burn[s.name] = 0
                self._last[s.name] = {"status": status, "value": value}
                # the judged snapshot IS the next window's baseline —
                # re-reading the registry here would drop any increment
                # that landed between the two reads from BOTH windows
                self._state[s.name] = cur
                obs.gauge("slo_healthy", slo=s.name).set(
                    0.0 if status == "breach" else 1.0)
                if value is not None:
                    obs.gauge("slo_value", slo=s.name).set(value)
            self._t_prev = now
            self._windows += 1
            want_dump = bool(fired) and (
                now - self._last_dump >= self.dump_min_interval_s)
            if want_dump:
                self._last_dump = now
        for s, value in fired:
            obs.counter("slo_breaches_total", slo=s.name).inc()
            obs.instant("slo.breach", slo=s.name, value=value,
                        target=s.target, window_s=window_s)
        if fired and want_dump:
            # throttled: ONE dump per interval names every spec that
            # fired this pass — a breach storm must not turn the flight
            # recorder into a second incident
            obs.dump_flight(
                "slo_breach:" + ",".join(s.name for s, _ in fired),
                extra={"slo": self.report()})
        return self.report()

    def report(self) -> dict:
        """JSON-able verdict: per-spec status/value/target/breaches +
        the pack rollup (`healthy`, `breaches`, `breached` names) —
        the /slo endpoint's body and the bench v11 `slo` arms' source."""
        with self._lock:
            slos = []
            for s in self.specs:
                last = self._last[s.name]
                slos.append({
                    "name": s.name,
                    "metric": s.metric,
                    "kind": s.kind,
                    "q": s.q if s.kind == "quantile_max" else None,
                    "target": s.target,
                    "value": last["value"],
                    "status": last["status"],
                    "burn": self._burn[s.name],
                    "burn_windows": s.burn_windows,
                    "breaches": self._breaches[s.name],
                })
            breached = [r["name"] for r in slos if r["breaches"] > 0]
            return {
                "pack": self.pack_name,
                "windows_evaluated": self._windows,
                "healthy": not breached,
                "breaches": sum(self._breaches.values()),
                "breached": breached,
                "slos": slos,
            }

    def arm_summary(self) -> dict:
        """Compact per-bench-arm verdict (the v11 `slo` block rows)."""
        r = self.report()
        return {"breaches": r["breaches"], "breached": r["breached"],
                "healthy": r["healthy"]}

    # -- background evaluator ------------------------------------------------

    def start(self, period_s: float = 5.0) -> "SloEngine":
        """Prime + evaluate every `period_s` on a daemon thread (the
        CLI's --slo mode).  Also installs this engine as the process's
        active one (the /slo endpoint and obs.rollup() read it)."""
        if period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {period_s}")
        if self._thread is not None:
            return self
        self.prime()
        install(self)
        self._stop.clear()

        def loop():
            while not self._stop.wait(period_s):
                try:
                    self.evaluate()
                except Exception:            # pragma: no cover - defensive
                    import logging
                    logging.getLogger(__name__).exception(
                        "slo evaluation failed")

        self._thread = threading.Thread(target=loop, name="obs-slo",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, final_evaluate: bool = True) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=10)
        self._thread = None
        if final_evaluate:
            self.evaluate()


# -- the process's active engine ---------------------------------------------
# One installable engine per process: /slo and obs.rollup() read it.
# Bench arms run their own short-lived engines without installing.

_active_lock = threading.Lock()
_active: Optional[SloEngine] = None


def install(engine: Optional[SloEngine]) -> None:
    global _active
    with _active_lock:
        _active = engine


def active() -> Optional[SloEngine]:
    return _active


def reset() -> None:
    """Test hook (obs.reset() calls through): drop the active engine."""
    global _active
    with _active_lock:
        eng = _active
        _active = None
    if eng is not None and eng._thread is not None:
        eng._stop.set()
