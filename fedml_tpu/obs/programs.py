"""Per-jit-program-family profile registry — live attribution of device
time, compiles, FLOPs and bytes to the program families the engines
hand-assemble (ISSUE 12).

The engine layer compiles 10+ distinct jitted program families
({resident, streaming, block-stream} x {fedavg, fednova, robust,
orderstat} + the async fold/commit/screened-fold pipeline — ROADMAP
item 5's matrix), but until now the only per-family numbers were
one-off manual ``jax.profiler`` sessions (the 47% MFU headline, the
PERF.md stage table).  This registry makes them STANDING artifacts:

* ``instrument(family, jitted_fn)`` wraps a compiled program so every
  dispatch counts (``program_dispatches_total{family}``) and times its
  host-side dispatch wall (``program_dispatch_seconds{family}`` on the
  sub-ms canonical ladder).  The wrapper passes ``lower``/attribute
  access through to the wrapped jit, so AOT consumers
  (tools/hlo_copy_audit.py) keep working, and it NEVER touches values
  — obs-on/off results stay bitwise identical (the existing pins);
* while a wrapped program runs, its family is the thread's CURRENT
  family — the ``jax.monitoring`` compile listener
  (fedml_tpu/obs/__init__.py) reads it to attribute backend-compile
  counts/seconds per family instead of one global pair (fallback label
  ``unattributed``), so a recompile storm names its culprit;
* an HLO flop/byte census joins in: either live (``enable_census()``
  — one extra AOT lower+compile per family on its first dispatch,
  reading ``compiled.cost_analysis()``; default OFF so the hot paths
  and tier-1 pay nothing) or from a ``tools/hlo_copy_audit.py --out``
  artifact (``load_census()``), giving per-family and whole-run
  MFU/bytes-moved gauges;
* every family maps to a canonical timeline stage
  (obs/timeline.py PROGRAM_FAMILY_STAGES), so the profile table groups
  into the same taxonomy as the round critical path.

``report(since=snapshot())`` is the standing replacement for the
manual profile session: per-family dispatch counts, wall p50/p95,
compile seconds, flops/bytes per dispatch, and MFU against
``peak_flops()`` (FEDML_PEAK_FLOPS env override; a documented
order-of-magnitude CPU heuristic otherwise) — bench.py's schema-v11
``programs`` block and PERF.md's "Performance observatory" table both
read it.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

from fedml_tpu.obs.metrics import quantile_from_cumulative

ENV_CENSUS = "FEDML_OBS_CENSUS"
ENV_PEAK_FLOPS = "FEDML_PEAK_FLOPS"

_lock = threading.Lock()
_families: dict[str, "ProgramFamily"] = {}
_tls = threading.local()
_census_enabled: Optional[bool] = None      # None = resolve env lazily


def _stage_of(family: str) -> str:
    from fedml_tpu.obs.timeline import PROGRAM_FAMILY_STAGES
    return PROGRAM_FAMILY_STAGES.get(family, "other")


class ProgramFamily:
    """Profile state of one program family.  Metric handles re-resolve
    when obs.reset() swapped the registry (identity check per call —
    cheaper than a registry lookup, correct across test resets)."""

    def __init__(self, name: str):
        self.name = name
        self.stage = _stage_of(name)
        self.flops_per_dispatch: Optional[float] = None
        self.bytes_per_dispatch: Optional[float] = None
        self.census_source: Optional[str] = None
        self._reg = None
        self._ctr = None
        self._hist = None

    def _handles(self):
        from fedml_tpu import obs
        reg = obs.registry()
        if reg is not self._reg:
            # a registry swap means obs.reset() ran: re-enter the family
            # table too, so a pre-reset wrapper's next dispatch shows up
            # in families()/snapshot()/report() again — without this the
            # fresh registry's dispatch counters would tick while the
            # profile report silently omitted the family
            with _lock:
                _families.setdefault(self.name, self)
            self._reg = reg
            self._ctr = reg.counter("program_dispatches_total",
                                    family=self.name)
            self._hist = reg.histogram("program_dispatch_seconds",
                                       family=self.name)
        return self._ctr, self._hist

    def observe_dispatch(self, seconds: float) -> None:
        ctr, hist = self._handles()
        hist.observe(seconds)
        ctr.inc()

    def attach_census(self, flops: Optional[float] = None,
                      bytes_accessed: Optional[float] = None,
                      source: str = "attached") -> None:
        if flops is not None:
            self.flops_per_dispatch = float(flops)
        if bytes_accessed is not None:
            self.bytes_per_dispatch = float(bytes_accessed)
        self.census_source = source


def register(family: str) -> ProgramFamily:
    with _lock:
        fam = _families.get(family)
        if fam is None:
            fam = _families[family] = ProgramFamily(family)
        return fam


def families() -> dict[str, ProgramFamily]:
    with _lock:
        return dict(_families)


def current() -> Optional[str]:
    """The family whose wrapped program is executing on THIS thread
    (the compile listener's attribution source), or None."""
    return getattr(_tls, "family", None)


def reset() -> None:
    """Test hook (obs.reset() calls through): fresh family table +
    cleared thread-local.  Wrappers built before the reset re-register
    their family on next dispatch."""
    with _lock:
        _families.clear()
    _tls.family = None


# -- census ------------------------------------------------------------------

def enable_census(on: bool = True) -> None:
    global _census_enabled
    _census_enabled = bool(on)


def census_enabled() -> bool:
    global _census_enabled
    if _census_enabled is None:
        _census_enabled = os.environ.get(ENV_CENSUS, "") not in ("", "0")
    return _census_enabled


def cost_analysis_of(compiled) -> tuple[Optional[float], Optional[float]]:
    """(flops, bytes_accessed) from a jax Compiled's cost analysis —
    handles the dict and the per-partition-list shapes across jax
    versions; (None, None) when the backend exposes nothing."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None, None
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed")
    return (float(flops) if flops is not None else None,
            float(nbytes) if nbytes is not None else None)


def load_census(report: Any) -> int:
    """Join an hlo_copy_audit artifact (path or loaded dict) into the
    registry: per family, flops/bytes summed over the family's
    programs.  Returns how many families gained census numbers."""
    import json
    if isinstance(report, str):
        with open(report) as f:
            report = json.load(f)
    n = 0
    for family, doc in (report.get("families") or {}).items():
        progs = doc.get("programs") or {}
        flops = [p.get("flops") for p in progs.values()
                 if p.get("flops") is not None]
        nbytes = [p.get("bytes_accessed") for p in progs.values()
                  if p.get("bytes_accessed") is not None]
        if not flops and not nbytes:
            continue
        register(family).attach_census(
            flops=sum(flops) if flops else None,
            bytes_accessed=sum(nbytes) if nbytes else None,
            source="hlo_copy_audit")
        n += 1
    return n


def peak_flops() -> Optional[float]:
    """Peak-FLOP/s denominator for MFU.  FEDML_PEAK_FLOPS overrides
    (the chip-attached runs set the real per-chip number); otherwise a
    documented order-of-magnitude CPU heuristic — cores x 3.2 GHz x 16
    f32 FLOP/cycle (one AVX2 FMA port's worth) — good enough to rank
    families and watch trends on the 2-core CI box, NOT a calibrated
    utilization claim (PERF.md says so next to the table)."""
    env = os.environ.get(ENV_PEAK_FLOPS)
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax
        if jax.default_backend() != "cpu":
            return None              # no honest default for unknown chips
    except Exception:
        return None
    return float(os.cpu_count() or 1) * 3.2e9 * 16


# -- the dispatch wrapper ----------------------------------------------------

class InstrumentedProgram:
    """Transparent wrapper around one jitted program: counts + times
    each dispatch, marks the thread's current family for compile
    attribution, and (census mode) runs a one-time AOT cost analysis.
    `lower` and every other attribute delegate to the wrapped jit, so
    AOT consumers (hlo_copy_audit's ``fn.lower(*args).compile()``) see
    the real thing."""

    __slots__ = ("_fn", "_family", "_census_tried")

    def __init__(self, fn, family: ProgramFamily):
        self._fn = fn
        self._family = family
        self._census_tried = False

    @property
    def inner(self):
        return self._fn

    @property
    def family(self) -> str:
        return self._family.name

    def __call__(self, *args, **kwargs):
        fam = self._family
        if (not self._census_tried and fam.flops_per_dispatch is None
                and census_enabled()):
            self._try_census(args, kwargs)
        prev = getattr(_tls, "family", None)
        _tls.family = fam.name
        t0 = time.perf_counter()
        try:
            return self._fn(*args, **kwargs)
        finally:
            dt = time.perf_counter() - t0
            _tls.family = prev
            fam.observe_dispatch(dt)

    def _try_census(self, args, kwargs) -> None:
        """One-time AOT lower+compile with the live call's args (shapes
        only are read — donation happens at execution, so the caller's
        buffers are untouched).  Census mode is opt-in: this pays one
        extra compile per family, amortized by the persistent compile
        cache."""
        self._census_tried = True
        fn = self._fn
        if not hasattr(fn, "lower"):
            return
        try:
            compiled = fn.lower(*args, **kwargs).compile()
        except Exception:
            return
        flops, nbytes = cost_analysis_of(compiled)
        if flops is not None or nbytes is not None:
            self._family.attach_census(flops=flops, bytes_accessed=nbytes,
                                       source="live")

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self):
        return (f"InstrumentedProgram({self._family.name}, "
                f"{self._fn!r})")


def instrument(family: str, fn) -> InstrumentedProgram:
    """Wrap one jitted program under `family`.  Idempotent-ish: an
    already-instrumented fn is re-tagged, not double-wrapped (double
    timing would inflate the family's dispatch walls)."""
    if isinstance(fn, InstrumentedProgram):
        fn = fn.inner
    return InstrumentedProgram(fn, register(family))


# -- windowed reporting ------------------------------------------------------

def snapshot() -> dict:
    """Opaque window baseline for report(since=...): per-family
    dispatch counts + histogram cumulative states + a wall-clock
    stamp."""
    from fedml_tpu import obs
    reg = obs.registry()
    state: dict = {"t": time.perf_counter(), "families": {}}
    for name, fam in families().items():
        ctr = reg.counter("program_dispatches_total", family=name)
        hist = reg.histogram("program_dispatch_seconds", family=name)
        state["families"][name] = {
            "dispatches": ctr.value,
            "cumulative": hist.cumulative(),
            "wall": hist.sum,
            "compile_seconds": reg.counter("jit_compile_seconds_total",
                                           family=name).value,
        }
    return state


def report(since: Optional[dict] = None, *,
           peak: Optional[float] = None,
           publish_gauges: bool = True) -> dict:
    """Per-family profile over the window since `since` (a snapshot();
    None = since process start / family registration).  Returns

        {"window_s", "peak_flops", "families": [
            {family, stage, dispatches, dispatch_wall_s,
             dispatch_p50_s, dispatch_p95_s, compile_seconds,
             flops_per_dispatch, bytes_per_dispatch, flops_total,
             bytes_total, mfu}, ...],
         "processes": [...],        # per-process breakdown rows from a
                                    # multihost run's origin-labeled
                                    # merged series (ISSUE 13)
         "total": {...}}            # the whole-run row

    MFU = flops_total / (window_s x peak_flops) — null without census
    numbers or a peak estimate.  `publish_gauges` mirrors the rows into
    ``program_mfu{family}`` / ``program_bytes_moved_total{family}``
    gauges (the "live MFU accounting" surface)."""
    from fedml_tpu import obs
    reg = obs.registry()
    if peak is None:
        peak = peak_flops()
    t0 = (since or {}).get("t")
    window_s = (time.perf_counter() - t0) if t0 is not None else None
    prev = (since or {}).get("families", {})
    rows = []
    for name, fam in sorted(families().items()):
        ctr = reg.counter("program_dispatches_total", family=name)
        hist = reg.histogram("program_dispatch_seconds", family=name)
        p = prev.get(name, {})
        dispatches = ctr.value - p.get("dispatches", 0.0)
        wall = hist.sum - p.get("wall", 0.0)
        before = p.get("cumulative")
        after = hist.cumulative()
        if dispatches <= 0:
            continue                 # idle family: not in this window
        flops_total = (fam.flops_per_dispatch * dispatches
                       if fam.flops_per_dispatch is not None else None)
        bytes_total = (fam.bytes_per_dispatch * dispatches
                       if fam.bytes_per_dispatch is not None else None)
        mfu = None
        if (flops_total is not None and peak and window_s
                and window_s > 0):
            mfu = flops_total / (window_s * peak)
        # windowed like everything else in the row: compiles BEFORE the
        # snapshot (the cold-start storm) must not re-report in later
        # windows' recompile attribution
        compile_s = (reg.counter("jit_compile_seconds_total",
                                 family=name).value
                     - p.get("compile_seconds", 0.0))
        rows.append({
            "family": name,
            "stage": fam.stage,
            "dispatches": int(dispatches),
            "dispatch_wall_s": round(wall, 6),
            "dispatch_p50_s": quantile_from_cumulative(before, after, 0.5),
            "dispatch_p95_s": quantile_from_cumulative(before, after,
                                                       0.95),
            "compile_seconds": round(compile_s, 4),
            "flops_per_dispatch": fam.flops_per_dispatch,
            "bytes_per_dispatch": fam.bytes_per_dispatch,
            "flops_total": flops_total,
            "bytes_total": bytes_total,
            "mfu": (round(mfu, 6) if mfu is not None else None),
            "census_source": fam.census_source,
        })
        if publish_gauges:
            if mfu is not None:
                obs.gauge("program_mfu", family=name).set(mfu)
            if bytes_total is not None:
                obs.gauge("program_bytes_moved_total",
                          family=name).set(bytes_total)
    total_flops = [r["flops_total"] for r in rows
                   if r["flops_total"] is not None]
    total_bytes = [r["bytes_total"] for r in rows
                   if r["bytes_total"] is not None]
    total_mfu = None
    if total_flops and peak and window_s and window_s > 0:
        total_mfu = sum(total_flops) / (window_s * peak)
    total = {
        "dispatches": sum(r["dispatches"] for r in rows),
        "dispatch_wall_s": round(sum(r["dispatch_wall_s"]
                                     for r in rows), 6),
        "flops_total": sum(total_flops) if total_flops else None,
        "bytes_total": sum(total_bytes) if total_bytes else None,
        "mfu": (round(total_mfu, 6) if total_mfu is not None else None),
    }
    if publish_gauges and total_mfu is not None:
        obs.gauge("program_mfu", family="_total").set(total_mfu)
    return {
        "window_s": (round(window_s, 3) if window_s is not None
                     else None),
        "peak_flops": peak,
        "families": rows,
        "processes": _per_process_rows(reg),
        "total": total,
    }


def _per_process_rows(reg) -> list:
    """Per-process breakdown (ISSUE 13): an N-process multihost run
    folds each rank's metric deltas into rank 0's registry under an
    ``origin`` label (MultihostRunner._rollup_metrics — the PR-7
    remote-fold shape, so no gauge is last-writer-wins across
    processes); these rows surface the merged per-family dispatch
    series per origin.  All-time, not windowed: the fold happens once
    at run end, so a window baseline taken mid-run has nothing to
    subtract."""
    from fedml_tpu.obs.metrics import MERGE_ORIGIN_LABEL
    counts: dict[tuple, float] = {}
    hists: dict[tuple, object] = {}
    for m in reg.metrics():
        labels = dict(m.labels)
        fam = labels.get("family")
        org = labels.get(MERGE_ORIGIN_LABEL)
        if fam is None or org is None:
            continue
        if m.name == "program_dispatches_total":
            counts[(fam, org)] = m.value
        elif m.name == "program_dispatch_seconds":
            hists[(fam, org)] = m
    rows = []
    for (fam, org) in sorted(counts):
        row = {"family": fam, "process": org,
               "dispatches": int(counts[(fam, org)]),
               "dispatch_wall_s": None, "dispatch_p50_s": None,
               "dispatch_p95_s": None}
        h = hists.get((fam, org))
        if h is not None:
            after = h.cumulative()
            row.update(
                dispatch_wall_s=round(h.sum, 6),
                dispatch_p50_s=quantile_from_cumulative(None, after,
                                                        0.5),
                dispatch_p95_s=quantile_from_cumulative(None, after,
                                                        0.95))
        rows.append(row)
    return rows


def format_table(rep: dict) -> str:
    """Human-readable per-family table (PERF.md's standing artifact)."""
    lines = [f"{'family':<24}{'stage':<8}{'disp':>8}{'wall s':>10}"
             f"{'p95 ms':>9}{'GFLOP/disp':>12}{'MFU':>8}"]
    for r in rep["families"]:
        gf = (f"{r['flops_per_dispatch'] / 1e9:.3f}"
              if r["flops_per_dispatch"] is not None else "-")
        mfu = f"{r['mfu']:.2%}" if r["mfu"] is not None else "-"
        lines.append(
            f"{r['family']:<24}{r['stage']:<8}{r['dispatches']:>8}"
            f"{r['dispatch_wall_s']:>10.3f}"
            f"{r['dispatch_p95_s'] * 1e3:>9.2f}{gf:>12}{mfu:>8}")
    t = rep["total"]
    mfu = f"{t['mfu']:.2%}" if t["mfu"] is not None else "-"
    lines.append(f"{'TOTAL':<24}{'':<8}{t['dispatches']:>8}"
                 f"{t['dispatch_wall_s']:>10.3f}{'':>9}{'':>12}{mfu:>8}")
    return "\n".join(lines)
