"""Cluster observatory — live cross-rank telemetry, barrier straggler
attribution, cluster-scoped SLOs, and coordinated incident dumps
(ISSUE 17).

PRs 13-16 made the cluster real but left the judgment layer (SLO
engine, flight recorder, metrics registry) one-process-at-a-time: rank
0 only folded peer metrics at end-of-run, SLO packs evaluated locally,
and nothing named which rank gated which commit barrier.  This module
is the cluster-wide view, riding the channels that already exist — no
new connections:

* **Live telemetry plane.**  Workers piggyback bounded metric deltas
  (the PR-7 ``__fedml_metrics__`` shape: ``delta_snapshot`` docs) on
  frames they already send — heartbeat headers on the ElasticChannel,
  a self-describing payload trailer on HostChannel allgathers — and
  rank 0 folds them continuously under ``origin="host<i>"``
  (`fold_remote`).  The piggyback attaches ONLY when an obs dir is
  configured (`telemetry_enabled`), so the obs-off wire bytes are
  byte-identical to the pre-observatory channel — the bitwise anchors
  never see it.

* **Barrier ledger.**  Rank 0 stamps per-rank arrival times at every
  gather/allgather/exchange and `note_barrier` turns them into ledger
  entries: ``round_gating_rank`` (the last arrival — the rank the
  whole commit waited on), ``gate_margin_s`` (how far behind the
  second-latest it was), and per-rank waits observed into
  ``multihost_barrier_wait_seconds{rank}``.  Always on: the ledger is
  local bookkeeping with zero wire impact, which is what lets the
  spawned-cluster test pins assert it without enabling obs.

* **Cluster SLO pack.**  `cluster_slo_pack` evaluated on rank 0 over
  the folded registry (committed-rounds/sec floor, barrier-wait p95
  ceiling, view-change latency ceiling, zero rank deaths), with
  `cluster_slo_report` attaching an **attribution** block naming the
  dead rank(s) and the dominant gating rank — green on clean arms,
  breaching with the culprit named on the chaos arm.

* **Coordinated incident dumps.**  A view change, rank death, or
  cluster-SLO breach on the coordinator routes through
  `maybe_coordinated_dump`: one throttle window (like PR 12's flight
  dumps), a local flight dump, and a registered broadcaster (the
  ElasticChannel's DUMP control frame) so every surviving rank
  snapshots the same incident window into its own obs dir.

Layering: this module must NOT import ``parallel.multihost`` — the
channels produce arrivals/deltas and register the dump broadcaster;
this module folds and judges.  `/cluster` (httpd) and the bench
``straggler`` block read the report builders here.
"""

from __future__ import annotations

import collections
import json
import logging
import struct
import threading
import time
from typing import Callable, Optional

from fedml_tpu import obs
from fedml_tpu.obs import metrics as _metrics
from fedml_tpu.obs import slo as _slo

log = logging.getLogger(__name__)

# Sidecar trailer marker for HostChannel payload piggybacks.  The frame
# is ``payload + delta_json + <u32 len(delta_json)> + SIDECAR_MAGIC``;
# self-describing, so a receiver strips it iff present (mixed
# enablement across ranks stays safe) and an astronomically-unlikely
# payload collision is rejected by the JSON/schema check.
SIDECAR_MAGIC = b"\x00fmlMET1"
# Per-beat piggyback budget: a delta bigger than this waits for the
# end-of-run rollup instead of bloating a control frame.
SIDECAR_CAP_BYTES = 64 * 1024
# Coordinated dumps share one throttle window (PR 12's flight-dump
# discipline): a breach storm yields one synchronized artifact set,
# not hundreds.
DUMP_MIN_INTERVAL_S = 30.0
# A rank whose last heartbeat is older than this reads as not-alive in
# the /cluster liveness view.
LIVENESS_STALE_S = 10.0
_MAX_LEDGER = 512


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.rank: Optional[int] = None
        self.world: Optional[int] = None
        self.elastic = False
        # sticky: once a rank-0 channel registers, this process is the
        # coordinator for scope/report purposes — in-process tests run
        # every rank's channel in one process and the workers must not
        # demote the view
        self.is_coord = False
        self.hb_last: dict[int, float] = {}     # rank -> monotonic
        self.fold_last: dict[int, float] = {}   # rank -> monotonic
        self.ledger: collections.deque = collections.deque(
            maxlen=_MAX_LEDGER)
        self.gating_counts: dict[int, int] = {}
        self.engine: Optional[_slo.SloEngine] = None
        self.last_dump = float("-inf")
        self.broadcaster: Optional[Callable[[str], None]] = None


_state = _State()


def reset() -> None:
    """Fresh observatory state (wired into obs.reset for tests)."""
    global _state
    _state = _State()


def telemetry_enabled() -> bool:
    """Piggyback/DUMP frames attach only when an obs dir is configured
    — the obs-off wire stays byte-identical by construction."""
    return obs.enabled()


def scope() -> str:
    """"cluster" when this process coordinates (its folded registry
    speaks for every rank), else "local" — the /slo + /cluster field
    that keeps one rank's green from masquerading as the cluster's."""
    return "cluster" if _state.is_coord else "local"


def set_role(rank: int, world: int, *, elastic: bool = False) -> None:
    """Channel construction hook: record this process's place in the
    cluster.  Rank 0 becomes the coordinator: it gets the cluster SLO
    engine, primed HERE so the evaluation window spans the run."""
    st = _state
    with st.lock:
        if rank == 0:
            st.rank, st.world, st.elastic = 0, int(world), bool(elastic)
            st.is_coord = True
            if st.engine is None:
                st.engine = _slo.SloEngine(cluster_slo_pack(),
                                           pack_name="cluster")
                st.engine.prime()
        elif st.rank is None:
            st.rank, st.world, st.elastic = (int(rank), int(world),
                                             bool(elastic))


def set_dump_broadcaster(fn: Optional[Callable[[str], None]]) -> None:
    """Register the channel-owned fan-out (ElasticChannel's DUMP
    frame).  None unregisters (channel close)."""
    with _state.lock:
        _state.broadcaster = fn


# ---------------------------------------------------------------------------
# live telemetry plane
# ---------------------------------------------------------------------------

def note_heartbeat(rank: int) -> None:
    with _state.lock:
        _state.hb_last[int(rank)] = time.monotonic()


def fold_remote(rank: int, delta) -> None:
    """Fold a peer's piggybacked ``delta_snapshot`` doc into this
    process's registry under ``origin="host<rank>"`` — the same merge
    the end-of-run rollup uses, so live folds and the final rollup
    land in the same series."""
    if not isinstance(delta, dict) or not delta.get("metrics"):
        return
    try:
        obs.registry().merge_delta(delta, origin=f"host{int(rank)}")
    except Exception:
        log.warning("cluster observatory: dropping unfoldable delta "
                    "from rank %s", rank, exc_info=True)
        return
    with _state.lock:
        _state.fold_last[int(rank)] = time.monotonic()


def attach_sidecar(payload: bytes, delta: Optional[dict]) -> bytes:
    """Append a self-describing metrics trailer to an allgather
    payload (worker side).  Returns `payload` unchanged when there is
    nothing to ship or the delta exceeds the frame budget."""
    if not delta or not delta.get("metrics"):
        return payload
    blob = json.dumps(delta, sort_keys=True).encode()
    if len(blob) > SIDECAR_CAP_BYTES:
        return payload
    return (payload + blob + struct.pack("<I", len(blob))
            + SIDECAR_MAGIC)


def split_sidecar(frame: bytes) -> tuple[bytes, Optional[dict]]:
    """Strip (payload, delta) from a possibly-trailered frame.  Frames
    without the trailer pass through untouched — receivers call this
    unconditionally, which is what makes mixed obs-on/obs-off ranks
    safe and keeps the broadcast payloads bitwise-clean."""
    tail = len(SIDECAR_MAGIC) + 4
    if len(frame) < tail or not frame.endswith(SIDECAR_MAGIC):
        return frame, None
    (n,) = struct.unpack_from("<I", frame, len(frame) - tail)
    end = len(frame) - tail
    if n == 0 or n > end:
        return frame, None
    try:
        delta = json.loads(frame[end - n:end].decode())
    except (UnicodeDecodeError, ValueError):
        return frame, None
    if not isinstance(delta, dict) or delta.get("schema") != 1:
        return frame, None
    return frame[:end - n], delta


# ---------------------------------------------------------------------------
# barrier ledger
# ---------------------------------------------------------------------------

def note_barrier(kind: str, seq: int, round_idx: Optional[int],
                 arrivals: dict) -> Optional[dict]:
    """Record one barrier's per-rank arrival stamps (rank 0 only —
    the star's single observer).  ``arrivals`` maps rank -> monotonic
    arrival time; the gate is the LAST arrival, and everyone else's
    wait is how long they idled behind it."""
    if len(arrivals) < 2:
        return None
    order = sorted(arrivals.items(), key=lambda kv: (kv[1], kv[0]))
    t_gate = order[-1][1]
    gating = int(order[-1][0])
    margin = float(t_gate - order[-2][1])
    waits = {int(r): float(t_gate - t) for r, t in arrivals.items()}
    entry = {
        "kind": str(kind),
        "seq": int(seq),
        "round": None if round_idx is None else int(round_idx),
        "round_gating_rank": gating,
        "gate_margin_s": margin,
        "waits_s": {str(r): waits[r] for r in sorted(waits)},
        "t_unix": time.time(),
    }
    with _state.lock:
        _state.ledger.append(entry)
        _state.gating_counts[gating] = (
            _state.gating_counts.get(gating, 0) + 1)
    for r in sorted(waits):
        obs.histogram("multihost_barrier_wait_seconds",
                      rank=str(r)).observe(waits[r])
    return entry


def barrier_ledger() -> list[dict]:
    with _state.lock:
        return list(_state.ledger)


def _quantile(xs: list[float], q: float) -> float:
    s = sorted(xs)
    return float(s[min(len(s) - 1, int(round(q * (len(s) - 1))))])


def straggler_summary(tail: int = 8) -> dict:
    """The bench/``/cluster`` straggler block: who gates, by how much,
    and each rank's wait distribution."""
    with _state.lock:
        entries = list(_state.ledger)
        gating = dict(_state.gating_counts)
    per_rank: dict[str, list[float]] = {}
    for e in entries:
        for r, w in e["waits_s"].items():
            per_rank.setdefault(r, []).append(w)
    top = max(gating, key=lambda r: gating[r]) if gating else None
    return {
        "barriers": len(entries),
        "gating_counts": {str(r): gating[r] for r in sorted(gating)},
        "top_gating_rank": top,
        "worst_gate_margin_s": max(
            (e["gate_margin_s"] for e in entries), default=0.0),
        "per_rank_wait_s": {
            r: {"p50": _quantile(ws, 0.5), "p95": _quantile(ws, 0.95),
                "max": max(ws)}
            for r, ws in sorted(per_rank.items())},
        "recent": entries[-int(tail):],
    }


# ---------------------------------------------------------------------------
# cluster SLO pack
# ---------------------------------------------------------------------------

def cluster_slo_pack() -> list:
    """Cluster-scoped objectives, judged on rank 0 over the FOLDED
    registry (local + piggybacked/rolled-up peer series)."""
    return [
        _slo.spec("cluster_round_floor",
                  "multihost_rounds_committed_total", "rate_min", 0.01,
                  description="cluster commits rounds at all: floor on "
                              "committed rounds/sec across the window"),
        _slo.spec("cluster_barrier_wait_p95",
                  "multihost_barrier_wait_seconds", "quantile_max", 2.5,
                  q=0.95,
                  description="straggler budget: p95 of per-rank commit-"
                              "barrier waits (the ledger's histogram)"),
        _slo.spec("cluster_view_change_p95",
                  "multihost_view_change_seconds", "quantile_max", 5.0,
                  q=0.95,
                  description="membership repair latency: p95 of view-"
                              "change (eviction -> survivors re-tasked)"),
        _slo.spec("cluster_no_rank_deaths",
                  "multihost_rank_deaths_total", "delta_max", 0.0,
                  description="zero rank deaths in the window (any "
                              "eviction breaches, naming the rank)"),
    ]


def _dead_ranks() -> list[str]:
    dead = []
    for m in obs.registry().metrics():
        if m.name != "multihost_rank_deaths_total":
            continue
        labels = dict(m.labels)
        if "rank" in labels and m.value > 0:
            dead.append(labels["rank"])
    return sorted(set(dead))


def attribution() -> dict:
    """Who to blame: dead ranks from the death counters, the dominant
    gating rank from the ledger, and each rank's wait p95."""
    summary = straggler_summary(tail=0)
    return {
        "dead_ranks": _dead_ranks(),
        "gating_rank": summary["top_gating_rank"],
        "gating_counts": summary["gating_counts"],
        "per_rank_wait_p95_s": {
            r: s["p95"] for r, s in summary["per_rank_wait_s"].items()},
    }


def cluster_slo_report() -> Optional[dict]:
    """Evaluate the cluster pack (rank 0 only; None elsewhere) and
    attach the attribution block.  A breached evaluation routes
    through the coordinated-dump chokepoint so every survivor
    snapshots the incident."""
    with _state.lock:
        eng = _state.engine
    if eng is None:
        return None
    eng.evaluate()
    rep = eng.report()
    rep["scope"] = "cluster"
    rep["attribution"] = attribution()
    if rep.get("breached"):
        maybe_coordinated_dump(
            "cluster_slo:" + ",".join(sorted(rep["breached"])))
    return rep


# ---------------------------------------------------------------------------
# coordinated incident dumps
# ---------------------------------------------------------------------------

def maybe_coordinated_dump(reason: str) -> bool:
    """THE coordinator-side incident chokepoint: one throttle window
    covering view changes, rank deaths, and SLO breaches.  Fires a
    local flight dump plus the registered channel broadcaster (the
    ElasticChannel DUMP frame) so every surviving rank snapshots the
    same window.  No-op (False) when telemetry is off — no obs dir
    means no artifact to write and no extra wire frames."""
    if not telemetry_enabled():
        return False
    now = time.monotonic()
    with _state.lock:
        if now - _state.last_dump < DUMP_MIN_INTERVAL_S:
            return False
        _state.last_dump = now
        bc = _state.broadcaster
    obs.counter("multihost_coordinated_dumps_total").inc()
    obs.dump_flight(f"coordinated:{reason}")
    if bc is not None:
        try:
            bc(str(reason))
        except Exception:
            log.warning("cluster observatory: dump broadcast failed",
                        exc_info=True)
    return True


# ---------------------------------------------------------------------------
# reports + export
# ---------------------------------------------------------------------------

def _top_counters(n: int = 10) -> list[dict]:
    rows = []
    for m in obs.registry().metrics():
        if not isinstance(m, _metrics.Counter):
            continue
        rows.append({"name": m.name, "labels": dict(m.labels),
                     "value": m.value})
    rows.sort(key=lambda r: -r["value"])
    return rows[:n]


def _epoch_by_rank() -> dict[int, float]:
    out: dict[int, float] = {}
    for m in obs.registry().metrics():
        if m.name != "multihost_epoch":
            continue
        labels = dict(m.labels)
        if "rank" in labels:
            try:
                out[int(labels["rank"])] = m.value
            except (TypeError, ValueError):
                continue
    return out


def cluster_report() -> dict:
    """The ``/cluster`` endpoint document: per-rank liveness (heartbeat
    age), telemetry freshness (last-fold age), epoch, the straggler
    summary, the cluster SLO view, and the hottest counters."""
    now = time.monotonic()
    st = _state
    with st.lock:
        rank, world, elastic = st.rank, st.world, st.elastic
        hb = dict(st.hb_last)
        folds = dict(st.fold_last)
        eng = st.engine
    epochs = _epoch_by_rank()
    known = set(hb) | set(folds) | set(epochs)
    if rank is not None:
        known.add(rank)
    ranks = {}
    for r in sorted(known):
        hb_age = (now - hb[r]) if r in hb else None
        fold_age = (now - folds[r]) if r in folds else None
        ranks[str(r)] = {
            "self": r == rank,
            "alive": (r == rank
                      or (hb_age is not None
                          and hb_age < LIVENESS_STALE_S)),
            "last_heartbeat_age_s": hb_age,
            "last_fold_age_s": fold_age,
            "epoch": epochs.get(r),
        }
    doc = {
        "scope": scope(),
        "rank": rank,
        "world": world,
        "elastic": elastic,
        "ranks": ranks,
        "straggler": straggler_summary(),
        "top_counters": _top_counters(),
    }
    if eng is not None:
        slo_doc = eng.report()
        slo_doc["scope"] = "cluster"
        doc["slo"] = slo_doc
    return doc


def export_dir(path) -> None:
    """Write barrier_ledger.json next to the other obs artifacts
    (obs.export calls this); silent no-op with an empty ledger."""
    entries = barrier_ledger()
    if not entries:
        return
    doc = {"schema": 1, "rank": _state.rank,
           "summary": straggler_summary(), "entries": entries}
    import os
    with open(os.path.join(str(path), "barrier_ledger.json"), "w",
              encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
