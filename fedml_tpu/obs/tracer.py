"""Span tracer — nestable wall-clock spans with Chrome-trace and JSONL
exporters.

One tracer per process collects *complete* trace events ("ph": "X") from
every thread: the round loop, comm recv loops, and the prefetch upload
workers all record against the same perf_counter epoch, so a
`h2d.upload` span produced on the background thread lines up on the same
timeline as the `round.block_step` spans that consumed it — exactly the
view needed to see whether uploads hid behind compute.  Nesting needs no
explicit parent links: Chrome/Perfetto reconstruct the stack per `tid`
from ts/dur containment.

Overhead when tracing is enabled: two perf_counter calls plus one
locked deque append per span.  The event buffer is a fixed-size ring
(default 200k events) so a week-long run cannot OOM the host; drops are
counted and surfaced in the export.  When observability is disabled the
tracer is never constructed at all — `obs.span()` returns a shared
no-op (see fedml_tpu/obs/__init__.py).
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Iterator, Optional


class SpanTracer:
    def __init__(self, max_events: int = 200_000, flight=None):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=max_events)
        self._recorded = 0
        self._epoch = time.perf_counter()
        # wall-clock of the epoch so exported ts can be correlated with
        # log timestamps (stored in export metadata)
        self.epoch_unix = time.time()
        self.pid = os.getpid()
        self._flight = flight

    def _now_us(self) -> float:
        return (time.perf_counter() - self._epoch) * 1e6

    def _record(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)
            self._recorded += 1
        if self._flight is not None:
            self._flight.record("span", ev)

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        ts = self._now_us()
        try:
            yield
        finally:
            dur = self._now_us() - ts
            self._record({"name": name, "ph": "X", "ts": ts, "dur": dur,
                          "pid": self.pid, "tid": threading.get_ident(),
                          "args": attrs})

    def instant(self, name: str, **attrs) -> None:
        """Zero-duration marker (Chrome "i" event, thread scope)."""
        self._record({"name": name, "ph": "i", "ts": self._now_us(),
                      "s": "t", "pid": self.pid,
                      "tid": threading.get_ident(), "args": attrs})

    # -- introspection -------------------------------------------------------
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._recorded - len(self._events)

    # -- exporters -----------------------------------------------------------
    def export_chrome(self, path: str) -> str:
        """Chrome trace-event JSON (load in chrome://tracing or
        https://ui.perfetto.dev).  Thread names become M (metadata)
        events so the timeline rows are readable."""
        events = self.events()
        tids = {e["tid"] for e in events}
        names = {t.ident: t.name for t in threading.enumerate()}
        meta = [{"name": "thread_name", "ph": "M", "pid": self.pid,
                 "tid": tid,
                 "args": {"name": names.get(tid, f"thread-{tid}")}}
                for tid in sorted(tids)]
        doc = {"traceEvents": meta + events,
               "displayTimeUnit": "ms",
               "otherData": {"epoch_unix": self.epoch_unix,
                             "dropped_events": self.dropped}}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def export_jsonl(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for ev in self.events():
                f.write(json.dumps(ev) + "\n")
        os.replace(tmp, path)
        return path


class _NoopSpan:
    """Shared no-op context manager — the disabled-by-default fast path.
    Stateless, so one instance serves every call site and nesting level
    concurrently; entering costs two trivial method calls."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        return None


NOOP_SPAN = _NoopSpan()
