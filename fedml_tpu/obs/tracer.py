"""Span tracer — nestable wall-clock spans with Chrome-trace and JSONL
exporters.

One tracer per process collects *complete* trace events ("ph": "X") from
every thread: the round loop, comm recv loops, and the prefetch upload
workers all record against the same perf_counter epoch, so a
`h2d.upload` span produced on the background thread lines up on the same
timeline as the `round.block_step` spans that consumed it — exactly the
view needed to see whether uploads hid behind compute.  Nesting needs no
explicit parent links: Chrome/Perfetto reconstruct the stack per `tid`
from ts/dur containment.

Overhead when tracing is enabled: two perf_counter calls plus one
locked deque append per span.  The event buffer is a fixed-size ring
(default 200k events) so a week-long run cannot OOM the host; drops are
counted and surfaced in every export path (Chrome metadata, the JSONL
meta line, `obs.rollup()`).  Long async/torture runs that must not lose
the trace head can additionally enable the streaming JSONL **spill**: every
event is appended to a side file as it is recorded, up to a byte cap
(`spill_limit_bytes`), after which truncation is counted instead of
silently eating disk — ring (tail) + spill (head) together lose nothing
until the cap.  When observability is disabled the tracer is never
constructed at all — `obs.span()` returns a shared no-op (see
fedml_tpu/obs/__init__.py).

Cross-process federation (ISSUE 7): `export_jsonl` leads with one
`__meta__` line (pid, epoch_unix, drop/spill accounting) so
tools/trace_timeline.py can rebase each process's perf_counter-relative
timestamps onto the unix clock and merge many processes into one
timeline; `digest()` is the compact per-round span summary
(name → [count, total_us]) the wire codec piggybacks on frames
(fedml_tpu/obs/propagate.py) so a client's stage walls reach the server
even when its trace file is never collected.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Iterator, Optional

DEFAULT_SPILL_LIMIT = 256 * 1024 * 1024      # bytes of spill JSONL


class SpanTracer:
    def __init__(self, max_events: int = 200_000,
                 spill_path: Optional[str] = None,
                 spill_limit_bytes: int = DEFAULT_SPILL_LIMIT):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=max_events)
        self._recorded = 0
        self._epoch = time.perf_counter()
        # wall-clock of the epoch so exported ts can be correlated with
        # log timestamps (stored in export metadata)
        self.epoch_unix = time.time()
        self.pid = os.getpid()
        # incremental per-name aggregate — digest() must not walk a
        # 200k-event ring on the frame-send hot path
        self._agg: dict[str, list] = {}
        self._spill_lock = threading.Lock()
        self._spill_f = None
        self._spill_bytes = 0
        self._spill_limit = spill_limit_bytes
        self._spilled = 0
        self._spill_truncated = 0
        self.spill_path = spill_path
        if spill_path is not None:
            self._spill_f = open(spill_path, "a", buffering=1)

    def _now_us(self) -> float:
        return (time.perf_counter() - self._epoch) * 1e6

    def _record(self, ev: dict) -> None:
        # serialize for the spill BEFORE taking the event lock: the
        # json.dumps + line-buffered write must not serialize every
        # tracing thread through disk I/O (the spill has its own lock,
        # so the spill-off hot path stays two perf_counters + one
        # locked append)
        line = json.dumps(ev) + "\n" if self._spill_f is not None else None
        with self._lock:
            self._events.append(ev)
            self._recorded += 1
            a = self._agg.get(ev["name"])
            if a is None:
                self._agg[ev["name"]] = [1, ev.get("dur", 0.0)]
            else:
                a[0] += 1
                a[1] += ev.get("dur", 0.0)
        if line is not None:
            with self._spill_lock:
                if self._spill_f is None:       # closed under our feet
                    return
                if self._spill_bytes < self._spill_limit:
                    self._spill_bytes += len(line)
                    self._spilled += 1
                    self._spill_f.write(line)
                else:
                    self._spill_truncated += 1

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        ts = self._now_us()
        try:
            yield
        finally:
            dur = self._now_us() - ts
            self._record({"name": name, "ph": "X", "ts": ts, "dur": dur,
                          "pid": self.pid, "tid": threading.get_ident(),
                          "args": attrs})

    def instant(self, name: str, **attrs) -> None:
        """Zero-duration marker (Chrome "i" event, thread scope)."""
        self._record({"name": name, "ph": "i", "ts": self._now_us(),
                      "s": "t", "pid": self.pid,
                      "tid": threading.get_ident(), "args": attrs})

    # -- introspection -------------------------------------------------------
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def tail(self, n: int) -> list[dict]:
        """Most recent `n` events (oldest first) — the flight
        recorder's dump payload.  Spans are NOT write-through-copied
        into the flight ring (that doubled the hot-path cost); dumps
        read this tail instead, which holds strictly more context
        (max_events vs the old 4096-event flight ring)."""
        with self._lock:
            if n >= len(self._events):
                return list(self._events)
            return list(itertools.islice(
                self._events, len(self._events) - n, None))

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._recorded - len(self._events)

    @property
    def spilled(self) -> int:
        """Events persisted to the spill file (0 when spill is off)."""
        with self._spill_lock:
            return self._spilled

    @property
    def spill_truncated(self) -> int:
        """Events the spill byte-cap refused (still in the ring until
        evicted — the cap bounds disk, the ring bounds memory)."""
        with self._spill_lock:
            return self._spill_truncated

    def digest(self, top: int = 8) -> dict[str, list]:
        """Compact span summary for piggybacking on wire frames:
        {name: [count, total_us]} for the `top` names by total wall.
        O(#distinct names), not O(events) — safe on the send path."""
        with self._lock:
            items = sorted(self._agg.items(), key=lambda kv: -kv[1][1])
        return {name: [int(c), round(float(t), 1)]
                for name, (c, t) in items[:top]}

    def _meta(self) -> dict:
        return {"pid": self.pid, "epoch_unix": self.epoch_unix,
                "dropped_events": self.dropped,
                "spilled_events": self.spilled,
                "spill_truncated": self.spill_truncated,
                "spill_path": self.spill_path}

    # -- exporters -----------------------------------------------------------
    def export_chrome(self, path: str) -> str:
        """Chrome trace-event JSON (load in chrome://tracing or
        https://ui.perfetto.dev).  Thread names become M (metadata)
        events so the timeline rows are readable."""
        events = self.events()
        tids = {e["tid"] for e in events}
        names = {t.ident: t.name for t in threading.enumerate()}
        meta = [{"name": "thread_name", "ph": "M", "pid": self.pid,
                 "tid": tid,
                 "args": {"name": names.get(tid, f"thread-{tid}")}}
                for tid in sorted(tids)]
        doc = {"traceEvents": meta + events,
               "displayTimeUnit": "ms",
               "otherData": {"epoch_unix": self.epoch_unix,
                             "dropped_events": self.dropped,
                             "spilled_events": self.spilled,
                             "spill_truncated": self.spill_truncated}}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def export_jsonl(self, path: str) -> str:
        """One JSON object per line; the FIRST line is a `__meta__`
        record (pid, epoch_unix, drop/spill accounting) that
        tools/trace_timeline.py uses to clock-align this process's
        events with other processes' exports."""
        with self._spill_lock:
            if self._spill_f is not None:
                self._spill_f.flush()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps({"__meta__": self._meta()}) + "\n")
            for ev in self.events():
                f.write(json.dumps(ev) + "\n")
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        with self._spill_lock:
            if self._spill_f is not None:
                self._spill_f.close()
                self._spill_f = None


class _NoopSpan:
    """Shared no-op context manager — the disabled-by-default fast path.
    Stateless, so one instance serves every call site and nesting level
    concurrently; entering costs two trivial method calls."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        return None


NOOP_SPAN = _NoopSpan()
