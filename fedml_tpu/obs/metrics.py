"""Metrics registry — counters / gauges / histograms with Prometheus-text
and JSON snapshot exporters.

The reference's observability is wandb scalars written once per eval round
(FedAVGAggregator.py:137-162); nothing counts bytes on the wire, retries,
or compile time.  This registry is the system of record for those
operational metrics: comm backends count bytes/messages per backend label,
the mesh engines feed transfer/round walls (utils/profiling.py
TransferOverlapStats writes through to it), and jax compile events land as
jit_compile_* (fedml_tpu/obs/__init__.py listener).

Design constraints:

* Thread-safe: comm recv loops, prefetch upload threads, and the round
  loop all write concurrently — every mutation takes the metric's lock
  (a bare ``self.value += n`` is NOT atomic under the GIL: it is a
  load/add/store that two threads can interleave).
* Cheap: one lock + one float op per event.  Metrics stay on even when
  span tracing is disabled — the expensive parts of observability are
  span event records and exporter I/O, not counter increments.
* Prometheus semantics: counters only go up, labels are stable
  identities (get-or-create returns the same object), histograms are
  cumulative-bucket.
* Mergeable (ISSUE 7): a registry can emit a compact snapshot DELTA
  (`delta_snapshot`) and fold a peer's delta into itself
  (`merge_delta`) — counters add, gauges max, histograms bucket-wise
  add.  Those are the only commutative/associative choices, so merge
  order across a federation's uplinks cannot change the rollup (laws
  pinned in tests/test_obs.py).  Client registries ship deltas
  piggybacked on uplink frames (fedml_tpu/obs/propagate.py) and fold
  into the server registry under an `origin` label — a COHORT rollup,
  never per-client labels, so server memory stays O(metrics) at a
  million clients.
"""
from __future__ import annotations

import bisect
import json
import threading
from typing import Optional, Sequence

# Prometheus' default duration buckets, extended for multi-minute round /
# compile walls (the tunnel chip's cold compiles run minutes).
DEFAULT_SECONDS_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)

# Wire-frame decode walls (`comm_decode_seconds`, comm/base.py + the
# async ingest pool): decodes of small control frames run ~10 µs and
# model-sized uplinks single-digit ms — the default duration buckets
# start at 1 ms and would flatten the whole distribution into two
# buckets, so this ladder extends three decades lower.  Shared here so
# every backend label and the ingest pool register ONE compatible
# histogram (the registry rejects same-name/different-bucket
# registrations).
DECODE_SECONDS_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 0.001, 0.0025, 0.005,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)

# Staleness buckets for the async federation's `async_staleness`
# histogram (fedml_tpu/async_): staleness is COMMIT counts, not seconds
# — integer-valued, small in healthy runs (FedBuff's useful regime is
# single digits), heavy-tailed under churn.  Shared here so the
# scheduler and the messaging FSM register one compatible histogram
# (the registry rejects same-name/different-bucket registrations).
STALENESS_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0,
                     24.0, 32.0, 48.0, 64.0)

# Canonical ladders by metric NAME: a bare histogram(name) get (no
# buckets argument) resolves here before falling back to the default
# seconds ladder, so get-or-create ORDER cannot decide a named
# instrument's resolution — without this, whichever caller ran first
# (a bare get in a test, say) would pin the default ladder and the
# next explicit registration would raise the bucket-conflict error.
CANONICAL_BUCKETS = {
    "comm_decode_seconds": DECODE_SECONDS_BUCKETS,
    "async_staleness": STALENESS_BUCKETS,
    # one-way frame transit estimates (obs/propagate.py): LAN transits
    # are sub-ms like decodes, WAN ones spill into the seconds tail
    "trace_transit_seconds": DECODE_SECONDS_BUCKETS,
    # the admission pipeline's per-row screen wall (async_/defense.py):
    # one O(P) jitted step, sub-ms like a decode — same ladder
    "defense_screen_seconds": DECODE_SECONDS_BUCKETS,
    # reactor transport (ISSUE 11, comm/reactor.py): how long one loop
    # iteration's event batch held the loop — healthy is tens of µs,
    # an overloaded loop spills into the ms decades the same sub-ms
    # ladder resolves
    "reactor_loop_lag_seconds": DECODE_SECONDS_BUCKETS,
    # admission latency (async_/lifecycle.py): transport hand-off ->
    # buffer insert; the connection bench's p95 gate
    "comm_admission_seconds": DECODE_SECONDS_BUCKETS,
    # per-jit-program-family host-side dispatch walls (ISSUE 12,
    # obs/programs.py): an arrival fold dispatches in tens of µs, a
    # full engine round in seconds — the same sub-ms-to-seconds ladder
    # the decode walls use resolves both ends
    "program_dispatch_seconds": DECODE_SECONDS_BUCKETS,
    # per-rank commit-barrier waits (ISSUE 17, obs/cluster.py): a
    # loopback barrier gates in µs-ms, a straggler/death stall spills
    # into seconds — the same sub-ms-to-seconds ladder covers both
    "multihost_barrier_wait_seconds": DECODE_SECONDS_BUCKETS,
}


def quantile_from_cumulative(before, after, q: float) -> float:
    """Approximate quantile of the observations BETWEEN two cumulative
    snapshots of one histogram (`Histogram.cumulative()` lists), with
    linear interpolation inside the bucket (lower edge 0 for the
    first).  `before` may be None/empty for an all-time quantile.  The
    ONE definition of histogram-delta percentiles — the torture bench's
    decode p50/p95 and `Histogram.quantile` both resolve here (bitwise
    pinned in tests/test_obs.py)."""
    if not before:
        before = [(le, 0) for le, _ in after]
    deltas = [(le, a - b) for (le, a), (_, b) in zip(after, before)]
    total = deltas[-1][1]
    if total <= 0:
        return 0.0
    target = q * total
    prev_le, prev_c = 0.0, 0
    for le, c in deltas:
        if c >= target:
            if le == float("inf"):
                return prev_le
            span = c - prev_c
            frac = (target - prev_c) / span if span > 0 else 1.0
            return prev_le + frac * (le - prev_le)
        prev_le, prev_c = (0.0 if le == float("inf") else le), c
    return prev_le


# label key merge_delta stamps on folded-in peer series; delta_snapshot
# refuses to re-ship series carrying it (echo-loop guard)
MERGE_ORIGIN_LABEL = "origin"


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    """Monotonic counter.  `inc` only; negative increments are rejected so
    rates stay meaningful."""

    kind = "counter"

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-value gauge with a `set_max` helper for peak tracking."""

    kind = "gauge"

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set_max(self, v: float) -> None:
        """Monotonic high-water mark (live/peak pairs share one code
        path: `live.set(x); peak.set_max(x)`)."""
        with self._lock:
            if v > self._value:
                self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Cumulative-bucket histogram (Prometheus shape: per-bucket counts of
    observations <= upper bound, plus sum and count)."""

    kind = "histogram"

    def __init__(self, name: str, labels: tuple,
                 buckets: Sequence[float] = DEFAULT_SECONDS_BUCKETS):
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)   # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le, cumulative_count), ...] ending with (+inf, total)."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for le, c in zip(self.buckets, counts):
            acc += c
            out.append((le, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def quantile(self, q: float, since=None) -> float:
        """Approximate q-quantile of this histogram's observations —
        all-time, or of the window SINCE a `cumulative()` snapshot
        (the torture bench's warmup-excluded percentiles)."""
        return quantile_from_cumulative(since, self.cumulative(), q)

    def raw_state(self) -> tuple[list[int], float, int]:
        """(per-bucket counts incl. +Inf, sum, count) — one consistent
        read, for delta/merge bookkeeping."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def merge_counts(self, counts: Sequence[int], vsum: float,
                     vcount: int) -> None:
        """Bucket-wise add of a peer delta (same ladder — callers go
        through MetricsRegistry.merge_delta, which resolves the ladder
        before handing over)."""
        if len(counts) != len(self._counts):
            raise ValueError(
                f"histogram {self.name}: merge of {len(counts)} buckets "
                f"into {len(self._counts)}")
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += int(c)
            self._sum += vsum
            self._count += int(vcount)


class MetricsRegistry:
    """Get-or-create registry keyed on (name, sorted labels).  Asking for
    an existing name with a different metric kind is a programming error
    and raises — silently returning the wrong type would corrupt both."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[tuple, object] = {}
        self._kinds: dict[str, type] = {}      # kind is per NAME, not
        #                                        per label set: one name
        #                                        = one # TYPE line

    def _get(self, cls, name: str, labels: dict, **kw):
        key = (name, _label_key(labels))
        with self._lock:
            known = self._kinds.setdefault(name, cls)
            if known is not cls:
                raise TypeError(f"metric {name!r} already registered as "
                                f"{known.kind}, requested {cls.kind}")
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, _label_key(labels), **kw)
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None,
                  **labels) -> Histogram:
        if buckets is None:
            buckets = CANONICAL_BUCKETS.get(name)
        kw = {} if buckets is None else {"buckets": buckets}
        h = self._get(Histogram, name, labels, **kw)
        if buckets is not None and h.buckets != tuple(sorted(buckets)):
            # same loud-failure policy as the kind conflict: silently
            # returning a histogram with different buckets would strand
            # observations at the wrong resolution
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}, requested {tuple(sorted(buckets))}")
        return h

    def metrics(self) -> list:
        with self._lock:
            return list(self._metrics.values())

    # -- snapshot-delta merge protocol (ISSUE 7) -----------------------------
    # Merge semantics, the only commutative/associative choices:
    #   counters   add
    #   gauges     max  (peak semantics — "last" would depend on merge
    #                    order, which a federation cannot promise)
    #   histograms bucket-wise add (same ladder enforced)
    # so  merge(a, merge(b, c)) == merge(merge(a, b), c)  and an empty
    # delta is the identity — pinned in tests/test_obs.py.

    def delta_snapshot(self, prev: Optional[dict] = None, *,
                       include_merged: bool = False
                       ) -> tuple[dict, dict]:
        """One atomic pass over the registry: returns
        ``(delta_doc, state)`` where `delta_doc` is the compact
        JSON-able delta SINCE `prev` (a `state` from an earlier call;
        None = since birth) and `state` is the new baseline.  Metrics
        whose delta is empty (unmoved counters/gauges, histograms with
        no new observations) are omitted — an idle client ships bytes
        proportional to what it DID, not to what exists.  Series that
        carry the merge-side ``origin`` label are SKIPPED by default:
        they were folded in from a peer's delta, and re-shipping them
        from a shared in-process registry would echo the rollup back
        into itself (quadratic inflation).  An intermediate aggregator
        re-exporting its fold up a hierarchy (client → edge → server)
        passes ``include_merged=True`` — associativity of that
        re-export is pinned in tests/test_obs.py."""
        prev = prev or {}
        entries, state = [], {}
        for m in self.metrics():
            if not include_merged and any(
                    k == MERGE_ORIGIN_LABEL for k, _ in m.labels):
                continue            # already-merged rollup, never re-ship
            key = (m.name, m.labels)
            labels = {k: v for k, v in m.labels}
            if m.kind == "histogram":
                counts, vsum, vcount = m.raw_state()
                state[key] = (counts, vsum, vcount)
                p_counts, p_sum, p_count = prev.get(
                    key, ([0] * len(counts), 0.0, 0))
                d_counts = [c - p for c, p in zip(counts, p_counts)]
                if vcount - p_count <= 0:
                    continue
                entries.append({
                    "name": m.name, "labels": labels, "kind": "histogram",
                    "buckets": list(m.buckets), "counts": d_counts,
                    "sum": vsum - p_sum, "count": vcount - p_count})
            else:
                v = m.value
                state[key] = v
                if m.kind == "counter":
                    d = v - prev.get(key, 0.0)
                    if d <= 0:
                        continue
                    entries.append({"name": m.name, "labels": labels,
                                    "kind": "counter", "value": d})
                else:
                    if key in prev and v == prev[key]:
                        continue
                    entries.append({"name": m.name, "labels": labels,
                                    "kind": "gauge", "value": v})
        return {"schema": 1, "metrics": entries}, state

    def merge_delta(self, delta: Optional[dict], **extra_labels) -> None:
        """Fold a peer's `delta_snapshot` doc into this registry.
        `extra_labels` are merged over the shipped labels — callers
        pass a LOW-CARDINALITY ``origin`` (e.g. ``origin="remote"``),
        never a per-client id: the million-client constraint is
        O(metrics) server memory, cohort rollups instead of per-rank
        label explosion.  The ``origin`` key also marks the series as
        merged-in, which is what keeps delta_snapshot from re-shipping
        it (the shared-registry echo-loop guard)."""
        if not delta or not delta.get("metrics"):
            return                      # empty delta is the merge identity
        for e in delta["metrics"]:
            labels = dict(e.get("labels", {}))
            labels.update(extra_labels)
            kind = e["kind"]
            if kind == "counter":
                self.counter(e["name"], **labels).inc(float(e["value"]))
            elif kind == "gauge":
                self.gauge(e["name"], **labels).set_max(float(e["value"]))
            elif kind == "histogram":
                h = self.histogram(e["name"], buckets=e["buckets"],
                                   **labels)
                h.merge_counts(e["counts"], float(e["sum"]),
                               int(e["count"]))
            else:
                raise ValueError(f"unknown metric kind {kind!r} in delta")

    # -- exporters -----------------------------------------------------------
    def to_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4)."""
        by_name: dict[str, list] = {}
        for m in self.metrics():
            by_name.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(by_name):
            group = by_name[name]
            lines.append(f"# TYPE {name} {group[0].kind}")
            for m in sorted(group, key=lambda m: m.labels):
                if m.kind == "histogram":
                    for le, c in m.cumulative():
                        le_s = "+Inf" if le == float("inf") else repr(le)
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels(m.labels + (('le', le_s),))} {c}")
                    lines.append(
                        f"{name}_sum{_fmt_labels(m.labels)} {m.sum}")
                    lines.append(
                        f"{name}_count{_fmt_labels(m.labels)} {m.count}")
                else:
                    lines.append(f"{name}{_fmt_labels(m.labels)} {m.value}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able snapshot: {name{labels}: scalar-or-histogram-dict}."""
        out = {}
        for m in self.metrics():
            key = m.name + _fmt_labels(m.labels)
            if m.kind == "histogram":
                out[key] = {
                    "type": "histogram", "sum": m.sum, "count": m.count,
                    "buckets": [
                        {"le": ("+Inf" if le == float("inf") else le),
                         "cumulative_count": c}
                        for le, c in m.cumulative()],
                }
            else:
                out[key] = m.value
        return out

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), indent=2, sort_keys=True)
