"""Round critical-path analyzer — merge per-process span traces into one
clock-aligned timeline and say WHICH STAGE a round's wall time went to
(ISSUE 7).

Since the async subsystem (PR 5/6) a round's wall is federated: client
train and uplink encode happen in client processes/threads, transport
transit on the wire, decode-into / streaming fold on the server's ingest
pool, and the commit on the server's jit.  The Smart-NIC FL study
(arXiv:2307.06561) identifies the server's stage attribution as exactly
what finds the FL bottleneck; this module computes it from the span
streams every layer already emits:

* **merge** — `load_trace_jsonl` + `merge_traces` rebase each process's
  perf_counter-relative timestamps onto the unix clock via the
  `__meta__` line's `epoch_unix`, shifted by the per-peer clock offsets
  the comm layer estimated from piggybacked frame timestamps
  (obs/propagate.py, exported as clock_offsets.json);
* **rounds** — commit spans (`async.commit`, args.version) delimit
  round windows: round v spans (previous commit end, this commit end].
  Synchronous traces fall back to their explicit `round` spans;
* **stages** — every span name maps to a canonical stage
  (dispatch → train → uplink → decode → fold → commit …).  Within a
  window each stage claims the union of its spans' intervals, clipped
  to the window, with more-specific stages claiming first (a decode
  nested inside a handler attributes to decode); the unclaimed
  remainder is `wait` — transport transit + idle, the federation's
  dead time.  Claimed + wait == round wall by construction, so the
  stage table always explains the measured wall;
* **attribution** — per-round stage seconds, aggregate shares, and the
  p95 straggler attribution: among the slowest (≥ p95 wall) rounds,
  the stage with the largest mean share is the named bottleneck.

`tools/trace_timeline.py` is the CLI; `critical_path()` also runs
in-process on a live tracer's events (bench.py's schema-v6
`critical_path` block, the torture report, AsyncFedAvgEngine
.timeline_report()).
"""
from __future__ import annotations

import json
from typing import Iterable, Optional

# span name -> canonical stage.  Priority = order in STAGE_PRIORITY:
# when spans overlap inside a window (nesting, concurrent threads), the
# earlier stage claims the interval and later ones only keep what's
# left — so a decode nested in a comm.handle books as decode, and train
# time under an outer wave span books once.
SPAN_STAGES = {
    "async.commit": "commit",
    "fsm.aggregate": "commit",
    "ingest.fold": "fold",
    "ingest.decode": "decode",
    "comm.decode": "decode",
    "async.local_train": "train",
    "fsm.local_train": "train",
    "async.wave": "train",
    "round.block_step": "train",
    "round.chunked": "train",
    "h2d.upload_block": "h2d",
    "h2d.upload": "h2d",
    "async.eval": "eval",
    "eval": "eval",
    "checkpoint": "checkpoint",
    # ISSUE 11: reactor transport housekeeping/drain (eviction scans,
    # shed batches, graceful close) — rare, but when overload handling
    # dominates a round's wall the timeline must say so
    "reactor.housekeep": "reactor",
    "reactor.drain": "reactor",
}
# commit-family span names: their end times delimit round windows on
# event-driven paths (the async scheduler's commits, the deployment
# FSM's aggregates) where no single `round` call frame exists
COMMIT_SPANS = ("async.commit", "fsm.aggregate")

# jit-program family -> canonical stage (ISSUE 12): the per-family
# profile registry (obs/programs.py) groups its dispatch-wall/MFU rows
# into the SAME stage taxonomy this analyzer attributes round walls to,
# so the PERF.md stage table and the program table speak one language.
# Families not listed here report stage "other" (profiled, unmapped).
PROGRAM_FAMILY_STAGES = {
    # the sync engines' round programs — cohort training + aggregation
    # in one compiled dispatch
    "fedavg_resident": "train", "fedavg_streaming": "train",
    "fedavg_blockstream": "train",
    "fednova_resident": "train", "fednova_streaming": "train",
    "fednova_blockstream": "train",
    "fedprox_resident": "train", "fedprox_streaming": "train",
    "fedprox_blockstream": "train",
    "fedopt_resident": "train", "fedopt_streaming": "train",
    "fedopt_blockstream": "train",
    "robust_orderstat": "train", "robust_blockstream": "train",
    "hierarchical": "train", "gossip": "train",
    # the two-level multihost programs (ISSUE 13): per-block partials
    # are training work, the replicated carry commit is aggregation
    "fedavg_twolevel": "train", "fedprox_twolevel": "train",
    "fedopt_twolevel": "train", "fednova_twolevel": "train",
    "twolevel_commit": "commit",
    # the async ingestion/commit pipeline
    "async_fold": "fold", "async_drain_fold": "fold",
    "async_screened_fold": "fold", "async_admission": "fold",
    "async_commit": "commit", "async_stream_commit": "commit",
    "async_bucket_commit": "commit",
}
STAGE_PRIORITY = ("commit", "decode", "fold", "train", "uplink",
                  "dispatch", "h2d", "eval", "checkpoint", "reactor")
WAIT_STAGE = "wait"


def stage_of(ev: dict) -> Optional[str]:
    """Canonical stage of one span event (None = not a stage span)."""
    name = ev.get("name", "")
    s = SPAN_STAGES.get(name)
    if s is not None:
        return s
    if name == "comm.send":
        # direction decides: a server send is a dispatch (downlink), a
        # client send is the uplink encode+write
        node = (ev.get("args") or {}).get("node")
        return "dispatch" if node == "server" else "uplink"
    return None


# -- trace IO / merging ------------------------------------------------------

def load_trace_jsonl(path: str) -> tuple[dict, list[dict]]:
    """(meta, events) from a SpanTracer.export_jsonl file (or a spill
    file, which has no meta line — meta comes back {})."""
    meta, events = {}, []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if "__meta__" in doc:
                meta = doc["__meta__"]
            else:
                events.append(doc)
    return meta, events


def rebase(events: list[dict], meta: dict,
           offset_s: float = 0.0) -> list[dict]:
    """Map one process's trace-relative `ts` (µs since its tracer
    epoch) onto the unix clock (µs), shifted by `offset_s` — the
    estimated correction of THIS process's clock onto the reference
    process's (obs/propagate.py sign convention: add the offset to the
    peer's timestamps)."""
    base_us = (float(meta.get("epoch_unix", 0.0)) + offset_s) * 1e6
    out = []
    for ev in events:
        ev = dict(ev)
        ev["ts"] = ev["ts"] + base_us
        out.append(ev)
    return out


def merge_traces(sources: Iterable[tuple[dict, list[dict], float]]
                 ) -> list[dict]:
    """Merge per-process traces into one unix-clock timeline.
    `sources` yields (meta, events, offset_s) triples; colliding pids
    across hosts are left as-is (Chrome renders them as separate
    process groups only if distinct — pass distinct pids via meta when
    merging across hosts that reuse pids)."""
    merged = []
    for meta, events, offset_s in sources:
        merged.extend(rebase(events, meta, offset_s))
    merged.sort(key=lambda e: e["ts"])
    return merged


def dir_offsets(metas_clocks: list[tuple[dict, list[dict]]]
                ) -> list[float]:
    """Per-source clock corrections from the clock_offsets.json
    exports.  `metas_clocks` is [(meta, clock_export_list)] per source
    dir; the reference is the source whose comm managers include rank 0
    (else the first source).  A source containing rank r is shifted by
    the reference's estimated offset for peer r (0.0 when the reference
    never heard from r — same-host clocks agree anyway)."""
    ranks = []
    for _meta, clocks in metas_clocks:
        ranks.append({c.get("rank") for c in clocks
                      if c.get("rank") is not None})
    ref = 0
    for i, rs in enumerate(ranks):
        if 0 in rs:
            ref = i
            break
    ref_offsets: dict[str, float] = {}
    for c in metas_clocks[ref][1]:
        ref_offsets.update(c.get("offsets_s", {}))
    out = []
    for i, rs in enumerate(ranks):
        if i == ref:
            out.append(0.0)
            continue
        offs = [ref_offsets[str(r)] for r in rs if str(r) in ref_offsets]
        out.append(sum(offs) / len(offs) if offs else 0.0)
    return out


# -- interval algebra --------------------------------------------------------

def _union(iv: list[tuple[float, float]]) -> list[tuple[float, float]]:
    if not iv:
        return []
    iv = sorted(iv)
    out = [list(iv[0])]
    for s, e in iv[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _subtract(iv, taken):
    """Set difference of two merged-sorted interval lists."""
    out = []
    for s, e in iv:
        cur = s
        for ts, te in taken:
            if te <= cur or ts >= e:
                continue
            if ts > cur:
                out.append((cur, ts))
            cur = max(cur, te)
            if cur >= e:
                break
        if cur < e:
            out.append((cur, e))
    return out


def _total(iv) -> float:
    return sum(e - s for s, e in iv)


# -- round windows -----------------------------------------------------------

def round_windows(events: list[dict]) -> list[tuple[object, float, float]]:
    """[(round_id, t0_us, t1_us)] — commit-to-commit windows when
    commit-family spans exist (async scheduler commits, deployment FSM
    aggregates), else the sync loop's explicit `round` spans."""
    commits = sorted((e for e in events if e.get("name") in COMMIT_SPANS
                      and e.get("ph") == "X"),
                     key=lambda e: e["ts"] + e.get("dur", 0.0))
    if commits:
        t_first = min(e["ts"] for e in events)
        windows, prev_end = [], t_first
        for c in commits:
            end = c["ts"] + c.get("dur", 0.0)
            args = c.get("args") or {}
            rid = args.get("version", args.get("round"))
            windows.append((rid, prev_end, end))
            prev_end = end
        return windows
    rounds = [e for e in events if e.get("name") == "round"
              and e.get("ph") == "X"]
    return [((e.get("args") or {}).get("round"), e["ts"],
             e["ts"] + e.get("dur", 0.0))
            for e in sorted(rounds, key=lambda e: e["ts"])]


# -- the analyzer ------------------------------------------------------------

def critical_path(events: list[dict]) -> dict:
    """Per-round stage attribution + straggler report over a (merged or
    single-process) event list.  Stage seconds + `wait` sum to each
    round's wall exactly; the p95 attribution names the stage with the
    largest mean share among the slowest rounds."""
    windows = round_windows(events)
    spans = [(stage_of(e), e["ts"], e["ts"] + e.get("dur", 0.0))
             for e in events if e.get("ph") == "X"]
    spans = [(s, a, b) for s, a, b in spans if s is not None and b > a]
    rounds = []
    for rid, t0, t1 in windows:
        if t1 <= t0:
            continue
        taken: list[tuple[float, float]] = []
        stages = {}
        for stage in STAGE_PRIORITY:
            iv = _union([(max(a, t0), min(b, t1))
                         for s, a, b in spans
                         if s == stage and b > t0 and a < t1])
            mine = _subtract(iv, taken)
            if mine:
                stages[stage] = _total(mine) / 1e6
                taken = _union(taken + mine)
        wall = (t1 - t0) / 1e6
        stages[WAIT_STAGE] = max(0.0, wall - _total(taken) / 1e6)
        dominant = max(stages, key=stages.get)
        rounds.append({"round": rid, "t0_us": t0, "wall_s": wall,
                       "stages": {k: round(v, 6)
                                  for k, v in stages.items()},
                       "dominant": dominant})
    report = {"n_rounds": len(rounds), "rounds": rounds}
    if not rounds:
        return report
    totals: dict[str, float] = {}
    for r in rounds:
        for k, v in r["stages"].items():
            totals[k] = totals.get(k, 0.0) + v
    wall_total = sum(r["wall_s"] for r in rounds)
    walls = sorted(r["wall_s"] for r in rounds)

    def pct(q):
        i = min(len(walls) - 1, int(round(q * (len(walls) - 1))))
        return walls[i]

    p95 = pct(0.95)
    slow = [r for r in rounds if r["wall_s"] >= p95] or rounds
    slow_mean = {}
    for r in slow:
        for k, v in r["stages"].items():
            slow_mean[k] = slow_mean.get(k, 0.0) + v / len(slow)
    attr = max(slow_mean, key=slow_mean.get)
    slow_wall = sum(r["wall_s"] for r in slow) / len(slow)
    report.update({
        "stage_totals_s": {k: round(v, 6) for k, v in totals.items()},
        "stage_share": {k: round(v / wall_total, 4)
                        for k, v in totals.items()} if wall_total else {},
        "round_wall_p50_s": round(pct(0.50), 6),
        "round_wall_p95_s": round(p95, 6),
        "p95_attribution": {
            "stage": attr,
            "share": round(slow_mean[attr] / slow_wall, 4)
            if slow_wall else 0.0,
            "n_rounds": len(slow),
        },
    })
    return report


# -- chrome export with per-round lanes --------------------------------------

LANES_PID = 1 << 30          # synthetic "critical path" process row


def lane_events(report: dict) -> list[dict]:
    """Synthetic Chrome events rendering the critical-path claims as
    per-stage lanes (one tid per stage under a dedicated pid), so the
    stage attribution is VISIBLE next to the raw spans."""
    stages = list(STAGE_PRIORITY) + [WAIT_STAGE]
    out = [{"name": "process_name", "ph": "M", "pid": LANES_PID, "tid": 0,
            "args": {"name": "round critical path"}}]
    for i, st in enumerate(stages):
        out.append({"name": "thread_name", "ph": "M", "pid": LANES_PID,
                    "tid": i + 1, "args": {"name": f"stage:{st}"}})
    for r in report.get("rounds", []):
        t0 = r["t0_us"]
        cursor = t0
        # lanes are schematic: stages laid end-to-end in pipeline order
        # with their claimed totals (the raw spans above carry the
        # true interleaving)
        for i, st in enumerate(stages):
            sec = r["stages"].get(st, 0.0)
            if sec <= 0:
                continue
            out.append({"name": st, "ph": "X", "pid": LANES_PID,
                        "tid": i + 1, "ts": cursor, "dur": sec * 1e6,
                        "args": {"round": r["round"]}})
            cursor += sec * 1e6
        out.append({"name": f"round {r['round']}", "ph": "X",
                    "pid": LANES_PID, "tid": 0, "ts": t0,
                    "dur": r["wall_s"] * 1e6,
                    "args": {"dominant": r["dominant"]}})
    return out


BARRIER_PID = LANES_PID + 1  # synthetic "cluster barriers" process row


def barrier_lane_events(entries: list[dict]) -> list[dict]:
    """Synthetic Chrome events rendering the coordinator's barrier
    ledger (obs/cluster.py, ISSUE 17) as per-RANK lanes: each rank's
    wait behind the gate is a slice ending at the gate instant, the
    gating rank's slice is labeled GATE, and a tid-0 instant names the
    gating rank per barrier — the cross-rank straggler view next to
    the per-stage critical path."""
    if not entries:
        return []
    ranks = sorted({int(r) for e in entries
                    for r in e.get("waits_s", {})})
    out = [{"name": "process_name", "ph": "M", "pid": BARRIER_PID,
            "tid": 0, "args": {"name": "cluster barriers"}}]
    tid_of = {}
    for i, r in enumerate(ranks):
        tid_of[r] = i + 1
        out.append({"name": "thread_name", "ph": "M",
                    "pid": BARRIER_PID, "tid": i + 1,
                    "args": {"name": f"rank {r} wait"}})
    for e in entries:
        gate_us = e["t_unix"] * 1e6
        gating = e.get("round_gating_rank")
        label = (f"round {e['round']}" if e.get("round") is not None
                 else f"{e.get('kind', 'barrier')} #{e.get('seq')}")
        out.append({"name": f"gate: rank {gating} ({label})",
                    "ph": "i", "pid": BARRIER_PID, "tid": 0,
                    "ts": gate_us, "s": "p",
                    "args": {"round_gating_rank": gating,
                             "gate_margin_s": e.get("gate_margin_s"),
                             "kind": e.get("kind"),
                             "seq": e.get("seq")}})
        for r_str, w in e.get("waits_s", {}).items():
            r = int(r_str)
            out.append({"name": ("GATE" if r == gating else "wait"),
                        "ph": "X", "pid": BARRIER_PID,
                        "tid": tid_of[r], "ts": gate_us - w * 1e6,
                        "dur": max(w * 1e6, 1.0),
                        "args": {"rank": r, "wait_s": w,
                                 "round": e.get("round"),
                                 "gating": r == gating}})
    return out


def export_chrome(events: list[dict], path: str,
                  report: Optional[dict] = None,
                  barriers: Optional[list[dict]] = None) -> str:
    doc = {"traceEvents": (events
                           + (lane_events(report) if report else [])
                           + (barrier_lane_events(barriers)
                              if barriers else [])),
           "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def format_report(report: dict) -> str:
    """Human-readable critical-path table (the CLI's stdout)."""
    lines = [f"rounds analyzed: {report.get('n_rounds', 0)}"]
    if not report.get("rounds"):
        return lines[0]
    lines.append(f"round wall p50/p95: "
                 f"{report['round_wall_p50_s'] * 1e3:.1f}/"
                 f"{report['round_wall_p95_s'] * 1e3:.1f} ms")
    lines.append(f"{'stage':<12}{'total s':>10}{'share':>8}")
    for k, v in sorted(report["stage_totals_s"].items(),
                       key=lambda kv: -kv[1]):
        lines.append(f"{k:<12}{v:>10.3f}"
                     f"{report['stage_share'].get(k, 0.0):>8.1%}")
    a = report["p95_attribution"]
    lines.append(f"p95 straggler attribution: {a['stage']} "
                 f"({a['share']:.0%} of the slowest "
                 f"{a['n_rounds']} round(s))")
    return "\n".join(lines)
