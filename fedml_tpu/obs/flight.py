"""Flight recorder — a fixed-size ring of recent span/metric events that
dumps to disk when something goes wrong, so stalls are diagnosable from
artifacts instead of reruns (tools/isolate_hang.py's whole reason to
exist).

Triggers (wired in fedml_tpu/obs/__init__.py and the engine run loop):

  * SIGUSR1 — an operator (or tools/isolate_hang.py watching a stuck
    child) pokes the process; the handler dumps the ring plus every
    thread's current Python stack.  Python-level hangs (a recv loop
    parked on a queue, a prefetch join) show up directly; a process
    wedged inside a C call dumps as soon as the interpreter resumes.
  * round-deadline overrun — `watchdog(seconds, tag)` arms a timer
    around each round; if the round doesn't finish in time the dump
    fires from the timer thread while the round is STILL stuck, which
    is precisely when the stacks are interesting.
  * unhandled engine error — the run loop dumps before re-raising.

The dump is one self-contained JSON file: reason, recent events (oldest
first), per-thread stacks, and a full metrics snapshot.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import sys
import threading
import time
import traceback
from typing import Iterator, Optional


def thread_stacks() -> dict[str, list[str]]:
    """Formatted Python stacks of every live thread, keyed by
    "name(ident)" — the hang-triage payload."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, 'unknown')}({ident})"
        out[key] = traceback.format_stack(frame)
    return out


class FlightRecorder:
    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._recorded = 0
        self._dump_seq = itertools.count()
        self.dumps: list[str] = []        # paths written so far
        # when set (obs.configure points it at the span tracer's tail),
        # dumps read recent events from there instead of the local ring
        # — spans then cost NOTHING here on the hot path
        self.source = None                # () -> list[dict] | None

    def record(self, kind: str, payload: dict) -> None:
        """Ring-append one event.  `payload` must be JSON-able; callers
        keep it small (span name/ts/dur/args) — the ring is memory, not
        an archive."""
        with self._lock:
            self._ring.append({"t": time.time(), "kind": kind, **payload})
            self._recorded += 1

    def dump(self, directory: str, reason: str,
             extra: Optional[dict] = None) -> str:
        """Write one dump file into `directory`; returns its path.
        Never raises on I/O trouble from a signal/timer context — a
        failed dump logs to stderr and returns "" rather than killing
        the (possibly still healthy) run."""
        with self._lock:
            events = list(self._ring)
            seq = next(self._dump_seq)
        if self.source is not None:
            events = self.source() + events
        doc = {
            "reason": reason,
            "pid": os.getpid(),
            "dumped_at_unix": time.time(),
            "events_retained": len(events),
            "events_recorded": self._recorded,
            "thread_stacks": thread_stacks(),
            "events": events,
        }
        if extra:
            doc.update(extra)
        path = os.path.join(
            directory, f"flight-{os.getpid()}-{seq}.json")
        try:
            os.makedirs(directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)
            os.replace(tmp, path)
        except OSError as e:                      # pragma: no cover - io
            print(f"flight recorder dump failed: {e}", file=sys.stderr)
            return ""
        self.dumps.append(path)
        return path

    @contextlib.contextmanager
    def watchdog(self, seconds: float, tag: str, directory: str,
                 extra_fn=None) -> Iterator[None]:
        """Arm a deadline: if the with-block is still running after
        `seconds`, dump (reason deadline_overrun) from the timer thread
        and keep waiting — the run is left to finish or hang on its
        own; the dump is the diagnosis, not the kill."""
        def fire():
            self.dump(directory, f"deadline_overrun:{tag}",
                      extra=(extra_fn() if extra_fn else None))

        t = threading.Timer(seconds, fire)
        t.daemon = True
        t.name = f"obs-watchdog-{tag}"
        t.start()
        try:
            yield
        finally:
            t.cancel()
