"""Unified observability: span tracer + metrics registry + flight recorder.

The process-global facade every layer instruments against:

  with obs.span("round.blockstream", round=r): ...      # tracing
  obs.counter("comm_sent_bytes_total", backend="tcp").inc(n)   # metrics
  with obs.deadline("round3", 120): ...                 # hang watchdog
  kill -USR1 <pid>                                      # flight dump

Two tiers, by cost:

* **Metrics are always on.**  A counter increment is one lock + one
  float add; comm backends, the prefetch pipeline, and jax compile
  events write through unconditionally so a later `obs.configure()`
  (or a test poking `obs.registry()`) sees history, not a cold start.
* **Tracing/flight-recording is opt-in** via `configure(obs_dir)` (the
  CLI's `--obs_dir`, or the FEDML_OBS_DIR env var for bench/tools).
  Until then `span()` returns a shared stateless no-op and nothing is
  buffered — the disabled fast path in the engine hot loop is a flag
  check and a constant return.

`configure()` also installs the SIGUSR1 flight-dump handler (main
thread only) and an atexit export, so any obs-enabled run leaves a
loadable Chrome trace + Prometheus snapshot behind even if nobody
called `export()` explicitly.  Everything here is pure-host and never
touches values inside jit — results are bitwise identical with
observability on or off (pinned by tests/test_obs.py).
"""
from __future__ import annotations

import atexit
import contextlib
import json
import os
import signal
import threading
import time
from typing import Iterator, Optional

from fedml_tpu.obs.flight import FlightRecorder, thread_stacks
from fedml_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                   MetricsRegistry)
from fedml_tpu.obs.tracer import NOOP_SPAN, SpanTracer

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "SpanTracer",
    "FlightRecorder", "configure", "configure_from_env", "enabled",
    "obs_dir", "span", "instant", "counter", "gauge", "histogram",
    "registry", "tracer", "flight", "deadline", "dump_flight", "export",
    "sample_device_memory", "reset", "serve_http", "http_server",
]

ENV_VAR = "FEDML_OBS_DIR"
ENV_HTTP = "FEDML_OBS_HTTP_PORT"
ENV_SPILL = "FEDML_OBS_SPILL"

_lock = threading.Lock()
_registry = MetricsRegistry()
_tracer: Optional[SpanTracer] = None
_flight: Optional[FlightRecorder] = None
_dir: Optional[str] = None
_http = None
_prev_sigusr1 = None
_atexit_registered = False


# -- lifecycle ---------------------------------------------------------------

def enabled() -> bool:
    return _dir is not None


def obs_dir() -> Optional[str]:
    return _dir


def configure(directory: str, *, flight_capacity: int = 4096,
              max_events: int = 200_000, install_signal: bool = True,
              export_at_exit: bool = True,
              spill_events: Optional[bool] = None,
              http_port: Optional[int] = None) -> None:
    """Enable tracing + flight recording, writing artifacts under
    `directory`.  Idempotent-ish: reconfiguring swaps in a fresh tracer
    and ring (old events already exported stay on disk).

    `spill_events` (or FEDML_OBS_SPILL=1) streams every span to
    `directory`/trace.spill.jsonl up to a byte cap — long async runs
    keep the trace head the ring would evict.  `http_port` (or
    FEDML_OBS_HTTP_PORT) starts the loopback introspection endpoint
    (/metrics, /rollup, /flight — fedml_tpu/obs/httpd.py)."""
    global _tracer, _flight, _dir, _atexit_registered
    os.makedirs(directory, exist_ok=True)
    if spill_events is None:
        spill_events = os.environ.get(ENV_SPILL, "") not in ("", "0")
    with _lock:
        old = _tracer
        _flight = FlightRecorder(capacity=flight_capacity)
        _tracer = SpanTracer(
            max_events=max_events,
            spill_path=(os.path.join(directory, "trace.spill.jsonl")
                        if spill_events else None))
        # dumps read the tracer's tail — spans don't write-through to a
        # second ring (that doubled the hot-path cost)
        t = _tracer
        _flight.source = lambda: t.tail(flight_capacity)
        _dir = directory
        if export_at_exit and not _atexit_registered:
            _atexit_registered = True
            atexit.register(_atexit_export)
    if old is not None:
        old.close()
    if install_signal:
        _install_sigusr1()
    if http_port is None:
        port = os.environ.get(ENV_HTTP)
        http_port = int(port) if port else None
    if http_port is not None:
        serve_http(http_port)


def configure_from_env() -> bool:
    """Enable from FEDML_OBS_DIR when set (bench.py / tools / child
    processes of tools/isolate_hang.py).  No-op if already enabled."""
    d = os.environ.get(ENV_VAR)
    if d and not enabled():
        configure(d)
        return True
    return False


def reset() -> None:
    """Test hook: back to the disabled-by-default state with a fresh
    registry.  Metric handles cached by already-constructed objects
    keep writing to the OLD registry — tests reset() before building
    the objects under test."""
    global _registry, _tracer, _flight, _dir, _http
    with _lock:
        old_tracer, old_http = _tracer, _http
        _registry = MetricsRegistry()
        _tracer = None
        _flight = None
        _dir = None
        _http = None
    if old_tracer is not None:
        old_tracer.close()
    if old_http is not None:
        old_http.close()
    from fedml_tpu.obs import propagate
    propagate.reset_clocks()
    from fedml_tpu.obs import cluster, programs, slo
    programs.reset()
    slo.reset()
    cluster.reset()


# -- tracing -----------------------------------------------------------------

def span(name: str, **attrs):
    """Nestable wall-clock span; the no-op singleton when disabled."""
    t = _tracer
    if t is None:
        return NOOP_SPAN
    return t.span(name, **attrs)


def instant(name: str, **attrs) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, **attrs)


def tracer() -> Optional[SpanTracer]:
    return _tracer


# -- metrics -----------------------------------------------------------------

def registry() -> MetricsRegistry:
    return _registry


def counter(name: str, **labels) -> Counter:
    return _registry.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return _registry.gauge(name, **labels)


def histogram(name: str, buckets=None, **labels) -> Histogram:
    return _registry.histogram(name, buckets=buckets, **labels)


def sample_device_memory() -> None:
    """Live/peak HBM gauges per local device, when the backend exposes
    allocator stats (TPU/GPU do; XLA:CPU returns None — skipped).
    Call sites gate on `enabled()`: polling every device per round is
    pointless when nothing exports the result."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:                       # pragma: no cover - no backend
        return
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        live = stats.get("bytes_in_use")
        if live is not None:
            gauge("device_bytes_in_use", device=str(d.id)).set(live)
            gauge("device_peak_bytes_in_use",
                  device=str(d.id)).set_max(
                      stats.get("peak_bytes_in_use", live))


# -- http introspection ------------------------------------------------------

def serve_http(port: int = 0):
    """Start (or return the already-running) loopback introspection
    endpoint — /metrics (Prometheus text), /rollup (JSON), /flight
    (dump trigger).  Works with metrics alone (no --obs_dir needed);
    /flight answers 503 until configure() arms the recorder.  Returns
    the ObsHttpServer (its `.port` is the bound port — pass 0 for an
    ephemeral one)."""
    global _http
    with _lock:
        if _http is not None:
            if port not in (0, _http.port):
                import sys
                print(f"obs.serve_http: endpoint already on port "
                      f"{_http.port}; ignoring request for {port}",
                      file=sys.stderr)
            return _http
    from fedml_tpu.obs.httpd import ObsHttpServer
    server = ObsHttpServer(port=port)
    with _lock:
        if _http is None:
            _http = server
            return server
    server.close()                    # lost a concurrent-start race
    return _http


def http_server():
    return _http


# -- flight recorder ---------------------------------------------------------

def flight() -> Optional[FlightRecorder]:
    return _flight


def dump_flight(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    """Dump the ring + thread stacks + a metrics snapshot; returns the
    path (None when disabled)."""
    f, d = _flight, _dir
    if f is None or d is None:
        return None
    payload = {"metrics": _registry.snapshot()}
    if extra:
        payload.update(extra)
    return f.dump(d, reason, extra=payload)


def deadline(tag: str, seconds: Optional[float]):
    """Round-deadline watchdog: a flight dump fires if the with-block
    overruns `seconds`.  No-op when disabled or seconds is None."""
    f, d = _flight, _dir
    if f is None or d is None or seconds is None:
        return contextlib.nullcontext()
    return f.watchdog(seconds, tag, d,
                      extra_fn=lambda: {"metrics": _registry.snapshot()})


def _install_sigusr1() -> None:
    """SIGUSR1 -> flight dump.  Only installable from the main thread
    (signal module restriction); elsewhere — e.g. an engine built on a
    worker thread — the caller keeps its current handler."""
    global _prev_sigusr1
    if not hasattr(signal, "SIGUSR1"):       # pragma: no cover - windows
        return

    def _dump_async():
        # settle briefly so the main thread has returned from the
        # handler (and its Thread.start() wait) back to wherever it is
        # actually stuck — the captured stack then shows the park site
        time.sleep(0.05)
        dump_flight("SIGUSR1")

    def handler(signum, frame):
        # dump from a SEPARATE thread, never inline: the handler runs on
        # the main thread between bytecodes, possibly while that thread
        # holds the (non-reentrant) ring or a metric lock — an inline
        # dump would deadlock the process it came to diagnose.  A side
        # benefit: the main thread's captured stack then shows where it
        # is actually parked, not these handler frames.
        threading.Thread(target=_dump_async, name="obs-sigusr1-dump",
                         daemon=True).start()
        prev = _prev_sigusr1
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)              # pragma: no cover - chained

    handler._fedml_obs = True                 # reconfigure: don't self-chain
    try:
        prev = signal.signal(signal.SIGUSR1, handler)
    except ValueError:                        # not the main thread
        return
    if not getattr(prev, "_fedml_obs", False):
        _prev_sigusr1 = prev


# -- exporters ---------------------------------------------------------------

def export() -> dict[str, str]:
    """Write every artifact into obs_dir:

        trace.chrome.json   Chrome trace-event file (chrome://tracing,
                            ui.perfetto.dev)
        trace.jsonl         same spans, one JSON object per line, led
                            by a __meta__ line (pid/epoch/drops) —
                            tools/trace_timeline.py's merge input
        metrics.prom        Prometheus text exposition
        metrics.json        JSON metrics snapshot
        clock_offsets.json  per-comm-manager peer clock offsets
                            (obs/propagate.py), when any traffic was
                            trace-stamped — the timeline tool's
                            cross-process alignment input
        barrier_ledger.json per-barrier arrival/wait ledger
                            (obs/cluster.py), written on the
                            coordinator when any barrier was recorded
                            — trace_timeline's straggler annotations

    Returns {artifact: path}.  No-op ({}) when disabled."""
    t, d = _tracer, _dir
    if d is None:
        return {}
    out = {}
    if t is not None:
        out["chrome_trace"] = t.export_chrome(
            os.path.join(d, "trace.chrome.json"))
        out["jsonl_trace"] = t.export_jsonl(os.path.join(d, "trace.jsonl"))
    prom = os.path.join(d, "metrics.prom")
    with open(prom, "w") as f:
        f.write(_registry.to_prometheus())
    out["prometheus"] = prom
    mj = os.path.join(d, "metrics.json")
    with open(mj, "w") as f:
        f.write(_registry.to_json())
    out["metrics_json"] = mj
    from fedml_tpu.obs import propagate
    clocks = propagate.clock_exports()
    if clocks:
        cj = os.path.join(d, "clock_offsets.json")
        with open(cj, "w") as f:
            json.dump(clocks, f, indent=1)
        out["clock_offsets"] = cj
    from fedml_tpu.obs import cluster
    cluster.export_dir(d)
    bl = os.path.join(d, "barrier_ledger.json")
    if os.path.exists(bl):
        out["barrier_ledger"] = bl
    return out


def _atexit_export() -> None:                # pragma: no cover - exit path
    try:
        export()
    except Exception:
        pass


def rollup() -> dict:
    """Small summary for embedding in bench JSON lines: where the
    artifacts are plus the headline counters."""
    t = _tracer
    from fedml_tpu.obs import programs, slo
    eng = slo.active()
    return {
        # ISSUE 12: the judgment layer's verdict rides every rollup —
        # the installed SLO engine's pack state (None when no engine
        # runs) plus the process-total breach count either way
        "slo": (eng.report() if eng is not None else None),
        "slo_breaches_total": sum(
            m.value for m in _registry.metrics()
            if m.name == "slo_breaches_total"),
        "program_families": sorted(programs.families()),
        "obs_dir": _dir,
        "spans_recorded": (0 if t is None
                           else len(t.events()) + t.dropped),
        # ring evictions, surfaced here so a truncated trace can never
        # masquerade as a complete one (ISSUE-7 satellite) — with the
        # spill accounting that says how much of the head survived
        "spans_dropped": 0 if t is None else t.dropped,
        "spans_spilled": 0 if t is None else t.spilled,
        "spill_truncated": 0 if t is None else t.spill_truncated,
        "http_port": None if _http is None else _http.port,
        "jit_compile_total": counter("jit_compile_total").value,
        "jit_compile_seconds_total":
            counter("jit_compile_seconds_total").value,
        "flight_dumps": [] if _flight is None else list(_flight.dumps),
    }


# -- jax compile accounting --------------------------------------------------
# jax.monitoring publishes per-compile duration events
# ("/jax/core/compile/backend_compile_duration" on this jaxlib); one
# listener turns them into jit_compile_total / jit_compile_seconds_total.
# Registered at import, once per process; the listener resolves the
# registry through the module global so reset() redirects it too.

def _on_jax_duration_event(event: str, duration: float, **kw) -> None:
    if event.endswith("backend_compile_duration"):
        _registry.counter("jit_compile_total").inc()
        _registry.counter("jit_compile_seconds_total").inc(duration)
        # compile-accounting attribution (ISSUE 12): when the compile
        # was triggered from inside an instrumented program family's
        # dispatch (obs/programs.py marks the calling thread), the
        # labeled series name the culprit — a recompile storm then
        # reads "fedavg_streaming recompiled 40x", not one global
        # counter ticking.  The unlabeled pair above stays the
        # process-total (rollup() and older consumers read it).
        fam = _program_family_of_thread()
        _registry.counter("jit_compile_total",
                          family=fam or "unattributed").inc()
        _registry.counter("jit_compile_seconds_total",
                          family=fam or "unattributed").inc(duration)
        t = _tracer
        if t is not None:
            t.instant("jit.backend_compile", seconds=duration,
                      family=fam)


def _program_family_of_thread():
    try:
        from fedml_tpu.obs import programs
        return programs.current()
    except Exception:                         # pragma: no cover - import
        return None


def _register_jax_listener() -> None:
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(
            _on_jax_duration_event)
    except Exception:                         # pragma: no cover - old jax
        pass


_register_jax_listener()
