"""Aux subsystems: orbax checkpoint/resume, run logger, step timer."""
import json
import os

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms import FedAvgEngine, FedOptEngine
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.models import create_model
from fedml_tpu.utils.checkpoint import FedCheckpointManager
from fedml_tpu.utils.config import FedConfig
from fedml_tpu.utils.metrics import RunLogger
from fedml_tpu.utils.profiling import StepTimer
from tests.test_fednas import tiny_data


def make_engine(cls=FedAvgEngine, **cfg_kw):
    cfg = FedConfig(client_num_in_total=3, client_num_per_round=2,
                    comm_round=4, epochs=1, batch_size=4, lr=0.1,
                    frequency_of_the_test=1, **cfg_kw)
    data = tiny_data(n_clients=3, bs=4, hw=8)
    return cls(ClientTrainer(create_model("lr", 10), lr=0.1), data, cfg,
               donate=False)


def assert_bitwise_resume(make, tmp_path, name):
    """Shared resume oracle: 4 straight rounds == 2 rounds + checkpoint +
    resumed 4 rounds, bitwise; asserts the checkpoint actually landed
    (a silent save failure would otherwise re-run from scratch and pass
    vacuously — FedAvgEngine.run falls back when no checkpoint exists)."""
    v_straight = make().run(rounds=4)
    ck = FedCheckpointManager(str(tmp_path / name))
    make().run(rounds=2, ckpt=ck, ckpt_every=1)
    assert ck.latest_round() == 1
    v_resumed = make().run(rounds=4, ckpt=ck, resume=True)
    for a, b in zip(jax.tree.leaves(v_straight), jax.tree.leaves(v_resumed)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=0)
    ck.close()


def test_checkpoint_resume_bitwise(tmp_path):
    """Run 4 rounds straight vs 2 rounds + checkpoint + resume: identical
    final variables (fold_in rngs + per-round sampler reseed)."""
    e1 = make_engine()
    v_straight = e1.run(rounds=4)

    ck = FedCheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    e2 = make_engine()
    e2.run(rounds=2, ckpt=ck, ckpt_every=1)
    assert ck.latest_round() == 1
    e3 = make_engine()
    v_resumed = e3.run(rounds=4, ckpt=ck, resume=True)
    for a, b in zip(jax.tree.leaves(v_straight), jax.tree.leaves(v_resumed)):
        np.testing.assert_allclose(a, b, rtol=0, atol=0)
    ck.close()


def test_checkpoint_resume_mesh_streaming(tmp_path):
    """Resume through the MESH engine (sharded variables via orbax, then
    re-placed by _prepare_variables) on the streaming cohort path."""
    from fedml_tpu.parallel import MeshFedAvgEngine
    from fedml_tpu.parallel.mesh import make_mesh

    def mesh_engine():
        cfg = FedConfig(client_num_in_total=4, client_num_per_round=4,
                        comm_round=4, epochs=1, batch_size=4, lr=0.1,
                        frequency_of_the_test=1)
        data = tiny_data(n_clients=4, bs=4, hw=8)
        return MeshFedAvgEngine(
            ClientTrainer(create_model("lr", 10), lr=0.1), data, cfg,
            mesh=make_mesh(4), donate=False, streaming=True)

    assert_bitwise_resume(mesh_engine, tmp_path, "ckm")


def test_checkpoint_nontrivial_server_state(tmp_path):
    """FedOpt's optax server state round-trips through orbax."""
    ck = FedCheckpointManager(str(tmp_path / "ck2"))
    e = make_engine(FedOptEngine, server_optimizer="adam", server_lr=0.01)
    e.run(rounds=2, ckpt=ck, ckpt_every=2)
    e2 = make_engine(FedOptEngine, server_optimizer="adam", server_lr=0.01)
    v0 = e2.init_variables()
    rd, v, ss = ck.restore(v0, e2.server_init(v0))
    assert rd == 1
    assert jax.tree.structure(ss) == jax.tree.structure(e2.server_init(v0))
    ck.close()


def test_run_logger_summary_contract(tmp_path):
    lg = RunLogger(root=str(tmp_path), project="p", name="r1")
    lg.log({"test_acc": 0.5, "train_loss": 1.0}, step=0)
    lg.log({"test_acc": 0.9}, step=1)
    lg.finish()
    summary = RunLogger.read_summary(lg.dir)
    assert summary["test_acc"] == 0.9       # last value wins
    assert summary["train_loss"] == 1.0
    lines = open(os.path.join(lg.dir, "history.jsonl")).read().splitlines()
    assert len(lines) == 2 and json.loads(lines[1])["_step"] == 1


def test_run_logger_context_manager_and_idempotent_close(tmp_path):
    """`with RunLogger(...)` closes on any exit; close() is finish()'s
    alias and both are idempotent; a closed logger refuses log()
    (silently dropping lines would corrupt the history contract)."""
    with RunLogger(root=str(tmp_path), project="p", name="cm") as lg:
        lg.log({"a": 1.0}, step=0)
        # flush-on-log: the line is durable BEFORE close (a killed run
        # keeps what it logged)
        lines = open(os.path.join(lg.dir, "history.jsonl")).read()
        assert json.loads(lines.splitlines()[0])["a"] == 1.0
    assert lg._hist.closed
    lg.close()                          # idempotent (alias of finish)
    lg.finish()
    with pytest.raises(ValueError, match="closed"):
        lg.log({"b": 2.0}, step=1)
    assert RunLogger.read_summary(lg.dir) == {"a": 1.0}


def test_engine_logs_to_logger(tmp_path):
    lg = RunLogger(root=str(tmp_path), project="p", name="r2")
    e = make_engine()
    e.run(rounds=2, logger=lg)
    lg.finish()
    s = RunLogger.read_summary(lg.dir)
    assert "test_acc" in s and s["round"] == 1


def test_step_timer():
    t = StepTimer()
    with t.phase("train"):
        pass
    with t.phase("train"):
        pass
    assert t.counts["train"] == 2
    assert "train_mean_s" in t.report()


def test_checkpoint_resume_full_feature_stack(tmp_path):
    """Resume bitwise-identically through the FULL mesh feature stack at
    once: streaming cohorts x bf16 local masters x chunked scan x adam
    client optimizer x poly LR schedule — interactions none of the
    single-feature resume tests exercise together."""
    import jax.numpy as jnp
    from fedml_tpu.core.trainer import make_lr_schedule
    from fedml_tpu.parallel import MeshFedAvgEngine
    from fedml_tpu.parallel.mesh import make_mesh

    def engine():
        cfg = FedConfig(client_num_in_total=6, client_num_per_round=4,
                        comm_round=4, epochs=1, batch_size=4, lr=0.05,
                        client_optimizer="adam", frequency_of_the_test=1)
        data = tiny_data(n_clients=6, bs=4, hw=8)
        B = data.client_shards["x"].shape[1]
        sched = make_lr_schedule("poly", cfg.lr, total_steps=B,
                                 iters_per_epoch=B)
        tr = ClientTrainer(create_model("lr", 10), lr=sched,
                           optimizer="adam")
        return MeshFedAvgEngine(tr, data, cfg, mesh=make_mesh(4),
                                donate=False, streaming=True, chunk=1,
                                local_dtype=jnp.bfloat16)

    assert_bitwise_resume(engine, tmp_path, "ckf")
