"""Test config: force an 8-device virtual CPU mesh so multi-device sharding
is testable without TPU hardware (SURVEY.md §4 implication).

The environment may register an out-of-tree TPU-tunnel PJRT plugin via
sitecustomize that (a) overrides jax_platforms and (b) blocks at backend
init when the tunnel is unavailable.  Tests must never depend on that
hardware path, so we force the CPU platform and drop any non-CPU backend
factories before the first backend initialization.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env vars)

# The sitecustomize hook force-sets jax_platforms="axon,cpu"; pin it back so
# backends() never initializes the (possibly unreachable) tunnel backend.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite compiles hundreds of XLA programs
# (mesh round programs dominate wall-clock — VERDICT r2 weak #8); repeat
# runs hit the disk cache instead of recompiling.  Safe to share across
# processes; keyed on program + compile options.  The dir constant lives
# in multihost_case so the multihost workers (fresh subprocesses) hit
# the SAME cache.
from multihost_case import JAX_TEST_CACHE_DIR  # noqa: E402

jax.config.update("jax_compilation_cache_dir", JAX_TEST_CACHE_DIR)
# 0.1 (was 0.5): the suite compiles many hundreds of 0.1-0.5 s
# programs across 8 xdist workers + the subprocess-spawning tests;
# caching them too trades ~ms of disk lookup for their compile CPU
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
