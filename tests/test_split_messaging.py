"""Remote SplitNN protocol tests (comm/split_messaging.py).

The reference's comm stress test (SURVEY.md §3.4): the process boundary is
crossed twice per minibatch.  Here: INPROC deployment with 2 clients taking
round-robin turns, and a TCP loopback variant over real sockets.
Closes VERDICT r1 missing #2 / next-round #3.
"""
import numpy as np

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.comm.inproc import InProcRouter
from fedml_tpu.comm.split_messaging import (SplitClientCompute,
                                            SplitNNClientManager,
                                            SplitNNServerManager,
                                            SplitServerCompute)


class _Lower(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.relu(nn.Dense(16)(x))


class _Upper(nn.Module):
    @nn.compact
    def __call__(self, a):
        return nn.Dense(3)(a)


def _shards(seed, n_batches=4, bs=8, dim=12):
    """Linearly separable 3-class task, padded-batch layout."""
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(7).randn(dim, 3)
    x = rng.randn(n_batches, bs, dim).astype(np.float32)
    y = np.argmax(x @ w, axis=-1).astype(np.int64)
    mask = np.ones((n_batches, bs), np.float32)
    return {"x": x, "y": y, "mask": mask}


def _build(n_clients=2, epochs=2, backend="INPROC", **bkw):
    ccomp = SplitClientCompute(_Lower(), lr=0.1)
    scomp = SplitServerCompute(_Upper(), lr=0.1)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((8, 12), jnp.float32)
    cp, copt = ccomp.init(rng, sample)
    acts = ccomp.forward(cp, sample)
    sp, sopt = scomp.init(rng, acts)

    server = SplitNNServerManager(scomp, sp, sopt, max_rank=n_clients,
                                  backend=backend, **bkw)
    clients = []
    for r in range(1, n_clients + 1):
        cpi = jax.tree.map(jnp.copy, cp)
        coi = jax.tree.map(jnp.copy, copt)
        clients.append(SplitNNClientManager(
            ccomp, cpi, coi, _shards(seed=r), _shards(seed=100 + r),
            rank=r, max_rank=n_clients, epochs=epochs,
            backend=backend, **bkw))
    return server, clients


def test_splitnn_inproc_two_clients_round_robin():
    router = InProcRouter()
    server, clients = _build(n_clients=2, epochs=2, backend="INPROC",
                             router=router)
    threads = [server.run_async()] + [c.run_async() for c in clients]
    clients[0].start_protocol()
    assert server.done.wait(timeout=60), "protocol did not finish"
    for c in clients:
        assert c.done.wait(timeout=10)
    # 2 clients x 2 epochs = 4 validation records, alternating active node
    assert len(server.val_history) == 4
    assert [h["active_node"] for h in server.val_history] == [1, 2, 1, 2]
    for h in server.val_history:
        assert 0.0 <= h["val_acc"] <= 1.0
        assert np.isfinite(h["val_loss"])
    # training happened: late accuracy beats the first sweep on this
    # separable task
    assert server.val_history[-1]["val_acc"] >= server.val_history[0]["val_acc"]
    # every client's lower net moved away from the shared init
    p0 = jax.tree.leaves(clients[0].params)
    p1 = jax.tree.leaves(clients[1].params)
    assert any(not np.allclose(a, b) for a, b in zip(p0, p1))


def test_splitnn_learns_inproc():
    """Longer run: server-side validation accuracy must clearly beat chance
    (1/3) — the distillation-free split semantics actually learn."""
    router = InProcRouter()
    server, clients = _build(n_clients=2, epochs=4, backend="INPROC",
                             router=router)
    _ = [server.run_async()] + [c.run_async() for c in clients]
    clients[0].start_protocol()
    assert server.done.wait(timeout=120)
    assert server.val_history[-1]["val_acc"] > 0.6


def test_splitnn_tcp_loopback():
    """The same protocol over real sockets (run_fedavg_grpc.sh-style
    deployment, single host, 3 ranks)."""
    ip_cfg = {0: "127.0.0.1", 1: "127.0.0.1", 2: "127.0.0.1"}
    server, clients = _build(n_clients=2, epochs=1, backend="TCP",
                             ip_config=ip_cfg, base_port=57300,
                             force_python_tcp=True)
    try:
        _ = [server.run_async()] + [c.run_async() for c in clients]
        clients[0].start_protocol()
        assert server.done.wait(timeout=120), "protocol did not finish"
        assert len(server.val_history) == 2
    finally:
        for m in clients + [server]:
            try:
                m.finish()
            except Exception:
                pass
