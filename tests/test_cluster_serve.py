"""Fused serving cluster tests (ISSUE 18, fedml_tpu/scale/cluster.py).

The fusion's two invariants, pinned over REAL sockets:

  * world==1 with matched traffic is byte-identical to the pre-fusion
    synthetic path — run_serve_sim's _ServeLane and the reactor-fed
    ClusterServeManager commit the SAME digest when fed the same rows
    in the same per-lane order (the fold never sees socket arrival
    order: uplinks buffer per lane, lanes fold in item order);
  * world==2 with live ingest commits the SAME digest on both ranks —
    the commit-barrier fold is a pure function of the block/lane
    partition, exchanged through ElasticChannel exactly like the
    elastic multihost tier.

Plus the satellite pins: the reactor's overload gate reads lane
saturation (registry pressure reaches the door), and the connswarm
fleet stripes across a multi-target endpoint list with per-target
stats and the burst-cap pacing knob.

Budget: everything here is in-process over loopback sockets except the
single spawned 2-rank smoke at the bottom (the ISSUE-18 tier-1 budget
allows at most ONE spawned-cluster arm).
"""
import json
import sys
import threading
import time

import numpy as np
import pytest

from fedml_tpu.parallel.multihost import (ElasticChannel, MultihostContext,
                                          free_port)
from fedml_tpu.scale.arrivals import ArrivalConfig
from fedml_tpu.scale.cluster import (ClusterServeManager, make_uplink_frame,
                                     run_cluster_serve, send_uplinks)
from fedml_tpu.scale.serve import run_serve_sim


def _feed(port, frames, hold, attempts=200):
    """Retry-dial a reactor endpoint that may not be listening yet and
    stream `frames` down one connection, holding it open on `hold` so
    the server never sees a mid-window disconnect."""
    for _ in range(attempts):
        try:
            send_uplinks("127.0.0.1", port, frames, hold_open=hold)
            return
        except OSError:
            time.sleep(0.05)


def test_world1_socket_path_matches_synthetic_digest():
    """The pre-fusion trace pin: run_serve_sim's synthetic lane and the
    reactor-fed cluster path, given the SAME rows in the same order,
    commit byte-identical variables.  The cluster run gets the rows
    over a real TCP connection — so this also pins that the wire
    (codec + decode pool + admission) is value-preserving end-to-end."""
    COMMITS, K, DIM, SEED, POP = 4, 4, 32, 7, 64
    sim = run_serve_sim(POP, commits=COMMITS, warmup_commits=1,
                        buffer_k=K, row_dim=DIM, seed=SEED,
                        arrival=ArrivalConfig(mode="constant",
                                              rate=1000.0, seed=SEED))
    # the exact row stream _ServeLane generates at banned_frac=0: the
    # 64-row pool is the FIRST draw from rng([seed, 2]), admitted
    # round-robin at weight 1.0 (see scale/serve.py)
    pool = np.random.default_rng([SEED, 2]).standard_normal(
        (64, DIM)).astype(np.float32)
    frames = [make_uplink_frame(pool[i % 64], sender=1, weight=1.0)
              for i in range(COMMITS * K)]
    port = free_port()
    hold = threading.Event()
    th = threading.Thread(target=_feed, args=(port, frames, hold),
                          daemon=True)
    th.start()
    try:
        rep = run_cluster_serve(POP, commits=COMMITS, warmup_commits=1,
                                buffer_k=K, row_dim=DIM, port=port,
                                n_connections=4, ingest_pool=1,
                                window_deadline_s=30.0, timeout_s=60.0,
                                backlog_cap=COMMITS * K)
    finally:
        hold.set()
    th.join(timeout=5)
    assert rep["committed_digest"] == sim["committed_digest"], (
        "world==1 reactor-fed path diverged from the synthetic "
        "pre-fusion trace — the fold saw socket arrival order or the "
        "wire mutated a row")
    assert rep["commits"] == COMMITS
    assert rep["committed_updates"] == COMMITS * K
    assert rep["misrouted"] == 0
    assert rep["lane_overflow_dropped"] == 0


def test_two_rank_live_ingest_digests_agree():
    """Invariant (a) executed: two in-process ranks, each fed DIFFERENT
    rows over its own socket, fold lane partials through a real
    ElasticChannel at every commit barrier and must commit the same
    global bits — the fold order is the block/lane partition, not
    arrival order."""
    COMMITS, K, DIM, SEED, POP, WORLD = 3, 4, 32, 5, 64, 2
    coord = free_port()
    ports = [free_port() for _ in range(WORLD)]
    reports = [None] * WORLD
    errors = []
    hold = threading.Event()
    pool = np.random.default_rng([SEED, 9]).standard_normal(
        (64, DIM)).astype(np.float32)

    def worker(r):
        ctx = MultihostContext(rank=r, world=WORLD,
                               coordinator=f"localhost:{coord}")
        ch = ElasticChannel(ctx, n_items=WORLD, config_digest="t2",
                            timeout_s=60.0, connect_timeout_s=30.0,
                            hb_interval_s=0.1, hb_timeout_s=2.0)
        try:
            reports[r] = run_cluster_serve(
                POP, commits=COMMITS, warmup_commits=1, buffer_k=K,
                row_dim=DIM, port=ports[r], partition=(r, WORLD),
                channel=ch, elastic=True, n_connections=4,
                ingest_pool=1, window_deadline_s=30.0, timeout_s=90.0,
                backlog_cap=COMMITS * K)
        except Exception as e:            # surfaced via the assert below
            errors.append((r, repr(e)))
        finally:
            ch.close()

    def feeder(r):
        frames = [make_uplink_frame(pool[(r * 16 + i) % 64], sender=1)
                  for i in range(COMMITS * K)]
        _feed(ports[r], frames, hold)

    ths = [threading.Thread(target=worker, args=(r,))
           for r in range(WORLD)]
    fds = [threading.Thread(target=feeder, args=(r,), daemon=True)
           for r in range(WORLD)]
    for t in ths + fds:
        t.start()
    for t in ths:
        t.join(timeout=120)
    hold.set()
    assert not errors, errors
    assert all(rep is not None for rep in reports)
    d = [rep["committed_digest"] for rep in reports]
    assert d[0] == d[1], (
        f"cross-rank digest mismatch with live ingest: {d} — the "
        "commit-barrier fold is no longer a pure function of the "
        "partition")
    assert all(rep["commits"] == COMMITS for rep in reports)


def test_overload_gate_reads_lane_saturation():
    """Satellite: registry/lane pressure reaches the reactor's door.
    A lane whose window is full AND whose backlog is at cap flips
    lane_pressure() -> the installed overload gate sheds new
    connections with reason "gate" instead of the backlog dropping."""
    mgr = ClusterServeManager(8, population=16, buffer_k=2, port=free_port(),
                              n_connections=4, ingest_pool=1,
                              backlog_cap=2)
    try:
        rg = getattr(mgr.com_manager, "_rg", None)
        assert rg is not None and rg._overload_gate is not None, (
            "ClusterServeManager must install lane_pressure as the "
            "reactor overload gate")
        assert mgr.lane_pressure() is False
        row = np.ones((8,), np.float32)
        # fill the window (buffer_k=2) then the backlog (cap=2)
        for i in range(4):
            mgr._ingest_row(i, row, 1.0, 0.0)
        lane = mgr._lanes[0]
        assert lane.full() and len(lane.backlog) == 2
        assert lane.saturated() and mgr.lane_pressure() is True
        assert rg._overload_reason(time.monotonic()) == "gate"
        # one more uplink beyond saturation drops at the cap
        mgr._ingest_row(4, row, 1.0, 0.0)
        assert lane.overflow_dropped == 1
        # draining the window (commit) releases the pressure: the
        # backlog refills the fresh window and the cap has room again
        parts = mgr.take_partials()
        assert 0 in parts and parts[0][2] == 2     # folded n == buffer_k
        assert mgr.lane_pressure() is False
    finally:
        mgr.finish()


def test_connswarm_multi_target_striping():
    """Satellite: the subprocess fleet config grows a multi-target
    list — sender i dials targets[(i-1) % N], stats carry a per_target
    block, and the token-bucket burst cap defaults to the historical
    1 s (the cluster bench tightens it)."""
    from fedml_tpu.comm.connswarm import ConnectionSwarm, SwarmConfig
    cfg = SwarmConfig.from_json(json.dumps({
        "host": "127.0.0.1", "port": 1, "n_connections": 4,
        "offered_rate": 10.0, "duration_s": 0.0,
        "targets": [["127.0.0.1", 1111], ["127.0.0.2", 2222]],
        "arrival": {"mode": "diurnal", "rate": 10.0, "period_s": 60.0},
    }))
    assert cfg.burst_cap_s == 1.0          # historical default
    assert cfg.arrival["mode"] == "diurnal"
    sw = ConnectionSwarm(cfg, frame=b"x")
    assert sw._target_of(1) == ("127.0.0.1", 1111)
    assert sw._target_of(2) == ("127.0.0.2", 2222)
    assert sw._target_of(3) == ("127.0.0.1", 1111)   # stripes, wraps
    pt = sw.stats["per_target"]
    assert set(pt) == {"127.0.0.1:1111", "127.0.0.2:2222"}
    for blk in pt.values():
        assert {"connects", "refused", "frames_sent"} <= set(blk)
    # single-target configs keep the legacy (host, port) shape
    solo = ConnectionSwarm(SwarmConfig(host="127.0.0.1", port=7, n_connections=1,
                             offered_rate=1.0), frame=b"x")
    assert solo._target_of(1) == ("127.0.0.1", 7)


def test_spawned_two_rank_cluster_smoke():
    """THE one spawned-cluster arm in tier-1 (budget: everything else
    in this file is in-process): two mh_worker processes take the
    serve_cluster route, adopt their shard ranges, ingest real frames
    from this process, fold through the elastic channel, and report
    equal digests over stdout JSON."""
    from fedml_tpu.parallel.multihost import spawn_cluster_report
    import tempfile
    WORLD, COMMITS, K, DIM = 2, 3, 4, 32
    ports = [free_port() for _ in range(WORLD)]
    cfg = {"serve_cluster": {
        "population": 256, "commits": COMMITS, "warmup_commits": 1,
        "buffer_k": K, "row_dim": DIM, "connections": 8,
        "ingest_pool": 1, "window_deadline_s": 20.0,
        "timeout_s": 120.0, "ports": ports,
    }, "channel_timeout_s": 120.0, "hb_timeout_s": 2.0,
       "hb_interval_s": 0.25}
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(cfg, f)
        path = f.name
    pool = np.random.default_rng(3).standard_normal(
        (64, DIM)).astype(np.float32)
    hold = threading.Event()

    def feeder(r):
        frames = [make_uplink_frame(pool[i % 64], sender=1)
                  for i in range(40)]
        _feed(ports[r], frames, hold, attempts=600)

    fds = [threading.Thread(target=feeder, args=(r,), daemon=True)
           for r in range(WORLD)]
    for t in fds:
        t.start()
    try:
        outs, rep = spawn_cluster_report(
            [sys.executable, "-m", "fedml_tpu.parallel.mh_worker", path],
            WORLD, timeout_s=180.0, elastic=True)
    finally:
        hold.set()
    assert all(r["rc"] == 0 for r in rep["ranks"].values()), rep["ranks"]
    docs = {}
    for r, out in enumerate(outs):
        for line in out.splitlines():
            if line.startswith("{"):
                docs[r] = json.loads(line)
    assert set(docs) == set(range(WORLD))
    d = [docs[r]["serve_cluster"]["committed_digest"]
         for r in range(WORLD)]
    assert d[0] == d[1], f"spawned-cluster digest mismatch: {d}"
    for r in range(WORLD):
        sc = docs[r]["serve_cluster"]
        assert sc["commits"] == COMMITS
        assert sc["recv_thread_deaths"] == 0
