"""Worker for test_multihost_spmd's checkpoint/resume case (argv: pid
port nprocs ndev mode ckpt_dir).  Two modes:

  interrupt — run the first 2 of 4 rounds with per-round orbax
              checkpointing, then EXIT (the "kill" in save→kill→resume:
              rounds 2-3 never run in this cluster).
  resume    — in a FRESH cluster: first run the uninterrupted 4-round
              oracle (same processes, same gloo topology — the digest
              comparison isolates the resume mechanics from any
              cross-topology reduction-order noise), then resume from
              the checkpoint and continue rounds 2-3.  Prints both
              digests; the test asserts they are identical.

The reference has no FL-state resume at all (SURVEY.md §5) — this is
the framework's own bar: round-level orbax checkpointing that survives
a multi-process SPMD cluster's death.
"""
import os
import sys

pid, port, nprocs, ndev, mode, ckpt_dir = (
    int(sys.argv[1]), sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5], sys.argv[6])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={ndev}"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from tests.multihost_case import JAX_TEST_CACHE_DIR  # noqa: E402

jax.config.update("jax_compilation_cache_dir", JAX_TEST_CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from fedml_tpu.parallel.multihost import init_multihost  # noqa: E402

init_multihost(coordinator_address=f"localhost:{port}",
               num_processes=nprocs, process_id=pid, required=True)

from fedml_tpu.utils.checkpoint import FedCheckpointManager  # noqa: E402
from tests.multihost_case import build_ckpt_case, digest  # noqa: E402

assert jax.device_count() == nprocs * ndev

if mode == "interrupt":
    eng = build_ckpt_case()
    mgr = FedCheckpointManager(ckpt_dir)
    eng.run(rounds=2, ckpt=mgr, ckpt_every=1)
    saved = mgr.latest_round()
    mgr.close()
    print(f"SAVED {saved}", flush=True)
elif mode == "resume":
    full = build_ckpt_case()
    v_full = full.run(rounds=4)
    print(f"CKFULL {digest(v_full):.10e}", flush=True)
    eng = build_ckpt_case()
    mgr = FedCheckpointManager(ckpt_dir)
    v_res = eng.run(rounds=4, ckpt=mgr, resume=True)
    mgr.close()
    print(f"CKRES {digest(v_res):.10e}", flush=True)
else:
    raise SystemExit(f"unknown mode {mode!r}")
