"""Model zoo shape checks (reference has only cv/test_cnn.py, a 13-LoC
shape test; here every factory entry gets one)."""
import jax
import jax.numpy as jnp
import pytest

from fedml_tpu.models import create_model

IMG32 = (2, 32, 32, 3)
IMG28 = (2, 28, 28, 1)


def _forward(model, shape, train=False, **init_kw):
    """init+apply under jit: eager dispatch of the deep zoo models costs
    tens of seconds per test on CPU and is uncacheable; as two compiled
    programs the persistent compilation cache (conftest) makes warm suite
    runs near-instant."""
    x = jnp.zeros(shape, jnp.float32)
    init = jax.jit(lambda k, xi: model.init(k, xi, train=False, **init_kw))
    variables = init(jax.random.PRNGKey(0), x)
    if train:
        apply = jax.jit(lambda v, xi, k: model.apply(
            v, xi, train=True, rngs={"dropout": k},
            mutable=["batch_stats"]))
        return apply(variables, x, jax.random.PRNGKey(1))[0]
    apply = jax.jit(lambda v, xi: model.apply(v, xi, train=False))
    return apply(variables, x)


@pytest.mark.parametrize("name,shape,classes", [
    ("mobilenet_v3", IMG32, 10),
    ("efficientnet-b0", IMG32, 10),
])
def test_new_cv_models_forward(name, shape, classes):
    logits = _forward(create_model(name, classes), shape)
    assert logits.shape == (shape[0], classes)
    assert jnp.all(jnp.isfinite(logits))


@pytest.mark.parametrize("name,shape,classes", [
    ("lr", (2, 784), 10),
    ("cnn", (2, 28, 28, 1), 62),
    ("cnn_dropout", (2, 28, 28, 1), 62),
    ("resnet18_gn", IMG32, 10),
    ("resnet20", IMG32, 10),
    ("resnet56", IMG32, 100),
    ("mobilenet", IMG32, 10),
    ("vgg11", IMG32, 10),
    ("vgg16", IMG32, 10),
])
def test_full_zoo_forward(name, shape, classes):
    """Every --model factory name produces finite logits of the right
    shape (reference model zoo §2.6 row-by-row)."""
    logits = _forward(create_model(name, classes), shape)
    assert logits.shape == (shape[0], classes)
    assert jnp.all(jnp.isfinite(logits))


@pytest.mark.parametrize("name,vocab,seq", [
    ("rnn", 90, 80),
    ("rnn_stackoverflow", 10004, 20),
])
def test_zoo_rnn_forward(name, vocab, seq):
    m = create_model(name, vocab)
    x = jnp.zeros((2, seq), jnp.int32)
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(v, x, train=False)
    assert out.shape == (2, seq, vocab)
    assert jnp.all(jnp.isfinite(out))


def test_mobilenet_v3_small_mode():
    m = create_model("mobilenet_v3", 10, mode="small")
    logits = _forward(m, IMG32)
    assert logits.shape == (2, 10)


def test_efficientnet_train_mode_with_drop_connect():
    m = create_model("efficientnet-b0", 10)
    logits = _forward(m, IMG32, train=True)
    assert logits.shape == (2, 10)
    assert jnp.all(jnp.isfinite(logits))


def test_efficientnet_variant_scaling():
    from fedml_tpu.models.efficientnet import PARAMS
    assert set(PARAMS) == {f"b{i}" for i in range(8)}


def test_factory_rejects_unknown():
    with pytest.raises(ValueError):
        create_model("no_such_model", 10)


def test_resnet18_gn_fusion_barrier_is_identity():
    """norm_fusion_barrier only changes XLA fusion decisions, never math:
    same rng init must give identical params (module structure apart from
    the GN class name is unchanged) and identical logits."""
    import numpy as np
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3),
                    jnp.float32)
    plain = create_model("resnet18_gn", 10)
    barrier = create_model("resnet18_gn", 10, norm_fusion_barrier=True)
    vp = plain.init(jax.random.PRNGKey(0), x, train=False)
    vb = barrier.init(jax.random.PRNGKey(0), x, train=False)
    for a, b in zip(jax.tree.leaves(vp), jax.tree.leaves(vb)):
        assert a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    lp = plain.apply(vp, x, train=False)
    lb = barrier.apply(vb, x, train=False)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(lb),
                               rtol=1e-6, atol=1e-6)
