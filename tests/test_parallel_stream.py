"""Streaming and block-streamed mesh-engine tests (split out of
test_parallel.py): the cohort-on-host paths — per-round streaming
uploads, block-streamed rounds (linear engines + the two-phase
order-statistic defenses), and their device-memory bounds.  Oracles:
each path must reproduce the HBM-resident round exactly.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.loaders import load_data
from fedml_tpu.models import create_model
from fedml_tpu.parallel import (MeshFedAvgEngine, MeshFedOptEngine,
                                MeshRobustEngine)
from fedml_tpu.parallel.mesh import make_mesh
from fedml_tpu.utils.config import FedConfig

from parallel_case import _mnist_like_cfg, _setup, run_donate_pair


def _live_bytes():
    """Total bytes across all live device arrays — the one accounting
    every memory-bound test in this file shares."""
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for a in jax.live_arrays())


def _spy_live_bytes(obj, attr, peaks):
    """Wrap obj.attr so each call first appends _live_bytes() to peaks."""
    orig = getattr(obj, attr)
    setattr(obj, attr,
            lambda *a: (peaks.append(_live_bytes()), orig(*a))[1])


def test_streaming_matches_resident():
    """Streaming cohort upload (host-gather, VERDICT r1 #5) must reproduce
    the HBM-resident path exactly — same sampling, same chunked round."""
    cfg = _mnist_like_cfg(client_num_per_round=12, comm_round=3)
    trainer, data = _setup(cfg)
    res = MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh(8),
                           donate=False)
    v0 = res.init_variables()
    v_res = res.run(variables=jax.tree.map(jnp.copy, v0), rounds=3)
    stream = MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh(8),
                              donate=False, streaming=True)
    v_str = stream.run(variables=jax.tree.map(jnp.copy, v0), rounds=3)
    for a, b in zip(jax.tree.leaves(v_res), jax.tree.leaves(v_str)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def _assert_blockstream_matches(engine_cls, cfg, trainer, data,
                                stream_block=8, rounds=2):
    """Shared oracle body: block-streamed == whole-cohort streaming for
    one engine class (same sampling, same per-client rngs — split
    prefixes are stable — zero-weight pad lanes contribute exactly 0)."""
    stream = engine_cls(trainer, data, cfg, mesh=make_mesh(8),
                        donate=False, streaming=True)
    v0 = stream.init_variables()
    v_str = stream.run(variables=jax.tree.map(jnp.copy, v0), rounds=rounds)
    blk = engine_cls(trainer, data, cfg, mesh=make_mesh(8),
                     donate=False, stream_block=stream_block)
    assert blk.streaming        # stream_block implies streaming
    v_blk = blk.run(variables=jax.tree.map(jnp.copy, v0), rounds=rounds)
    for a, b in zip(jax.tree.leaves(v_str), jax.tree.leaves(v_blk)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_blockstream_matches_streaming():
    """12 sampled clients in blocks of 8 on an 8-shard mesh exercises the
    final block's shard-level zero-weight padding."""
    cfg = _mnist_like_cfg(client_num_per_round=12, comm_round=3)
    trainer, data = _setup(cfg)
    _assert_blockstream_matches(MeshFedAvgEngine, cfg, trainer, data,
                                rounds=3)


def test_blockstream_block_multiple_padding():
    """stream_block=16 on the 8-shard mesh with 12 sampled clients: ids
    are shard-padded 12->16 by _sample_padded_np and the BLOCK padding
    branch (pad to a stream_block multiple with zero-weight repeated-id
    lanes) is a no-op at 16... so use 20 sampled of 24: shard-pad
    20->24, block-pad 24->32 — the branch the block-equals-streaming
    oracle must also survive (differing rng split counts are prefix-
    stable; pad lanes carry weight 0)."""
    cfg = _mnist_like_cfg(client_num_in_total=24, client_num_per_round=20,
                          comm_round=2)
    trainer, data = _setup(cfg)
    _assert_blockstream_matches(MeshFedAvgEngine, cfg, trainer, data,
                                stream_block=16)


def test_blockstream_fedopt_and_gates():
    """FedOpt server state threads through the block finalize; the
    block-multiple gates hold."""
    cfg = _mnist_like_cfg(server_optimizer="adam", server_lr=0.05,
                          comm_round=2)
    trainer, data = _setup(cfg)
    _assert_blockstream_matches(MeshFedOptEngine, cfg, trainer, data)

    r_cfg = FedConfig(**{**cfg.__dict__, "norm_bound": 0.5})
    # order statistics cannot ignore padded lanes: the cohort (16) must
    # be a stream_block multiple (32 is not a divisor -> refuse)
    with pytest.raises(ValueError, match="block multiple"):
        MeshRobustEngine(trainer, data, r_cfg, defense="krum",
                         mesh=make_mesh(8), donate=False, stream_block=32)
    # norm_clip is per-client and streams fine
    MeshRobustEngine(trainer, data, r_cfg, defense="norm_clip",
                     mesh=make_mesh(8), donate=False, stream_block=8)
    with pytest.raises(ValueError, match="multiple"):
        MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh(8),
                         donate=False, stream_block=3)


def test_blockstream_orderstat_device_memory_is_bounded():
    """SCALING.md "Order statistics beyond HBM": a 32-client median
    round in 8-client blocks must hold device data O(block) in phase 1
    and O(K x Pb) in phase 2 — never the O(K x P) cohort matrix, which
    stays in host RAM.  Same live-bytes harness as the linear-path
    bound test.  (Sizes chosen for CI cost: the bound is scale-free —
    both phases still run multiple steps per round, and round 2 guards
    cross-round accumulation.)"""
    n = 32
    cfg = _mnist_like_cfg(client_num_in_total=n, client_num_per_round=n,
                          comm_round=2, frequency_of_the_test=100,
                          norm_bound=0.5)
    data = load_data("femnist", client_num_in_total=n, batch_size=20,
                     synthetic_scale=0.0, seed=0)
    model = create_model("cnn", output_dim=data.class_num)
    trainer = ClientTrainer(model, lr=0.05)
    # param_block_bytes small enough that phase 2 still runs MANY
    # slices: the engine sizes each device slice [K, pb] to
    # param_block_bytes total, i.e. pb = param_block_bytes/(K*4)
    # elements — 4 MiB at K=32 gives pb=32768 and ~52 slices over the
    # 1.69M-param CNN
    eng = MeshRobustEngine(trainer, data, cfg, defense="median",
                           n_byzantine=1, mesh=make_mesh(8),
                           stream_block=8, param_block_bytes=4 << 20)

    block = eng._upload_block(np.arange(8), np.ones(8, np.float32),
                              np.asarray(jax.random.split(
                                  jax.random.PRNGKey(0), 8)))
    block_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                      for a in jax.tree.leaves(block))
    del block
    v = eng.init_variables()
    v = eng._prepare_variables(v)
    var_bytes = sum(int(np.prod(a.shape)) * 4 for a in jax.tree.leaves(v))
    # flats [B, P] per block-step + the phase-2 [K, Pb] slice + result
    P_flat = var_bytes // 4    # f32 leaves -> element count upper bound
    flats_bytes = 8 * P_flat * 4
    slice_bytes = 2 * (4 << 20)
    baseline = _live_bytes() + block_bytes

    peaks = []
    # sample BOTH phases: phase 1 at every block upload, phase 2 at
    # every param-slice colstat call (a regression that materializes the
    # whole [K, P] matrix on device in either phase must land in peaks)
    _spy_live_bytes(eng, "_upload_block", peaks)
    _spy_live_bytes(eng, "_colstat", peaks)
    v = eng.run(variables=v, rounds=2)
    assert eng._stack is None
    assert len(peaks) >= 2 * (n // 8)
    eval_bytes = sum(np.asarray(x).nbytes
                     for shard in (data.train_global, data.test_global)
                     for x in shard.values())
    # new_flat [P] + host->device result assembly ride the var_bytes term
    bound = (baseline + 2 * block_bytes + 2 * var_bytes + flats_bytes
             + slice_bytes + eval_bytes + (8 << 20))
    assert max(peaks) <= bound, (max(peaks), bound)
    # the bound must itself sit well below resident-cohort scale, or the
    # test guards nothing
    cohort_matrix_bytes = n * P_flat * 4     # what the resident path holds
    assert bound < baseline + cohort_matrix_bytes // 2, (
        bound, cohort_matrix_bytes)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(v))


def test_blockstream_orderstat_refuses_multiprocess(monkeypatch):
    """The two-phase path offloads client-sharded flats with np.asarray,
    which a multi-process mesh cannot address — refusal must land at
    CONSTRUCTION, not mid-round after training work."""
    cfg = _mnist_like_cfg(comm_round=2, norm_bound=0.5)
    trainer, data = _setup(cfg)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="single-process"):
        MeshRobustEngine(trainer, data, cfg, defense="median",
                         mesh=make_mesh(8), donate=False, stream_block=8)


@pytest.mark.parametrize("defense", ["median", "trimmed_mean", "krum",
                                     "multi_krum"])
def test_blockstream_orderstat_matches_resident(defense):
    """VERDICT r4 #3: the two-phase block-streamed order-stat defenses
    (client-major training blocks -> host [K, P] matrix -> param-major
    [K, Pb] device slices) must reproduce the HBM-resident defense.
    median/trimmed_mean are bitwise-equal (same values, same per-column
    sort); krum matches the same selected client.  param_block_bytes is
    shrunk so phase 2 actually runs MULTIPLE param slices."""
    cfg = _mnist_like_cfg(comm_round=2, norm_bound=0.5)
    trainer, data = _setup(cfg)
    res = MeshRobustEngine(trainer, data, cfg, defense=defense,
                           n_byzantine=1, mesh=make_mesh(8), donate=False)
    v0 = res.init_variables()
    v_res = res.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    blk = MeshRobustEngine(trainer, data, cfg, defense=defense,
                           n_byzantine=1, mesh=make_mesh(8), donate=False,
                           stream_block=8, param_block_bytes=16 * 64)
    assert blk.round_fn == blk._round_blockstream_orderstat
    v_blk = blk.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    for a, b in zip(jax.tree.leaves(v_res), jax.tree.leaves(v_blk)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_blockstream_fednova_matches_streaming():
    """FedNova's extra linear sums (tau-normalized d, Σ w·τ) thread
    through the generic block accumulators — block-streamed FedNova must
    match the whole-cohort streaming round."""
    from fedml_tpu.parallel import MeshFedNovaEngine
    cfg = _mnist_like_cfg(client_num_per_round=12, comm_round=2)
    trainer, data = _setup(cfg)
    _assert_blockstream_matches(MeshFedNovaEngine, cfg, trainer, data)


def test_blockstream_fedprox_matches_streaming():
    """The prox term (global_params anchor inside local_train) rides the
    block path unchanged."""
    from fedml_tpu.parallel import MeshFedProxEngine
    cfg = _mnist_like_cfg(client_num_per_round=12, comm_round=2,
                          prox_mu=0.1)
    trainer, data = _setup(cfg, prox_mu=0.1)
    _assert_blockstream_matches(MeshFedProxEngine, cfg, trainer, data)


def test_streaming_matches_resident_fedopt():
    """The shared _train_and_update tail must apply subclass server_update
    overrides identically on both cohort paths (FedOpt's optimizer state
    persists across rounds)."""
    cfg = _mnist_like_cfg(server_optimizer="adam", server_lr=0.05,
                          comm_round=3)
    trainer, data = _setup(cfg)
    res = MeshFedOptEngine(trainer, data, cfg, mesh=make_mesh(8),
                           donate=False)
    v0 = res.init_variables()
    v_res = res.run(variables=jax.tree.map(jnp.copy, v0), rounds=3)
    stream = MeshFedOptEngine(trainer, data, cfg, mesh=make_mesh(8),
                              donate=False, streaming=True)
    v_str = stream.run(variables=jax.tree.map(jnp.copy, v0), rounds=3)
    for a, b in zip(jax.tree.leaves(v_res), jax.tree.leaves(v_str)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_streaming_large_client_count():
    """Femnist-shaped scale proxy: many clients, tiny per-round cohort —
    the streaming path never uploads the full stack."""
    cfg = _mnist_like_cfg(client_num_in_total=96, client_num_per_round=8,
                          comm_round=2)
    data = load_data("mnist", client_num_in_total=96, batch_size=8,
                     synthetic_scale=0.02, seed=0)
    model = create_model("lr", output_dim=data.class_num)
    trainer = ClientTrainer(model, lr=0.1)
    eng = MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh(8),
                           streaming=True)
    assert eng._stack is None
    v = eng.run(rounds=2)
    assert eng._stack is None          # full stack never touched the device
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(v))


@pytest.mark.slow   # ~2 min XLA:CPU (3,400-client host stack): the
#                     O(block)/O(cohort) device bounds stay tier-1 via
#                     the two blockstream live-bytes tests above/below;
#                     this reference-scale proxy runs in full suites
def test_streaming_reference_scale_memory_bound():
    """The reference's FEMNIST benchmark client count — 3,400 clients
    (benchmark/README.md:54) — through the streaming engine, with a
    device-residency assertion: across all rounds the live device bytes
    never exceed the pre-round baseline (model + optimizer + eval shards)
    plus TWO padded cohorts (the double-buffer prefetch) — i.e. device
    memory is O(cohort), not O(client_num_in_total)."""
    n = 3400
    cfg = _mnist_like_cfg(client_num_in_total=n, client_num_per_round=10,
                          comm_round=3, frequency_of_the_test=100)
    data = load_data("femnist", client_num_in_total=n, batch_size=20,
                     synthetic_scale=0.0, seed=0)
    assert data.client_num == n
    stack_bytes = sum(np.asarray(v).nbytes
                      for v in data.client_shards.values())
    model = create_model("cnn", output_dim=data.class_num)
    trainer = ClientTrainer(model, lr=0.05)
    eng = MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh(8),
                           streaming=True)

    cohort, w = eng.stream_cohort(0)
    cohort_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                       for a in jax.tree.leaves(cohort)) + w.nbytes
    del cohort, w
    v = eng.init_variables()
    v = eng._prepare_variables(v)
    baseline = _live_bytes() + cohort_bytes  # v + anything engine init left

    peaks = []
    # spy the upload half (_stream_gather): the prefetched rounds call
    # it directly on the background thread — sampling stays on the
    # round loop's thread (engine._round_args) and stream_cohort only
    # fronts it for unprefetched gathers
    _spy_live_bytes(eng, "_stream_gather", peaks)
    v = eng.run(variables=v, rounds=3)
    assert eng._stack is None          # resident stack never built
    assert len(peaks) >= 3
    # every observation: <= baseline + 2 cohorts (prefetch double buffer)
    # + the uploaded eval shards + slack; crucially O(cohort), never
    # O(stack): the full stack is >100x a cohort at this scale
    eval_bytes = sum(np.asarray(x).nbytes
                     for shard in (data.train_global, data.test_global)
                     for x in shard.values())
    bound = baseline + 2 * cohort_bytes + eval_bytes + (8 << 20)
    assert max(peaks) <= bound, (max(peaks), bound)
    assert stack_bytes > 20 * cohort_bytes   # the bound is meaningful
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(v))


@pytest.mark.slow   # 74 s XLA:CPU (the heaviest streaming test —
#                     ISSUE-4 fast/nightly split): the O(block) device
#                     bound stays tier-1-guarded by the orderstat
#                     live-bytes test above (same harness, both phases,
#                     46 s); this linear-path twin runs in the nightly
#                     profile — zero coverage loss across the two
def test_blockstream_device_memory_is_o_block():
    """stream_block's point: a round over a 64-client cohort in 8-client
    blocks must never hold device bytes O(cohort) — only O(block)
    (current + prefetched next + accumulators), even though the cohort
    is 8x the block."""
    n = 64
    cfg = _mnist_like_cfg(client_num_in_total=n, client_num_per_round=n,
                          comm_round=2, frequency_of_the_test=100)
    data = load_data("femnist", client_num_in_total=n, batch_size=20,
                     synthetic_scale=0.0, seed=0)
    model = create_model("cnn", output_dim=data.class_num)
    trainer = ClientTrainer(model, lr=0.05)
    eng = MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh(8),
                           stream_block=8)

    block = eng._upload_block(np.arange(8),
                              np.ones(8, np.float32),
                              np.asarray(jax.random.split(
                                  jax.random.PRNGKey(0), 8)))
    block_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                      for a in jax.tree.leaves(block))
    del block
    v = eng.init_variables()
    v = eng._prepare_variables(v)
    # num accumulator = one f32 copy of the variables
    var_bytes = sum(int(np.prod(a.shape)) * 4
                    for a in jax.tree.leaves(v))
    baseline = _live_bytes() + block_bytes

    peaks = []
    _spy_live_bytes(eng, "_upload_block", peaks)
    v = eng.run(variables=v, rounds=2)
    assert eng._stack is None
    assert len(peaks) >= 2 * (n // 8)      # every block observed
    eval_bytes = sum(np.asarray(x).nbytes
                     for shard in (data.train_global, data.test_global)
                     for x in shard.values())
    bound = baseline + 2 * block_bytes + var_bytes + eval_bytes + (8 << 20)
    assert max(peaks) <= bound, (max(peaks), bound)
    cohort_bytes = 8 * block_bytes          # full participation, 64 clients
    assert cohort_bytes > 4 * block_bytes   # the bound is meaningful
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(v))



def test_donate_bitwise_streaming():
    """The run-loop streaming variant donates the per-round cohort
    (engine._round_fn_streaming_consume); the public replay entry must
    stay un-donated so bench.py-style cohort reuse survives."""
    cfg = _mnist_like_cfg(client_num_per_round=12, comm_round=2)
    trainer, data = _setup(cfg)
    run_donate_pair(lambda donate: MeshFedAvgEngine(
        trainer, data, cfg, mesh=make_mesh(8), donate=donate,
        streaming=True))
    # replay safety: round_fn_streaming does NOT donate the cohort — the
    # same uploaded cohort must survive two calls (bench.py's pattern)
    eng = MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh(8),
                           donate=True, streaming=True)
    v = eng._prepare_variables(eng.init_variables())
    ss = eng.server_init(v)
    cohort, weights = eng.stream_cohort(0)
    rng = jax.random.PRNGKey(0)
    v, ss, _ = eng.round_fn_streaming(v, ss, cohort, weights, rng)
    v, ss, _ = eng.round_fn_streaming(v, ss, cohort, weights, rng)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(v))


def test_donate_bitwise_blockstream():
    cfg = _mnist_like_cfg(client_num_per_round=12, comm_round=2)
    trainer, data = _setup(cfg)
    run_donate_pair(lambda donate: MeshFedAvgEngine(
        trainer, data, cfg, mesh=make_mesh(8), donate=donate,
        stream_block=8))


def test_donate_bitwise_blockstream_orderstat():
    """Two-phase order-stat rounds with donation end-to-end (flats block
    step, donated phase-2 slices, donated finalize) == the non-donating
    compile, bitwise."""
    cfg = _mnist_like_cfg(comm_round=2, norm_bound=0.5)
    trainer, data = _setup(cfg)
    run_donate_pair(lambda donate: MeshRobustEngine(
        trainer, data, cfg, defense="median", n_byzantine=1,
        mesh=make_mesh(8), donate=donate, stream_block=8,
        param_block_bytes=16 * 64))


def test_blockstream_uint8_h2d_byte_reduction():
    """Transfer-compression acceptance (ISSUE 3): on the SAME
    block-streamed round, the uint8 cohort stack must cross host→device
    in ≥3.5x fewer bytes than the f32 stack and ≥1.9x fewer than bf16
    (x dominates; y/mask/weights/rngs ride uncompressed), the byte
    counters must land in the per-round records, and the uint8 round
    must still train close to f32."""
    cfg = _mnist_like_cfg(client_num_per_round=16, comm_round=1)
    trainer, data = _setup(cfg)
    bytes_per, results = {}, {}
    v0 = None
    for sd, tag in ((None, "f32"), (jnp.bfloat16, "bf16"),
                    (jnp.uint8, "u8")):
        eng = MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh(8),
                               donate=False, stream_block=8,
                               stack_dtype=sd)
        if v0 is None:
            v0 = eng.init_variables()
        results[tag] = eng.run(variables=jax.tree.map(jnp.copy, v0),
                               rounds=1)
        bytes_per[tag] = eng.transfer_stats.h2d_bytes
        assert bytes_per[tag] > 0
        # per-round records carry the byte accounting (bench.py schema)
        assert eng.transfer_stats.rounds[0]["h2d_bytes"] > 0
    assert bytes_per["f32"] / bytes_per["u8"] >= 3.5, bytes_per
    assert bytes_per["bf16"] / bytes_per["u8"] >= 1.9, bytes_per
    for a, b in zip(jax.tree.leaves(results["f32"]),
                    jax.tree.leaves(results["u8"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0.05, atol=0.02)
