"""Fused GroupNorm: value and gradient parity with flax nn.GroupNorm (the
spec), on the reference path (the test platform is CPU, where _use_pallas
is False).  The pallas TPU path shares the custom-VJP plumbing but its
kernels only compile on hardware — run `python tools/tpu_smoke.py` on a
TPU host to check pallas-vs-reference parity there."""
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.models import create_model
from fedml_tpu.ops.groupnorm import FusedGroupNorm, group_norm


def _ref_gn(x, gamma, beta, G, eps=1e-5):
    mod = nn.GroupNorm(num_groups=G, epsilon=eps)
    return mod.apply({"params": {"scale": gamma, "bias": beta}}, x)


def test_forward_matches_flax():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(8, 4, 4, 16).astype(np.float32))
    gamma = jnp.asarray(rs.rand(16).astype(np.float32))
    beta = jnp.asarray(rs.rand(16).astype(np.float32))
    got = group_norm(x, gamma, beta, 8)
    want = _ref_gn(x, gamma, beta, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_gradients_match_flax_autodiff():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.rand(4, 2, 2, 8).astype(np.float32))
    gamma = jnp.asarray(rs.rand(8).astype(np.float32))
    beta = jnp.asarray(rs.rand(8).astype(np.float32))

    def loss_fused(x, g, b):
        return jnp.sum(jnp.sin(group_norm(x, g, b, 4)))

    def loss_ref(x, g, b):
        return jnp.sum(jnp.sin(_ref_gn(x, g, b, 4)))

    got = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b_ in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-5)


def test_module_param_tree_matches_nn_groupnorm():
    x = jnp.zeros((2, 4, 4, 16))
    v_fused = FusedGroupNorm(num_groups=8).init(jax.random.PRNGKey(0), x)
    v_plain = nn.GroupNorm(num_groups=8).init(jax.random.PRNGKey(0), x)
    assert jax.tree.structure(v_fused) == jax.tree.structure(v_plain)


def test_resnet18gn_still_trains():
    """Flagship-model training smoke test.  Note: ResNet18GN deliberately
    uses plain nn.GroupNorm — XLA's fused GN beat the hand kernel on
    hardware (see ops/groupnorm.py MEASURED OUTCOME); FusedGroupNorm is
    covered by the op-level tests above."""
    model = create_model("resnet18_gn", 10)
    x = jnp.asarray(np.random.RandomState(0).rand(4, 16, 16, 3),
                    jnp.float32)
    y = jnp.asarray([0, 1, 2, 3])
    v = model.init(jax.random.PRNGKey(0), x, train=False)

    import optax
    def loss(p):
        logits = model.apply(p, x, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
    l0, g = jax.value_and_grad(loss)(v)
    assert np.isfinite(float(l0))
    gn = sum(float(jnp.sum(jnp.abs(t))) for t in jax.tree.leaves(g))
    assert gn > 0
