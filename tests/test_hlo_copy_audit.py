"""Copy-audit regression gate (the PR-4 tentpole's enforcement arm).

tools/hlo_copy_audit.py compiles every engine family's round program on
the 8-device virtual CPU mesh and censuses the optimized HLO for
`copy`/`copy-start` instructions.  These tests pin that census:

* per-family copy-bytes/ops CEILINGS (benchmarks/hlo_copy_ceilings.json)
  — a carry-layout or donation regression shows up as new copies here
  long before a chip window can price it in wall-clock;
* donation floors — the alias maps (donated args XLA actually aliased
  into outputs) must not shrink;
* the FedAvg reduction vs the committed pre-PR baseline
  (benchmarks/hlo_copy_baseline.json, generated from the seed engines) —
  the flat chunk-carry restructure removed the donated-conv-kernel
  staging copy, and that win must not silently evaporate;
* the obs gauge (`engine_copy_bytes_compiled{family=...}`) the audit
  publishes.

Recalibration protocol (same as benchmarks/quality_bands.json): the
optimized HLO is deterministic per jax/jaxlib build, so the pins are
EXACT — but if a pin trips and the running toolchain differs from the
calibration env recorded in the ceilings file, the failure names the
version skew and says "recalibrate" instead of pointing at the training
code.
"""
import json
import os
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import hlo_copy_audit  # noqa: E402

CEILINGS_PATH = os.path.join(REPO, "benchmarks", "hlo_copy_ceilings.json")
BASELINE_PATH = os.path.join(REPO, "benchmarks", "hlo_copy_baseline.json")


def _toolchain_skew(calibration: dict) -> list[str]:
    import jaxlib
    skew = []
    if calibration.get("jax") != jax.__version__:
        skew.append(f"jax {calibration.get('jax')} -> {jax.__version__}")
    if calibration.get("jaxlib") != jaxlib.__version__:
        skew.append(
            f"jaxlib {calibration.get('jaxlib')} -> {jaxlib.__version__}")
    return skew


def _pin_failure(what: str, calibration: dict):
    """Band-violation failure that names a toolchain skew when there is
    one (VERDICT next-#7 protocol: a version-skew failure must say
    'recalibrate', not masquerade as a code regression)."""
    skew = _toolchain_skew(calibration)
    if skew:
        pytest.fail(
            f"{what} — AND the toolchain moved since calibration "
            f"({', '.join(skew)}): RECALIBRATE benchmarks/"
            f"hlo_copy_ceilings.json on this build (python tools/"
            f"hlo_copy_audit.py) instead of hunting an engine regression")
    pytest.fail(
        f"{what} on the CALIBRATED toolchain (jax {jax.__version__}) — "
        f"a real carry-layout/donation regression in the round programs")


@pytest.fixture(scope="module")
def audit():
    """One full-family census per test run (~16 s of tiny-CNN compiles;
    the jitted programs land in the persistent compile cache)."""
    return hlo_copy_audit.audit_families()


@pytest.fixture(scope="module")
def ceilings():
    return json.load(open(CEILINGS_PATH))


def test_ceilings_artifact_shape(ceilings):
    """The committed artifact must carry the calibration env machine-
    readably and one ceiling row per audited family."""
    cal = ceilings["calibration"]
    for key in ("jax", "jaxlib", "backend", "n_devices", "model", "date"):
        assert key in cal, f"calibration lost {key!r}"
    assert set(ceilings["families"]) == set(hlo_copy_audit.ALL_FAMILIES)


def test_copy_bytes_under_ceilings(audit, ceilings):
    cal = ceilings["calibration"]
    over = []
    for fam, pins in ceilings["families"].items():
        got = audit["families"][fam]
        if got["copy_bytes"] > pins["copy_bytes_ceiling"]:
            over.append(f"{fam}: copy_bytes {got['copy_bytes']} > "
                        f"ceiling {pins['copy_bytes_ceiling']}")
        if got["copy_ops"] > pins["copy_ops_ceiling"]:
            over.append(f"{fam}: copy_ops {got['copy_ops']} > "
                        f"ceiling {pins['copy_ops_ceiling']}")
    if over:
        _pin_failure("copy-audit ceilings exceeded: " + "; ".join(over),
                     cal)


def test_donation_alias_floors(audit, ceilings):
    """Donation completeness must not regress: the alias map (donated
    args XLA aliased into outputs) per family stays at or above the
    pinned floors."""
    cal = ceilings["calibration"]
    under = []
    for fam, pins in ceilings["families"].items():
        got = audit["families"][fam]
        if got["donated_args"] < pins["donated_args_floor"]:
            under.append(f"{fam}: donated_args {got['donated_args']} < "
                         f"floor {pins['donated_args_floor']}")
        if got["aliased_outputs"] < pins["aliased_outputs_floor"]:
            under.append(f"{fam}: aliased_outputs "
                         f"{got['aliased_outputs']} < floor "
                         f"{pins['aliased_outputs_floor']}")
    if under:
        _pin_failure("donation alias floors violated: " +
                     "; ".join(under), cal)


def test_fedavg_copy_bytes_reduced_vs_baseline(audit):
    """ISSUE-4 acceptance: the FedAvg round program's copy bytes are
    REDUCED vs the committed pre-PR baseline (the flat chunk-carry
    restructure removed the donated-conv-kernel staging copy — 204.8 KB
    on the census model)."""
    base = json.load(open(BASELINE_PATH))
    cal = base["meta"]
    now = audit["families"]["fedavg_resident"]["copy_bytes"]
    was = base["families"]["fedavg_resident"]["copy_bytes"]
    if not now < was:
        _pin_failure(
            f"fedavg_resident copy_bytes {now} not reduced vs the pre-PR "
            f"baseline {was} (benchmarks/hlo_copy_baseline.json)",
            {"jax": cal["jax"], "jaxlib": cal["jaxlib"]})
    # streaming shares the round body and must hold the reduction too
    assert (audit["families"]["fedavg_streaming"]["copy_bytes"]
            < base["families"]["fedavg_streaming"]["copy_bytes"])


def test_audit_publishes_obs_gauge(audit):
    from fedml_tpu import obs
    for fam in hlo_copy_audit.ALL_FAMILIES:
        g = obs.gauge("engine_copy_bytes_compiled", family=fam)
        assert g.value == audit["families"][fam]["copy_bytes"], fam
