"""Per-client batch parallelism (the "batch" mesh axis).

When chips outnumber the cohort, the engine splits each client's per-step
batch over a second mesh axis and completes the gradient with one psum per
step (core/trainer.py batch_axes).  The invariant: a clients×batch mesh
must reproduce the single-device engine's weights — the batch split is an
execution layout, not an algorithm change.  Ragged clients (hetero LDA
partition) make some batch shards all-padding, exercising the GLOBAL
empty-batch guard and the S/C_g loss normalization.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.algorithms.fednova import FedNovaEngine
from fedml_tpu.algorithms.fedopt import FedOptEngine
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.loaders import load_data
from fedml_tpu.models import create_model
from fedml_tpu.parallel import (MeshFedAvgEngine, MeshFedNovaEngine,
                                MeshFedOptEngine, MeshFedProxEngine,
                                MeshRobustEngine)
from fedml_tpu.parallel.mesh import make_mesh_batch
from fedml_tpu.utils.config import FedConfig


def _cfg(**kw):
    base = dict(model="lr", dataset="mnist",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=3, epochs=2, batch_size=16, lr=0.1,
                partition_method="hetero",      # ragged shards
                frequency_of_the_test=100)
    base.update(kw)
    return FedConfig(**base)


def _setup(cfg, prox_mu=0.0, momentum=0.0):
    data = load_data(cfg.dataset, client_num_in_total=cfg.client_num_in_total,
                     batch_size=cfg.batch_size, synthetic_scale=0.02,
                     partition_method=cfg.partition_method, seed=cfg.seed)
    model = create_model(cfg.model, output_dim=data.class_num)
    trainer = ClientTrainer(model, lr=cfg.lr, optimizer=cfg.client_optimizer,
                            prox_mu=prox_mu, momentum=momentum)
    return trainer, data


def _assert_close(v_ref, v_got, rtol=2e-4, atol=2e-5):
    for a, b in zip(jax.tree.leaves(v_ref), jax.tree.leaves(v_got)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol)


def test_batch_axis_matches_single_device():
    cfg = _cfg()
    trainer, data = _setup(cfg)
    ref = FedAvgEngine(trainer, data, cfg, donate=False)
    v0 = ref.init_variables()
    v_ref = ref.run(variables=jax.tree.map(jnp.copy, v0), rounds=3)

    eng = MeshFedAvgEngine(trainer, data, cfg,
                           mesh=make_mesh_batch(2, 4), donate=False)
    assert eng.batch_axes == ("batch",)
    assert eng.n_shards == 2                     # padding: client axes only
    v_b = eng.run(variables=jax.tree.map(jnp.copy, v0), rounds=3)
    _assert_close(v_ref, v_b)


def test_batch_axis_with_momentum_and_ragged_guard():
    # momentum makes the empty-batch guard meaningful: a frozen-vs-applied
    # divergence between batch shards would corrupt the momentum buffer
    cfg = _cfg(client_num_in_total=6, client_num_per_round=6)
    trainer, data = _setup(cfg, momentum=0.9)
    ref = FedAvgEngine(trainer, data, cfg, donate=False)
    v0 = ref.init_variables()
    v_ref = ref.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    eng = MeshFedAvgEngine(trainer, data, cfg,
                           mesh=make_mesh_batch(2, 4), donate=False)
    v_b = eng.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    _assert_close(v_ref, v_b)


def test_batch_axis_streaming():
    cfg = _cfg(client_num_per_round=4)
    trainer, data = _setup(cfg)
    ref = FedAvgEngine(trainer, data, cfg, donate=False)
    v0 = ref.init_variables()
    v_ref = ref.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    eng = MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh_batch(2, 4),
                           streaming=True, donate=False)
    v_b = eng.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    _assert_close(v_ref, v_b)


def test_batch_axis_fedopt_and_prox():
    cfg = _cfg(server_optimizer="adam", server_lr=0.05)
    trainer, data = _setup(cfg, prox_mu=0.1)
    ref = FedOptEngine(trainer, data, cfg, donate=False)
    v0 = ref.init_variables()
    v_ref = ref.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    eng = MeshFedOptEngine(trainer, data, cfg,
                           mesh=make_mesh_batch(2, 4), donate=False)
    v_b = eng.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    _assert_close(v_ref, v_b)

    cfgp = _cfg(prox_mu=0.1)
    trainer_p, data_p = _setup(cfgp, prox_mu=0.1)
    engp = MeshFedProxEngine(trainer_p, data_p, cfgp,
                             mesh=make_mesh_batch(4, 2), donate=False)
    refp = FedAvgEngine(trainer_p, data_p, cfgp, donate=False)
    v0p = refp.init_variables()
    v_refp = refp.run(variables=jax.tree.map(jnp.copy, v0p), rounds=2)
    v_bp = engp.run(variables=jax.tree.map(jnp.copy, v0p), rounds=2)
    _assert_close(v_refp, v_bp)


def test_batch_axis_fednova():
    cfg = _cfg()
    trainer, data = _setup(cfg)
    ref = FedNovaEngine(trainer, data, cfg, donate=False)
    v0 = ref.init_variables()
    v_ref = ref.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    eng = MeshFedNovaEngine(trainer, data, cfg,
                            mesh=make_mesh_batch(2, 4), donate=False)
    v_b = eng.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    _assert_close(v_ref, v_b)


def test_batch_axis_robust_norm_clip_and_order_stat_guard():
    cfg = _cfg(norm_bound=0.5, stddev=0.0)
    trainer, data = _setup(cfg)
    ref = MeshRobustEngine(trainer, data, cfg, defense="norm_clip",
                           mesh=make_mesh_batch(8, 1), donate=False)
    v0 = ref.init_variables()
    v_ref = ref.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    eng = MeshRobustEngine(trainer, data, cfg, defense="norm_clip",
                           mesh=make_mesh_batch(2, 4), donate=False)
    v_b = eng.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    _assert_close(v_ref, v_b)

    with pytest.raises(ValueError, match="batch"):
        MeshRobustEngine(trainer, data, cfg, defense="median",
                         mesh=make_mesh_batch(2, 4), donate=False)


def test_batch_axis_batchnorm_guard_and_sync_bn_oracle():
    """Plain BatchNorm under a batch split would normalize by shard-local
    statistics — the engine rejects it; the cross-replica variant
    (sync_batch_norm bound to the 'batch' axis) is oracle-equal to the
    effectively-unsplit (batch axis of size 1) run."""
    import flax.linen as nn

    from fedml_tpu.models.norms import sync_batch_norm

    class BNNet(nn.Module):
        sync: bool = False

        @nn.compact
        def __call__(self, x, train=False):
            h = nn.Dense(16)(x.reshape((x.shape[0], -1)))
            h = sync_batch_norm(use_running_average=not train,
                                sync=self.sync, axis_name="batch")(h)
            return nn.Dense(10)(nn.relu(h))

    cfg = _cfg(epochs=1)
    data = load_data("mnist", client_num_in_total=8, batch_size=16,
                     synthetic_scale=0.02,
                     partition_method="hetero", seed=cfg.seed)

    plain = ClientTrainer(BNNet(sync=False), lr=cfg.lr)
    eng = MeshFedAvgEngine(plain, data, cfg, mesh=make_mesh_batch(2, 4),
                           donate=False)
    with pytest.raises(ValueError, match="batch_stats"):
        eng.run(rounds=1)

    sync = ClientTrainer(BNNet(sync=True), lr=cfg.lr)
    ref = MeshFedAvgEngine(sync, data, cfg, mesh=make_mesh_batch(8, 1),
                           donate=False, allow_batch_stats=True)
    v0 = ref.init_variables()
    v_ref = ref.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    eng2 = MeshFedAvgEngine(sync, data, cfg, mesh=make_mesh_batch(2, 4),
                            donate=False, allow_batch_stats=True)
    v_b = eng2.run(variables=jax.tree.map(jnp.copy, v0), rounds=2)
    _assert_close(v_ref, v_b, rtol=5e-4, atol=5e-5)


def test_batch_axis_indivisible_raises():
    cfg = _cfg(batch_size=16)
    trainer, data = _setup(cfg)
    with pytest.raises(ValueError, match="divide"):
        MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh_batch(2, 3),
                         donate=False)


def test_batch_axis_composes_with_stack_dtype_and_unroll():
    """The three round-3 perf levers compose: a clients x batch mesh with
    bf16 cohort storage and an unrolled batch scan still trains close to
    the plain single-device run (stack_dtype is a precision tradeoff, so
    closeness not equality; unroll and the batch split are exact)."""
    cfg = _cfg()
    trainer, data = _setup(cfg)
    ref = FedAvgEngine(trainer, data, cfg, donate=False)
    v0 = ref.init_variables()
    v_ref = ref.run(variables=jax.tree.map(jnp.copy, v0), rounds=3)

    import copy
    tr2 = copy.copy(trainer)
    tr2.batch_unroll = 2
    eng = MeshFedAvgEngine(tr2, data, cfg, mesh=make_mesh_batch(2, 4),
                           stack_dtype=jnp.bfloat16, donate=False)
    stack, _w = eng._device_stack()
    assert stack["x"].dtype == jnp.bfloat16
    v_b = eng.run(variables=jax.tree.map(jnp.copy, v0), rounds=3)
    _assert_close(v_ref, v_b, rtol=0.05, atol=0.02)   # bf16-input band
