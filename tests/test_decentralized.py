"""Decentralized online learning: DSGD gossip and push-sum over a topology
(reference fedml_api/standalone/decentralized/ on UCI SUSY/Room-Occupancy
streams)."""
import jax
import numpy as np

from fedml_tpu.algorithms import DecentralizedGossipEngine
from fedml_tpu.core.topology import (AsymmetricTopologyManager,
                                     SymmetricTopologyManager)
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data import load_data
from fedml_tpu.models import create_model
from fedml_tpu.utils.config import FedConfig


def make_engine(push_sum=False, n=8):
    data = load_data("susy", client_num_in_total=n, batch_size=8,
                     synthetic_scale=0.01, seed=0)
    cfg = FedConfig(client_num_in_total=n, client_num_per_round=n,
                    comm_round=15, epochs=1, batch_size=8, lr=0.1,
                    frequency_of_the_test=5)
    trainer = ClientTrainer(
        create_model("lr", 2, input_dim=18), lr=0.1)
    if push_sum:
        topo = AsymmetricTopologyManager(n, neighbor_num=3,
                                         deleted_ratio=0.3)
    else:
        topo = SymmetricTopologyManager(n, neighbor_num=2)
    topo.generate_topology()
    return DecentralizedGossipEngine(trainer, data, cfg, topology=topo,
                                     push_sum=push_sum), data


def test_dsgd_learns_susy_stream():
    eng, _ = make_engine(push_sum=False)
    stacked, _ = eng.run()
    assert eng.metrics_history[-1]["test_acc"] > 0.75


def test_push_sum_directed_graph():
    eng, _ = make_engine(push_sum=True)
    stacked, weights = eng.run()
    assert eng.metrics_history[-1]["test_acc"] > 0.7
    # push-sum mass stays positive and finite
    assert np.all(np.asarray(weights) > 0)


def test_gossip_consensus():
    """Mixing with a doubly-stochastic-ish W shrinks client disagreement."""
    eng, _ = make_engine(push_sum=False)
    stacked, w = eng.init_states()

    def spread(s):
        leaves = [np.asarray(l).reshape(l.shape[0], -1)
                  for l in jax.tree.leaves(s)]
        flat = np.concatenate(leaves, axis=1)
        return float(np.std(flat, axis=0).mean())

    # perturb each client differently, then mix a few times (no SGD)
    rs = np.random.RandomState(0)
    stacked = jax.tree.map(
        lambda l: l + rs.normal(0, 1, l.shape).astype(np.float32), stacked)
    s0 = spread(stacked)
    for _ in range(5):
        stacked, w = eng._mix(stacked, w)
    assert spread(stacked) < s0 * 0.5
