"""Shared setup for the mesh-engine test files (test_parallel.py and
test_parallel_stream.py — split so pytest-xdist's per-file scheduling
can run the resident-mesh and streaming/block-stream groups in
parallel workers)."""
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.loaders import load_data
from fedml_tpu.models import create_model
from fedml_tpu.utils.config import FedConfig


def _mnist_like_cfg(**kw):
    base = dict(model="lr", dataset="mnist",
                client_num_in_total=16, client_num_per_round=16,
                comm_round=4, epochs=1, batch_size=16, lr=0.1,
                partition_method="homo", frequency_of_the_test=100)
    base.update(kw)
    return FedConfig(**base)


def _setup(cfg, prox_mu=0.0):
    data = load_data(cfg.dataset, client_num_in_total=cfg.client_num_in_total,
                     batch_size=cfg.batch_size, synthetic_scale=0.02,
                     seed=cfg.seed)
    model = create_model(cfg.model, output_dim=data.class_num)
    trainer = ClientTrainer(model, lr=cfg.lr, optimizer=cfg.client_optimizer,
                            prox_mu=prox_mu)
    return trainer, data
