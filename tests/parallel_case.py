"""Shared setup for the mesh-engine test files (test_parallel.py and
test_parallel_stream.py — split so pytest-xdist's per-file scheduling
can run the resident-mesh and streaming/block-stream groups in
parallel workers)."""
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.loaders import load_data
from fedml_tpu.models import create_model
from fedml_tpu.utils.config import FedConfig


def _mnist_like_cfg(**kw):
    base = dict(model="lr", dataset="mnist",
                client_num_in_total=16, client_num_per_round=16,
                comm_round=4, epochs=1, batch_size=16, lr=0.1,
                partition_method="homo", frequency_of_the_test=100)
    base.update(kw)
    return FedConfig(**base)


def _setup(cfg, prox_mu=0.0):
    data = load_data(cfg.dataset, client_num_in_total=cfg.client_num_in_total,
                     batch_size=cfg.batch_size, synthetic_scale=0.02,
                     seed=cfg.seed)
    model = create_model(cfg.model, output_dim=data.class_num)
    trainer = ClientTrainer(model, lr=cfg.lr, optimizer=cfg.client_optimizer,
                            prox_mu=prox_mu)
    return trainer, data


def run_donate_pair(make_engine, rounds=2):
    """Bitwise donation-correctness pin (ISSUE 4), shared by the resident
    and streaming test files: donation is a memory optimization — the
    SAME program must produce IDENTICAL bits with donate on and off.
    assert_array_equal, not allclose: any drift means donation changed
    the computation, not just the buffers."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    eng_d = make_engine(donate=True)
    v0 = eng_d.init_variables()
    v_don = eng_d.run(variables=jax.tree.map(jnp.copy, v0), rounds=rounds)
    eng_n = make_engine(donate=False)
    v_not = eng_n.run(variables=jax.tree.map(jnp.copy, v0), rounds=rounds)
    for a, b in zip(jax.tree.leaves(v_don), jax.tree.leaves(v_not)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
