"""Pallas aggregation kernels vs their pure-XLA references (interpret mode
on the CPU test platform)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.robust import norm_diff_clip
from fedml_tpu.ops import (flatten_stacked_tree, robust_weighted_mean_pallas,
                           unflatten_to_tree, weighted_mean_pallas)


def random_stack(rng, C=5):
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "dense": {"kernel": jax.random.normal(k1, (C, 7, 13)),
                  "bias": jax.random.normal(k2, (C, 13))},
        "out": {"kernel": jax.random.normal(k3, (C, 13, 3))},
    }


def test_flatten_roundtrip():
    stack = random_stack(jax.random.PRNGKey(0))
    flat, spec = flatten_stacked_tree(stack)
    assert flat.shape[0] == 5 and flat.shape[1] % 512 == 0
    one = jax.tree.map(lambda x: x[2], stack)
    back = unflatten_to_tree(flat[2], spec)
    for a, b in zip(jax.tree.leaves(one), jax.tree.leaves(back)):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_weighted_mean_matches_tree_mean():
    stack = random_stack(jax.random.PRNGKey(1))
    w = jnp.asarray([1.0, 2.0, 0.0, 4.0, 3.0])
    got = weighted_mean_pallas(stack, w, interpret=True)
    want = tree_weighted_mean(stack, w)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_weighted_mean_under_jit():
    stack = random_stack(jax.random.PRNGKey(2))
    w = jnp.asarray([1.0, 1.0, 1.0, 1.0, 1.0])
    f = jax.jit(lambda s, w: weighted_mean_pallas(s, w, interpret=True))
    got = f(stack, w)
    want = tree_weighted_mean(stack, w)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("tau", [0.5, 100.0])
def test_robust_matches_clip_then_mean(tau):
    """Fused kernel == vmap(norm_diff_clip) + weighted mean, for both a
    binding clip (tau small) and a no-op clip (tau large)."""
    stack = random_stack(jax.random.PRNGKey(3))
    g = jax.tree.map(lambda x: x[0] * 0.5, stack)
    w = jnp.asarray([1.0, 2.0, 3.0, 4.0, 5.0])
    got = robust_weighted_mean_pallas(stack, w, g, tau, interpret=True)
    clipped = jax.vmap(lambda p: norm_diff_clip(p, g, tau))(stack)
    want = tree_weighted_mean(clipped, w)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_engine_pallas_agg_matches_default():
    """FedAvgEngine(pallas_agg=True) produces the same round output."""
    from fedml_tpu.algorithms import FedAvgEngine
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.models import create_model
    from fedml_tpu.utils.config import FedConfig
    from tests.test_fednas import tiny_data

    data = tiny_data(n_clients=3, bs=4, hw=8)
    cfg = FedConfig(client_num_in_total=3, client_num_per_round=3,
                    comm_round=1, epochs=1, batch_size=4, lr=0.1,
                    frequency_of_the_test=1)
    trainer = ClientTrainer(create_model("lr", 10), lr=0.1)
    e1 = FedAvgEngine(trainer, data, cfg, donate=False)
    e2 = FedAvgEngine(trainer, data, cfg, donate=False, pallas_agg=True)
    v0 = e1.init_variables()
    ids = e1.sampler.sample(0)
    cohort, _ = data.cohort(ids)
    r = jax.random.PRNGKey(7)
    va, _, _ = e1.round_fn(v0, e1.server_init(v0), cohort, r)
    vb, _, _ = e2.round_fn(v0, e2.server_init(v0), cohort, r)
    for a, b in zip(jax.tree.leaves(va), jax.tree.leaves(vb)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
