"""Serving-spine tests (fedml_tpu/scale — the ISSUE-10 tentpole).

Anchors, in order of importance:

* Degenerate sampling pin: the streaming cohort sampler in uniform mode
  with a fully-eligible registry reproduces the existing ClientSampler
  cohorts BITWISE, and ClientSampler.sample_fast is the bitwise
  non-mutating twin of the reference `sample` — the new spine is
  anchored to the old sampler, not merely plausible.
* Statistical pins: reservoir and stratified draws are chi-square
  uniform at a fixed seed, deterministic per seed, two seeds differ
  (the chaos/adversary seeded-stream convention).
* Registry memory: lazy shard growth (touching k clients allocates
  O(k/shard) shards, not the population), <= ~100 bytes/client fully
  allocated, orbax checkpoint round-trip through a SHAPE-STABLE state.
* ShardStore: on-demand cohorts bitwise-equal to the materialized
  all-client stack (mmap and generator backends), feeding the PR-1
  prefetcher and the async scheduler unchanged.
* Serve smoke: the 100k-client virtual-time serve loop sustains
  commits with sub-linear server memory; the 1M arm is slow/nightly.
"""
import os

import jax
import numpy as np
import pytest

from fedml_tpu.core.sampling import ClientSampler
from fedml_tpu.scale import (BYTES_PER_CLIENT, ArrivalConfig,
                             ClientRegistry, ConstantArrivals,
                             DiurnalArrivals, FlashCrowdArrivals,
                             GeneratorShardStore, MaterializedShardStore,
                             MmapShardStore, StreamingCohortSampler,
                             TraceArrivals, make_arrivals, run_serve_sim)
from fedml_tpu.scale import registry as R

from parallel_case import _mnist_like_cfg, _setup


# -- ClientSampler fast path (satellite) -------------------------------------

def test_sample_fast_bitwise_matches_reference_oracle():
    """The non-mutating fast path IS the reference draw: np.random.seed
    + global choice(range(N)) delegates to a global RandomState, so a
    private RandomState(round) walks the identical stream — cross-
    pinned bitwise over populations and rounds, including the
    full-participation branch."""
    for n, k in ((100, 10), (1000, 16), (4096, 128), (8, 16)):
        s = ClientSampler(n, k)
        for r in (0, 1, 7, 12345):
            np.testing.assert_array_equal(s.sample(r), s.sample_fast(r))


def test_sample_fast_does_not_mutate_global_rng():
    np.random.seed(4242)
    before = np.random.get_state()
    ClientSampler(10_000, 64).sample_fast(7)
    after = np.random.get_state()
    assert before[0] == after[0]
    np.testing.assert_array_equal(before[1], after[1])
    assert before[2:] == after[2:]
    # ...while the reference path famously does mutate
    ClientSampler(10_000, 64).sample(7)
    assert not np.array_equal(before[1], np.random.get_state()[1])


def test_sample_fast_k_override():
    s = ClientSampler(1000, 16)
    a = s.sample_fast(3, k=5)
    assert a.shape == (5,) and len(np.unique(a)) == 5
    np.testing.assert_array_equal(s.sample_fast(3, k=16), s.sample(3))


# -- registry ----------------------------------------------------------------

def test_registry_lifecycle_counters():
    reg = ClientRegistry(100, shard_size=16)
    reg.note_dispatch(np.asarray([3, 17, 99]), version=2)
    assert reg.count_in_flight == 3 and reg.count_free == 97
    np.testing.assert_array_equal(reg.outstanding_of([3, 17, 99]),
                                  [2, 2, 2])
    np.testing.assert_array_equal(np.sort(reg.outstanding_ids()),
                                  [3, 17, 99])
    assert reg.note_return(17) == 2
    reg.note_contribution(17, staleness=1.5, version=3)
    assert reg.count_in_flight == 2
    assert reg.participation([17])[0] == 1
    assert reg.last_staleness([17])[0] == np.float32(1.5)
    reg.note_crash(3, rejoins=True)
    reg.note_crash(99, rejoins=False)
    assert reg.count_crashed == 1 and reg.count_dead == 1
    assert reg.count_in_flight == 0
    reg.note_rejoin(3)
    assert reg.count_crashed == 0 and reg.count_free == 99
    reg.note_quarantine(17)
    assert reg.quarantines([17])[0] == 1
    reg.ban([5, 6])
    assert reg.count_banned == 2
    assert not reg.eligible([5])[0] and reg.eligible([7])[0]
    assert reg.total_participation() == 1


def test_registry_lazy_memory_growth():
    """The O(1)-memory-growth pin: touching a handful of clients in a
    2M-client registry allocates only their shards; even fully
    allocated, the field set stays <= ~100 bytes/client (acceptance
    bound) — 29 today."""
    assert BYTES_PER_CLIENT <= 100
    reg = ClientRegistry(2_000_000)
    assert reg.nbytes == 0 and reg.n_shards == 31
    reg.note_dispatch(np.asarray([0, 1, 2]), 0)          # shard 0
    reg.note_contribution(1_999_999, 0.0, 0)             # last shard
    assert len(reg._shards) == 2
    assert reg.nbytes <= 2 * reg.shard_size * BYTES_PER_CLIENT
    assert reg.bytes_per_client < 2.0                    # sub-linear
    # fully-allocated worst case still under the gate
    assert (reg.n_clients * BYTES_PER_CLIENT / reg.n_clients) <= 100


def test_registry_quarantine_ban_threshold():
    """Below the threshold a quarantined client returns to the pool
    (the PR-9 redispatch contract — one false positive never exiles an
    honest client); at the threshold it auto-BANs and leaves the
    sampler's eligibility mask for good."""
    reg = ClientRegistry(50, quarantine_ban_threshold=3)
    assert not reg.note_quarantine(7)
    assert not reg.note_quarantine(7)
    assert reg.eligible([7])[0]                 # still in the pool
    assert reg.note_quarantine(7)               # third strike: banned
    assert not reg.eligible([7])[0]
    assert reg.count_banned == 1
    # threshold 0 (default) never bans — counter only
    reg0 = ClientRegistry(50)
    for _ in range(10):
        assert not reg0.note_quarantine(7)
    assert reg0.eligible([7])[0] and reg0.quarantines([7])[0] == 10


def test_registry_ban_is_sticky_and_dupes_dont_corrupt_counters():
    """A ban survives every lifecycle transition (dispatch/rejoin/
    crash cannot silently un-ban — only unban() can), and duplicated
    ids in the vectorized transition APIs count once."""
    reg = ClientRegistry(64, shard_size=16)
    reg.ban([9])
    reg.note_dispatch(np.asarray([9, 10]), 3)
    assert int(reg.status_of([9])[0]) == R.BANNED
    assert reg.outstanding_of([9])[0] == -1        # no dispatch marker
    assert reg.count_in_flight == 1                # only 10 moved
    reg.note_dispatch_one(9, 4)
    assert int(reg.status_of([9])[0]) == R.BANNED
    reg.note_rejoin(9)
    assert int(reg.status_of([9])[0]) == R.BANNED
    reg.unban([9])
    assert reg.eligible([9])[0] and reg.count_banned == 0
    # duplicate ids: one distinct client, one counter increment
    reg2 = ClientRegistry(64, shard_size=16)
    reg2.note_dispatch(np.asarray([1, 1, 2]), 0)
    assert reg2.count_in_flight == 2
    reg2.ban(np.asarray([5, 5, 5]))
    assert reg2.count_banned == 1
    assert reg2.count_free == 64 - 2 - 1


def test_scheduler_migrates_legacy_checkpoint_arrays(small_data=None):
    """A pre-PR-10 async_state (client_last_staleness/client_contribs
    arrays, no 'registry') still restores: the arrays migrate into
    registry counters instead of raising KeyError."""
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.models import create_model
    from fedml_tpu.async_ import AsyncFedAvgEngine
    cfg = _mnist_like_cfg(client_num_in_total=16, client_num_per_round=4)
    _t, data = _setup(cfg)
    trainer = ClientTrainer(create_model("lr", output_dim=10), lr=cfg.lr)
    eng = AsyncFedAvgEngine(trainer, data, cfg, buffer_k=4,
                            concurrency=4, donate=False)
    legacy = eng.async_state()
    legacy.pop("registry")
    contribs = np.zeros(16, np.int64)
    contribs[[2, 7]] = [3, 1]
    stale = np.zeros(16, np.float32)
    stale[2] = 2.0
    legacy["client_contribs"] = contribs
    legacy["client_last_staleness"] = stale
    eng.load_async_state(legacy)
    assert eng.registry.participation([2, 7]).tolist() == [3, 1]
    assert eng.registry.last_staleness([2])[0] == np.float32(2.0)
    legacy.pop("client_contribs")
    with pytest.raises(ValueError, match="neither 'registry'"):
        eng.load_async_state(legacy)


def test_registry_free_ids_skips_ineligible():
    reg = ClientRegistry(40, shard_size=8)
    reg.note_dispatch(np.arange(0, 4), 0)
    reg.ban([4, 5])
    np.testing.assert_array_equal(reg.free_ids(5), [6, 7, 8, 9, 10])
    assert reg.eligible_per_shard()[0] == 2       # 6, 7 of shard 0


def test_registry_state_shape_stable_and_sparse_restore():
    """state() from a fresh registry and a touched one have identical
    tree shapes (the orbax-template requirement), and load_state
    re-sparsifies — all-default shards stay unallocated."""
    a = ClientRegistry(1000, shard_size=64)
    b = ClientRegistry(1000, shard_size=64)
    b.note_dispatch(np.asarray([100, 700]), 5)
    b.note_return(100)
    b.note_contribution(100, 2.0, 6)
    sa, sb = a.state(), b.state()
    assert set(sa) == set(sb)
    for k in sa:
        assert np.asarray(sa[k]).shape == np.asarray(sb[k]).shape, k
    c = ClientRegistry(1000, shard_size=64)
    c.load_state(sb)
    assert len(c._shards) == 2                    # shards 1 and 10 only
    assert c.count_in_flight == 1
    assert c.participation([100])[0] == 1
    np.testing.assert_array_equal(c.state()["participation"],
                                  sb["participation"])
    with pytest.raises(ValueError, match="registry shape mismatch"):
        ClientRegistry(1000, shard_size=32).load_state(sb)


def test_registry_roundtrips_through_orbax(tmp_path):
    """The checkpoint path the scheduler/manager use: registry shards
    ride FedCheckpointManager extra_state bit-exactly."""
    from fedml_tpu.utils.checkpoint import FedCheckpointManager
    reg = ClientRegistry(200, shard_size=32)
    reg.note_dispatch(np.asarray([1, 33, 199]), 4)
    reg.note_return(33)
    reg.note_contribution(33, 1.0, 5)
    reg.note_quarantine(199)
    v = {"w": np.zeros(3, np.float32)}
    ck = FedCheckpointManager(str(tmp_path / "reg"))
    ck.save(0, v, (), extra_state={"registry": reg.state()})
    _s, _v, _ss, extra = ck.restore(
        v, (), extra_template={"registry": ClientRegistry(
            200, shard_size=32).state()})
    fresh = ClientRegistry(200, shard_size=32)
    fresh.load_state(jax.tree.map(np.asarray, extra["registry"]))
    assert fresh.participation([33])[0] == 1
    assert fresh.quarantines([199])[0] == 1
    assert fresh.count_in_flight == 2
    ck.close()


def test_registry_obs_gauges():
    from fedml_tpu import obs
    reg = ClientRegistry(5000, shard_size=512)
    assert obs.gauge("registry_clients_total").value == 5000
    reg.note_dispatch(np.asarray([0]), 0)
    assert obs.gauge("registry_bytes").value == reg.nbytes > 0


# -- streaming cohort sampler ------------------------------------------------

@pytest.mark.parametrize("mode", ("uniform", "reservoir", "stratified"))
def test_sampler_deterministic_and_seeds_differ(mode):
    reg = ClientRegistry(5000, shard_size=512)
    s0 = StreamingCohortSampler(reg, 64, seed=0, mode=mode)
    a, b = s0.sample(3), s0.sample(3)
    np.testing.assert_array_equal(a, b)
    assert a.size == 64 and np.unique(a).size == 64
    c = StreamingCohortSampler(reg, 64, seed=1, mode=mode).sample(3)
    if mode != "uniform":      # uniform ignores the sampler seed by design
        assert not np.array_equal(np.sort(a), np.sort(c))
    assert not np.array_equal(s0.sample(4), a)       # rounds differ


@pytest.mark.parametrize("mode", ("uniform", "reservoir", "stratified"))
def test_sampler_excludes_ineligible(mode):
    reg = ClientRegistry(2000, shard_size=256)
    banned = np.arange(0, 2000, 7)
    reg.ban(banned)
    inflight = np.asarray([1, 2, 3, 500, 1500])
    reg.note_dispatch(inflight, 0)
    dead = np.asarray([10, 1000])
    for d in dead:
        reg.note_crash(int(d), rejoins=False)
    samp = StreamingCohortSampler(reg, 128, seed=0, mode=mode)
    for r in range(6):
        ids = samp.sample(r)
        bad = np.union1d(np.union1d(banned, inflight), dead)
        assert np.intersect1d(ids, bad).size == 0, mode
        assert np.unique(ids).size == ids.size == 128


def test_sampler_uniform_degenerate_reproduces_client_sampler():
    """THE acceptance pin: small-N uniform sampling over a fully-
    eligible registry reproduces the existing ClientSampler cohorts
    exactly (order included)."""
    for n, k in ((100, 10), (1000, 16)):
        reg = ClientRegistry(n)
        samp = StreamingCohortSampler(reg, k, seed=9, mode="uniform")
        ref = ClientSampler(n, k)
        for r in range(8):
            np.testing.assert_array_equal(samp.sample(r), ref.sample(r))


def _inclusion_chi2(mode, n=2000, shard=128, k=50, rounds=400, seed=0):
    reg = ClientRegistry(n, shard_size=shard)
    samp = StreamingCohortSampler(reg, k, seed=seed, mode=mode)
    counts = np.zeros(n, np.int64)
    for r in range(rounds):
        ids = samp.sample(r)
        assert ids.size == k
        counts[ids] += 1
    exp = rounds * k / n
    return float(((counts - exp) ** 2 / exp).sum() / (n - 1)), counts


@pytest.mark.parametrize("mode", ("reservoir", "stratified"))
def test_sampler_chi_square_uniformity(mode):
    """Chi-square-style uniformity at fixed seed: per-client inclusion
    counts over 400 rounds have chi2/dof ~ 1 (the 0.8-1.25 band is
    generous: dof=1999, a biased sampler lands far outside; the
    stratified mode exercises the MAX_STRATA shard-subset rotation —
    2000/128 = 16 shards > 8)."""
    stat, counts = _inclusion_chi2(mode)
    assert 0.8 < stat < 1.25, (mode, stat)
    assert counts.min() >= 0 and counts.max() < 40


def test_stratified_scratch_stays_shard_bounded():
    """The streaming-memory claim: a 1M-client stratified draw's peak
    numpy scratch is O(k + shard), nowhere near the population."""
    reg = ClientRegistry(1_000_000)
    samp = StreamingCohortSampler(reg, 64, seed=0, mode="stratified")
    for r in range(4):
        samp.sample(r)
    assert samp.peak_scratch_bytes < reg.shard_size * 8
    res = StreamingCohortSampler(reg, 64, seed=0, mode="reservoir")
    res.sample(0)
    # reservoir materializes one shard's keys+ids at a time, never the
    # population's
    assert res.peak_scratch_bytes < 4 * reg.shard_size * 16


# -- shard stores ------------------------------------------------------------

@pytest.fixture(scope="module")
def small_data():
    cfg = _mnist_like_cfg(client_num_in_total=12, client_num_per_round=4)
    _trainer, data = _setup(cfg)
    return cfg, data


def _assert_cohort_bitwise(a, b):
    ca, wa = a
    cb, wb = b
    assert set(ca) == set(cb)
    for k in ca:
        np.testing.assert_array_equal(np.asarray(ca[k]), np.asarray(cb[k]))
    np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))


def test_mmap_store_cohort_bitwise_matches_stack(small_data, tmp_path):
    """The shardstore-vs-materialized-stack pin: an MmapShardStore built
    from the same source hands back bitwise-identical cohorts (values
    AND weights) to the device-resident stack's gather."""
    _cfg, data = small_data
    store = MmapShardStore.build(data, str(tmp_path / "shards"),
                                 cache_clients=4)
    for ids in ([0, 3, 7], [11, 2], [5]):
        _assert_cohort_bitwise(store.cohort(np.asarray(ids)),
                               data.cohort(np.asarray(ids)))
    # cache path returns the same bits too
    _assert_cohort_bitwise(store.cohort(np.asarray([0, 3])),
                           data.cohort(np.asarray([0, 3])))
    # reopen from disk: no rebuild, same bits
    store2 = MmapShardStore(str(tmp_path / "shards"))
    _assert_cohort_bitwise(store2.cohort(np.asarray([7, 0])),
                           data.cohort(np.asarray([7, 0])))


def test_materialized_store_delegates(small_data):
    _cfg, data = small_data
    store = MaterializedShardStore(data)
    _assert_cohort_bitwise(store.cohort(np.asarray([1, 8])),
                           data.cohort(np.asarray([1, 8])))


def test_generator_store_deterministic_without_population_state():
    store = GeneratorShardStore(1_000_000, seed=3, cache_clients=2)
    a = store.client_shard(999_999)
    b = GeneratorShardStore(1_000_000, seed=3).client_shard(999_999)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])
    # weights are their own stream: identical whether or not the shard
    # was fetched first
    w1 = store._weight(123_456)
    w2 = GeneratorShardStore(1_000_000, seed=3)._weight(123_456)
    assert w1 == w2
    c = GeneratorShardStore(1_000_000, seed=4).client_shard(999_999)
    assert not np.array_equal(a["x"], c["x"])
    # LRU: second fetch of a cached client hits
    from fedml_tpu import obs
    h0 = obs.counter("shardstore_cache_hits_total").value
    store.client_shard(999_999)
    assert obs.counter("shardstore_cache_hits_total").value == h0 + 1


def test_shardstore_feeds_prefetcher(small_data, tmp_path):
    """The PR-1 double buffer consumes a shard store unchanged: the
    prefetched cohort stream equals direct cohort() calls bitwise."""
    _cfg, data = small_data
    store = MmapShardStore.build(data, str(tmp_path / "pf"))
    cohorts = [np.asarray([0, 1]), np.asarray([9, 4]), np.asarray([2])]
    with store.prefetcher(cohorts) as pf:
        got = [pf.get() for _ in cohorts]
    for ids, g in zip(cohorts, got):
        _assert_cohort_bitwise(g, data.cohort(ids))


def test_async_scheduler_runs_on_shardstore_bitwise(small_data):
    """End-to-end wiring pin: the async engine fed by an on-demand
    shard store produces BITWISE the run it produces on the resident
    stack (the store pin lifted to the full scheduler)."""
    cfg, data = small_data
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.models import create_model
    from fedml_tpu.async_ import AsyncFedAvgEngine

    def run(shardstore):
        trainer = ClientTrainer(create_model("lr", output_dim=10),
                                lr=cfg.lr)
        eng = AsyncFedAvgEngine(trainer, data, cfg, buffer_k=4,
                                concurrency=4, donate=False,
                                shardstore=shardstore)
        v = eng.run(rounds=2)
        return jax.tree.map(np.asarray, v), eng.trace

    v_stack, t_stack = run(None)
    v_store, t_store = run(MaterializedShardStore(data))
    assert t_stack == t_store
    for a, b in zip(jax.tree.leaves(v_stack), jax.tree.leaves(v_store)):
        np.testing.assert_array_equal(a, b)


# -- arrival processes -------------------------------------------------------

def test_arrivals_deterministic_and_seeds_differ():
    proc = DiurnalArrivals(rate=50.0, period_s=60.0, amplitude=0.8)

    def take(seed, n=200):
        it = proc.arrivals(0.0, np.random.default_rng([seed, 1]))
        return np.asarray([next(it) for _ in range(n)])

    np.testing.assert_array_equal(take(0), take(0))
    assert not np.array_equal(take(0), take(1))
    t = take(0)
    assert np.all(np.diff(t) > 0)                 # strictly increasing


def test_diurnal_rate_modulates_arrivals():
    proc = DiurnalArrivals(rate=100.0, period_s=100.0, amplitude=0.9)
    it = proc.arrivals(0.0, np.random.default_rng(0))
    ts = np.asarray([next(it) for _ in range(4000)])
    ts = ts[ts < 100.0]
    peak = np.count_nonzero((ts >= 15.0) & (ts < 35.0))    # sin ~ +1
    trough = np.count_nonzero((ts >= 65.0) & (ts < 85.0))  # sin ~ -1
    assert peak > 4 * trough, (peak, trough)
    # slowdown mirrors the curve: trough responds slower than peak
    assert proc.slowdown(75.0) > 3.0 * proc.slowdown(25.0)
    assert proc.slowdown(25.0) >= 1.0


def test_flash_crowd_bursts():
    proc = FlashCrowdArrivals(rate=50.0, period_s=1e9, amplitude=0.0,
                              flash_at_s=10.0, flash_duration_s=5.0,
                              flash_boost=8.0)
    it = proc.arrivals(0.0, np.random.default_rng(7))
    ts = np.asarray([next(it) for _ in range(3000)])
    ts = ts[ts < 30.0]
    inside = np.count_nonzero((ts >= 10.0) & (ts < 15.0))
    before = np.count_nonzero(ts < 5.0)
    assert inside > 4 * before, (inside, before)


def test_trace_replay_exact(tmp_path):
    times = np.asarray([0.5, 1.25, 2.0, 2.0, 9.5])
    proc = TraceArrivals(times)
    assert list(proc.arrivals(0.0)) == [0.5, 1.25, 2.0, 2.0, 9.5]
    assert list(proc.arrivals(1.0)) == [1.25, 2.0, 2.0, 9.5]
    p = tmp_path / "trace.txt"
    p.write_text("".join(f"{t}\n" for t in times))
    assert list(TraceArrivals.from_file(str(p)).arrivals(0.0)) == \
        list(proc.arrivals(0.0))
    cfg = ArrivalConfig(mode="trace", trace_path=str(p))
    assert isinstance(make_arrivals(cfg), TraceArrivals)


def test_arrival_config_validation():
    with pytest.raises(ValueError, match="unknown arrival mode"):
        ArrivalConfig(mode="tidal")
    with pytest.raises(ValueError, match="amplitude"):
        ArrivalConfig(mode="diurnal", amplitude=1.5)
    with pytest.raises(ValueError, match="trace_path"):
        make_arrivals(ArrivalConfig(mode="trace"))
    assert make_arrivals(ArrivalConfig(mode="none")) is None
    assert isinstance(make_arrivals(ArrivalConfig(mode="constant")),
                      ConstantArrivals)


def test_scheduler_arrivals_shape_trace_deterministically(small_data):
    """The scheduler wiring: a diurnal arrival process changes the
    event trace (latencies stretch at the trough) but stays
    deterministic — two runs with the same seed+process produce
    identical traces, like every other seeded stream."""
    cfg, data = small_data
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.models import create_model
    from fedml_tpu.async_ import AsyncFedAvgEngine, LifecycleConfig

    def run(arrivals):
        trainer = ClientTrainer(create_model("lr", output_dim=10),
                                lr=cfg.lr)
        lc = LifecycleConfig(latency="lognormal", latency_scale=1.0,
                             seed=5)
        eng = AsyncFedAvgEngine(trainer, data, cfg, buffer_k=4,
                                concurrency=8, lifecycle_cfg=lc,
                                donate=False, arrivals=arrivals)
        eng.run(rounds=3)
        return eng.trace

    arr = ArrivalConfig(mode="diurnal", rate=100.0, period_s=10.0,
                        amplitude=0.9)
    t1, t2 = run(arr), run(arr)
    assert t1 == t2
    assert t1 != run(None)                  # the load curve is visible


# -- the serve loop ----------------------------------------------------------

def test_serve_smoke_100k_clients():
    """Fast virtual-time serve smoke at 100k clients: every commit
    lands, the registry stays under the byte gate with only touched
    shards allocated, eligibility holds (banned clients never
    contribute), and the report is reproducible per seed."""
    arr = ArrivalConfig(mode="diurnal", rate=1000.0, period_s=30.0,
                        amplitude=0.8)
    rep = run_serve_sim(100_000, commits=8, warmup_commits=2,
                        buffer_k=16, row_dim=256, arrival=arr,
                        dropout_prob=0.05, banned_frac=0.01, seed=0)
    assert rep["commits"] == 8
    assert rep["committed_updates"] == 8 * 16
    assert rep["committed_updates_per_sec"] > 0
    assert rep["registry_bytes_per_client"] <= 100.0
    assert rep["registry_bytes"] <= 100_000 * BYTES_PER_CLIENT
    assert rep["banned"] > 0 and rep["crashed"] > 0
    assert rep["sampler_peak_scratch_bytes"] < 1 << 20
    rep2 = run_serve_sim(100_000, commits=8, warmup_commits=2,
                         buffer_k=16, row_dim=256, arrival=arr,
                         dropout_prob=0.05, banned_frac=0.01, seed=0)
    # virtual-time trajectory is a pure function of the seed
    assert rep2["virtual_time_s"] == rep["virtual_time_s"]
    assert rep2["crashed"] == rep["crashed"]


def test_serve_loop_has_no_per_client_python_objects():
    """The no-per-client-Python-objects acceptance: after a serve run
    at 200k clients, the registry holds only numpy shards (no dict/
    set/list keyed by client) and the biggest Python container in the
    subsystem is O(shards), not O(population)."""
    reg = ClientRegistry(200_000)
    samp = StreamingCohortSampler(reg, 32, seed=0, mode="stratified")
    for r in range(20):
        ids = samp.sample(r)
        reg.note_dispatch(ids, r)
        for c in ids:
            reg.note_return(int(c))
            reg.note_contribution(int(c), 0.0, r)
    for container in (reg._shards, samp.__dict__):
        assert len(container) < 64
    for sh in reg._shards.values():
        for arr in sh.values():
            assert isinstance(arr, np.ndarray)


@pytest.mark.slow
def test_serve_sustains_1m_clients():
    """NIGHTLY acceptance (ISSUE 10): the 1M-client arm sustains
    committed-updates/sec (>= 0.4x of a 10k-client run of the same
    shape — the fold is the floor, the spine must not add O(N) work)
    with registry memory <= ~100 bytes/client."""
    arr = ArrivalConfig(mode="diurnal", rate=2000.0, period_s=600.0,
                        amplitude=0.8)
    kw = dict(commits=30, warmup_commits=4, buffer_k=32, row_dim=4096,
              arrival=arr, dropout_prob=0.02, banned_frac=0.01, seed=0)
    small = run_serve_sim(10_000, **kw)
    big = run_serve_sim(1_000_000, **kw)
    assert big["registry_bytes_per_client"] <= 100.0
    assert big["committed_updates"] == 30 * 32
    assert (big["committed_updates_per_sec"]
            >= 0.4 * small["committed_updates_per_sec"]), (small, big)


def test_serve_validation():
    with pytest.raises(ValueError, match="commits"):
        run_serve_sim(1000, commits=2, warmup_commits=2)


def test_serve_arrival_seed_changes_trace():
    """ArrivalConfig.seed is consumed: two serve runs differing only in
    the arrival seed walk different virtual-time traces."""
    kw = dict(commits=4, warmup_commits=1, buffer_k=8, row_dim=64, seed=0)
    a = run_serve_sim(1000, arrival=ArrivalConfig(
        mode="constant", rate=500.0, seed=0), **kw)
    b = run_serve_sim(1000, arrival=ArrivalConfig(
        mode="constant", rate=500.0, seed=1), **kw)
    assert a["virtual_time_s"] != b["virtual_time_s"]


def test_serve_exhausted_trace_names_the_problem(tmp_path):
    p = tmp_path / "short.txt"
    p.write_text("0.1\n0.2\n0.3\n")
    with pytest.raises(ValueError, match="arrival trace exhausted"):
        run_serve_sim(1000, commits=4, warmup_commits=1, buffer_k=8,
                      row_dim=64,
                      arrival=ArrivalConfig(mode="trace",
                                            trace_path=str(p)))


def test_serve_host_sharded_partition_commits_identically():
    """ISSUE 13: the serve loop sharded across two ranks by client-id
    range — each rank owns HALF the population's registry shards,
    samples/folds its own range, and the commit folds the partial
    aggregates upward over the HostChannel (rank-ordered sum).  Both
    ranks must commit the IDENTICAL global mix (committed_digest), and
    each rank's registry holds only its range."""
    import threading

    from fedml_tpu.parallel.multihost import (HostChannel,
                                              MultihostContext,
                                              free_port)
    port = free_port()
    pop = 4096
    reports: dict = {}
    errs: list = []

    def rank(r):
        try:
            ctx = MultihostContext(rank=r, world=2,
                                   coordinator=f"localhost:{port}")
            ch = HostChannel(ctx, timeout_s=60, connect_timeout_s=30)
            try:
                reports[r] = run_serve_sim(
                    pop, commits=4, warmup_commits=1, buffer_k=8,
                    row_dim=64,
                    arrival=ArrivalConfig(mode="constant", rate=500.0,
                                          seed=0),
                    seed=0, partition=(r, 2), channel=ch)
            finally:
                ch.close()
        except Exception as e:          # surfaced below, never hangs
            errs.append((r, e))

    ts = [threading.Thread(target=rank, args=(r,)) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=180)
    assert not errs, errs
    assert set(reports) == {0, 1}
    a, b = reports[0], reports[1]
    assert a["committed_digest"] == b["committed_digest"], (
        "host-sharded serve ranks committed different global mixes")
    assert a["local_population"] == b["local_population"] == pop // 2
    assert a["partition"] == [0, 2] and b["partition"] == [1, 2]
    # the partial aggregates really crossed ranks
    assert a["carry_allreduce_bytes"] > 0
    assert b["carry_allreduce_bytes"] > 0
    # world > 1 without a channel is a loud error
    with pytest.raises(ValueError, match="HostChannel"):
        run_serve_sim(100, commits=2, warmup_commits=1,
                      partition=(0, 2))


def test_serve_elastic_readopts_dead_ranks_range():
    """ISSUE 14: the host-sharded serve loop survives a rank death —
    rank 2 of 3 vanishes mid-run (crash_at_commit closes its channel),
    the survivors' next exchange evicts it (one view change), the
    window where the death lands folds deterministic ZEROS for the
    dead range, and at the next commit barrier the view's new owner
    re-adopts the range as a fresh lane.  The survivors must finish
    every commit with IDENTICAL committed_digest (they fold the same
    allgathered bytes every window), host every range exactly once
    between them, and report the adoption."""
    import threading

    from fedml_tpu.parallel.multihost import (ElasticChannel,
                                              MultihostContext,
                                              free_port)
    port = free_port()
    pop, world = 3072, 3
    reports: dict = {}
    errs: list = []

    def rank(r):
        try:
            ctx = MultihostContext(rank=r, world=world,
                                   coordinator=f"localhost:{port}")
            ch = ElasticChannel(ctx, n_items=world,
                                config_digest="serve-elastic",
                                timeout_s=60, connect_timeout_s=30,
                                hb_interval_s=0.1, hb_timeout_s=1.0)
            try:
                reports[r] = run_serve_sim(
                    pop, commits=8, warmup_commits=1, buffer_k=8,
                    row_dim=64,
                    arrival=ArrivalConfig(mode="constant", rate=500.0,
                                          seed=0),
                    seed=0, partition=(r, world), channel=ch,
                    elastic=True,
                    crash_at_commit=3 if r == 2 else None)
            finally:
                ch.close()
        except Exception as e:          # surfaced below, never hangs
            errs.append((r, e))

    ts = [threading.Thread(target=rank, args=(r,))
          for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=180)
    assert not errs, errs
    assert set(reports) == {0, 1, 2}
    a, b, c = reports[0], reports[1], reports[2]
    assert a["committed_digest"] == b["committed_digest"], (
        "survivors committed different global mixes after the death")
    assert a["commits"] == b["commits"] == 8
    assert c["commits"] == 3 and c["elastic"]["crashed_at_commit"] == 3
    # the dead range was re-adopted, and every range has EXACTLY one
    # host among the survivors (no double-hosting)
    hosted = sorted(a["elastic"]["lanes"] + b["elastic"]["lanes"])
    assert hosted == [0, 1, 2], hosted
    adopted = a["elastic"]["adopted_items"] + b["elastic"]["adopted_items"]
    assert 2 in adopted, f"range 2 never re-adopted: {adopted}"
    assert a["elastic"]["view_changes"] >= 1
    assert a["elastic"]["epoch"] >= 1
    # elastic=True without an ElasticChannel is a loud error
    with pytest.raises(ValueError, match="ElasticChannel"):
        run_serve_sim(100, commits=2, warmup_commits=1,
                      partition=(0, 2), channel=object(), elastic=True)


def test_serve_uniform_sampler_not_low_id_biased():
    """The legacy uniform draw is prefix-stable in k at a fixed round;
    the serve loop must advance the sampler round per DRAW, or every
    refill would re-select in-flight ids and fall back to ascending
    free_ids — concentrating cohorts at low ids."""
    rep = run_serve_sim(
        20_000, commits=8, warmup_commits=1, buffer_k=16, row_dim=64,
        sampler_mode="uniform",
        arrival=ArrivalConfig(mode="constant", rate=1000.0), seed=0)
    assert rep["commits"] == 8
    # with 8*16 = 128 admitted updates over 20k clients a uniform draw
    # almost never reuses a client; the old bug concentrated refills on
    # the lowest free ids (max participation >> 1, few distinct)
    assert rep["distinct_contributors"] >= 100
    assert rep["max_client_participation"] <= 3


# -- scheduler registry integration ------------------------------------------

def test_scheduler_registry_tracks_participation(small_data):
    cfg, data = small_data
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.models import create_model
    from fedml_tpu.async_ import AsyncFedAvgEngine
    trainer = ClientTrainer(create_model("lr", output_dim=10), lr=cfg.lr)
    eng = AsyncFedAvgEngine(trainer, data, cfg, buffer_k=4,
                            concurrency=4, donate=False)
    eng.run(rounds=3)
    reg = eng.registry
    # 3 commits x 4 admitted results each, all in registry counters
    assert reg.total_participation() == 12
    assert reg.n_clients == data.client_num
    ids = np.arange(reg.n_clients)
    assert reg.participation(ids).sum() == 12
    assert np.all(reg.last_staleness(ids) >= 0.0)
