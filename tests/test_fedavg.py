"""End-to-end FedAvg tests, including the reference's mathematical
equivalence oracle (CI-script-fedavg.sh:41-59 / BASELINE.md):

  FedAvg with full participation, full batch, E=1  ==  centralized GD
  (same accuracy to 3 decimals; here we assert parameter-level closeness,
  which is stronger).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.centralized import CentralizedTrainer
from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.federated import FederatedData, build_client_shards, build_eval_shard
from fedml_tpu.data.loaders import load_data
from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.utils.config import FedConfig


def make_uniform_data(n_clients=4, per_client=32, dim=16, classes=4, seed=0):
    """Equal-sized clients, full-batch shards (one batch per client)."""
    rng = np.random.RandomState(seed)
    n = n_clients * per_client
    w = rng.randn(dim, classes)
    x = rng.randn(n, dim).astype(np.float32)
    y = np.argmax(x @ w + 0.3 * rng.randn(n, classes), axis=1).astype(np.int64)
    idx_map = {i: np.arange(i * per_client, (i + 1) * per_client)
               for i in range(n_clients)}
    shards = build_client_shards(x, y, idx_map, per_client)
    return FederatedData(
        train_data_num=n, test_data_num=n,
        train_global=build_eval_shard(x, y, n),
        test_global=build_eval_shard(x, y, n),
        client_shards=shards,
        client_num_samples=np.full(n_clients, per_client, np.float32),
        test_client_shards=None, class_num=classes), x, y


class TestEquivalenceOracle:
    """FedAvg(full participation, full batch, E=1) == centralized full-batch
    GD, round for round. With equal client sizes and one full batch each,
    mean-of-client-gradient-steps == one global gradient step exactly."""

    def test_fedavg_equals_centralized(self):
        data, x, y = make_uniform_data()
        cfg = FedConfig(client_num_in_total=4, client_num_per_round=4,
                        comm_round=5, epochs=1, lr=0.1, batch_size=128)
        model = LogisticRegression(num_classes=4, flatten=False)
        t_fed = ClientTrainer(model, lr=0.1)
        t_cen = ClientTrainer(model, lr=0.1)

        engine = FedAvgEngine(t_fed, data, cfg, donate=False)
        v0 = engine.init_variables()
        v_fed = engine.run(variables=jax.tree.map(jnp.copy, v0))

        # centralized: full-batch GD on the union of client data, same #steps
        cen = CentralizedTrainer(t_cen, data, cfg)
        v_cen = cen.run(epochs=5, variables=jax.tree.map(jnp.copy, v0))

        fed_acc = engine.evaluate(v_fed)["train_acc"]
        cen_acc = cen.evaluate(v_cen)["train_acc"]
        assert round(fed_acc, 3) == round(cen_acc, 3)
        # parameter-level equivalence (stronger than the reference's oracle)
        for a, b in zip(jax.tree.leaves(v_fed), jax.tree.leaves(v_cen)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=1e-3)

    def test_weighted_aggregation_unequal_clients(self):
        """Unequal client sizes: the weighted mean must use true sample
        counts (padding must not leak into weights or gradients)."""
        rng = np.random.RandomState(1)
        x = rng.randn(48, 8).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.int64)
        idx_map = {0: np.arange(0, 8), 1: np.arange(8, 48)}  # sizes 8 vs 40
        shards = build_client_shards(x, y, idx_map, 8)
        data = FederatedData(48, 48, build_eval_shard(x, y, 48),
                             build_eval_shard(x, y, 48), shards,
                             np.array([8., 40.], np.float32), None, 2)
        cfg = FedConfig(client_num_in_total=2, client_num_per_round=2,
                        comm_round=3, epochs=1, lr=0.5, batch_size=8)
        model = LogisticRegression(num_classes=2, flatten=False)
        engine = FedAvgEngine(ClientTrainer(model, lr=0.5), data, cfg)
        v = engine.run()
        acc = engine.evaluate(v)["train_acc"]
        assert acc > 0.85  # learns the separable task

    @pytest.mark.parametrize("opt_kw", [
        dict(),                                   # plain SGD
        dict(momentum=0.9, weight_decay=0.01),    # momentum+decay: update
                                                  # nonzero even at zero grad
        dict(prox_mu=0.1),                        # prox pulls toward global
    ])
    def test_padding_mask_is_noop(self, opt_kw):
        """A fully-padded batch must be a complete no-op: training with
        8 real + 8 padded samples == training with just the 8 real ones,
        even with momentum / weight decay / prox terms."""
        rng = np.random.RandomState(2)
        x = rng.randn(8, 4).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.int64)
        model = LogisticRegression(num_classes=2, flatten=False)
        trainer = ClientTrainer(model, lr=0.3, **opt_kw)
        v0 = trainer.init(jax.random.PRNGKey(0), jnp.asarray(x))

        sh_real = build_client_shards(x, y, {0: np.arange(8)}, 8)
        sh_pad = build_client_shards(x, y, {0: np.arange(8)}, 8, max_batches=2)
        # force an extra all-padding batch
        pad = {k: np.concatenate([v, np.zeros_like(v)], axis=1)
               for k, v in sh_real.items()}
        r = jax.random.PRNGKey(1)
        one = lambda sh: trainer.local_train(
            jax.tree.map(jnp.copy, v0),
            jax.tree.map(lambda a: jnp.asarray(a[0]), sh), r, 1,
            global_params=v0["params"])
        v_real, _, n_real = one(sh_real)
        v_pad, _, n_pad = one(pad)
        assert float(n_real) == float(n_pad) == 8.0
        for a, b in zip(jax.tree.leaves(v_real), jax.tree.leaves(v_pad)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


class TestFedAvgLearning:
    def test_mnist_synthetic_reaches_target(self):
        """BASELINE.md row 1 analogue: standalone FedAvg, LR model, 10
        sampled clients/round. On the synthetic stand-in task accuracy must
        clear 75% (the real-MNIST bar)."""
        data = load_data("mnist", client_num_in_total=50, batch_size=10,
                         synthetic_scale=0.02, seed=0)
        cfg = FedConfig(client_num_in_total=50, client_num_per_round=10,
                        comm_round=20, epochs=1, lr=0.1, batch_size=10,
                        frequency_of_the_test=100)
        model = LogisticRegression(num_classes=10, flatten=False)
        engine = FedAvgEngine(ClientTrainer(model, lr=0.1), data, cfg)
        v = engine.run()
        assert engine.evaluate(v)["test_acc"] > 0.75

    def test_deterministic_given_seed(self):
        data, *_ = make_uniform_data()
        cfg = FedConfig(client_num_in_total=4, client_num_per_round=2,
                        comm_round=3, epochs=2, lr=0.1, batch_size=32, seed=3)
        model = LogisticRegression(num_classes=4, flatten=False)
        runs = []
        for _ in range(2):
            e = FedAvgEngine(ClientTrainer(model, lr=0.1), data, cfg)
            runs.append(e.run())
        for a, b in zip(jax.tree.leaves(runs[0]), jax.tree.leaves(runs[1])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_local_eval_on_per_client_test_shards():
    """The reference's _local_test_on_all_clients (fedavg_api.py:117-213):
    weighted accuracy over every client's OWN test shard, with --ci
    truncating to one client."""
    from fedml_tpu.models import create_model

    rs = np.random.RandomState(0)
    C, per = 3, 8
    n = C * per
    x = rs.rand(n, 6).astype(np.float32)
    y = (x.sum(-1) > 3).astype(np.int64)
    idx = {i: np.arange(i * per, (i + 1) * per) for i in range(C)}
    shards = build_client_shards(x, y, idx, 4)
    data = FederatedData(
        train_data_num=n, test_data_num=n,
        train_global=build_eval_shard(x, y, 4),
        test_global=build_eval_shard(x, y, 4),
        client_shards=shards,
        client_num_samples=np.full(C, per, np.float32),
        test_client_shards=shards,           # same data as local test sets
        class_num=2, synthetic=True)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=C,
                    comm_round=1, batch_size=4, lr=0.1,
                    frequency_of_the_test=100)
    eng = FedAvgEngine(ClientTrainer(create_model("lr", 2), lr=0.1),
                       data, cfg, donate=False)
    v = eng.init_variables()
    m = eng.evaluate(v)
    # local test == global test here (identical underlying samples)
    assert abs(m["local_test_acc"] - m["test_acc"]) < 1e-6
    assert "local_test_loss" in m
    # --ci truncates to client 0 only
    cfg_ci = FedConfig(**{**cfg.__dict__, "ci": True})
    eng_ci = FedAvgEngine(ClientTrainer(create_model("lr", 2), lr=0.1),
                          data, cfg_ci, donate=False)
    m_ci = eng_ci.evaluate_local(v)
    one = jax.tree.map(lambda a: a[:1], shards)
    sums = jax.vmap(eng_ci.trainer.evaluate, in_axes=(None, 0))(v, one)
    expect = float(jnp.sum(sums["correct"])) / float(jnp.sum(sums["count"]))
    assert abs(m_ci["local_test_acc"] - expect) < 1e-6


def test_local_train_eval_always_available():
    """split='train' evaluates on the clients' own TRAIN shards (the
    reference's local Train/Acc) and needs no natural test split."""
    from fedml_tpu.models import create_model

    data = load_data("mnist", client_num_in_total=4, batch_size=4,
                     synthetic_scale=0.001, seed=0)
    assert data.test_client_shards is None       # synthetic: no test split
    cfg = FedConfig(client_num_in_total=4, client_num_per_round=4,
                    comm_round=1, batch_size=4, lr=0.1,
                    frequency_of_the_test=100)
    eng = FedAvgEngine(ClientTrainer(create_model("lr", 10), lr=0.1),
                       data, cfg, donate=False)
    v = eng.init_variables()
    m = eng.evaluate_local(v, split="train")
    assert 0.0 <= m["local_train_acc"] <= 1.0
    assert np.isfinite(m["local_train_loss"])
    with pytest.raises(ValueError):
        eng.evaluate_local(v, split="test")
    with pytest.raises(ValueError):
        eng.evaluate_local(v, split="validation")


def test_local_train_eval_mesh_flat_stack_conv():
    """Regression (round-4 review): the mesh engine's resident stack is
    stored FLAT under flat_stack; evaluate_local(split='train') reuses
    that stack and must restore the image shape in-program — a conv
    model crashed on the flattened x before the _local_eval_transform
    hook."""
    from fedml_tpu.models import create_model
    from fedml_tpu.parallel import MeshFedAvgEngine
    from fedml_tpu.parallel.mesh import make_mesh

    data = load_data("femnist", client_num_in_total=8, batch_size=4,
                     synthetic_scale=0.001, max_batches_per_client=1,
                     seed=0)
    cfg = FedConfig(client_num_in_total=8, client_num_per_round=8,
                    comm_round=1, batch_size=4, lr=0.1,
                    frequency_of_the_test=100)
    eng = MeshFedAvgEngine(ClientTrainer(create_model("cnn", data.class_num),
                                         lr=0.1),
                           data, cfg, mesh=make_mesh(), donate=False)
    assert eng.flat_stack
    v = eng.init_variables()
    v = eng._prepare_variables(v)
    eng._device_stack()               # builds the (flat) resident stack
    assert eng._x_image_shape == (28, 28, 1)
    m = eng.evaluate_local(v, split="train")
    assert 0.0 <= m["local_train_acc"] <= 1.0
    assert np.isfinite(m["local_train_loss"])


def test_centralized_mesh_batch_parallel_matches_single():
    """CentralizedTrainer with a mesh = the reference's DDP as a
    batch-sharded axis: results match the unsharded trainer (zero-mask
    sample padding is invisible to the masked loss)."""
    from fedml_tpu.algorithms.centralized import CentralizedTrainer
    from fedml_tpu.models import create_model
    from fedml_tpu.parallel.mesh import make_mesh

    data = load_data("mnist", client_num_in_total=4, batch_size=10,
                     synthetic_scale=0.01, seed=0)
    cfg = FedConfig(client_num_in_total=4, client_num_per_round=4,
                    comm_round=4, batch_size=10, lr=0.1,
                    frequency_of_the_test=100)
    ref = CentralizedTrainer(ClientTrainer(create_model("lr", 10), lr=0.1),
                             data, cfg)
    v_ref = ref.run(epochs=4)
    # bs of the global eval shard is 64 -> pads to 64 (already multiple);
    # use a mesh of 8 over the sample axis
    dp = CentralizedTrainer(ClientTrainer(create_model("lr", 10), lr=0.1),
                            data, cfg, mesh=make_mesh(8))
    v_dp = dp.run(epochs=4)
    for a, b in zip(jax.tree.leaves(v_ref), jax.tree.leaves(v_dp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    m1, m2 = ref.evaluate(v_ref), dp.evaluate(v_dp)
    assert abs(m1["test_acc"] - m2["test_acc"]) < 1e-6
