"""Cluster observatory tests (ISSUE 17): the live telemetry plane
(heartbeat/allgather piggybacks folding under origin="host<i>"), the
barrier straggler ledger, the cluster SLO pack + attribution, the
coordinated incident dumps, and the obs-off wire-bytes pin — all
in-process over real loopback sockets (threads, NOT spawned clusters:
the spawned-pin riders live in test_multihost_spmd.py on the runs that
already exist).

Pinned invariants:

* the metrics sidecar round-trips exactly and rejects non-sidecar
  tails (mixed obs-on/obs-off ranks stay safe);
* a slowed rank is NAMED as the round's gating rank with its margin,
  and the per-rank waits land in multihost_barrier_wait_seconds;
* a worker's heartbeat piggyback folds into the coordinator's registry
  continuously (origin="host1"), and /cluster reports it alive;
* the cluster SLO pack breaches cluster_no_rank_deaths with the dead
  rank named in the attribution block;
* one coordinated dump fans out to every member's flight recorder and
  the throttle window holds (a breach storm yields ONE artifact set);
* with obs disabled the wire bytes are IDENTICAL to the
  pre-observatory channel: heartbeat headers stay exactly {}, worker
  allgather frames are exactly the payload, and no DUMP frame exists;
* tools/trace_timeline.py auto-discovers rank*/ obs dirs (rejoin
  rank<i>-pid<pid> namespaces included) and merges the barrier ledger
  into the report + Chrome trace.
"""
import glob
import importlib.util
import json
import os
import signal
import threading
import time

import pytest

from fedml_tpu import obs
from fedml_tpu.obs import cluster, slo

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture
def clean_obs():
    prev = signal.getsignal(signal.SIGUSR1)
    obs.reset()
    yield
    obs.reset()
    signal.signal(signal.SIGUSR1, prev)


def _series_with_origin(name: str, origin: str) -> bool:
    return any(m.name == name
               and dict(m.labels).get("origin") == origin
               for m in obs.registry().metrics())


def _elastic(rank, world, port, *, n_items=2, hb_timeout_s=2.0):
    from fedml_tpu.parallel.multihost import (ElasticChannel,
                                              MultihostContext)
    ctx = MultihostContext(rank=rank, world=world,
                           coordinator=f"localhost:{port}")
    return ElasticChannel(ctx, n_items=n_items, config_digest="cfg",
                          timeout_s=20.0, connect_timeout_s=10.0,
                          hb_interval_s=0.1, hb_timeout_s=hb_timeout_s)


def _build_pair(port, world=2):
    """Construct one channel per rank concurrently (the hello
    handshake needs both sides live)."""
    chans, errs = {}, []

    def mk(r):
        try:
            chans[r] = _elastic(r, world, port)
        except Exception as e:           # pragma: no cover - diagnostics
            errs.append((r, e))

    ts = [threading.Thread(target=mk, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(20)
    assert not errs, errs
    chans[0].wait_members()
    return chans


# -- sidecar wire format -----------------------------------------------------


def test_sidecar_roundtrip_cap_and_rejection(clean_obs):
    delta = {"schema": 1, "metrics": [{"name": "x_total", "value": 3}]}
    payload = b"\x01\x02carry-bytes\x00fml"
    frame = cluster.attach_sidecar(payload, delta)
    assert frame != payload and frame.startswith(payload)
    got_payload, got_delta = cluster.split_sidecar(frame)
    assert got_payload == payload and got_delta == delta
    # nothing to ship / oversized delta -> frame untouched
    assert cluster.attach_sidecar(payload, None) == payload
    assert cluster.attach_sidecar(payload, {"schema": 1,
                                            "metrics": []}) == payload
    big = {"schema": 1, "metrics": [{"name": "y", "value": "z" * (
        cluster.SIDECAR_CAP_BYTES + 1)}]}
    assert cluster.attach_sidecar(payload, big) == payload
    # frames WITHOUT the trailer pass through untouched — including a
    # payload that happens to end in the magic but carries no sane
    # length/JSON behind it
    for raw in (payload, b"", b"x" * 3, payload + cluster.SIDECAR_MAGIC,
                b"\xff" * 12 + cluster.SIDECAR_MAGIC):
        p, d = cluster.split_sidecar(raw)
        assert p == raw and d is None


# -- barrier ledger ----------------------------------------------------------


def test_note_barrier_names_gating_rank_and_summary(clean_obs):
    cluster.set_role(0, 3)
    base = 1000.0
    # rank 2 arrives last by 0.4s twice, rank 1 once
    cluster.note_barrier("allgather", 1, 0,
                         {0: base, 1: base + 0.1, 2: base + 0.5})
    cluster.note_barrier("allgather", 2, 1,
                         {0: base, 1: base + 0.6, 2: base + 0.2})
    cluster.note_barrier("exchange", 3, 2,
                         {0: base, 1: base + 0.1, 2: base + 0.5})
    # a 1-rank "barrier" is not a barrier
    assert cluster.note_barrier("gather", 4, None, {0: base}) is None
    led = cluster.barrier_ledger()
    assert [e["round_gating_rank"] for e in led] == [2, 1, 2]
    assert led[0]["gate_margin_s"] == pytest.approx(0.4)
    assert led[0]["waits_s"] == {"0": pytest.approx(0.5),
                                 "1": pytest.approx(0.4),
                                 "2": pytest.approx(0.0)}
    s = cluster.straggler_summary()
    assert s["barriers"] == 3
    assert s["gating_counts"] == {"1": 1, "2": 2}
    assert s["top_gating_rank"] == 2
    assert s["worst_gate_margin_s"] == pytest.approx(0.4)
    assert s["per_rank_wait_s"]["0"]["max"] == pytest.approx(0.6)
    # the waits landed in the histogram the SLO pack judges
    h = obs.histogram("multihost_barrier_wait_seconds", rank="0")
    assert h.count == 3


def test_hostchannel_allgather_ledger_names_slowed_rank(clean_obs,
                                                        tmp_path):
    """3-rank HostChannel over loopback with rank 2 slowed ~0.3s: the
    ledger entry must name rank 2 as gating with a comparable margin,
    and (obs on) the workers' payload sidecars must fold into the
    coordinator's registry under origin labels while the BROADCAST
    payloads stay bitwise-clean."""
    from fedml_tpu.parallel.multihost import (HostChannel,
                                              MultihostContext,
                                              free_port)
    obs.configure(str(tmp_path), install_signal=False,
                  export_at_exit=False)
    obs.counter("probe_sidecar_total").inc()
    port = free_port()
    out, errs = {}, []

    def run(r):
        try:
            ch = HostChannel(MultihostContext(
                rank=r, world=3, coordinator=f"localhost:{port}"),
                timeout_s=20.0, connect_timeout_s=10.0)
            try:
                ch.round_hint = 7
                if r == 2:
                    time.sleep(0.3)
                out[r] = ch.allgather(b"p%d" % r)
            finally:
                ch.close()
        except Exception as e:
            errs.append((r, e))

    ts = [threading.Thread(target=run, args=(r,)) for r in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert not errs, errs
    # broadcast payloads are the raw contributions — sidecars stripped
    assert out[0] == out[1] == out[2] == [b"p0", b"p1", b"p2"]
    led = cluster.barrier_ledger()
    assert len(led) == 1 and led[0]["kind"] == "allgather"
    assert led[0]["round"] == 7
    assert led[0]["round_gating_rank"] == 2
    assert led[0]["gate_margin_s"] > 0.15, led[0]
    assert led[0]["waits_s"]["2"] == 0.0
    # at least one worker's piggybacked delta folded live (the other's
    # may have found an already-advanced baseline -> None, by design)
    assert (_series_with_origin("probe_sidecar_total", "host1")
            or _series_with_origin("probe_sidecar_total", "host2")), (
        "no worker sidecar folded into the coordinator registry")


# -- live telemetry plane (heartbeat piggyback) ------------------------------


def test_hb_piggyback_folds_into_coordinator_live(clean_obs, tmp_path):
    from fedml_tpu.parallel.multihost import free_port
    obs.configure(str(tmp_path), install_signal=False,
                  export_at_exit=False)
    chans = _build_pair(free_port())
    try:
        obs.counter("piggy_probe_total").inc()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if _series_with_origin("piggy_probe_total", "host1"):
                break
            time.sleep(0.05)
        assert _series_with_origin("piggy_probe_total", "host1"), (
            "the worker's heartbeat delta never folded into the "
            "coordinator's registry")
        rep = cluster.cluster_report()
        assert rep["scope"] == "cluster" and rep["world"] == 2
        assert rep["ranks"]["1"]["alive"] is True
        assert rep["ranks"]["1"]["last_fold_age_s"] is not None
        json.dumps(rep)                  # endpoint doc must serialize
    finally:
        for ch in chans.values():
            ch.close()


# -- cluster SLO pack + attribution ------------------------------------------


def test_cluster_slo_breach_names_dead_rank(clean_obs):
    cluster.set_role(0, 3)
    obs.counter("multihost_rounds_committed_total", rank="0").inc()
    cluster.note_barrier("exchange", 0, 0,
                         {0: 1.0, 1: 1.2, 2: 1.1})
    rep = cluster.cluster_slo_report()
    assert rep["scope"] == "cluster" and rep["healthy"], rep
    # a rank death breaches with the rank NAMED
    obs.counter("multihost_rank_deaths_total", rank="1").inc()
    rep = cluster.cluster_slo_report()
    assert rep["healthy"] is False
    assert "cluster_no_rank_deaths" in rep["breached"], rep
    att = rep["attribution"]
    assert att["dead_ranks"] == ["1"]
    assert att["gating_rank"] == 1
    assert "1" in att["per_rank_wait_p95_s"]
    # non-coordinators have no engine -> no cluster verdict to fake
    obs.reset()
    cluster.set_role(2, 3)
    assert cluster.cluster_slo_report() is None
    assert cluster.scope() == "local"


# -- coordinated incident dumps ----------------------------------------------


def test_coordinated_dump_fans_out_and_throttles(clean_obs, tmp_path):
    from fedml_tpu.parallel.multihost import free_port
    obs.configure(str(tmp_path), install_signal=False,
                  export_at_exit=False)
    chans = _build_pair(free_port())
    try:
        assert cluster.maybe_coordinated_dump("test_incident") is True
        # inside the throttle window: a breach storm yields ONE set
        assert cluster.maybe_coordinated_dump("storm") is False
        assert obs.counter("multihost_coordinated_dumps_total"
                           ).value == 1
        # the DUMP frame is consumed on the worker's next exchange
        res = {}

        def rnd(r):
            parts = {b: b"r%d" % r for b in chans[r].view.assigned(r)}
            res[r] = chans[r].exchange(0, parts, lambda need: {})

        ts = [threading.Thread(target=rnd, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(20)
        assert set(res) == {0, 1}
        dumps = []
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and len(dumps) < 2:
            dumps = [p for p in glob.glob(str(tmp_path / "flight-*.json"))
                     if json.load(open(p))["reason"]
                     == "coordinated:test_incident"]
            time.sleep(0.05)
        assert len(dumps) == 2, (
            f"expected the coordinator's dump AND the worker's "
            f"fanned-out dump, got {len(dumps)}")
    finally:
        for ch in chans.values():
            ch.close()


def test_coordinated_dump_noop_with_obs_off(clean_obs):
    assert not cluster.telemetry_enabled()
    assert cluster.maybe_coordinated_dump("nope") is False


# -- THE wire pin: obs off => bytes identical --------------------------------


def test_wire_bytes_identical_with_obs_off(clean_obs, monkeypatch):
    """With no obs dir configured the observatory must be INVISIBLE on
    the wire: every heartbeat header is exactly {}, no DUMP frame is
    ever sent, and a worker's allgather frame is exactly its payload —
    the PR-13/14/16 bitwise anchors ride these bytes."""
    from fedml_tpu.parallel import multihost as mh
    assert not cluster.telemetry_enabled()
    sent_msgs = []
    real_send_msg = mh._send_msg

    def spy_send_msg(sock, mtype, header, payload=b""):
        sent_msgs.append((mtype, dict(header)))
        return real_send_msg(sock, mtype, header, payload)

    monkeypatch.setattr(mh, "_send_msg", spy_send_msg)
    chans = _build_pair(mh.free_port())
    try:
        res = {}

        def rnd(r):
            parts = {b: b"r%d" % r for b in chans[r].view.assigned(r)}
            res[r] = chans[r].exchange(0, parts, lambda need: {})

        ts = [threading.Thread(target=rnd, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(20)
        time.sleep(0.35)                 # let a few heartbeats flow
        assert set(res) == {0, 1}
    finally:
        for ch in chans.values():
            ch.close()
    hbs = [h for (m, h) in sent_msgs if m == "hb"]
    assert hbs and all(h == {} for h in hbs), (
        "obs-off heartbeat headers must stay exactly {} — found "
        f"{[h for h in hbs if h != {}][:3]}")
    assert all(m != "dump" for (m, _h) in sent_msgs)

    # HostChannel tier: the worker frame is EXACTLY the payload
    sent_frames = []
    real_send_frame = mh._send_frame

    def spy_send_frame(sock, payload):
        sent_frames.append(bytes(payload))
        return real_send_frame(sock, payload)

    monkeypatch.setattr(mh, "_send_frame", spy_send_frame)
    port = mh.free_port()
    out, errs = {}, []

    def run(r):
        try:
            ch = mh.HostChannel(mh.MultihostContext(
                rank=r, world=2, coordinator=f"localhost:{port}"),
                timeout_s=20.0, connect_timeout_s=10.0)
            try:
                out[r] = ch.allgather(b"payload-%d" % r)
            finally:
                ch.close()
        except Exception as e:
            errs.append((r, e))

    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(20)
    assert not errs, errs
    assert out[0] == out[1] == [b"payload-0", b"payload-1"]
    assert b"payload-1" in sent_frames, (
        "obs-off worker allgather frame must be exactly the payload "
        "(no metrics trailer)")


# -- endpoints ---------------------------------------------------------------


def test_httpd_cluster_endpoint_and_slo_scope(clean_obs):
    import urllib.request
    cluster.set_role(0, 2)
    eng = slo.SloEngine([slo.spec("ok", "q_total", "delta_max", 10.0)])
    eng.prime()
    eng.evaluate()
    slo.install(eng)
    srv = obs.serve_http(0)
    base = f"http://127.0.0.1:{srv.port}"
    cl = json.loads(urllib.request.urlopen(f"{base}/cluster").read())
    assert cl["scope"] == "cluster" and cl["world"] == 2
    assert "straggler" in cl and "ranks" in cl
    assert cl["slo"]["scope"] == "cluster"      # the coordinator pack
    sl = json.loads(urllib.request.urlopen(f"{base}/slo").read())
    assert sl["healthy"] and sl["scope"] == "cluster"


# -- timeline auto-discovery -------------------------------------------------


def test_trace_timeline_autodiscovers_rank_dirs(clean_obs, tmp_path):
    """A parent obs dir expands to its rank*/ children — the plain
    rank0 AND a rejoiner's rank1-pid777 namespace, labeled apart — and
    rank 0's barrier ledger lands in the report + the Chrome trace's
    barrier lanes."""
    parent = tmp_path / "obs"
    for sub in ("rank0", "rank1-pid777"):
        obs.reset()
        obs.configure(str(parent / sub), install_signal=False,
                      export_at_exit=False)
        with obs.span("round", idx=0):
            time.sleep(0.01)
        if sub == "rank0":
            cluster.set_role(0, 2)
            cluster.note_barrier("exchange", 0, 0, {0: 5.0, 1: 5.4})
        obs.export()
    obs.reset()
    assert os.path.exists(parent / "rank0" / "barrier_ledger.json")

    spec = importlib.util.spec_from_file_location(
        "trace_timeline", os.path.join(REPO, "tools",
                                       "trace_timeline.py"))
    tt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tt)
    assert tt.main([str(parent)]) == 0
    report = json.load(open(parent / "critical_path.json"))
    labels = {s["label"] for s in report["sources"]}
    assert labels == {"rank0", "rank1-pid777"}, report["sources"]
    assert report["straggler"]["barriers"] == 1
    assert report["straggler"]["gating_counts"] == {"1": 1}
    chrome = json.load(open(parent / "merged.chrome.json"))
    if isinstance(chrome, dict):
        chrome = chrome["traceEvents"]
    names = {e.get("name") for e in chrome}
    assert any(e.get("name") == "process_name"
               and (e.get("args") or {}).get("name") == "cluster barriers"
               for e in chrome), "barrier lane process missing"
    assert "GATE" in names, "per-rank gate slices missing"
    assert any(str(e.get("name", "")).startswith("gate: rank 1")
               for e in chrome), "gating-rank annotation missing"
