"""Comm-layer tests: codec round-trip, manager FSM over the in-proc router,
message-driven FedAvg == engine FedAvg, and a real gRPC localhost loopback.

Mirrors the reference's framework liveness CI (CI-script-framework.sh:16-24)
but as actual unit tests (the reference has none — SURVEY.md §4).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedml_tpu.comm import (ClientManager, InProcRouter, Message,
                            MessageCodec, ServerManager)
from fedml_tpu.comm.fedavg_messaging import run_messaging_fedavg


def test_message_codec_roundtrip():
    msg = Message(3, sender_id=2, receiver_id=0)
    msg.add_params("model_params", {
        "dense": {"kernel": np.arange(12, dtype=np.float32).reshape(3, 4),
                  "bias": np.zeros(4, np.float64)},
        "nested": [np.ones(2, np.int32), "a string", 7, 3.5],
        "tup": (np.full((2, 2), 5, np.int64), True),
    })
    msg.add_params("num_samples", 42.0)
    out = MessageCodec.decode(MessageCodec.encode(msg))
    assert out.get_type() == 3
    assert out.get_sender_id() == 2 and out.get_receiver_id() == 0
    p = out.get("model_params")
    np.testing.assert_array_equal(p["dense"]["kernel"],
                                  np.arange(12, dtype=np.float32).reshape(3, 4))
    assert p["dense"]["bias"].dtype == np.float64
    assert p["nested"][1] == "a string" and p["nested"][2] == 7
    assert isinstance(p["tup"], tuple)
    np.testing.assert_array_equal(p["tup"][0], np.full((2, 2), 5))
    assert out.get("num_samples") == 42.0


def test_message_json_mobile_parity():
    msg = Message(1, 0, 1)
    msg.add_params("w", np.eye(2, dtype=np.float32))
    back = Message.from_json(msg.to_json())
    assert back.get("w") == [[1.0, 0.0], [0.0, 1.0]]   # nested lists


def test_manager_fsm_ping_pong():
    """Base-framework liveness: server sends, client echoes, round-trips N
    times (the reference's base_framework/decentralized_framework fakes)."""
    router = InProcRouter()
    log = []

    class Server(ServerManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler("pong", self._on_pong)

        def _on_pong(self, msg):
            log.append(("pong", msg.get("hops")))
            if msg.get("hops") < 3:
                out = Message("ping", 0, 1)
                out.add_params("hops", msg.get("hops") + 1)
                self.send_message(out)
            else:
                self.finish()

    class Client(ClientManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler("ping", self._on_ping)

        def _on_ping(self, msg):
            out = Message("pong", 1, 0)
            out.add_params("hops", msg.get("hops"))
            self.send_message(out)

    server = Server(0, 2, "INPROC", router=router)
    client = Client(1, 2, "INPROC", router=router)
    ct = client.run_async()
    st = server.run_async()
    first = Message("ping", 0, 1)
    first.add_params("hops", 0)
    server.send_message(first)
    st.join(timeout=10)
    client.finish()
    assert [h for _, h in log] == [0, 1, 2, 3]


def _tiny_setup():
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.data.loaders import load_data
    from fedml_tpu.models import create_model
    from fedml_tpu.utils.config import FedConfig

    cfg = FedConfig(client_num_in_total=4, client_num_per_round=4,
                    comm_round=3, epochs=1, batch_size=8, lr=0.1,
                    frequency_of_the_test=100)
    data = load_data("mnist", client_num_in_total=4, batch_size=8,
                     synthetic_scale=0.005)
    model = create_model("lr", output_dim=10)
    trainer = ClientTrainer(model, lr=0.1)
    return trainer, data, cfg


def test_messaging_fedavg_matches_engine():
    """The message-driven path (wire codec and all) must agree with the
    jitted engine on the same config — same weighted average, same
    deterministic sampling (full participation here)."""
    from fedml_tpu.algorithms.fedavg import FedAvgEngine

    trainer, data, cfg = _tiny_setup()
    engine = FedAvgEngine(trainer, data, cfg, donate=False)
    v0 = engine.init_variables()
    v_engine = engine.run(variables=jax.tree.map(jnp.copy, v0), rounds=3)

    v_msg = run_messaging_fedavg(trainer, data, cfg)
    for a, b in zip(jax.tree.leaves(v_engine), jax.tree.leaves(v_msg)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_tcp_loopback():
    """Two ranks over the raw-socket transport: model there and back."""
    from fedml_tpu.comm.tcp_backend import TcpBackend

    cfg = {0: "127.0.0.1", 1: "127.0.0.1"}
    a = TcpBackend(0, cfg, base_port=57200)
    b = TcpBackend(1, cfg, base_port=57200)
    try:
        w = np.random.RandomState(1).rand(128, 16).astype(np.float32)
        msg = Message(3, 0, 1)
        msg.add_params("w", w)
        a.send_message(msg)
        got = b._inbox.get(timeout=10)
        assert got.get_type() == 3
        np.testing.assert_array_equal(got.get("w"), w)
        rsp = Message(4, 1, 0)
        rsp.add_params("n", 17)
        b.send_message(rsp)
        got2 = a._inbox.get(timeout=10)
        assert got2.get("n") == 17
    finally:
        a.close()
        b.close()


def test_grpc_loopback():
    """Two ranks over real gRPC on localhost: send a model, get it back."""
    grpc = pytest.importorskip("grpc")
    from fedml_tpu.comm.grpc_backend import GrpcBackend

    cfg = {0: "127.0.0.1", 1: "127.0.0.1"}
    a = GrpcBackend(0, cfg, base_port=56100)
    b = GrpcBackend(1, cfg, base_port=56100)
    try:
        w = np.random.RandomState(0).rand(64, 32).astype(np.float32)
        msg = Message(7, 0, 1)
        msg.add_params("w", w)
        a.send_message(msg)
        got = b._inbox.get(timeout=10)
        assert got.get_type() == 7
        np.testing.assert_array_equal(got.get("w"), w)
        # reply path
        rsp = Message(8, 1, 0)
        rsp.add_params("ok", 1)
        b.send_message(rsp)
        got2 = a._inbox.get(timeout=10)
        assert got2.get_type() == 8 and got2.get("ok") == 1
    finally:
        a.close()
        b.close()
