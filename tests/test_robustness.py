"""Adversarial-robust async federation tests (ISSUE 9).

Anchors, in order of importance:

* Degenerate-config BITWISE pin: B=1 buckets + no screening + constant
  weights reproduces the PR-6 streaming commit exactly — at the
  program level (make_bucket_commit_fn vs make_stream_commit_fn over
  the same streaming buffer) and at the manager level (a defended
  AsyncServerManager driven through the ONE insert path produces
  bit-identical variables to an undefended one on the same arrival
  sequence).
* Seeded adversary determinism: same seed ⇒ identical byzantine set,
  corruption streams and event traces (the comm/chaos.py contract);
  two seeds differ.
* Admission pipeline: the finite canary, the shared-definition norm
  clip, the staleness-aware z/cosine screen — each stage catches its
  designated attack and never an honest update (the false-positive
  gate).
* One norm-clip definition: core/pytree.clip_scale is the factor for
  norm_diff_clip, the pallas clip-agg AND the flat-row clip — pinned
  bitwise on equal inputs, so DP-FedAvg and admission clipping cannot
  drift.
* Quality bands: attacked-undefended degrades below the clean band
  while attacked-defended stays within it, with zero honest
  quarantines (benchmarks/quality_bands.json, the PR-4 RECALIBRATE
  protocol).
* core/robust.py flat-path helpers under adversarial fixtures:
  analytically-checkable krum/multi-krum selections, trimmed-mean /
  coordinate-median values, and the NaN/Inf-poisoned-row guard.
"""
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.async_ import (AsyncBuffer, AsyncFedAvgEngine, AttackConfig,
                              AdversarySim, DefenseConfig, LifecycleConfig,
                              UpdateAdmission, make_bucket_commit_fn,
                              make_stream_commit_fn, run_async_messaging)
from fedml_tpu.async_.defense import make_flatten_fn
from fedml_tpu.async_.staleness import flat_dim, flatten_vars_row
from fedml_tpu.core.pytree import clip_scale, tree_clip_by_norm, tree_l2_norm
from fedml_tpu.core.robust import (clip_row, coordinate_median,
                                   krum_scores_flat, krum_select_flat,
                                   multi_krum_select_flat, norm_diff_clip,
                                   trimmed_mean)

from parallel_case import _mnist_like_cfg, _setup
from test_quality_regression import _assert_band, _band


def _assert_trees_bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# ONE norm-clip definition (the dedupe satellite)
# ---------------------------------------------------------------------------

class TestOneClipDefinition:
    def test_clip_scale_is_the_shared_factor_bitwise(self):
        """All three clip call sites reduce to core/pytree.clip_scale:
        fed the SAME squared norm, the factors are bit-identical (they
        are literally one function), and each path's end-to-end clip
        agrees with factor * input."""
        rs = np.random.RandomState(0)
        for sq in (0.0, 1e-30, 0.04, 25.0, 4e6):
            f = clip_scale(jnp.float32(sq), 2.0)
            # flat-row path
            row = rs.randn(33).astype(np.float32)
            row *= np.float32(np.sqrt(sq) / max(np.linalg.norm(row), 1e-30))
            got = clip_row(jnp.asarray(row), 2.0)
            want = jnp.asarray(row) * clip_scale(
                jnp.sum(jnp.asarray(row) * jnp.asarray(row)), 2.0)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
            assert np.isfinite(float(f))

    def test_tree_clip_routes_through_clip_scale(self):
        """tree_clip_by_norm's factor == clip_scale of its own squared
        norm, bitwise — the pytree path cannot drift from the flat
        one."""
        rs = np.random.RandomState(1)
        tree = {"a": jnp.asarray(rs.randn(4, 3), jnp.float32),
                "b": jnp.asarray(rs.randn(7), jnp.float32)}
        clipped = tree_clip_by_norm(tree, 1.5)
        sq = sum(float(jnp.sum(jnp.square(l))) for l in jax.tree.leaves(tree))
        factor = clip_scale(jnp.float32(sq), 1.5)
        want = jax.tree.map(lambda l: l * factor, tree)
        _assert_trees_bitwise(clipped, want)

    def test_flat_clip_matches_norm_diff_clip_semantics(self):
        """g + clip_row(local − g) == norm_diff_clip(local, g) to float
        tolerance (the reductions differ in order, the factor is
        shared)."""
        rs = np.random.RandomState(2)
        g = {"w": jnp.asarray(rs.randn(5, 4), jnp.float32)}
        l = jax.tree.map(lambda x: x + 3.0, g)
        want = norm_diff_clip(l, g, 1.0)
        d = flatten_vars_row(l) - flatten_vars_row(g)
        got_row = flatten_vars_row(g) + np.asarray(clip_row(d, 1.0))
        np.testing.assert_allclose(got_row, flatten_vars_row(want),
                                   rtol=1e-5, atol=1e-6)
        # and the re-applied update's norm respects the bound
        diff = jax.tree.map(lambda a, b: a - b, want, g)
        assert float(tree_l2_norm(diff)) == pytest.approx(1.0, rel=1e-4)


# ---------------------------------------------------------------------------
# core/robust.py flat-path helpers under adversarial fixtures
# ---------------------------------------------------------------------------

class TestRobustFlatHelpers:
    def _cluster_with_outlier(self, k=8, p=6, scale=0.01, seed=3):
        rs = np.random.RandomState(seed)
        flat = rs.randn(k, p).astype(np.float32) * scale
        flat[k - 1] = 50.0                      # the byzantine row
        return jnp.asarray(flat)

    def test_krum_selects_from_the_honest_cluster(self):
        flat = self._cluster_with_outlier()
        sel = int(krum_select_flat(flat, n_byzantine=1))
        assert sel != 7
        # analytic check on a 1-D construction: points 0,1,2,100 with
        # f=1 ⇒ k = n-f-2 = 1 nearest neighbor; scores are 1,1,1,98² —
        # krum must pick one of the first three, and the score math is
        # exactly the nearest-neighbor distances
        line = jnp.asarray([[0.0], [1.0], [2.0], [100.0]], jnp.float32)
        scores = np.asarray(krum_scores_flat(line, 1))
        np.testing.assert_allclose(scores[:3], [1.0, 1.0, 1.0], atol=1e-4)
        assert scores[3] == pytest.approx(98.0 ** 2, rel=1e-5)
        assert int(krum_select_flat(line, 1)) in (0, 1, 2)

    def test_multi_krum_excludes_byzantine_rows(self):
        flat = self._cluster_with_outlier()
        sel = set(int(i) for i in multi_krum_select_flat(flat, 1, m=5))
        assert 7 not in sel and len(sel) == 5

    def test_trimmed_mean_and_median_flat_analytic(self):
        # columns are permutations of 1..5: median 3, trim-1 mean 3
        base = np.asarray([[1, 5], [2, 4], [3, 3], [4, 2], [5, 1]],
                          np.float32)
        tm = np.asarray(trimmed_mean(jnp.asarray(base), 1))
        np.testing.assert_allclose(tm, [3.0, 3.0], rtol=1e-6)
        med = np.asarray(coordinate_median(jnp.asarray(base)))
        np.testing.assert_allclose(med, [3.0, 3.0], rtol=1e-6)

    def test_nan_poisoned_row_cannot_poison_krum(self):
        """A NaN/Inf row must score +inf (never selected) and drop out
        of every honest row's neighbor sums — without the guard, NaN
        distances propagate through sort/argmin and the selection is
        garbage for everyone."""
        flat = np.asarray(self._cluster_with_outlier())
        clean_scores = np.asarray(krum_scores_flat(jnp.asarray(flat), 1))
        poisoned = flat.copy()
        poisoned[7] = np.nan
        scores = np.asarray(krum_scores_flat(jnp.asarray(poisoned), 1))
        assert np.isinf(scores[7])
        # honest rows' scores are finite and the selection stays in the
        # cluster (the NaN row's distances became +inf, outside every
        # k-nearest window — n=8, f=1 ⇒ k=5 of the 6 finite neighbors)
        assert np.isfinite(scores[:7]).all()
        assert int(krum_select_flat(jnp.asarray(poisoned), 1)) != 7
        sel = set(int(i) for i in
                  multi_krum_select_flat(jnp.asarray(poisoned), 1, m=4))
        assert 7 not in sel
        # and an inf row behaves the same
        poisoned[7] = np.inf
        assert int(krum_select_flat(jnp.asarray(poisoned), 1)) != 7
        del clean_scores  # documentational: guard is identity on finite

    def test_trimmed_mean_drops_nan_rows_with_enough_trim(self):
        """jnp.sort places NaN last, so trim_k >= #poisoned rows trims
        them per coordinate — the order-stat defense's own NaN story
        (the admission canary is the primary guard upstream)."""
        base = np.ones((5, 3), np.float32)
        base[4] = np.nan
        tm = np.asarray(trimmed_mean(jnp.asarray(base), 1))
        np.testing.assert_allclose(tm, [1.0, 1.0, 1.0], rtol=1e-6)


# ---------------------------------------------------------------------------
# seeded adversary determinism (the comm/chaos.py contract)
# ---------------------------------------------------------------------------

class TestAdversaryDeterminism:
    def test_same_seed_same_byzantine_set_and_streams(self):
        cfg = AttackConfig(mode="gaussian", frac=0.25, noise_std=2.0,
                           seed=11)
        a, b = AdversarySim(cfg, 16), AdversarySim(cfg, 16)
        assert a.byzantine == b.byzantine and len(a.byzantine) == 4
        rs = np.random.RandomState(0)
        row = rs.randn(20).astype(np.float32)
        g = np.zeros(20, np.float32)
        for cid in sorted(a.byzantine):
            np.testing.assert_array_equal(a.corrupt_row(cid, row, g, 3),
                                          b.corrupt_row(cid, row, g, 3))
        assert a.trace() == b.trace()

    def test_two_seeds_differ(self):
        c1 = AttackConfig(mode="gaussian", frac=0.25, seed=1)
        c2 = AttackConfig(mode="gaussian", frac=0.25, seed=2)
        a, b = AdversarySim(c1, 32), AdversarySim(c2, 32)
        rs = np.random.RandomState(0)
        row = rs.randn(16).astype(np.float32)
        g = np.zeros(16, np.float32)
        differ = a.byzantine != b.byzantine
        common = a.byzantine & b.byzantine
        for cid in common:
            if not np.array_equal(a.corrupt_row(cid, row, g, 0),
                                  b.corrupt_row(cid, row, g, 0)):
                differ = True
        assert differ

    def test_honest_clients_pass_through_untouched(self):
        cfg = AttackConfig(mode="boost", frac=0.25, boost=10.0, seed=0)
        a = AdversarySim(cfg, 8)
        honest = next(c for c in range(8) if c not in a.byzantine)
        row = np.ones(5, np.float32)
        out = a.corrupt_row(honest, row, np.zeros(5, np.float32), 0)
        np.testing.assert_array_equal(out, row)

    def test_collusion_sends_identical_rows(self):
        cfg = AttackConfig(mode="gaussian", frac=0.5, collude=True,
                           boost=5.0, noise_std=2.0, seed=4)
        a = AdversarySim(cfg, 8)
        b1, b2 = sorted(a.byzantine)[:2]
        rs = np.random.RandomState(1)
        g = np.zeros(12, np.float32)
        r1 = a.corrupt_row(b1, rs.randn(12).astype(np.float32), g, 5)
        r2 = a.corrupt_row(b2, rs.randn(12).astype(np.float32), g, 5)
        np.testing.assert_array_equal(r1, r2)   # different inputs, one row
        # a different version crafts a different shared row
        r3 = a.corrupt_row(b1, rs.randn(12).astype(np.float32), g, 6)
        assert not np.array_equal(r1, r3)

    def test_stale_attack_adds_latency_for_byzantine_only(self):
        cfg = AttackConfig(mode="boost", frac=0.5, stale=True,
                           stale_lag=7.5, seed=0)
        a = AdversarySim(cfg, 8)
        byz = sorted(a.byzantine)[0]
        honest = next(c for c in range(8) if c not in a.byzantine)
        assert a.stale_extra_latency(byz) == 7.5
        assert a.stale_extra_latency(honest) == 0.0

    def test_attack_config_validation(self):
        with pytest.raises(ValueError, match="unknown attack mode"):
            AttackConfig(mode="meteor")
        with pytest.raises(ValueError, match="frac"):
            AttackConfig(mode="boost", frac=1.5)


# ---------------------------------------------------------------------------
# the admission pipeline (canary -> clip -> staleness-aware screen)
# ---------------------------------------------------------------------------

class TestAdmission:
    P = 48

    def _warmed(self, cfg, rs, n=12):
        adm = UpdateAdmission(cfg, self.P)
        g = jnp.zeros((self.P,), jnp.float32)
        adm.note_global(0, g)
        base = rs.randn(self.P).astype(np.float32) * 0.1
        for i in range(n):
            ok, why, _ = adm.screen(
                base + rs.randn(self.P).astype(np.float32) * 0.02,
                sender=i, version=0)
            assert ok, (i, why)
        return adm, g, base

    def test_finite_canary_quarantines_nan_and_inf(self):
        rs = np.random.RandomState(0)
        adm, g, base = self._warmed(DefenseConfig(), rs)  # canary only
        for bad_val in (np.nan, np.inf, -np.inf):
            bad = base.copy()
            bad[5] = bad_val
            ok, why, row = adm.screen(bad, sender=99, version=0)
            assert not ok and why == "nonfinite" and row is None
        assert adm.report()["quarantined"]["nonfinite"] == 3

    def test_no_clip_passthrough_is_bitwise(self):
        """Canary-only admission must hand back the INPUT row values
        untouched — the degenerate-config pin depends on it (g + 1·Δ
        would not be bitwise row)."""
        rs = np.random.RandomState(1)
        adm = UpdateAdmission(DefenseConfig(), self.P)
        adm.note_global(0, jnp.zeros((self.P,), jnp.float32))
        row = rs.randn(self.P).astype(np.float32)
        ok, _why, out = adm.screen(row, sender=0, version=0)
        assert ok
        np.testing.assert_array_equal(np.asarray(out), row)

    def test_clip_bounds_the_delta_via_the_shared_definition(self):
        rs = np.random.RandomState(2)
        adm = UpdateAdmission(DefenseConfig(norm_bound=1.0), self.P)
        g = jnp.asarray(rs.randn(self.P), jnp.float32)
        adm.note_global(0, g)
        row = np.asarray(g) + rs.randn(self.P).astype(np.float32) * 5.0
        ok, _why, out = adm.screen(row, sender=0, version=0)
        assert ok
        d = np.asarray(out) - np.asarray(g)
        assert np.linalg.norm(d) == pytest.approx(1.0, rel=1e-4)
        # the shared flat clip, modulo fusion: the admission compiles
        # g + cf·d as ONE program while clip_row+add is two — XLA's
        # fusion rounds ulp-differently, so the cross-check is tight
        # float equality; the factor itself is bitwise-shared (it IS
        # clip_scale, TestOneClipDefinition)
        want = np.asarray(g) + np.asarray(
            clip_row(jnp.asarray(row) - g, 1.0))
        np.testing.assert_allclose(np.asarray(out), want,
                                   rtol=1e-6, atol=1e-7)

    def test_z_screen_catches_boost_and_stats_ignore_bound_breakers(self):
        rs = np.random.RandomState(3)
        cfg = DefenseConfig(norm_bound=2.0, screen=True, z_max=6.0,
                            screen_warmup=8)
        adm, g, base = self._warmed(cfg, rs)
        ok, why, _ = adm.screen(base * 300.0, sender=50, version=0)
        assert not ok and why == "norm_z"
        # a rejected (and bound-breaking) row must not have taught the
        # reference: the next honest update still passes
        ok, why, _ = adm.screen(
            base + rs.randn(self.P).astype(np.float32) * 0.02,
            sender=51, version=0)
        assert ok, why

    def test_cosine_screen_catches_signflip(self):
        rs = np.random.RandomState(4)
        cfg = DefenseConfig(norm_bound=5.0, screen=True, z_max=8.0,
                            cos_min=-0.5, screen_warmup=8)
        adm, g, base = self._warmed(cfg, rs)
        ok, why, _ = adm.screen(-base, sender=60, version=0)
        assert not ok and why == "cosine"

    def test_screen_is_staleness_aware(self):
        """A stale honest update (trained from an OLD global) must not
        be quarantined — its delta is computed against the global it
        trained from, not the drifted current one.  This is the ROADMAP
        item-4 'stale adversarial updates' edge: without version-keyed
        globals the drift lands in the delta and honest stragglers read
        as anomalies."""
        rs = np.random.RandomState(5)
        cfg = DefenseConfig(norm_bound=2.0, screen=True, z_max=5.0,
                            screen_warmup=8)
        adm = UpdateAdmission(cfg, self.P)
        step = rs.randn(self.P).astype(np.float32) * 0.1
        g0 = jnp.zeros((self.P,), jnp.float32)
        adm.note_global(0, g0)
        # warm up at version 0
        for i in range(10):
            ok, why, _ = adm.screen(
                step + rs.randn(self.P).astype(np.float32) * 0.02,
                sender=i, version=0)
            assert ok, why
        # the server commits 5 times; the model drifts far from g0
        drift = np.zeros(self.P, np.float32)
        for v in range(1, 6):
            drift += 10.0 * np.abs(step)
            adm.note_global(v, jnp.asarray(drift))
        # a STALE honest update from version 0: raw row is near g0 —
        # against the current global its delta norm would be ~5x the
        # reference and z would fire; against g0 it is honest-sized
        stale_row = step + rs.randn(self.P).astype(np.float32) * 0.02
        ok, why, _ = adm.screen(stale_row, sender=70, version=0)
        assert ok, why
        # while a boosted update from the CURRENT version is caught
        fresh_boost = drift + 100.0 * step
        ok, why, _ = adm.screen(fresh_boost, sender=71, version=5)
        assert not ok and why == "norm_z"

    def test_admission_state_roundtrip(self):
        rs = np.random.RandomState(6)
        cfg = DefenseConfig(norm_bound=2.0, screen=True, screen_warmup=4)
        adm, g, base = self._warmed(cfg, rs)
        state = adm.state()
        fresh = UpdateAdmission(cfg, self.P)
        fresh.load_state(state)
        fresh.note_global(0, g)
        assert fresh.accepted == adm.accepted
        np.testing.assert_array_equal(np.asarray(fresh._ref),
                                      np.asarray(adm._ref))
        with pytest.raises(ValueError, match="shape mismatch"):
            UpdateAdmission(cfg, self.P + 1).load_state(state)

    def test_defense_config_validation(self):
        with pytest.raises(ValueError, match="dp_clip"):
            DefenseConfig(dp_noise=1.0)
        with pytest.raises(ValueError, match="unknown bucket combine"):
            DefenseConfig(combine="krum")

    def test_quarantine_metrics_and_flight_instants(self, tmp_path):
        """Obs satellite: async_updates_quarantined_total{reason} and
        defense_screen_seconds move, and the quarantine reason lands in
        the tracer's events (what a flight dump carries)."""
        from fedml_tpu import obs
        obs.reset()
        obs.configure(str(tmp_path), install_signal=False,
                      export_at_exit=False)
        try:
            rs = np.random.RandomState(7)
            adm = UpdateAdmission(DefenseConfig(), self.P)
            adm.note_global(0, jnp.zeros((self.P,), jnp.float32))
            before = obs.counter("async_updates_quarantined_total",
                                 reason="nonfinite").value
            h = obs.histogram("defense_screen_seconds",
                              buckets=obs.metrics.DECODE_SECONDS_BUCKETS)
            h0 = h.count
            bad = rs.randn(self.P).astype(np.float32)
            bad[0] = np.nan
            ok, why, _ = adm.screen(bad, sender=3, version=0)
            assert not ok
            assert obs.counter("async_updates_quarantined_total",
                               reason="nonfinite").value == before + 1
            assert h.count > h0
            evs = [e for e in obs.tracer().events()
                   if e.get("name") == "defense.quarantine"]
            assert evs and evs[-1]["args"]["reason"] == "nonfinite"
        finally:
            obs.reset()


# ---------------------------------------------------------------------------
# bucketed robust streaming aggregation
# ---------------------------------------------------------------------------

def _rand_rows(seed, k, p):
    rs = np.random.RandomState(seed)
    return (rs.randn(k, p).astype(np.float32),
            rs.randint(1, 40, k).astype(np.float32),
            rs.randint(0, 5, k).astype(np.float32))


class TestBucketedCommit:
    def test_degenerate_b1_bitwise_matches_stream_commit(self):
        """THE tentpole pin: B=1 + trim 0 + no screening reproduces the
        PR-6 streaming commit BITWISE (same folds, same division, same
        mix) — full and partial buffers, constant and polynomial
        weights."""
        template = {"params": {"a": jnp.zeros((5, 7), jnp.float32),
                               "b": jnp.zeros((2,), jnp.float32)}}
        P = flat_dim(template)
        rs = np.random.RandomState(99)
        variables = jax.tree.map(
            lambda l: jnp.asarray(rs.randn(*l.shape), jnp.float32),
            template)
        for mode, n_real in (("constant", 6), ("constant", 3),
                             ("polynomial", 6), ("polynomial", 3)):
            rows, w, s = _rand_rows(11 + n_real, n_real, P)
            b1 = AsyncBuffer(6, P, streaming=True, staleness_mode=mode,
                             staleness_a=0.5)
            b2 = AsyncBuffer(6, P, streaming=True, staleness_mode=mode,
                             staleness_a=0.5)
            for i in range(n_real):
                b1.add(rows[i], float(w[i]), float(s[i]))
                b2.add(rows[i], float(w[i]), float(s[i]))
            acc, wsum, *_ = b1.take_stream()
            accs, wsums, *_ = b2.take_stream_buckets()
            sc = make_stream_commit_fn(variables, donate=False)
            bc = make_bucket_commit_fn(variables, combine="trimmed_mean",
                                       trim_k=0, donate=False)
            v1, _ = sc(variables, acc, wsum, jnp.float32(0.7))
            v2, st = bc(variables, accs, wsums, jnp.float32(0.7))
            _assert_trees_bitwise(v1, v2)
            assert float(st["n_buckets"]) == 1.0

    def test_seeded_bucket_assignment_is_deterministic(self):
        b1 = AsyncBuffer(8, 4, streaming=True, buckets=4, bucket_seed=5)
        b2 = AsyncBuffer(8, 4, streaming=True, buckets=4, bucket_seed=5)
        b3 = AsyncBuffer(8, 4, streaming=True, buckets=4, bucket_seed=6)
        seq1 = [b1._next_bucket() for _ in range(16)]
        seq2 = [b2._next_bucket() for _ in range(16)]
        seq3 = [b3._next_bucket() for _ in range(16)]
        assert seq1 == seq2
        assert seq1 != seq3
        # every window of B inserts covers every bucket exactly once
        for lo in range(0, 16, 4):
            assert sorted(seq1[lo:lo + 4]) == [0, 1, 2, 3]

    def test_trimmed_buckets_exclude_a_boosted_row(self):
        template = {"params": {"w": jnp.zeros((37,), jnp.float32)}}
        P = 37
        rs = np.random.RandomState(3)
        rows = rs.randn(8, P).astype(np.float32) * 0.1
        rows[5] = 1000.0                        # boosted model replacement
        buf = AsyncBuffer(8, P, streaming=True, buckets=4, bucket_seed=3)
        for i in range(8):
            buf.add(rows[i], 1.0, 0.0)
        accs, wsums, *_ = buf.take_stream_buckets()
        commit = make_bucket_commit_fn(template, combine="trimmed_mean",
                                       trim_k=1, donate=False)
        zero = jax.tree.map(jnp.zeros_like, template)
        v, _ = commit(zero, accs, wsums, jnp.float32(1.0))
        out = np.asarray(jax.tree.leaves(v)[0])
        assert np.abs(out).max() < 1.0          # the 1000x row is gone
        med = make_bucket_commit_fn(template, combine="median",
                                    donate=False)
        v2, _ = med(zero, accs, wsums, jnp.float32(1.0))
        assert np.abs(np.asarray(jax.tree.leaves(v2)[0])).max() < 1.0

    def test_partial_commit_masks_empty_buckets(self):
        """A deadline commit with fewer arrivals than buckets: empty
        buckets must not poison the combine (masked to +inf outside
        every rank window), and the result equals the explicit mean of
        the populated buckets."""
        template = {"params": {"w": jnp.zeros((9,), jnp.float32)}}
        buf = AsyncBuffer(8, 9, streaming=True, buckets=4, bucket_seed=0)
        rows = np.arange(18, dtype=np.float32).reshape(2, 9)
        buf.add(rows[0], 1.0, 0.0)
        buf.add(rows[1], 1.0, 0.0)
        accs, wsums, *_ = buf.take_stream_buckets()
        assert int(np.sum(np.asarray(wsums) > 0)) == 2
        commit = make_bucket_commit_fn(template, combine="trimmed_mean",
                                       trim_k=1, donate=False)
        zero = jax.tree.map(jnp.zeros_like, template)
        v, st = commit(zero, accs, wsums, jnp.float32(1.0))
        out = np.asarray(jax.tree.leaves(v)[0])
        assert np.isfinite(out).all()
        assert float(st["n_buckets"]) == 2.0
        # m=2 ⇒ k_eff = min(1, 0) = 0 ⇒ plain mean of the two rows
        np.testing.assert_allclose(out, rows.mean(0), rtol=1e-6)

    def test_bucketed_checkpoint_roundtrip_and_validation(self):
        P = 13
        rows, w, s = _rand_rows(21, 5, P)
        buf = AsyncBuffer(8, P, streaming=True, buckets=4, bucket_seed=1)
        for i in range(5):
            buf.add(rows[i], float(w[i]), float(s[i]))
        snap = buf.state()
        assert snap["acc"].shape == (4, P)
        assert int(snap["bucket_draws"]) == 5
        fresh = AsyncBuffer(8, P, streaming=True, buckets=4, bucket_seed=1)
        fresh.load_state(snap)
        a0, w0, *_ = buf.take_stream_buckets()
        a1, w1, *_ = fresh.take_stream_buckets()
        np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))
        np.testing.assert_array_equal(np.asarray(w0), np.asarray(w1))
        # the assignment stream RESUMES mid-schedule: the restored
        # buffer's future draws continue exactly where the crashed
        # run's stream stopped (not a fresh permutation window)
        assert ([fresh._next_bucket() for _ in range(6)]
                == [buf._next_bucket() for _ in range(6)])
        # bucket-count change refuses
        with pytest.raises(ValueError, match="buckets or model changed"):
            AsyncBuffer(8, P, streaming=True, buckets=2).load_state(snap)
        # a drain-mode checkpoint REPLAYS through the bucketed fold
        dbuf = AsyncBuffer(8, P)
        for i in range(5):
            dbuf.add(rows[i], float(w[i]), float(s[i]))
        sbuf = AsyncBuffer(8, P, streaming=True, buckets=4, bucket_seed=1)
        sbuf.load_state(dbuf.state())
        a2, w2, *_ = sbuf.take_stream_buckets()
        np.testing.assert_array_equal(np.asarray(a0), np.asarray(a2))

    def test_bucket_constructor_validation(self):
        with pytest.raises(ValueError, match="streaming"):
            AsyncBuffer(4, 8, buckets=2)
        with pytest.raises(ValueError, match="cannot exceed"):
            AsyncBuffer(2, 8, streaming=True, buckets=4)

    def test_dp_commit_deterministic_and_clips_into_noise_scale(self):
        """DP-FedAvg: same rng key ⇒ same noised commit; different keys
        differ; dp off is the noise-free program (the degenerate pin's
        arm)."""
        template = {"params": {"w": jnp.zeros((25,), jnp.float32)}}
        rows, w, s = _rand_rows(31, 4, 25)
        buf = AsyncBuffer(4, 25, streaming=True, buckets=2, bucket_seed=0)
        for i in range(4):
            buf.add(rows[i], float(w[i]), 0.0)
        accs, wsums, *_ = buf.take_stream_buckets()
        zero = jax.tree.map(jnp.zeros_like, template)
        dp = make_bucket_commit_fn(template, combine="mean",
                                   dp_noise=1.0, dp_clip=0.5, donate=False)
        k1, k2 = jax.random.PRNGKey(0), jax.random.PRNGKey(1)
        n = jnp.float32(4.0)
        v1, _ = dp(zero, accs, wsums, jnp.float32(1.0), n, k1)
        v1b, _ = dp(zero, accs, wsums, jnp.float32(1.0), n, k1)
        v2, _ = dp(zero, accs, wsums, jnp.float32(1.0), n, k2)
        _assert_trees_bitwise(v1, v1b)
        assert not np.array_equal(np.asarray(jax.tree.leaves(v1)[0]),
                                  np.asarray(jax.tree.leaves(v2)[0]))
        # sigma divides by the CONTRIBUTOR count (sensitivity S/n), not
        # the bucket count: more contributors => strictly less noise
        devs = []
        for nc in (1.0, 64.0):
            vn, _ = dp(zero, accs, wsums, jnp.float32(1.0),
                       jnp.float32(nc), k1)
            base, _ = make_bucket_commit_fn(
                template, combine="mean", donate=False)(
                    zero, accs, wsums, jnp.float32(1.0))
            devs.append(float(np.abs(
                np.asarray(jax.tree.leaves(vn)[0])
                - np.asarray(jax.tree.leaves(base)[0])).mean()))
        assert devs[1] < devs[0] / 8.0, devs
        plain = make_bucket_commit_fn(template, combine="mean",
                                      donate=False)
        v0, _ = plain(zero, accs, wsums, jnp.float32(1.0))
        assert not np.array_equal(np.asarray(jax.tree.leaves(v0)[0]),
                                  np.asarray(jax.tree.leaves(v1)[0]))


# ---------------------------------------------------------------------------
# the manager-level degenerate pin + quarantine at the ONE insert path
# ---------------------------------------------------------------------------

class TestManagerIngest:
    def _manager(self, template, defense):
        from fedml_tpu.async_ import AsyncServerManager
        from fedml_tpu.comm.inproc import InProcRouter
        return AsyncServerManager(
            template, total_commits=2, buffer_k=3, rank=0, size=1,
            backend="INPROC", streaming=True, redispatch=False,
            defense=defense, router=InProcRouter())

    def test_defended_degenerate_ingest_is_bitwise(self):
        """Drive the ONE insert path (_ingest_row) with an identical
        deterministic arrival sequence through an undefended and a
        degenerate-defended (B=1, canary only) server: the committed
        variables must be bit-identical — threads are not involved, so
        this pins the manager wiring, not just the commit program."""
        rs = np.random.RandomState(8)
        template = {"params": {"w": rs.randn(6, 5).astype(np.float32),
                               "b": rs.randn(3).astype(np.float32)}}
        P = flat_dim(template)
        rows = rs.randn(6, P).astype(np.float32)
        servers = [self._manager(template, None),
                   self._manager(template, DefenseConfig())]
        try:
            for srv in servers:
                for i in range(6):
                    srv._ingest_row(sender=1, row=rows[i].copy(),
                                    weight=float(10 + i), dispatched=0)
            assert servers[0].version == servers[1].version == 2
            _assert_trees_bitwise(servers[0].variables,
                                  servers[1].variables)
        finally:
            for srv in servers:
                srv.finish()

    def test_quarantined_row_never_reaches_the_accumulator(self):
        rs = np.random.RandomState(9)
        template = {"params": {"w": rs.randn(4, 4).astype(np.float32)}}
        P = flat_dim(template)
        srv = self._manager(template, DefenseConfig())
        try:
            bad = rs.randn(P).astype(np.float32)
            bad[0] = np.nan
            srv._ingest_row(sender=1, row=bad, weight=1.0, dispatched=0)
            assert srv.buffer.count == 0
            assert srv._admission.report()["quarantined_total"] == 1
            good = rs.randn(P).astype(np.float32)
            srv._ingest_row(sender=1, row=good, weight=1.0, dispatched=0)
            assert srv.buffer.count == 1
            assert all(np.isfinite(np.asarray(l)).all()
                       for l in jax.tree.leaves(srv.variables))
        finally:
            srv.finish()


# ---------------------------------------------------------------------------
# end-to-end: the virtual-time scheduler under attack
# ---------------------------------------------------------------------------

def _band_workload():
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.data.loaders import load_data
    from fedml_tpu.models import create_model
    from fedml_tpu.utils.config import FedConfig
    data = load_data("mnist", client_num_in_total=1000, batch_size=10,
                     synthetic_scale=0.2, seed=0)
    assert data.synthetic
    cfg = FedConfig(client_num_in_total=1000, client_num_per_round=16,
                    comm_round=16, epochs=1, batch_size=10, lr=0.03,
                    frequency_of_the_test=10_000)
    trainer = ClientTrainer(create_model("lr", output_dim=10), lr=cfg.lr)
    lc = LifecycleConfig(latency="lognormal", latency_scale=1.0,
                         latency_sigma=0.8, heterogeneity=0.5, seed=0)
    return trainer, data, cfg, lc


# the calibrated band arms' exact shapes (benchmarks/quality_bands.json
# records them in the calibration notes — keep in sync)
BAND_ATTACK = dict(mode="mixed", frac=0.2, boost=8.0, poison_frac=1.0,
                   seed=0)
# cosine stays OFF in the band config: under this workload's extreme
# non-iid partition (one class per client), honest update directions
# legitimately oppose the reference (measured cos < -0.5) — the mixed
# attack is caught by clip + z; the cosine stage is unit-tested against
# sign-flip on direction-consistent traffic (TestAdmission)
BAND_DEFENSE = dict(norm_bound=2.0, screen=True, z_max=8.0, cos_min=-1.0,
                    screen_warmup=10, buckets=4, combine="trimmed_mean",
                    trim_k=0)


def _band_run(attack=None, defense=None):
    trainer, data, cfg, lc = _band_workload()
    eng = AsyncFedAvgEngine(trainer, data, cfg, buffer_k=8, concurrency=16,
                            staleness="polynomial", staleness_a=0.5,
                            lifecycle_cfg=lc, attack=attack,
                            defense=defense)
    v = eng.run(rounds=16)
    return eng, float(eng.evaluate(v)["test_acc"])


def test_attacked_undefended_degrades_below_the_clean_band():
    """The attack arm's teeth: 20% byzantine boosted model-replacement
    + label-flip measurably degrades the undefended async run — it
    lands in its own (degraded) band AND below the clean band's floor."""
    eng, acc = _band_run(attack=AttackConfig(**BAND_ATTACK))
    _assert_band("async_mnist_lr_attacked_undefended_acc", acc)
    clean = _band("async_mnist_lr_acc")
    assert acc < clean["value"] - clean["tol"], (
        f"undefended attacked acc {acc:.4f} does not degrade below the "
        f"clean band floor {clean['value'] - clean['tol']:.4f} — the "
        f"attack arm lost its teeth")


def test_attacked_defended_stays_in_band_with_zero_false_positives():
    """The ISSUE-9 acceptance gate: the defended run under the same
    mixed attack stays within its calibrated band (which sits inside
    the clean band), quarantines only byzantine clients, and the
    undefended/defended contrast is the matrix's headline row."""
    eng, acc = _band_run(attack=AttackConfig(**BAND_ATTACK),
                         defense=DefenseConfig(**BAND_DEFENSE))
    _assert_band("async_mnist_lr_attacked_defended_acc", acc)
    attrib = eng.quarantine_attribution()
    assert attrib["honest"] == 0, attrib      # false-positive gate
    assert attrib["byzantine"] > 0, attrib    # the screen genuinely fired
    # the defended band must sit WITHIN the clean band (static check on
    # the committed artifacts — the recalibrate protocol keeps both)
    clean = _band("async_mnist_lr_acc")
    defended = _band("async_mnist_lr_attacked_defended_acc")
    assert (clean["value"] - clean["tol"]
            <= defended["value"] <= clean["value"] + clean["tol"] + 0.05), (
        "defended band drifted outside the clean band")


def test_clean_defended_quarantines_nothing():
    """False-positive gate, clean arm: the full defense config on an
    attack-free run must quarantine ZERO updates and stay within the
    clean band."""
    eng, acc = _band_run(defense=DefenseConfig(**BAND_DEFENSE))
    rep = eng.async_report()
    assert rep["quarantined_total"] == 0, rep
    _assert_band("async_mnist_lr_acc", acc)


def test_attacked_defended_run_is_seed_deterministic():
    """Two defended runs under the same attack seed produce identical
    traces (attack + quarantine events included) and variables."""
    cfg = _mnist_like_cfg(client_num_per_round=8, comm_round=5)
    trainer, data = _setup(cfg)
    lc = LifecycleConfig(latency="lognormal", latency_scale=1.0,
                         latency_sigma=0.5, seed=2)

    def once():
        eng = AsyncFedAvgEngine(
            trainer, data, cfg, buffer_k=4, concurrency=8,
            lifecycle_cfg=lc, donate=False,
            attack=AttackConfig(mode="boost", frac=0.25, boost=50.0,
                                seed=1),
            defense=DefenseConfig(norm_bound=2.0, screen=True, z_max=4.0,
                                  screen_warmup=4, buckets=4, trim_k=1))
        v = eng.run(rounds=5)
        return eng.trace, v

    t1, v1 = once()
    t2, v2 = once()
    assert t1 == t2
    _assert_trees_bitwise(v1, v2)
    assert "attack" in {t[0] for t in t1}


def test_defended_scheduler_checkpoint_roundtrips_defense_state(tmp_path):
    """Crash-resume satellite: a defended engine's async_state carries
    the bucket accumulators AND the admission running reference, and a
    fresh engine restores both."""
    cfg = _mnist_like_cfg(client_num_per_round=8, comm_round=4)
    trainer, data = _setup(cfg)

    def make():
        return AsyncFedAvgEngine(
            trainer, data, cfg, buffer_k=4, concurrency=8, donate=False,
            defense=DefenseConfig(norm_bound=5.0, screen=True,
                                  screen_warmup=4, buckets=2))

    from fedml_tpu.utils.checkpoint import FedCheckpointManager
    ck = FedCheckpointManager(str(tmp_path / "dck"))
    eng = make()
    eng.run(rounds=4, ckpt=ck, ckpt_every=2)
    saved = eng.async_state()
    assert "defense" in saved and saved["buffer"]["acc"].shape[0] == 2
    fresh = make()
    step, v, _ss, extra = ck.restore(
        fresh.init_variables(), (), extra_template=fresh.async_state())
    fresh.load_async_state(extra)
    assert fresh.version == step + 1
    assert fresh._admission.accepted == eng._admission.accepted
    np.testing.assert_array_equal(np.asarray(fresh._admission._ref),
                                  np.asarray(eng._admission._ref))
    out = fresh.run(variables=v, rounds=fresh.version + 2)
    assert np.isfinite(fresh.evaluate(out)["test_loss"])
    ck.close()


# ---------------------------------------------------------------------------
# messaging path: fast smoke tier-1, heavy grid nightly
# ---------------------------------------------------------------------------

def test_messaging_attacked_defended_smoke_inproc():
    """3-client INPROC smoke (tier-1): a boosted byzantine client under
    the full admission pipeline — the run completes its commits, the
    variables stay finite, and the deadline path carries the
    quarantine-starved windows."""
    cfg = _mnist_like_cfg(client_num_per_round=4, comm_round=3)
    trainer, data = _setup(cfg)
    v, server = run_async_messaging(
        trainer, data, cfg, buffer_k=2, total_commits=3, backend="INPROC",
        worker_num=3, deadline_s=5.0,
        attack=AttackConfig(mode="boost", frac=0.34, boost=100.0, seed=5),
        defense=DefenseConfig(norm_bound=2.0, screen=True, z_max=4.0,
                              screen_warmup=3, buckets=2),
        timeout_s=120)
    assert server.version == 3
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree.leaves(v))
    rep = server._admission.report()
    assert rep["accepted"] > 0


@pytest.mark.slow
def test_attack_defense_grid_over_tcp():
    """Nightly: the heavy attack x defense grid over a REAL transport —
    every model-level attack family against the full pipeline, plus the
    admission-overhead gate (>= 0.9x of the screen-off torture rate)."""
    from fedml_tpu.async_.torture import run_ingest_torture
    cfg = _mnist_like_cfg(client_num_per_round=4, comm_round=3)
    trainer, data = _setup(cfg)
    for i, mode in enumerate(("signflip", "boost", "gaussian")):
        v, server = run_async_messaging(
            trainer, data, cfg, buffer_k=2, total_commits=3, backend="TCP",
            worker_num=4, deadline_s=10.0, base_port=53650 + 10 * i,
            ip_config={r: "127.0.0.1" for r in range(5)},
            force_python_tcp=True,
            attack=AttackConfig(mode=mode, frac=0.25, boost=50.0,
                                noise_std=5.0, seed=i),
            defense=DefenseConfig(norm_bound=2.0, screen=True, z_max=5.0,
                                  screen_warmup=3, buckets=2),
            timeout_s=180)
        assert server.version == 3, mode
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(v)), mode
        server_rep = server._admission.report()
        assert server_rep["accepted"] > 0, mode
    # admission-overhead pair (honest traffic): zero false-positive
    # quarantines, and the fused screen keeps a floor fraction of the
    # screen-off ingest rate.  The floor is calibrated to THIS 2-core
    # box, where the serial fold is the bottleneck and the screen's
    # extra row+g passes show up fully (paired-median 0.73x at the
    # canonical 32-client point, per-call 2.05x fused vs 0.5x e2e for
    # the rejected unfused design — PERF.md "Adversarial robustness");
    # the ISSUE-9 >=0.9x target is the chip gate, priced by
    # profile_bench exp_ATTACK where the fold dispatches to the
    # accelerator and the screen rides its pass.
    off = run_ingest_torture(n_clients=16, backend="TCP", buffer_k=8,
                             commits=12, warmup_commits=2, ingest_pool=4,
                             base_port=53700)
    on = run_ingest_torture(n_clients=16, backend="TCP", buffer_k=8,
                            commits=12, warmup_commits=2, ingest_pool=4,
                            base_port=53710,
                            defense=DefenseConfig(screen=True, z_max=8.0,
                                                  screen_warmup=8))
    assert on["admission"]["quarantined_total"] == 0
    ratio = (on["committed_updates_per_sec"]
             / max(off["committed_updates_per_sec"], 1e-9))
    assert ratio >= 0.5, (
        f"admission screen costs too much: {ratio:.2f}x of the "
        f"screen-off ingest rate (2-core floor 0.5x; the single-pair "
        f"measurement varies ~0.55-0.9 on this box — a failure here "
        f"means a structural regression, e.g. the screen lost its "
        f"fusion with the fold)")
