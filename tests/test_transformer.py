"""TransformerLM (beyond-reference model family, models/transformer.py):
shape contract, causality, learning on the synthetic Markov task, and
mesh-engine compatibility (the model must run under shard_map/vmap like
the LSTMs it upgrades)."""
import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.models import create_model
from fedml_tpu.models.transformer import TransformerLM


def test_forward_shapes_and_factory():
    m = create_model("transformer", 90, d_model=32, n_heads=2, n_layers=1,
                     d_ff=64)
    x = jnp.zeros((3, 12), jnp.int32)
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(v, x, train=False)
    assert out.shape == (3, 12, 90)
    last = create_model("transformer", 90, d_model=32, n_heads=2,
                        n_layers=1, d_ff=64, last_only=True)
    vl = last.init(jax.random.PRNGKey(0), x, train=False)
    assert last.apply(vl, x, train=False).shape == (3, 90)


def test_causal_mask_blocks_future_tokens():
    """Changing token t must not change logits at positions < t."""
    m = TransformerLM(vocab_size=50, d_model=32, n_heads=2, n_layers=2,
                      d_ff=64)
    rs = np.random.RandomState(0)
    x = rs.randint(0, 50, (2, 10)).astype(np.int32)
    v = m.init(jax.random.PRNGKey(0), jnp.asarray(x), train=False)
    a = m.apply(v, jnp.asarray(x), train=False)
    x2 = x.copy()
    x2[:, 7] = (x2[:, 7] + 1) % 50
    b = m.apply(v, jnp.asarray(x2), train=False)
    np.testing.assert_allclose(np.asarray(a[:, :7]), np.asarray(b[:, :7]),
                               atol=1e-5)
    assert float(np.abs(np.asarray(a[:, 7:]) -
                        np.asarray(b[:, 7:])).max()) > 1e-4


def test_learns_markov_task_under_mesh_engine():
    """Federated training of the transformer through the mesh engine on
    the synthetic Markov sequences: loss must fall well below the uniform
    floor ln(vocab) — the same data contract the LSTM models train on."""
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.data.federated import (FederatedData, build_client_shards,
                                          build_eval_shard)
    from fedml_tpu.data.synthetic import synthetic_sequences
    from fedml_tpu.parallel import MeshFedAvgEngine
    from fedml_tpu.parallel.mesh import make_mesh
    from fedml_tpu.utils.config import FedConfig

    vocab, seq, C, spc, bs = 23, 12, 8, 32, 8
    x, y = synthetic_sequences(C * spc, seq, vocab, seed=1)
    idx = {i: np.arange(i * spc, (i + 1) * spc) for i in range(C)}
    data = FederatedData(
        train_data_num=len(y), test_data_num=len(y),
        train_global=build_eval_shard(x, y, 64),
        test_global=build_eval_shard(x, y, 64),
        client_shards=build_client_shards(x, y, idx, bs),
        client_num_samples=np.full(C, spc, np.float32),
        test_client_shards=None, class_num=vocab)
    cfg = FedConfig(client_num_in_total=C, client_num_per_round=C,
                    comm_round=6, epochs=1, batch_size=bs, lr=0.003,
                    frequency_of_the_test=100)
    model = create_model("transformer", vocab, d_model=32, n_heads=2,
                        n_layers=1, d_ff=64)
    trainer = ClientTrainer(model, lr=cfg.lr, optimizer="adam",
                            has_time_axis=True)
    eng = MeshFedAvgEngine(trainer, data, cfg, mesh=make_mesh(8),
                           donate=False)
    v = eng.run()
    m = eng.evaluate(v)
    assert m["test_loss"] < np.log(vocab) - 0.3, m
    assert m["test_acc"] > 1.5 / vocab, m

def test_cli_transformer_nwp(tmp_path):
    """--model transformer on stackoverflow_nwp (per-position loss via the
    dataset-keyed has_time wiring) trains through the CLI."""
    import json
    import os

    from fedml_tpu.cli import main
    rc = main(["--algorithm", "fedavg", "--dataset", "stackoverflow_nwp",
               "--model", "transformer", "--client_num_in_total", "12",
               "--client_num_per_round", "4", "--comm_round", "2",
               "--batch_size", "8", "--lr", "0.003",
               "--client_optimizer", "adam", "--synthetic_scale", "0.001",
               "--run_dir", str(tmp_path), "--run_name", "t"])
    assert rc == 0
    s = json.load(open(os.path.join(tmp_path, "fedml_tpu", "t",
                                    "summary.json")))
    assert np.isfinite(s["test_loss"])
