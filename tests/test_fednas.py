"""FedNAS / DARTS: search network, architect, genotype derivation, and the
two-phase search→train flow (reference CI-script-fednas.sh).

Round tests use a micro search space (steps=2, 1 cell) — the full supernet
compiles in minutes on the CPU test platform; the micro space exercises the
identical code paths (MixedOp over all 8 primitives, bilevel steps,
dual-tree aggregation) at test-friendly compile cost.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

# the 2nd-order/GDAS/mesh searches are the suite's heaviest XLA:CPU
# programs (30-170 s each): marked slow so the serial tier-1 selection
# (-m 'not slow') fits its 870 s budget; `pytest tests/test_fednas.py`
# runs them all

from fedml_tpu.algorithms.fednas import FedNASSearchEngine, make_train_engine
from fedml_tpu.data.federated import (FederatedData, build_client_shards,
                                      build_eval_shard)
from fedml_tpu.models.darts import (DARTS_V2, DartsNetwork,
                                    DartsSearchNetwork, PRIMITIVES,
                                    derive_genotype, init_alphas, num_edges)
from fedml_tpu.utils.config import FedConfig


def tiny_data(n_clients=2, bs=2, n_batches=2, hw=8, classes=10):
    rs = np.random.RandomState(0)
    n = n_clients * bs * n_batches
    x = rs.rand(n, hw, hw, 3).astype(np.float32)
    y = rs.randint(0, classes, n).astype(np.int64)
    idx = {i: np.arange(i * bs * n_batches, (i + 1) * bs * n_batches)
           for i in range(n_clients)}
    ev = build_eval_shard(x[:bs], y[:bs], bs)
    return FederatedData(
        train_data_num=n, test_data_num=bs, train_global=ev, test_global=ev,
        client_shards=build_client_shards(x, y, idx, bs),
        client_num_samples=np.full(n_clients, bs * n_batches, np.float32),
        test_client_shards=None, class_num=classes, synthetic=True)


def micro_engine(data, unrolled=False, **kw):
    cfg = FedConfig(client_num_in_total=2, client_num_per_round=2,
                    comm_round=1, epochs=1, batch_size=2, lr=0.05,
                    frequency_of_the_test=1)
    return FedNASSearchEngine(data, cfg, C=4, layers=1, steps=2,
                              multiplier=2, unrolled=unrolled, donate=False,
                              **kw)


def test_search_network_forward():
    model = DartsSearchNetwork(num_classes=10, C=4, layers=2, steps=2,
                               multiplier=2)
    alphas = init_alphas(jax.random.PRNGKey(0), steps=2)
    x = jnp.zeros((2, 8, 8, 3))
    variables = model.init(jax.random.PRNGKey(1), x, alphas)
    logits = model.apply(variables, x, alphas)
    assert logits.shape == (2, 10)
    assert jnp.all(jnp.isfinite(logits))


def test_alphas_shape():
    alphas = init_alphas(jax.random.PRNGKey(0))
    assert alphas["normal"].shape == (num_edges(4), len(PRIMITIVES))
    assert alphas["reduce"].shape == (14, 8)


def test_genotype_derivation():
    g = derive_genotype(init_alphas(jax.random.PRNGKey(3)))
    # 4 nodes x 2 kept edges, 'none' never selected, edge ids in range
    for gene in (g.normal, g.reduce):
        assert len(gene) == 8
        for node in range(4):
            for op, j in gene[2 * node:2 * node + 2]:
                assert op in PRIMITIVES and op != "none"
                assert 0 <= j < node + 2
    assert list(g.normal_concat) == [2, 3, 4, 5]


@pytest.mark.slow
def test_unrolled_arch_grad():
    """The exact 2nd-order architect: grad through the unrolled w-step."""
    data = tiny_data()
    eng = micro_engine(data, unrolled=True)
    params, alphas = eng.init_state()
    batch = jax.tree.map(lambda a: jnp.asarray(a[0, 0]),
                         data.client_shards)   # one [bs, ...] batch
    g2 = jax.jit(eng._arch_grad)(params, alphas, batch, batch)
    assert g2["normal"].shape == alphas["normal"].shape
    assert all(bool(jnp.all(jnp.isfinite(v))) for v in jax.tree.leaves(g2))
    # layers=1 → the single cell is a reduction cell, so only the reduce
    # alphas receive signal; the unrolled (2nd-order) gradient must differ
    # from the first-order one there
    assert float(np.max(np.abs(np.asarray(g2["reduce"])))) > 0.0
    eng1 = micro_engine(data, unrolled=False)
    g1 = jax.jit(eng1._arch_grad)(params, alphas, batch, batch)
    assert float(np.max(np.abs(np.asarray(g1["reduce"])
                               - np.asarray(g2["reduce"])))) > 0.0


def test_search_round_and_train_phase():
    data = tiny_data()
    eng = micro_engine(data)
    p0, a0 = eng.init_state()
    params, alphas = eng.run(rounds=1)
    assert eng.metrics_history and "test_acc" in eng.metrics_history[-1]
    assert np.isfinite(eng.metrics_history[-1]["train_loss"])
    # both trees moved (server averages weights AND alphas); layers=1 means
    # the lone cell is a reduction cell, so inspect the reduce alphas
    assert not np.allclose(np.asarray(alphas["reduce"]),
                           np.asarray(a0["reduce"]))
    changed = jax.tree.map(lambda a, b: not np.allclose(a, b, atol=1e-12),
                           p0, params)
    assert any(jax.tree.leaves(changed))
    # phase 2: discretize and retrain with FedAvg
    genotype = eng.genotype(alphas)
    for gene in (genotype.normal, genotype.reduce):
        assert len(gene) == 4          # steps=2 → 2 nodes × 2 edges
    train_eng = make_train_engine(genotype, data, eng.cfg, C=4, layers=2,
                                  donate=False)
    variables = train_eng.run(rounds=1)
    assert variables is not None
    assert train_eng.metrics_history


def test_fixed_network_from_published_genotype():
    model = DartsNetwork(num_classes=10, genotype=DARTS_V2, C=4, layers=2)
    x = jnp.zeros((2, 8, 8, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 10)
    assert jnp.all(jnp.isfinite(logits))


@pytest.mark.slow
def test_gdas_single_path_search():
    """GDAS mode (model_search_gdas.py): straight-through gumbel samples
    one op per edge; search still moves both trees and eval works."""
    from fedml_tpu.models.darts import st_gumbel_softmax
    import jax.numpy as jnp
    w = st_gumbel_softmax(jnp.zeros((5, 8)), jax.random.PRNGKey(0))
    # forward value is exactly one-hot per edge
    np.testing.assert_allclose(np.asarray(jnp.sum(w, -1)), np.ones(5),
                               rtol=1e-6)
    assert float(jnp.max(w)) == 1.0

    data = tiny_data()
    eng = micro_engine(data, gdas=True)
    p0, a0 = eng.init_state()
    params, alphas = eng.run(rounds=1)
    assert eng.metrics_history and "test_acc" in eng.metrics_history[-1]
    assert not np.allclose(np.asarray(alphas["reduce"]),
                           np.asarray(a0["reduce"]))


@pytest.mark.slow
def test_mesh_fednas_matches_single_device():
    """Mesh FedNAS search (sharded bilevel searches, psum'd w+alpha
    averages) == the vmap engine."""
    from fedml_tpu.algorithms.fednas import (FedNASSearchEngine,
                                             make_mesh_fednas_engine)
    from fedml_tpu.data.loaders import load_data
    from fedml_tpu.parallel.mesh import make_mesh
    from fedml_tpu.utils.config import FedConfig

    data = load_data("cifar10", client_num_in_total=8, batch_size=4,
                     synthetic_scale=0.002, seed=0)
    cfg = FedConfig(client_num_in_total=8, client_num_per_round=8,
                    comm_round=1, epochs=1, batch_size=4, lr=0.05,
                    frequency_of_the_test=100)
    kw = dict(C=4, layers=2, steps=2, multiplier=2)
    ref = FedNASSearchEngine(data, cfg, donate=False, **kw)
    p0, a0 = ref.init_state()
    rng = jax.random.PRNGKey(3)
    p1, a1, m1 = ref.round_fn(jax.tree.map(jnp.copy, p0),
                              jax.tree.map(jnp.copy, a0),
                              *ref._round_args(0), rng)
    eng = make_mesh_fednas_engine(data, cfg, mesh=make_mesh(8),
                                  donate=False, **kw)
    p2, a2, m2 = eng.round_fn(jax.tree.map(jnp.copy, p0),
                              jax.tree.map(jnp.copy, a0),
                              *eng._round_args(0), rng)
    for a, b in zip(jax.tree.leaves((p1, a1)), jax.tree.leaves((p2, a2))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)
    assert abs(float(m1["train_loss"]) - float(m2["train_loss"])) < 1e-3
    # derived genotypes agree
    assert ref.genotype(a1) == eng.genotype(a2)
