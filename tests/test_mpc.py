"""MPC primitive pins (ISSUE 20 satellite): the field-arithmetic layer
under fedml_tpu/secure/secagg.py.  Everything here is numpy-only — no
jax import, no engines — so a primitive regression is attributed to
mpc.py itself, never to the data plane riding it.

The quantize overflow bound is the load-bearing pin: with p = 2^31-1
and scale 2^16 the usable float range is ±16383.999, and a value past
it must raise the NAMED ValueError, not alias across the sign boundary
(a silent wrap reads a large positive back as negative and poisons
every downstream aggregate while masks still cancel perfectly)."""
import numpy as np
import pytest

from fedml_tpu.core import mpc

P = mpc.DEFAULT_PRIME


# -- quantize / dequantize ---------------------------------------------------

def test_quantize_roundtrip_including_negatives():
    x = np.array([0.0, 1.5, -1.5, 1234.5678, -9999.25, 1e-4, -1e-4])
    q = mpc.quantize(x)
    assert q.dtype == np.int64
    assert (q >= 0).all() and (q < P).all()
    back = mpc.dequantize(q)
    np.testing.assert_allclose(back, x, atol=1.0 / 2 ** 16)


def test_quantize_negative_maps_to_upper_half():
    q = mpc.quantize(np.array([-1.0]))
    assert q[0] > P // 2, "negatives must wrap to the upper half-range"
    assert mpc.dequantize(q)[0] == -1.0


def test_quantize_overflow_raises_named_both_signs():
    # the bound is |round(x*scale)| <= (p-1)//2: 16383.999... fits,
    # 16384.0 is the first magnitude past it — pin BOTH signs, the
    # bug class is an asymmetric wrap
    bound = (P - 1) // 2                           # 1073741823
    ok = bound / 2.0 ** 16                          # largest exact fit
    assert mpc.quantize(np.array([ok]))[0] == bound
    assert mpc.quantize(np.array([-ok]))[0] == P - bound
    for sign in (+1.0, -1.0):
        with pytest.raises(ValueError, match="fixed-point field overflow"):
            mpc.quantize(np.array([sign * (ok + 1.0 / 2 ** 16)]))


def test_quantize_rejects_non_finite_by_name():
    """REVIEW: inf/NaN cast to INT64_MIN under .astype(np.int64), and
    np.abs(INT64_MIN) stays negative, so non-finite values slid past
    the max-abs guard and encoded as garbage.  They must be refused
    FIRST, by name."""
    for bad in (np.inf, -np.inf, np.nan):
        with pytest.raises(ValueError, match="non-finite"):
            mpc.quantize(np.array([bad, 0.5]))


def test_quantize_max_abs_enforces_aggregate_headroom():
    """max_abs tightens the per-value bound below the field half-range
    so K-summand callers can pre-buy sum headroom (secagg client_row
    passes (p−1)//(2K)); the refusal stays the named overflow error."""
    assert mpc.quantize(np.array([0.5]), max_abs=2 ** 15)[0] == 2 ** 15
    with pytest.raises(ValueError, match="fixed-point field overflow"):
        mpc.quantize(np.array([1.0]), max_abs=2 ** 15)
    with pytest.raises(ValueError, match="aggregate"):
        mpc.quantize(np.array([-1.0]), max_abs=2 ** 15)
    # a max_abs at/above the half-range is a no-op, not a loosening
    bound = (P - 1) // 2
    assert mpc.quantize(np.array([bound / 2.0 ** 16]),
                        max_abs=2 * bound)[0] == bound


def test_quantize_sum_bound_documented_for_aggregates():
    # K summands share one bound: K * max|x| * scale <= (p-1)//2.  Two
    # half-bound values sum INSIDE the field; the same two past half
    # would alias — the docstring's contract, pinned numerically.
    half = ((P - 1) // 2) // 2
    x = half / 2.0 ** 16
    q = mpc.quantize(np.array([x, x]))
    total = int(q.sum() % P)
    assert mpc.dequantize(np.array([total]))[0] == pytest.approx(
        2 * x, abs=1.0 / 2 ** 16)


# -- BGW (Shamir) sharing ----------------------------------------------------

def test_bgw_roundtrip_at_threshold():
    secret = mpc.quantize(np.array([3.25, -7.5, 0.0, 16000.0]))
    N, T = 7, 3                       # any T+1 = 4 shares reconstruct
    shares = mpc.BGW_encoding(secret, N, T, seed=11)
    assert shares.shape == (N,) + secret.shape
    idx = np.array([0, 2, 4, 6])      # exactly T+1 of them
    out = mpc.BGW_decoding(shares[idx], idx)
    np.testing.assert_array_equal(out, secret)
    # a different qualifying subset agrees — reconstruction is a
    # property of the polynomial, not of which workers survived
    idx2 = np.array([1, 3, 5, 6])
    np.testing.assert_array_equal(mpc.BGW_decoding(shares[idx2], idx2),
                                  secret)


def test_bgw_below_threshold_fails_by_value():
    """T shares (one short of T+1) must NOT reconstruct: Shamir privacy
    means any T-subset is consistent with EVERY candidate secret, so
    interpolating it yields garbage, not the secret.  The failure mode
    is wrong-value (information-theoretic), not an exception — pin
    that the decode disagrees."""
    secret = mpc.quantize(np.array([42.0, -42.0]))
    N, T = 7, 3
    shares = mpc.BGW_encoding(secret, N, T, seed=13)
    idx = np.array([0, 2, 4])         # T shares: one short
    out = mpc.BGW_decoding(shares[idx], idx)
    assert not np.array_equal(out, secret), (
        "T shares reconstructed the secret — threshold privacy broken")


# -- LCC ---------------------------------------------------------------------

def test_lcc_roundtrip():
    rs = np.random.RandomState(7)
    K, N, T = 3, 8, 1
    X = rs.randint(0, P, (K, 5)).astype(np.int64)
    coded = mpc.LCC_encoding(X, N, K, T=T, seed=5)
    assert coded.shape == (N, 5)
    idx = np.array([1, 3, 5, 7])      # any K+T = 4 coded blocks
    out = mpc.LCC_decoding(coded[idx], idx, N, K, T=T)
    np.testing.assert_array_equal(out, X)


# -- additive shares ---------------------------------------------------------

def test_additive_shares_sum_to_secret():
    rs = np.random.RandomState(3)
    X = rs.randint(0, P, (6,)).astype(np.int64)
    shares = mpc.additive_shares(X, N=5, seed=17)
    assert shares.shape == (5, 6)
    total = np.mod(shares.astype(object).sum(axis=0), P).astype(np.int64)
    np.testing.assert_array_equal(total, X)
    # no single share equals the secret (vanishing probability; seeded)
    for s in shares:
        assert not np.array_equal(s, X)


# -- DH key agreement --------------------------------------------------------

def test_dh_shared_key_symmetry():
    sk_a, sk_b, sk_c = 123457, 987653, 55555
    pk_a, pk_b = mpc.pk_gen(sk_a), mpc.pk_gen(sk_b)
    k_ab = mpc.shared_key(pk_b, sk_a)
    k_ba = mpc.shared_key(pk_a, sk_b)
    assert k_ab == k_ba, "DH agreement must be symmetric"
    # a third party derives a DIFFERENT pair key
    assert mpc.shared_key(pk_a, sk_c) != k_ab
